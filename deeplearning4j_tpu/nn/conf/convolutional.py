"""Convolutional layer family.

Parity surface: reference ``nn/conf/layers/``: ConvolutionLayer,
Convolution1DLayer, SeparableConvolution2D, SubsamplingLayer,
Subsampling1DLayer, Upsampling1D/2D, ZeroPadding1D/2DLayer,
and impls in ``nn/layers/convolution/`` (ConvolutionLayer.java:334 im2col path,
CudnnConvolutionHelper — deeplearning4j-cuda/.../CudnnConvolutionHelper.java:54).

TPU-native design: **NHWC layout with HWIO kernels**, lowered through
``lax.conv_general_dilated`` — XLA:TPU tiles these directly onto the MXU;
there is no im2col fallback and no cuDNN-style helper indirection (the
double-implementation pattern of the reference dissolves: one traced op,
one compiler). Pooling uses ``lax.reduce_window`` (VPU-friendly windowed
reductions).

Convolution mode semantics follow the reference's ``ConvolutionMode``:
``truncate`` (= VALID, silently dropping trailing pixels) and ``same``
(= SAME padding); explicit padding tuples correspond to ``Strict`` with
manual pads.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.initializers import init_weights
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    BaseLayer, Layer, register_layer, dropout_input,
)


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def _conv_out(size, k, s, pad, mode, dilation=1):
    eff_k = (k - 1) * dilation + 1  # effective kernel under dilation
    if mode == "same":
        return -(-size // s)
    out = (size + 2 * pad - eff_k) // s + 1
    if out <= 0:
        raise ValueError(
            f"Invalid convolution/pooling geometry: input size {size}, kernel {k} "
            f"(effective {eff_k}), stride {s}, padding {pad} gives non-positive "
            f"output size {out}. Use convolution_mode='same' or adjust kernel/padding.")
    return out


def _padding_cfg(mode: str, padding):
    """lax padding argument (per spatial dim) for the given convolution mode."""
    if mode == "same":
        return "SAME"
    ph, pw = _pair(padding)
    return ((ph, ph), (pw, pw))


def _s2d_eligible(x, kernel_size, stride, dilation, mode):
    """See ConvolutionLayer._space_to_depth_eligible."""
    return (mode == "same"
            and _pair(kernel_size) == (7, 7)
            and _pair(stride) == (2, 2)
            and _pair(dilation) == (1, 1)
            and x.shape[1] % 2 == 0 and x.shape[2] % 2 == 0
            and x.shape[3] <= 4)


def conv2d_forward(x, w, kernel_size, stride, padding, mode, dilation=(1, 1)):
    """The one 2-D convolution lowering, shared by ConvolutionLayer and the
    fused conv→BN→act block so both take the identical compute path
    (including the ImageNet-stem space-to-depth rewrite)."""
    if _s2d_eligible(x, kernel_size, stride, dilation, mode):
        return ConvolutionLayer._space_to_depth_conv(x, w)
    return lax.conv_general_dilated(
        x, w,
        window_strides=_pair(stride),
        padding=_padding_cfg(mode, padding),
        rhs_dilation=_pair(dilation),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


@register_layer
@dataclasses.dataclass(frozen=True)
class ConvolutionLayer(BaseLayer):
    """2-D convolution (reference nn/conf/layers/ConvolutionLayer.java +
    nn/layers/convolution/ConvolutionLayer.java; cuDNN fast path
    CudnnConvolutionHelper.java:54). NHWC in, HWIO kernel, NHWC out."""

    n_in: Optional[int] = None  # input channels (inferred)
    n_out: int = 0              # output channels
    kernel_size: Tuple[int, int] = (3, 3)
    stride: Tuple[int, int] = (1, 1)
    padding: Tuple[int, int] = (0, 0)
    convolution_mode: str = "truncate"  # truncate|same
    dilation: Tuple[int, int] = (1, 1)
    has_bias: bool = True
    activation: str = "identity"

    def input_kind(self):
        return "cnn"

    def output_type(self, it: InputType) -> InputType:
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        dh, dw = _pair(self.dilation)
        h = _conv_out(it.height, kh, sh, ph, self.convolution_mode, dh)
        w = _conv_out(it.width, kw, sw, pw, self.convolution_mode, dw)
        return InputType.convolutional(h, w, self.n_out)

    def with_n_in(self, n_in):
        # n_in is channels: set from the input type's channel count in init
        return self

    def init(self, rng, it: InputType, dtype=jnp.float32):
        kh, kw = _pair(self.kernel_size)
        c_in = self.n_in or it.channels
        fan_in = c_in * kh * kw
        fan_out = self.n_out * kh * kw
        params = {"W": init_weights(rng, (kh, kw, c_in, self.n_out), fan_in,
                                    fan_out, self.weight_init, self.dist, dtype)}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return params, {}

    def _space_to_depth_eligible(self, x):
        """The ImageNet-stem case (7x7 stride-2 SAME on <=4 channels) maps
        poorly onto the MXU: <8 input channels waste the systolic array's
        input tiling. Rewriting via 2x2 space-to-depth turns it into an
        exact-math 4x4 stride-1 conv over 4x the channels."""
        return (self.convolution_mode == "same"
                and _pair(self.kernel_size) == (7, 7)
                and _pair(self.stride) == (2, 2)
                and _pair(self.dilation) == (1, 1)
                and x.shape[1] % 2 == 0 and x.shape[2] % 2 == 0
                and x.shape[3] <= 4)

    @staticmethod
    def _space_to_depth_conv(x, w):
        """Exact rewrite of conv(x, w[7,7,C,F], stride 2, SAME) for even H/W.

        SAME here pads (2,3); in 2x2-block space that is pad (1,2) with the
        7x7 kernel zero-extended to 8x8 (index 7 multiplies only padding).
        Derivation: output o(i) reads input t = 2i-2..2i+4; with t = 2j+p
        (j the block index, p the parity) the kernel tap is k = 2(j-i)+p+2,
        so blocks j-i in -1..2 and W'[a, p] = w[2a+p] (a = j-i+1, w[7] = 0).
        """
        b, h, wd, c = x.shape
        f = w.shape[-1]
        x2 = x.reshape(b, h // 2, 2, wd // 2, 2, c)
        x2 = x2.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 2, wd // 2, 4 * c)
        w8 = jnp.pad(w, ((0, 1), (0, 1), (0, 0), (0, 0)))
        w2 = w8.reshape(4, 2, 4, 2, c, f)
        w2 = w2.transpose(0, 2, 1, 3, 4, 5).reshape(4, 4, 4 * c, f)
        return lax.conv_general_dilated(
            x2, w2, window_strides=(1, 1), padding=((1, 2), (1, 2)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = dropout_input(x, self.dropout, train, rng)
        z = conv2d_forward(x, params["W"], self.kernel_size, self.stride,
                           self.padding, self.convolution_mode, self.dilation)
        if self.has_bias:
            z = z + params["b"]
        return get_activation(self.activation)(z), state


@register_layer
@dataclasses.dataclass(frozen=True)
class SeparableConvolution2D(BaseLayer):
    """Depthwise-separable conv (reference nn/conf/layers/SeparableConvolution2D.java).
    Depthwise (feature_group_count=C) then 1x1 pointwise — both MXU-lowered."""

    n_in: Optional[int] = None
    n_out: int = 0
    kernel_size: Tuple[int, int] = (3, 3)
    stride: Tuple[int, int] = (1, 1)
    padding: Tuple[int, int] = (0, 0)
    convolution_mode: str = "truncate"
    depth_multiplier: int = 1
    has_bias: bool = True
    activation: str = "identity"

    def input_kind(self):
        return "cnn"

    def regularizable(self):
        return ("W_dw", "W_pw")

    def output_type(self, it: InputType) -> InputType:
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        h = _conv_out(it.height, kh, sh, ph, self.convolution_mode)
        w = _conv_out(it.width, kw, sw, pw, self.convolution_mode)
        return InputType.convolutional(h, w, self.n_out)

    def with_n_in(self, n_in):
        return self

    def init(self, rng, it: InputType, dtype=jnp.float32):
        kh, kw = _pair(self.kernel_size)
        c_in = self.n_in or it.channels
        k1, k2 = jax.random.split(rng)
        dw_out = c_in * self.depth_multiplier
        params = {
            "W_dw": init_weights(k1, (kh, kw, 1, dw_out), kh * kw, kh * kw * self.depth_multiplier,
                                 self.weight_init, self.dist, dtype),
            "W_pw": init_weights(k2, (1, 1, dw_out, self.n_out), dw_out, self.n_out,
                                 self.weight_init, self.dist, dtype),
        }
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return params, {}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = dropout_input(x, self.dropout, train, rng)
        c_in = x.shape[-1]
        z = lax.conv_general_dilated(
            x, params["W_dw"],
            window_strides=_pair(self.stride),
            padding=_padding_cfg(self.convolution_mode, self.padding),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=c_in,
        )
        z = lax.conv_general_dilated(
            z, params["W_pw"], window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        if self.has_bias:
            z = z + params["b"]
        return get_activation(self.activation)(z), state


@register_layer
@dataclasses.dataclass(frozen=True)
class SubsamplingLayer(Layer):
    """Spatial pooling (reference nn/conf/layers/SubsamplingLayer.java +
    nn/layers/convolution/subsampling/; cuDNN path CudnnSubsamplingHelper.java).
    Modes: max | avg | pnorm, via lax.reduce_window."""

    kernel_size: Tuple[int, int] = (2, 2)
    stride: Tuple[int, int] = (2, 2)
    padding: Tuple[int, int] = (0, 0)
    convolution_mode: str = "truncate"
    pooling_type: str = "max"  # max|avg|pnorm
    pnorm: int = 2

    def input_kind(self):
        return "cnn"

    def output_type(self, it: InputType) -> InputType:
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        h = _conv_out(it.height, kh, sh, ph, self.convolution_mode)
        w = _conv_out(it.width, kw, sw, pw, self.convolution_mode)
        return InputType.convolutional(h, w, it.channels)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        if self.convolution_mode == "same":
            pad = "SAME"
        else:
            ph, pw = _pair(self.padding)
            pad = ((0, 0), (ph, ph), (pw, pw), (0, 0))
        window = (1, kh, kw, 1)
        strides = (1, sh, sw, 1)
        pt = self.pooling_type.lower()
        if pt == "max":
            out = lax.reduce_window(x, -jnp.inf, lax.max, window, strides, pad)
        elif pt == "avg":
            s = lax.reduce_window(x, 0.0, lax.add, window, strides, pad)
            out = s / (kh * kw)
        elif pt == "pnorm":
            p = float(self.pnorm)
            s = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, window, strides, pad)
            out = s ** (1.0 / p)
        else:
            raise ValueError(f"Unknown pooling type '{self.pooling_type}'")
        return out, state


@register_layer
@dataclasses.dataclass(frozen=True)
class Upsampling2D(Layer):
    """Nearest-neighbour upsampling (reference nn/conf/layers/Upsampling2D.java)."""

    size: Tuple[int, int] = (2, 2)

    def input_kind(self):
        return "cnn"

    def output_type(self, it: InputType) -> InputType:
        sh, sw = _pair(self.size)
        return InputType.convolutional(it.height * sh, it.width * sw, it.channels)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        sh, sw = _pair(self.size)
        out = jnp.repeat(jnp.repeat(x, sh, axis=1), sw, axis=2)
        return out, state


@register_layer
@dataclasses.dataclass(frozen=True)
class ZeroPaddingLayer(Layer):
    """Spatial zero padding (reference nn/conf/layers/ZeroPaddingLayer.java).
    ``padding`` = (top, bottom, left, right) or (h, w) symmetric."""

    padding: Tuple[int, ...] = (1, 1)

    def input_kind(self):
        return "cnn"

    def _pads(self):
        p = self.padding
        if len(p) == 2:
            return (p[0], p[0], p[1], p[1])
        return tuple(int(v) for v in p)

    def output_type(self, it: InputType) -> InputType:
        t, b, l, r = self._pads()
        return InputType.convolutional(it.height + t + b, it.width + l + r, it.channels)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        t, b, l, r = self._pads()
        return jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0))), state


@register_layer
@dataclasses.dataclass(frozen=True)
class Convolution1DLayer(BaseLayer):
    """1-D convolution over (batch, time, channels) (reference
    nn/conf/layers/Convolution1DLayer.java). Lowered as NWC/WIO conv."""

    n_in: Optional[int] = None
    n_out: int = 0
    kernel_size: int = 3
    stride: int = 1
    padding: int = 0
    convolution_mode: str = "truncate"
    dilation: int = 1
    has_bias: bool = True
    activation: str = "identity"

    def input_kind(self):
        return "rnn"

    def is_recurrent(self):
        return True

    def output_type(self, it: InputType) -> InputType:
        t = it.timeseries_length
        if t is not None:
            t = _conv_out(t, self.kernel_size, self.stride, self.padding,
                          self.convolution_mode, self.dilation)
        return InputType.recurrent(self.n_out, t)

    def init(self, rng, it: InputType, dtype=jnp.float32):
        c_in = self.n_in or it.size
        fan_in = c_in * self.kernel_size
        fan_out = self.n_out * self.kernel_size
        params = {"W": init_weights(rng, (self.kernel_size, c_in, self.n_out),
                                    fan_in, fan_out, self.weight_init, self.dist, dtype)}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return params, {}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = dropout_input(x, self.dropout, train, rng)
        pad = ("SAME" if self.convolution_mode == "same"
               else ((self.padding, self.padding),))
        z = lax.conv_general_dilated(
            x, params["W"], window_strides=(self.stride,), padding=pad,
            rhs_dilation=(self.dilation,),
            dimension_numbers=("NWC", "WIO", "NWC"))
        if self.has_bias:
            z = z + params["b"]
        return get_activation(self.activation)(z), state


@register_layer
@dataclasses.dataclass(frozen=True)
class Subsampling1DLayer(Layer):
    """1-D pooling over (batch, time, channels) (reference
    nn/conf/layers/Subsampling1DLayer.java)."""

    kernel_size: int = 2
    stride: int = 2
    padding: int = 0
    pooling_type: str = "max"
    pnorm: int = 2
    convolution_mode: str = "truncate"

    def input_kind(self):
        return "rnn"

    def is_recurrent(self):
        return True

    def output_type(self, it: InputType) -> InputType:
        t = it.timeseries_length
        if t is not None:
            t = _conv_out(t, self.kernel_size, self.stride, self.padding,
                          self.convolution_mode)
        return InputType.recurrent(it.size, t)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        if self.convolution_mode == "same":
            pad = "SAME"
        else:
            pad = ((0, 0), (self.padding, self.padding), (0, 0))
        window = (1, self.kernel_size, 1)
        strides = (1, self.stride, 1)
        pt = self.pooling_type.lower()
        if pt == "max":
            out = lax.reduce_window(x, -jnp.inf, lax.max, window, strides, pad)
        elif pt == "avg":
            out = lax.reduce_window(x, 0.0, lax.add, window, strides, pad) / self.kernel_size
        elif pt == "pnorm":
            p = float(self.pnorm)
            s = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, window, strides, pad)
            out = s ** (1.0 / p)
        else:
            raise ValueError(f"Unknown pooling type '{self.pooling_type}'")
        return out, state


@register_layer
@dataclasses.dataclass(frozen=True)
class Upsampling1D(Layer):
    """(reference nn/conf/layers/Upsampling1D.java)"""

    size: int = 2

    def input_kind(self):
        return "rnn"

    def is_recurrent(self):
        return True

    def output_type(self, it: InputType) -> InputType:
        t = it.timeseries_length
        return InputType.recurrent(it.size, None if t is None else t * self.size)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return jnp.repeat(x, self.size, axis=1), state


@register_layer
@dataclasses.dataclass(frozen=True)
class Cropping2D(Layer):
    """Spatial cropping, the inverse of ZeroPaddingLayer (Keras Cropping2D
    import target). ``cropping`` = (top, bottom, left, right) or (h, w)."""

    cropping: Tuple[int, ...] = (0, 0)

    def input_kind(self):
        return "cnn"

    def _crops(self):
        c = self.cropping
        if len(c) == 2:
            return (c[0], c[0], c[1], c[1])
        return tuple(int(v) for v in c)

    def output_type(self, it: InputType) -> InputType:
        t, b, l, r = self._crops()
        h, w = it.height - t - b, it.width - l - r
        if h <= 0 or w <= 0:
            raise ValueError(f"Cropping {self.cropping} consumes the whole "
                             f"{it.height}x{it.width} input")
        return InputType.convolutional(h, w, it.channels)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        t, b, l, r = self._crops()
        return x[:, t:x.shape[1] - b, l:x.shape[2] - r, :], state


@register_layer
@dataclasses.dataclass(frozen=True)
class Cropping1D(Layer):
    """Temporal cropping (Keras Cropping1D). ``cropping`` = (left, right)."""

    cropping: Tuple[int, int] = (0, 0)

    def input_kind(self):
        return "cnn1d"

    def output_type(self, it: InputType) -> InputType:
        l, r = self.cropping
        t = None if it.timeseries_length is None else it.timeseries_length - l - r
        if t is not None and t <= 0:
            raise ValueError(f"Cropping {self.cropping} consumes the whole "
                             f"length-{it.timeseries_length} sequence")
        return InputType.recurrent(it.size, t)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        l, r = self.cropping
        return x[:, l:x.shape[1] - r, :], state


@register_layer
@dataclasses.dataclass(frozen=True)
class ZeroPadding1DLayer(Layer):
    """Temporal zero padding (Keras ZeroPadding1D; reference
    ZERO_PADDING_1D in KerasLayerConfiguration)."""

    padding: Tuple[int, int] = (1, 1)

    def input_kind(self):
        return "cnn1d"

    def output_type(self, it: InputType) -> InputType:
        l, r = self.padding
        t = None if it.timeseries_length is None else it.timeseries_length + l + r
        return InputType.recurrent(it.size, t)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        l, r = self.padding
        return jnp.pad(x, ((0, 0), (l, r), (0, 0))), state


# ---------------------------------------------------------------------------
# Fused Conv→BN→Activation(→residual-add) block (perf/fusion.py rewriter
# target). Motivation (tools/PROFILE_r5.md): train-mode BN costs ~4.7 full
# activation-set HBM crossings beyond the conv floor — BN backward alone
# re-reads saved activation-sized buffers. The fused block's custom VJP
# saves ONLY the conv output z plus O(C) per-batch mean/inv-std and
# recomputes x-hat (and the activation pre-image) in the backward, the
# In-Place Activated BatchNorm recipe (Bulò et al., CVPR 2018) expressed
# through jax.custom_vjp instead of a hand-written kernel.

def _bn_train_stats(z):
    """Per-channel (mean, var) with the same numerics as
    BatchNormalization.apply: single-pass f32-accumulated for low-precision
    compute, exact centered two-pass otherwise."""
    axes = tuple(range(z.ndim - 1))
    if z.dtype in (jnp.bfloat16, jnp.float16):
        zf = z.astype(jnp.float32)
        n = zf.size // zf.shape[-1]
        mean = jnp.sum(zf, axis=axes) / n
        var = jnp.maximum(jnp.sum(zf * zf, axis=axes) / n - mean * mean, 0.0)
    else:
        mean = jnp.mean(z, axis=axes)
        var = jnp.var(z, axis=axes)
    return mean, var


def _bn_act_fwd_math(act_name, eps, z, gamma, beta, res):
    # Pallas kernel family "bn_act": one VMEM-resident stats+normalize+
    # activation kernel when selection resolves to it, this jnp reference
    # otherwise — take() records kernel.pallas_/.xla_ either way
    from deeplearning4j_tpu.perf import pallas as _pk
    from deeplearning4j_tpu.perf.pallas import bn as _pk_bn
    if _pk.take("bn_act", _pk_bn.supported(z)):
        return _pk_bn.bn_act_fwd(act_name, eps, z, gamma, beta, res)
    mean, var = _bn_train_stats(z)
    sdt = var.dtype
    inv = lax.rsqrt(var + jnp.asarray(eps, sdt))
    scale = gamma.astype(sdt) * inv
    shift = beta.astype(sdt) - mean * scale
    pre = z * scale.astype(z.dtype) + shift.astype(z.dtype)
    if res is not None:
        pre = pre + res
    return get_activation(act_name)(pre), mean, var, inv


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def fused_bn_act_train(act_name, eps, z, gamma, beta, res):
    """Train-mode BN + activation (+ optional residual add) over the conv
    output ``z``, with a memory-efficient VJP: the backward recomputes the
    normalized x-hat from ``z`` plus the saved O(C) (mean, inv-std) instead
    of keeping activation-sized normalize/pre-activation buffers alive.

    Returns ``(out, mean, var)``; the (mean, var) outputs exist ONLY to feed
    the running-stat EMA and are not differentiated (their cotangents are
    ignored — the running buffers are non-trainable state)."""
    out, mean, var, _ = _bn_act_fwd_math(act_name, eps, z, gamma, beta, res)
    return out, mean, var


def _fused_bn_act_fwd(act_name, eps, z, gamma, beta, res):
    out, mean, var, inv = _bn_act_fwd_math(act_name, eps, z, gamma, beta, res)
    # residuals: z (which the conv dW backward saves anyway) + O(C) vectors
    # (+ the residual-add input, itself another block's saved output)
    return (out, mean, var), (z, gamma, beta, res, mean, inv)


def _fused_bn_act_bwd(act_name, eps, saved, cts):
    z, gamma, beta, res, mean, inv = saved
    dout = cts[0]  # mean/var cotangents ignored (EMA-only outputs)
    from deeplearning4j_tpu.perf import pallas as _pk
    from deeplearning4j_tpu.perf.pallas import bn as _pk_bn
    if _pk.take("bn_act_bwd", _pk_bn.supported(z)):
        dz, dgamma, dbeta, dpre = _pk_bn.bn_act_bwd(
            act_name, eps, z, gamma, beta, res, mean, inv, dout)
        dres = None if res is None else dpre.astype(res.dtype)
        return (dz, dgamma, dbeta, dres)
    sdt = mean.dtype
    scale = gamma.astype(sdt) * inv
    shift = beta.astype(sdt) - mean * scale
    pre = z * scale.astype(z.dtype) + shift.astype(z.dtype)
    if res is not None:
        pre = pre + res
    # activation backward through the SAME activation implementation the
    # forward used (recomputed pre-image, no saved buffer)
    _, act_vjp = jax.vjp(get_activation(act_name), pre)
    dpre = act_vjp(dout)[0]
    axes = tuple(range(z.ndim - 1))
    n = z.size // z.shape[-1]
    zf = z.astype(sdt)
    xhat = (zf - mean) * inv
    dpre32 = dpre.astype(sdt)
    dgamma = jnp.sum(dpre32 * xhat, axis=axes)
    dbeta = jnp.sum(dpre32, axis=axes)
    # full train-mode BN backward (gradients flow through the batch stats):
    # dz = gamma*inv * (dpre - mean(dpre) - xhat * mean(dpre * xhat))
    dz = (scale * (dpre32 - dbeta / n - xhat * (dgamma / n))).astype(z.dtype)
    dres = None if res is None else dpre.astype(res.dtype)
    return (dz, dgamma.astype(gamma.dtype), dbeta.astype(beta.dtype), dres)


fused_bn_act_train.defvjp(_fused_bn_act_fwd, _fused_bn_act_bwd)


@register_layer
@dataclasses.dataclass(frozen=True)
class FusedSeparableConvBNActivation(BaseLayer):
    """SeparableConvolution2D → train-mode BatchNorm → activation as ONE
    layer sharing :func:`fused_bn_act_train`'s memory-efficient VJP (the
    BN backward recomputes x-hat from the saved pointwise-conv output plus
    O(C) mean/inv-std). Produced by ``perf.fusion.fuse`` from matched
    SeparableConvolution2D → BatchNormalization → ActivationLayer chains
    (the PR 4 leftover); math identical to the unfused stack within fp
    tolerance. Non-residual only — depthwise stems don't sit on residual
    adds in the reference topologies."""

    n_in: Optional[int] = None
    n_out: int = 0
    kernel_size: Tuple[int, int] = (3, 3)
    stride: Tuple[int, int] = (1, 1)
    padding: Tuple[int, int] = (0, 0)
    convolution_mode: str = "truncate"
    depth_multiplier: int = 1
    has_bias: bool = False
    activation: str = "relu"
    decay: float = 0.9
    eps: float = 1e-5
    gamma: float = 1.0
    beta: float = 0.0

    def input_kind(self):
        return "cnn"

    def regularizable(self):
        return ("W_dw", "W_pw")

    def output_type(self, it: InputType) -> InputType:
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        h = _conv_out(it.height, kh, sh, ph, self.convolution_mode)
        w = _conv_out(it.width, kw, sw, pw, self.convolution_mode)
        return InputType.convolutional(h, w, self.n_out)

    def with_n_in(self, n_in):
        return self

    def init(self, rng, it: InputType, dtype=jnp.float32):
        kh, kw = _pair(self.kernel_size)
        c_in = self.n_in or it.channels
        k1, k2 = jax.random.split(rng)
        dw_out = c_in * self.depth_multiplier
        params = {
            "W_dw": init_weights(k1, (kh, kw, 1, dw_out), kh * kw,
                                 kh * kw * self.depth_multiplier,
                                 self.weight_init, self.dist, dtype),
            "W_pw": init_weights(k2, (1, 1, dw_out, self.n_out), dw_out,
                                 self.n_out, self.weight_init, self.dist,
                                 dtype),
        }
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        params["gamma"] = jnp.full((self.n_out,), self.gamma, dtype)
        params["beta"] = jnp.full((self.n_out,), self.beta, dtype)
        state = {"mean": jnp.zeros((self.n_out,), dtype),
                 "var": jnp.ones((self.n_out,), dtype)}
        return params, state

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        from deeplearning4j_tpu.perf.compile_watch import bump_active
        bump_active("fusion.fused_block")
        x = dropout_input(x, self.dropout, train, rng)
        z = lax.conv_general_dilated(
            x, params["W_dw"], window_strides=_pair(self.stride),
            padding=_padding_cfg(self.convolution_mode, self.padding),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=x.shape[-1])
        z = lax.conv_general_dilated(
            z, params["W_pw"], window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.has_bias:
            z = z + params["b"]
        return _fused_bn_tail(self, params, state, z, train)


@register_layer
@dataclasses.dataclass(frozen=True)
class FusedConv1DBNActivation(BaseLayer):
    """Convolution1DLayer → train-mode BatchNorm → activation as ONE layer
    over (batch, time, channels), sharing :func:`fused_bn_act_train`'s
    memory-efficient VJP (the normalize axes are 'all but last', so the
    same custom VJP covers NWC exactly as it covers NHWC). Produced by
    ``perf.fusion.fuse`` from matched Convolution1DLayer →
    BatchNormalization → ActivationLayer chains (the PR 4 leftover)."""

    n_in: Optional[int] = None
    n_out: int = 0
    kernel_size: int = 3
    stride: int = 1
    padding: int = 0
    convolution_mode: str = "truncate"
    dilation: int = 1
    has_bias: bool = False
    activation: str = "relu"
    decay: float = 0.9
    eps: float = 1e-5
    gamma: float = 1.0
    beta: float = 0.0

    def input_kind(self):
        return "rnn"

    def is_recurrent(self):
        return True

    def output_type(self, it: InputType) -> InputType:
        t = it.timeseries_length
        if t is not None:
            t = _conv_out(t, self.kernel_size, self.stride, self.padding,
                          self.convolution_mode, self.dilation)
        return InputType.recurrent(self.n_out, t)

    def init(self, rng, it: InputType, dtype=jnp.float32):
        c_in = self.n_in or it.size
        fan_in = c_in * self.kernel_size
        fan_out = self.n_out * self.kernel_size
        params = {"W": init_weights(rng, (self.kernel_size, c_in, self.n_out),
                                    fan_in, fan_out, self.weight_init,
                                    self.dist, dtype)}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        params["gamma"] = jnp.full((self.n_out,), self.gamma, dtype)
        params["beta"] = jnp.full((self.n_out,), self.beta, dtype)
        state = {"mean": jnp.zeros((self.n_out,), dtype),
                 "var": jnp.ones((self.n_out,), dtype)}
        return params, state

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        from deeplearning4j_tpu.perf.compile_watch import bump_active
        bump_active("fusion.fused_block")
        x = dropout_input(x, self.dropout, train, rng)
        pad = ("SAME" if self.convolution_mode == "same"
               else ((self.padding, self.padding),))
        z = lax.conv_general_dilated(
            x, params["W"], window_strides=(self.stride,), padding=pad,
            rhs_dilation=(self.dilation,),
            dimension_numbers=("NWC", "WIO", "NWC"))
        if self.has_bias:
            z = z + params["b"]
        return _fused_bn_tail(self, params, state, z, train)


def _fused_bn_tail(layer, params, state, z, train):
    """Shared BN(+activation) tail of the fused blocks: train mode goes
    through the memory-efficient custom VJP, eval mode through the folded
    running-stat scale/shift — identical to FusedConvBNActivation.apply's
    non-residual path."""
    gamma, beta = params["gamma"], params["beta"]
    if train:
        out, mean, var = fused_bn_act_train(layer.activation, layer.eps,
                                            z, gamma, beta, None)
        new_state = {
            "mean": layer.decay * state["mean"] + (1.0 - layer.decay) * mean,
            "var": layer.decay * state["var"] + (1.0 - layer.decay) * var,
        }
        return out, new_state
    mean, var = state["mean"], state["var"]
    sdt = var.dtype
    inv = lax.rsqrt(var + jnp.asarray(layer.eps, sdt))
    scale = gamma.astype(sdt) * inv
    shift = beta.astype(sdt) - mean * scale
    pre = z * scale.astype(z.dtype) + shift.astype(z.dtype)
    return get_activation(layer.activation)(pre), state


@register_layer
@dataclasses.dataclass(frozen=True)
class FusedConvBNActivation(BaseLayer):
    """Conv → train-mode BatchNorm → activation (optionally + residual add
    before the activation) as ONE layer whose BN backward recomputes x-hat
    instead of re-reading activation-sized saves (see fused_bn_act_train).

    Produced by ``perf.fusion.fuse`` from matched ConvolutionLayer →
    BatchNormalization → ActivationLayer(→ ElementWiseVertex add) patterns;
    usable directly as well. ``residual=True`` (ComputationGraph only) adds
    a second vertex input to the pre-activation. Math is identical to the
    unfused stack within fp tolerance; parameter layout is the union of the
    conv's (W[, b]) and the BN's (gamma, beta) with the BN running stats in
    the layer state."""

    n_in: Optional[int] = None
    n_out: int = 0
    kernel_size: Tuple[int, int] = (3, 3)
    stride: Tuple[int, int] = (1, 1)
    padding: Tuple[int, int] = (0, 0)
    convolution_mode: str = "truncate"
    dilation: Tuple[int, int] = (1, 1)
    has_bias: bool = False
    activation: str = "relu"
    # BatchNormalization fields (gamma/beta are the INIT values)
    decay: float = 0.9
    eps: float = 1e-5
    gamma: float = 1.0
    beta: float = 0.0
    residual: bool = False

    def input_kind(self):
        return "cnn"

    def output_type(self, it: InputType) -> InputType:
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        dh, dw = _pair(self.dilation)
        h = _conv_out(it.height, kh, sh, ph, self.convolution_mode, dh)
        w = _conv_out(it.width, kw, sw, pw, self.convolution_mode, dw)
        return InputType.convolutional(h, w, self.n_out)

    def with_n_in(self, n_in):
        return self  # n_in is channels, set from the input type in init

    def init(self, rng, it: InputType, dtype=jnp.float32):
        kh, kw = _pair(self.kernel_size)
        c_in = self.n_in or it.channels
        fan_in = c_in * kh * kw
        fan_out = self.n_out * kh * kw
        params = {"W": init_weights(rng, (kh, kw, c_in, self.n_out), fan_in,
                                    fan_out, self.weight_init, self.dist,
                                    dtype)}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        params["gamma"] = jnp.full((self.n_out,), self.gamma, dtype)
        params["beta"] = jnp.full((self.n_out,), self.beta, dtype)
        state = {"mean": jnp.zeros((self.n_out,), dtype),
                 "var": jnp.ones((self.n_out,), dtype)}
        return params, state

    def apply(self, params, state, x, *, train=False, rng=None, mask=None,
              res=None):
        from deeplearning4j_tpu.perf.compile_watch import bump_active
        bump_active("fusion.fused_block")
        x = dropout_input(x, self.dropout, train, rng)
        z = conv2d_forward(x, params["W"], self.kernel_size, self.stride,
                           self.padding, self.convolution_mode, self.dilation)
        if self.has_bias:
            z = z + params["b"]
        gamma, beta = params["gamma"], params["beta"]
        if train:
            out, mean, var = fused_bn_act_train(self.activation, self.eps,
                                                z, gamma, beta, res)
            new_state = {
                "mean": self.decay * state["mean"] + (1.0 - self.decay) * mean,
                "var": self.decay * state["var"] + (1.0 - self.decay) * var,
            }
        else:
            mean, var = state["mean"], state["var"]
            sdt = var.dtype
            inv = lax.rsqrt(var + jnp.asarray(self.eps, sdt))
            scale = gamma.astype(sdt) * inv
            shift = beta.astype(sdt) - mean * scale
            pre = z * scale.astype(z.dtype) + shift.astype(z.dtype)
            if res is not None:
                pre = pre + res
            out = get_activation(self.activation)(pre)
            new_state = state
        return out, new_state
