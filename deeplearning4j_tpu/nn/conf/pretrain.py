"""Pretrainable feed-forward layers (denoising AutoEncoder).

Parity surface: reference ``nn/conf/layers/AutoEncoder.java`` (builder:
corruptionLevel=0.3, sparsity) + ``nn/layers/feedforward/autoencoder/
AutoEncoder.java`` (encode/decode with tied weights W / W^T and a visible
bias), on top of ``nn/conf/layers/BasePretrainNetwork.java`` /
``nn/layers/BasePretrainNetwork.java:37`` (the layerwise-pretraining
contract MultiLayerNetwork.pretrain drives).

TPU-native: pretraining is a jitted loss on the corrupted input; autodiff
replaces the hand-written W/b/vb gradient assembly of the reference
(AutoEncoder.java:123).

RBM (reference ``nn/conf/layers/RBM.java`` + ``nn/layers/feedforward/rbm/
RBM.java`` contrastiveDivergence) is implemented below via the
free-energy-difference formulation: ``pretrain_loss = F(v_data) -
F(stop_gradient(v_model))`` where ``v_model`` comes from k jitted Gibbs
steps — the autodiff gradient of that scalar IS the CD-k update
(positive phase minus negative phase), so the same pretrain driver that
runs the AE/VAE runs the RBM.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import BaseLayer, register_layer
from deeplearning4j_tpu.nn.initializers import init_weights


@register_layer
@dataclasses.dataclass(frozen=True)
class AutoEncoder(BaseLayer):
    """Denoising autoencoder layer.

    Supervised forward = encode(x). Pretraining reconstructs the clean input
    from a masking-corrupted copy (``corruption_level`` = probability an
    input unit is zeroed, reference getCorruptedInput). ``loss``: 'mse' or
    'xent' (binary cross-entropy — use with sigmoid activation and [0,1]
    data, the reference's RECONSTRUCTION_CROSSENTROPY analogue)."""

    n_in: Optional[int] = None
    n_out: int = 0
    corruption_level: float = 0.3
    sparsity: float = 0.0
    loss: str = "mse"
    activation: str = "sigmoid"

    def input_kind(self):
        return "ff"

    def is_pretrainable(self):
        return True

    def output_type(self, input_type):
        return InputType.feed_forward(self.n_out)

    def init(self, rng, input_type, dtype=jnp.float32):
        n_in = self.n_in or input_type.flat_size()
        k_w, _ = jax.random.split(rng)
        return {
            "W": init_weights(k_w, (n_in, self.n_out), n_in, self.n_out,
                              self.weight_init, self.dist, dtype),
            "b": jnp.full((self.n_out,), self.bias_init, dtype),
            "vb": jnp.full((n_in,), self.bias_init, dtype),
        }, {}

    # --------------------------------------------------------------- forward
    def encode(self, params, x):
        return get_activation(self.activation)(x @ params["W"] + params["b"])

    def decode(self, params, h):
        """Tied weights: decode through W^T (reference decode :71)."""
        return get_activation(self.activation)(h @ params["W"].T + params["vb"])

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return self.encode(params, x), state

    # -------------------------------------------------------------- pretrain
    def pretrain_loss(self, params, state, x, rng):
        x_in = x
        if self.corruption_level > 0 and rng is not None:
            rng, k = jax.random.split(rng)
            keep = jax.random.bernoulli(k, 1.0 - self.corruption_level, x.shape)
            x_in = jnp.where(keep, x, 0.0).astype(x.dtype)
        h = self.encode(params, x_in)
        z = self.decode(params, h)
        if self.loss == "mse":
            recon = jnp.mean(jnp.sum((z - x) ** 2, -1))
        elif self.loss == "xent":
            eps = 1e-7
            recon = jnp.mean(-jnp.sum(
                x * jnp.log(z + eps) + (1 - x) * jnp.log(1 - z + eps), -1))
        else:
            raise ValueError(self.loss)
        if self.sparsity > 0:
            # KL(sparsity || mean activation) sparsity penalty
            rho = self.sparsity
            rho_hat = jnp.clip(jnp.mean(h, 0), 1e-6, 1 - 1e-6)
            recon = recon + jnp.sum(rho * jnp.log(rho / rho_hat) +
                                    (1 - rho) * jnp.log((1 - rho) / (1 - rho_hat)))
        return recon


@register_layer
@dataclasses.dataclass(frozen=True)
class RBM(BaseLayer):
    """Restricted Boltzmann Machine with CD-k pretraining.

    Parity surface: reference ``nn/conf/layers/RBM.java`` (builder: k,
    hiddenUnit/visibleUnit, sparsity) + ``nn/layers/feedforward/rbm/RBM.java``
    (contrastiveDivergence: sampleHiddenGivenVisible /
    sampleVisibleGivenHidden Gibbs chain; supervised forward propagates
    hidden activations).

    Units: 'binary' (Bernoulli) for both sides, or visible_unit='gaussian'
    (identity mean, unit variance — reference VisibleUnit.GAUSSIAN). The
    supervised forward is sigmoid(xW + c) exactly like the reference's
    activate().

    CD-k as autodiff: ``pretrain_loss`` returns
    ``mean(F(v0)) - mean(F(stop_grad(vk)))`` — free-energy difference
    between the data and the k-step Gibbs reconstruction. Its gradient wrt
    (W, b, vb) is the classic CD-k update, so the standard pretrain driver
    (MultiLayerNetwork.pretrain -> jax.value_and_grad) trains it without a
    bespoke code path. The Gibbs chain runs under stop_gradient inside the
    same jitted step (lax.scan over k).
    """

    n_in: Optional[int] = None
    n_out: int = 0
    k: int = 1
    visible_unit: str = "binary"   # binary | gaussian
    hidden_unit: str = "binary"
    sparsity: float = 0.0
    activation: str = "sigmoid"

    def input_kind(self):
        return "ff"

    def is_pretrainable(self):
        return True

    def output_type(self, input_type):
        return InputType.feed_forward(self.n_out)

    def init(self, rng, input_type, dtype=jnp.float32):
        n_in = self.n_in or input_type.flat_size()
        k_w, _ = jax.random.split(rng)
        return {
            "W": init_weights(k_w, (n_in, self.n_out), n_in, self.n_out,
                              self.weight_init, self.dist, dtype),
            "b": jnp.full((self.n_out,), self.bias_init, dtype),   # hidden
            "vb": jnp.full((n_in,), self.bias_init, dtype),        # visible
        }, {}

    # --------------------------------------------------------------- forward
    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return (get_activation(self.activation)(x @ params["W"] + params["b"]),
                state)

    # ------------------------------------------------------------ energetics
    def free_energy(self, params, v):
        """F(v) = -v.vb - sum_j softplus(v W_j + b_j) (binary visible);
        Gaussian visible adds the quadratic self-energy v^2/2."""
        pre = v @ params["W"] + params["b"]
        f = -v @ params["vb"] - jnp.sum(jax.nn.softplus(pre), -1)
        if self.visible_unit == "gaussian":
            f = f + 0.5 * jnp.sum(v * v, -1)
        return f

    def _sample_h(self, params, v, key):
        p = jax.nn.sigmoid(v @ params["W"] + params["b"])
        return jax.random.bernoulli(key, p).astype(v.dtype), p

    def _sample_v(self, params, h, key):
        pre = h @ params["W"].T + params["vb"]
        if self.visible_unit == "gaussian":
            return pre + jax.random.normal(key, pre.shape, pre.dtype), pre
        p = jax.nn.sigmoid(pre)
        return jax.random.bernoulli(key, p).astype(h.dtype), p

    def gibbs_chain(self, params, v0, rng, k=None):
        """k alternating Gibbs steps from v0; returns the final visible
        MEAN-FIELD value (probabilities, the reference's negative-phase
        input). Runs under lax.scan — k is static."""
        k = self.k if k is None else k

        def body(carry, key):
            v, _ = carry
            kh, kv = jax.random.split(key)
            h, _ = self._sample_h(params, v, kh)
            v2, v2_mean = self._sample_v(params, h, kv)
            return (v2, v2_mean), None

        keys = jax.random.split(rng, k)
        (_, vk_mean), _ = jax.lax.scan(body, (v0, v0), keys)
        return vk_mean

    # -------------------------------------------------------------- pretrain
    def pretrain_loss(self, params, state, x, rng):
        v0 = x
        vk = jax.lax.stop_gradient(
            self.gibbs_chain(params, jax.lax.stop_gradient(v0), rng))
        loss = jnp.mean(self.free_energy(params, v0)) \
            - jnp.mean(self.free_energy(params, vk))
        if self.sparsity > 0:
            rho_hat = jnp.clip(
                jnp.mean(jax.nn.sigmoid(x @ params["W"] + params["b"]), 0),
                1e-6, 1 - 1e-6)
            rho = self.sparsity
            loss = loss + jnp.sum(
                rho * jnp.log(rho / rho_hat)
                + (1 - rho) * jnp.log((1 - rho) / (1 - rho_hat)))
        return loss

    def reconstruction_error(self, params, x, rng):
        """Mean-squared reconstruction error after one Gibbs step (the
        reference's monitoring quantity for RBM training progress)."""
        vk = self.gibbs_chain(params, x, rng, k=1)
        return jnp.mean(jnp.sum((vk - x) ** 2, -1))
