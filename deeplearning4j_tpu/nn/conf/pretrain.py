"""Pretrainable feed-forward layers (denoising AutoEncoder).

Parity surface: reference ``nn/conf/layers/AutoEncoder.java`` (builder:
corruptionLevel=0.3, sparsity) + ``nn/layers/feedforward/autoencoder/
AutoEncoder.java`` (encode/decode with tied weights W / W^T and a visible
bias), on top of ``nn/conf/layers/BasePretrainNetwork.java`` /
``nn/layers/BasePretrainNetwork.java:37`` (the layerwise-pretraining
contract MultiLayerNetwork.pretrain drives).

TPU-native: pretraining is a jitted loss on the corrupted input; autodiff
replaces the hand-written W/b/vb gradient assembly of the reference
(AutoEncoder.java:123). RBM is intentionally not replicated: contrastive
divergence is a pre-2012 technique the reference itself deprecated, and the
denoising AE + VAE cover the pretraining capability.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import BaseLayer, register_layer
from deeplearning4j_tpu.nn.initializers import init_weights


@register_layer
@dataclasses.dataclass(frozen=True)
class AutoEncoder(BaseLayer):
    """Denoising autoencoder layer.

    Supervised forward = encode(x). Pretraining reconstructs the clean input
    from a masking-corrupted copy (``corruption_level`` = probability an
    input unit is zeroed, reference getCorruptedInput). ``loss``: 'mse' or
    'xent' (binary cross-entropy — use with sigmoid activation and [0,1]
    data, the reference's RECONSTRUCTION_CROSSENTROPY analogue)."""

    n_in: Optional[int] = None
    n_out: int = 0
    corruption_level: float = 0.3
    sparsity: float = 0.0
    loss: str = "mse"
    activation: str = "sigmoid"

    def input_kind(self):
        return "ff"

    def is_pretrainable(self):
        return True

    def output_type(self, input_type):
        return InputType.feed_forward(self.n_out)

    def init(self, rng, input_type, dtype=jnp.float32):
        n_in = self.n_in or input_type.flat_size()
        k_w, _ = jax.random.split(rng)
        return {
            "W": init_weights(k_w, (n_in, self.n_out), n_in, self.n_out,
                              self.weight_init, self.dist, dtype),
            "b": jnp.full((self.n_out,), self.bias_init, dtype),
            "vb": jnp.full((n_in,), self.bias_init, dtype),
        }, {}

    # --------------------------------------------------------------- forward
    def encode(self, params, x):
        return get_activation(self.activation)(x @ params["W"] + params["b"])

    def decode(self, params, h):
        """Tied weights: decode through W^T (reference decode :71)."""
        return get_activation(self.activation)(h @ params["W"].T + params["vb"])

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return self.encode(params, x), state

    # -------------------------------------------------------------- pretrain
    def pretrain_loss(self, params, state, x, rng):
        x_in = x
        if self.corruption_level > 0 and rng is not None:
            rng, k = jax.random.split(rng)
            keep = jax.random.bernoulli(k, 1.0 - self.corruption_level, x.shape)
            x_in = jnp.where(keep, x, 0.0).astype(x.dtype)
        h = self.encode(params, x_in)
        z = self.decode(params, h)
        if self.loss == "mse":
            recon = jnp.mean(jnp.sum((z - x) ** 2, -1))
        elif self.loss == "xent":
            eps = 1e-7
            recon = jnp.mean(-jnp.sum(
                x * jnp.log(z + eps) + (1 - x) * jnp.log(1 - z + eps), -1))
        else:
            raise ValueError(self.loss)
        if self.sparsity > 0:
            # KL(sparsity || mean activation) sparsity penalty
            rho = self.sparsity
            rho_hat = jnp.clip(jnp.mean(h, 0), 1e-6, 1 - 1e-6)
            recon = recon + jnp.sum(rho * jnp.log(rho / rho_hat) +
                                    (1 - rho) * jnp.log((1 - rho) / (1 - rho_hat)))
        return recon
