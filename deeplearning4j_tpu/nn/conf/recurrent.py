"""Recurrent layer family: LSTM, GravesLSTM (peepholes), bidirectional
wrappers, RnnOutputLayer, embeddings.

Parity surface: reference ``nn/conf/layers/{LSTM,GravesLSTM,
GravesBidirectionalLSTM,RnnOutputLayer,EmbeddingLayer}.java`` and the shared
imperative math in ``nn/layers/recurrent/LSTMHelpers.java`` (785 LoC fwd/bwd
for all LSTM variants; cuDNN path CudnnLSTMHelper.java).

TPU-native design:
- activations are (batch, time, size) — time-major is used only inside the
  scan; the input-to-hidden projection for ALL timesteps is hoisted out of the
  recurrence as one large MXU matmul ``(b*t, n_in) @ (n_in, 4n)``, so the
  scan body is just the small recurrent matmul + gate math.
- the backward pass is jax autodiff through ``lax.scan`` (replacing the
  hand-written backpropGradientHelper of LSTMHelpers.java:462).
- per-timestep masking holds cell/hidden state through masked steps and zeroes
  the output, matching the reference's variable-length masking semantics.
- stateful inference (``rnnTimeStep`` — MultiLayerNetwork.java:2615) and
  truncated BPTT carry an explicit (h, c) pytree; layers expose
  ``init_carry``/``apply_seq`` so the network can thread carries through jit.

Gate ordering is (i, f, g, o); forget-gate bias init defaults to 1.0 like the
reference's ``forgetGateBiasInit``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.initializers import init_weights
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    BaseLayer, BaseOutputLayer, register_layer, dropout_input, layer_from_dict,
)


@dataclasses.dataclass(frozen=True)
class BaseRecurrentLayer(BaseLayer):
    """Common recurrent contract: carries + sequence application."""

    def is_recurrent(self):
        return True

    def input_kind(self):
        return "rnn"

    def init_carry(self, batch: int, dtype=jnp.float32):
        raise NotImplementedError

    def apply_seq(self, params, carry, x, *, train=False, rng=None, mask=None):
        """(out, new_carry); x is (batch, time, n_in)."""
        raise NotImplementedError

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        out, _ = self.apply_seq(params, self.init_carry(x.shape[0], x.dtype),
                                x, train=train, rng=rng, mask=mask)
        return out, state


@register_layer
@dataclasses.dataclass(frozen=True)
class LSTM(BaseRecurrentLayer):
    """Standard LSTM (reference nn/conf/layers/LSTM.java — no peepholes;
    matches CudnnLSTMHelper-supported config: sigmoid gates + tanh)."""

    n_in: Optional[int] = None
    n_out: int = 0
    activation: str = "tanh"
    gate_activation: str = "sigmoid"
    forget_gate_bias_init: float = 1.0

    def regularizable(self):
        return ("W", "U")

    def output_type(self, it: InputType) -> InputType:
        return InputType.recurrent(self.n_out, it.timeseries_length)

    def init(self, rng, it: InputType, dtype=jnp.float32):
        n_in = self.n_in or it.size
        n = self.n_out
        k1, k2 = jax.random.split(rng)
        # fused gate weights: order (i, f, g, o)
        W = init_weights(k1, (n_in, 4 * n), n_in, n, self.weight_init, self.dist, dtype)
        U = init_weights(k2, (n, 4 * n), n, n, self.weight_init, self.dist, dtype)
        b = jnp.zeros((4 * n,), dtype)
        b = b.at[n:2 * n].set(self.forget_gate_bias_init)
        return {"W": W, "U": U, "b": b}, {}

    def init_carry(self, batch, dtype=jnp.float32):
        n = self.n_out
        return {"h": jnp.zeros((batch, n), dtype), "c": jnp.zeros((batch, n), dtype)}

    def _gates(self, z, c_prev, params):
        n = self.n_out
        act = get_activation(self.activation)
        gate = get_activation(self.gate_activation)
        i = gate(z[:, 0 * n:1 * n])
        f = gate(z[:, 1 * n:2 * n])
        g = act(z[:, 2 * n:3 * n])
        o = gate(z[:, 3 * n:4 * n])
        c = f * c_prev + i * g
        h = o * act(c)
        return h, c

    def apply_seq(self, params, carry, x, *, train=False, rng=None, mask=None):
        x = dropout_input(x, self.dropout, train, rng)
        b, t, _ = x.shape
        # hoisted input projection: one big MXU matmul over all timesteps
        xw = (x.reshape(b * t, -1) @ params["W"] + params["b"]).reshape(b, t, -1)
        xw_t = jnp.swapaxes(xw, 0, 1)                      # (t, b, 4n)
        m_t = None if mask is None else jnp.swapaxes(mask, 0, 1)  # (t, b)

        U = params["U"]

        def step(c, inp):
            if m_t is None:
                xw_i = inp
            else:
                xw_i, m_i = inp
            h_prev, c_prev = c["h"], c["c"]
            z = xw_i + h_prev @ U
            h, cc = self._gates(z, c_prev, params)
            if m_t is not None:
                keep = m_i[:, None]
                h = keep * h + (1.0 - keep) * h_prev
                cc = keep * cc + (1.0 - keep) * c_prev
                out = keep * h
            else:
                out = h
            return {"h": h, "c": cc}, out

        xs = xw_t if m_t is None else (xw_t, m_t)
        new_carry, outs = lax.scan(step, carry, xs)
        return jnp.swapaxes(outs, 0, 1), new_carry


@register_layer
@dataclasses.dataclass(frozen=True)
class GravesLSTM(LSTM):
    """LSTM with peephole connections (reference nn/conf/layers/GravesLSTM.java;
    math per LSTMHelpers.java with hasPeepholeConnections=true): diagonal
    peepholes c_{t-1} -> i,f gates and c_t -> o gate."""

    def init(self, rng, it: InputType, dtype=jnp.float32):
        params, state = super().init(rng, it, dtype)
        n = self.n_out
        k = jax.random.fold_in(rng, 7)
        k1, k2, k3 = jax.random.split(k, 3)
        params["p_i"] = init_weights(k1, (n,), n, n, "uniform", None, dtype)
        params["p_f"] = init_weights(k2, (n,), n, n, "uniform", None, dtype)
        params["p_o"] = init_weights(k3, (n,), n, n, "uniform", None, dtype)
        return params, state

    def _gates(self, z, c_prev, params):
        n = self.n_out
        act = get_activation(self.activation)
        gate = get_activation(self.gate_activation)
        i = gate(z[:, 0 * n:1 * n] + c_prev * params["p_i"])
        f = gate(z[:, 1 * n:2 * n] + c_prev * params["p_f"])
        g = act(z[:, 2 * n:3 * n])
        c = f * c_prev + i * g
        o = gate(z[:, 3 * n:4 * n] + c * params["p_o"])
        h = o * act(c)
        return h, c


def _flip_time(x, mask):
    """Reverse the time axis; with a mask, reverse only the valid prefix of
    each sequence (matches the reference's bidirectional reversal semantics)."""
    if mask is None:
        return jnp.flip(x, axis=1)
    t = x.shape[1]
    lengths = jnp.sum(mask, axis=1).astype(jnp.int32)          # (b,)
    idx = jnp.arange(t)[None, :]                               # (1, t)
    src = lengths[:, None] - 1 - idx                           # reversed valid prefix
    src = jnp.where(src >= 0, src, idx)                        # padding stays in place
    return jnp.take_along_axis(x, src[..., None].astype(jnp.int32), axis=1)


@register_layer
@dataclasses.dataclass(frozen=True)
class Bidirectional(BaseRecurrentLayer):
    """Generic bidirectional wrapper (reference
    nn/conf/layers/GravesBidirectionalLSTM.java generalized; mode semantics
    from the later Bidirectional wrapper): runs the wrapped recurrent layer
    forward and time-reversed, combining with mode add|mul|average|concat."""

    layer: Optional[LSTM] = None
    mode: str = "concat"

    # Carrying state across windows/steps is temporally invalid for the
    # backward direction (the reference's GravesBidirectionalLSTM.rnnTimeStep
    # throws UnsupportedOperationException); under tBPTT each window is
    # processed statelessly.
    supports_stateful = False

    def regularizable(self):
        # Regularize both directions' wrapped weights (the reference applies
        # l1/l2 to fwd and bwd input+recurrent weights alike); "/"-paths are
        # resolved into the nested param tree by the network's _regularization.
        inner = self.layer.regularizable() if self.layer is not None else ()
        return tuple(f"{d}/{k}" for d in ("fwd", "bwd") for k in inner)

    def output_type(self, it: InputType) -> InputType:
        inner = self.layer.output_type(it)
        n = inner.size * 2 if self.mode == "concat" else inner.size
        return InputType.recurrent(n, it.timeseries_length)

    def init(self, rng, it: InputType, dtype=jnp.float32):
        k1, k2 = jax.random.split(rng)
        fwd, _ = self.layer.init(k1, it, dtype)
        bwd, _ = self.layer.init(k2, it, dtype)
        return {"fwd": fwd, "bwd": bwd}, {}

    def init_carry(self, batch, dtype=jnp.float32):
        return {"fwd": self.layer.init_carry(batch, dtype),
                "bwd": self.layer.init_carry(batch, dtype)}

    def apply_seq(self, params, carry, x, *, train=False, rng=None, mask=None):
        k1 = k2 = None
        if rng is not None:
            k1, k2 = jax.random.split(rng)
        out_f, c_f = self.layer.apply_seq(params["fwd"], carry["fwd"], x,
                                          train=train, rng=k1, mask=mask)
        x_rev = _flip_time(x, mask)
        out_b, c_b = self.layer.apply_seq(params["bwd"], carry["bwd"], x_rev,
                                          train=train, rng=k2, mask=mask)
        out_b = _flip_time(out_b, mask)
        m = self.mode
        if m == "concat":
            out = jnp.concatenate([out_f, out_b], axis=-1)
        elif m == "add":
            out = out_f + out_b
        elif m == "mul":
            out = out_f * out_b
        elif m == "average":
            out = 0.5 * (out_f + out_b)
        else:
            raise ValueError(f"Unknown bidirectional mode '{self.mode}'")
        return out, {"fwd": c_f, "bwd": c_b}

    def with_n_in(self, n_in):
        if self.layer is not None and getattr(self.layer, "n_in", 0) in (None, 0):
            return dataclasses.replace(self, layer=self.layer.with_n_in(n_in))
        return self


@register_layer
@dataclasses.dataclass(frozen=True)
class GravesBidirectionalLSTM(Bidirectional):
    """reference nn/conf/layers/GravesBidirectionalLSTM.java — bidirectional
    GravesLSTM with summed outputs."""

    mode: str = "add"

    def __post_init__(self):
        if self.layer is None:
            raise ValueError("GravesBidirectionalLSTM requires layer=GravesLSTM(...)")


@register_layer
@dataclasses.dataclass(frozen=True)
class RnnOutputLayer(BaseOutputLayer):
    """Per-timestep output + loss (reference nn/conf/layers/RnnOutputLayer.java).
    Dense over the feature axis of (batch, time, n_in); the loss averages over
    unmasked timesteps."""

    n_in: Optional[int] = None
    n_out: int = 0
    has_bias: bool = True
    activation: str = "softmax"

    def input_kind(self):
        return "rnn"

    def is_recurrent(self):
        return True

    def output_type(self, it: InputType) -> InputType:
        return InputType.recurrent(self.n_out, it.timeseries_length)

    def init(self, rng, it: InputType, dtype=jnp.float32):
        n_in = self.n_in or it.size
        params = {"W": init_weights(rng, (n_in, self.n_out), n_in, self.n_out,
                                    self.weight_init, self.dist, dtype)}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return params, {}

    def pre_output(self, params, x):
        z = x @ params["W"]
        if "b" in params:
            z = z + params["b"]
        return z

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = dropout_input(x, self.dropout, train, rng)
        return get_activation(self.activation)(self.pre_output(params, x)), state


@register_layer
@dataclasses.dataclass(frozen=True)
class EmbeddingLayer(BaseLayer):
    """Index -> vector lookup (reference nn/conf/layers/EmbeddingLayer.java +
    nn/layers/feedforward/embedding/EmbeddingLayer.java): input is a column of
    integer indices (batch,) or (batch, 1). On TPU this is a gather — a single
    HLO — rather than the reference's row-view copy."""

    n_in: Optional[int] = None  # vocab size
    n_out: int = 0
    has_bias: bool = True
    activation: str = "identity"

    def input_kind(self):
        return "ff"

    def output_type(self, it: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def init(self, rng, it: InputType, dtype=jnp.float32):
        n_in = self.n_in or it.flat_size()
        params = {"W": init_weights(rng, (n_in, self.n_out), n_in, self.n_out,
                                    self.weight_init, self.dist, dtype)}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return params, {}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        idx = x.astype(jnp.int32)
        if idx.ndim == 2 and idx.shape[-1] == 1:
            idx = idx[:, 0]
        z = params["W"][idx]
        if "b" in params:
            z = z + params["b"]
        return get_activation(self.activation)(z), state


@register_layer
@dataclasses.dataclass(frozen=True)
class EmbeddingSequenceLayer(BaseLayer):
    """Sequence of indices (batch, time) -> (batch, time, n_out). Not in the
    0.9.x reference (added upstream later as EmbeddingSequenceLayer); included
    because char-RNN/NLP models on TPU want gathers, not one-hot matmuls."""

    n_in: Optional[int] = None  # vocab size
    n_out: int = 0

    # features are (batch, time) integer ids, not (batch, time, channels)
    takes_index_sequence = True

    def input_kind(self):
        return "rnn"

    def is_recurrent(self):
        return True

    def output_type(self, it: InputType) -> InputType:
        return InputType.recurrent(self.n_out, it.timeseries_length)

    def init(self, rng, it: InputType, dtype=jnp.float32):
        n_in = self.n_in or it.size
        return {"W": init_weights(rng, (n_in, self.n_out), n_in, self.n_out,
                                  self.weight_init, self.dist, dtype)}, {}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        if x.ndim == 3 and x.shape[-1] == 1:
            x = x[..., 0]
        return params["W"][x.astype(jnp.int32)], state


@register_layer
@dataclasses.dataclass(frozen=True)
class LastTimeStep(BaseRecurrentLayer):
    """Wrap a recurrent layer and emit only the last (unmasked) timestep as a
    feed-forward activation (reference nn/graph/vertex/impl/rnn/
    LastTimeStepVertex.java as a layer wrapper)."""

    layer: Optional[LSTM] = None

    def regularizable(self):
        # params ARE the wrapped layer's params (init delegates directly)
        return self.layer.regularizable() if self.layer is not None else ()

    def output_type(self, it: InputType) -> InputType:
        inner = self.layer.output_type(it)
        return InputType.feed_forward(inner.size)

    def init(self, rng, it: InputType, dtype=jnp.float32):
        return self.layer.init(rng, it, dtype)

    def init_carry(self, batch, dtype=jnp.float32):
        return self.layer.init_carry(batch, dtype)

    def apply_seq(self, params, carry, x, *, train=False, rng=None, mask=None):
        out, new_carry = self.layer.apply_seq(params, carry, x, train=train,
                                              rng=rng, mask=mask)
        if mask is None:
            last = out[:, -1, :]
        else:
            lengths = jnp.sum(mask, axis=1).astype(jnp.int32)
            idx = jnp.maximum(lengths - 1, 0)
            last = jnp.take_along_axis(out, idx[:, None, None], axis=1)[:, 0, :]
        return last, new_carry

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        out, _ = self.apply_seq(params, self.init_carry(x.shape[0], x.dtype), x,
                                train=train, rng=rng, mask=mask)
        return out, state

    def with_n_in(self, n_in):
        if self.layer is not None and getattr(self.layer, "n_in", 0) in (None, 0):
            return dataclasses.replace(self, layer=self.layer.with_n_in(n_in))
        return self


@register_layer
@dataclasses.dataclass(frozen=True)
class SimpleRnn(BaseRecurrentLayer):
    """Vanilla recurrent layer h_t = act(x_t W + h_{t-1} U + b)
    (reference nn/conf/layers — Keras SimpleRNN import target). Input
    projection is hoisted into one MXU matmul over all timesteps, like LSTM."""

    n_in: Optional[int] = None
    n_out: int = 0
    activation: str = "tanh"

    def regularizable(self):
        return ("W", "U")

    def output_type(self, it: InputType) -> InputType:
        return InputType.recurrent(self.n_out, it.timeseries_length)

    def init(self, rng, it: InputType, dtype=jnp.float32):
        n_in = self.n_in or it.size
        k1, k2 = jax.random.split(rng)
        return {
            "W": init_weights(k1, (n_in, self.n_out), n_in, self.n_out,
                              self.weight_init, self.dist, dtype),
            "U": init_weights(k2, (self.n_out, self.n_out), self.n_out,
                              self.n_out, self.weight_init, self.dist, dtype),
            "b": jnp.zeros((self.n_out,), dtype),
        }, {}

    def init_carry(self, batch, dtype=jnp.float32):
        return {"h": jnp.zeros((batch, self.n_out), dtype)}

    def apply_seq(self, params, carry, x, *, train=False, rng=None, mask=None):
        x = dropout_input(x, self.dropout, train, rng)
        b, t, _ = x.shape
        act = get_activation(self.activation)
        xw = (x.reshape(b * t, -1) @ params["W"] + params["b"]).reshape(b, t, -1)
        xw_t = jnp.swapaxes(xw, 0, 1)
        m_t = None if mask is None else jnp.swapaxes(mask, 0, 1)
        U = params["U"]

        def step(c, inp):
            xw_i, m_i = inp if m_t is not None else (inp, None)
            h_prev = c["h"]
            h = act(xw_i + h_prev @ U)
            if m_i is not None:
                keep = m_i[:, None]
                h = keep * h + (1.0 - keep) * h_prev
                out = keep * h
            else:
                out = h
            return {"h": h}, out

        xs = xw_t if m_t is None else (xw_t, m_t)
        new_carry, outs = lax.scan(step, carry, xs)
        return jnp.swapaxes(outs, 0, 1), new_carry


@register_layer
@dataclasses.dataclass(frozen=True)
class GRU(BaseRecurrentLayer):
    """Gated recurrent unit (Keras GRU import target; gate order z, r, h).

    ``reset_after=False`` (classic): hh = act(xWh + (r*h)Uh + bh).
    ``reset_after=True`` (CuDNN-compatible Keras 2.x default): separate
    input/recurrent biases, hh = act(xWh + bh + r*(hUh + bhr)); params then
    carry "br" with the recurrent half."""

    n_in: Optional[int] = None
    n_out: int = 0
    activation: str = "tanh"
    gate_activation: str = "sigmoid"
    reset_after: bool = False

    def regularizable(self):
        return ("W", "U")

    def output_type(self, it: InputType) -> InputType:
        return InputType.recurrent(self.n_out, it.timeseries_length)

    def init(self, rng, it: InputType, dtype=jnp.float32):
        n_in = self.n_in or it.size
        n = self.n_out
        k1, k2 = jax.random.split(rng)
        params = {
            "W": init_weights(k1, (n_in, 3 * n), n_in, n, self.weight_init,
                              self.dist, dtype),
            "U": init_weights(k2, (n, 3 * n), n, n, self.weight_init,
                              self.dist, dtype),
            "b": jnp.zeros((3 * n,), dtype),
        }
        if self.reset_after:
            params["br"] = jnp.zeros((3 * n,), dtype)
        return params, {}

    def init_carry(self, batch, dtype=jnp.float32):
        return {"h": jnp.zeros((batch, self.n_out), dtype)}

    def apply_seq(self, params, carry, x, *, train=False, rng=None, mask=None):
        x = dropout_input(x, self.dropout, train, rng)
        b, t, _ = x.shape
        n = self.n_out
        act = get_activation(self.activation)
        gate = get_activation(self.gate_activation)
        xw = (x.reshape(b * t, -1) @ params["W"] + params["b"]).reshape(b, t, -1)
        xw_t = jnp.swapaxes(xw, 0, 1)
        m_t = None if mask is None else jnp.swapaxes(mask, 0, 1)
        U = params["U"]
        br = params.get("br")

        def step(c, inp):
            xw_i, m_i = inp if m_t is not None else (inp, None)
            h_prev = c["h"]
            if self.reset_after:
                hu = h_prev @ U + br
                z = gate(xw_i[:, :n] + hu[:, :n])
                r = gate(xw_i[:, n:2 * n] + hu[:, n:2 * n])
                hh = act(xw_i[:, 2 * n:] + r * hu[:, 2 * n:])
            else:
                z = gate(xw_i[:, :n] + h_prev @ U[:, :n])
                r = gate(xw_i[:, n:2 * n] + h_prev @ U[:, n:2 * n])
                hh = act(xw_i[:, 2 * n:] + (r * h_prev) @ U[:, 2 * n:])
            h = z * h_prev + (1.0 - z) * hh
            if m_i is not None:
                keep = m_i[:, None]
                h = keep * h + (1.0 - keep) * h_prev
                out = keep * h
            else:
                out = h
            return {"h": h}, out

        xs = xw_t if m_t is None else (xw_t, m_t)
        new_carry, outs = lax.scan(step, carry, xs)
        return jnp.swapaxes(outs, 0, 1), new_carry
