from deeplearning4j_tpu.nn.conf.inputs import InputType  # noqa: F401
from deeplearning4j_tpu.nn.conf.network import (  # noqa: F401
    NeuralNetConfiguration,
    MultiLayerConfiguration,
)
