from deeplearning4j_tpu.nn.conf.inputs import InputType  # noqa: F401
from deeplearning4j_tpu.nn.conf.network import (  # noqa: F401
    NeuralNetConfiguration,
    MultiLayerConfiguration,
)

# import layer modules for their registry side effects (JSON serde)
from deeplearning4j_tpu.nn.conf import convolutional as _conv  # noqa: F401,E402
from deeplearning4j_tpu.nn.conf import normalization as _norm  # noqa: F401,E402
from deeplearning4j_tpu.nn.conf import pooling as _pool  # noqa: F401,E402
from deeplearning4j_tpu.nn.conf import recurrent as _rnn  # noqa: F401,E402
from deeplearning4j_tpu.nn.conf import objdetect as _objdetect  # noqa: F401,E402
from deeplearning4j_tpu.nn.conf import pretrain as _pretrain  # noqa: F401,E402
from deeplearning4j_tpu.nn.conf import variational as _vae  # noqa: F401,E402
from deeplearning4j_tpu.nn.conf import regularization as _reg  # noqa: F401,E402
from deeplearning4j_tpu.nn.conf import attention as _attn  # noqa: F401,E402
