"""Normalization layers: BatchNormalization, LocalResponseNormalization.

Parity surface: reference ``nn/conf/layers/BatchNormalization.java`` +
``nn/layers/normalization/BatchNormalization.java:57`` (helper hook; cuDNN
path CudnnBatchNormalizationHelper.java) and
``LocalResponseNormalization.java`` (+ CudnnLocalResponseNormalizationHelper).

TPU-native: one fused traced expression; the running-stat buffers live in the
layer *state* pytree (non-trainable), updated functionally inside the jitted
train step — no mutable INDArray views.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import BaseLayer, Layer, register_layer


@register_layer
@dataclasses.dataclass(frozen=True)
class BatchNormalization(BaseLayer):
    """Batch norm over the channel/feature axis (last axis in both the
    (batch, features) and NHWC layouts). Reference defaults: decay=0.9,
    eps=1e-5, lockGammaBeta=false (BatchNormalization.java conf)."""

    decay: float = 0.9
    eps: float = 1e-5
    lock_gamma_beta: bool = False
    gamma: float = 1.0  # fixed value when locked
    beta: float = 0.0

    def regularizable(self):
        return ()

    def init(self, rng, it: InputType, dtype=jnp.float32):
        n = it.channels if it.kind == "cnn" else it.flat_size()
        params = {}
        if not self.lock_gamma_beta:
            params = {"gamma": jnp.full((n,), self.gamma, dtype),
                      "beta": jnp.full((n,), self.beta, dtype)}
        state = {"mean": jnp.zeros((n,), dtype), "var": jnp.ones((n,), dtype)}
        return params, state

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        axes = tuple(range(x.ndim - 1))  # all but channel/feature axis
        # batch statistics and the running buffers stay float32 regardless of
        # the compute dtype (bf16 stats lose precision); the normalization
        # itself runs in x's dtype so bf16 activations stay bf16 end to end
        if train:
            if x.dtype in (jnp.bfloat16, jnp.float16):
                # low-precision compute: single-pass f32-accumulated stats.
                # sum and sum-of-squares fuse into ONE traversal of x
                # (jnp.var's mean((x-mean)^2) needs a second, dependent
                # pass — 2x the HBM reads on conv-sized activations,
                # measured ~8% of the ResNet50 train step). E[x^2]-E[x]^2
                # cancellation only bites when mean^2/var >~ 2^24; but bf16
                # DATA already loses the signal at mean^2/var ~ 2^16, so in
                # every regime where the input itself is meaningful the
                # single-pass f32 accumulator is as accurate as two-pass.
                xf = x.astype(jnp.float32)
                n = xf.size // xf.shape[-1]
                mean32 = jnp.sum(xf, axis=axes) / n
                var32 = jnp.maximum(
                    jnp.sum(xf * xf, axis=axes) / n - mean32 * mean32, 0.0)
            else:
                # full-precision compute (incl. f64 gradcheck): the exact
                # centered two-pass form — immune to cancellation for
                # channels whose mean dwarfs their std (e.g. BN applied
                # directly to unnormalized raw features)
                mean32 = jnp.mean(x, axis=axes)
                var32 = jnp.var(x, axis=axes)
            new_state = {
                "mean": self.decay * state["mean"] + (1.0 - self.decay) * mean32,
                "var": self.decay * state["var"] + (1.0 - self.decay) * var32,
            }
        else:
            mean32, var32 = state["mean"], state["var"]
            new_state = state
        # fold to one fused multiply-add per element: out = x*scale + shift.
        # scale/shift are per-channel (C,) vectors computed in f32, so the
        # per-element work is minimal and fuses into the producing conv.
        sdt = var32.dtype  # f32 for low-precision compute, f64 for gradcheck
        inv = lax.rsqrt(var32 + jnp.asarray(self.eps, sdt))
        if self.lock_gamma_beta:
            g, b = jnp.asarray(self.gamma, sdt), jnp.asarray(self.beta, sdt)
        else:
            g, b = params["gamma"].astype(sdt), params["beta"].astype(sdt)
        scale = g * inv
        shift = b - mean32 * scale
        out = x * scale.astype(x.dtype) + shift.astype(x.dtype)
        return out, new_state


@register_layer
@dataclasses.dataclass(frozen=True)
class LocalResponseNormalization(Layer):
    """Cross-channel LRN (reference nn/conf/layers/LocalResponseNormalization.java;
    defaults k=2, n=5, alpha=1e-4, beta=0.75 as in the reference conf)."""

    k: float = 2.0
    n: int = 5
    alpha: float = 1e-4
    beta: float = 0.75

    def input_kind(self):
        return "cnn"

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        # sum of squares over a centred window of 2*(n//2)+1 channels — the
        # reference loops i=1..n/2 on both sides of the centre
        # (LocalResponseNormalization.java halfN), so even n covers n+1 channels
        half = self.n // 2
        win = 2 * half + 1
        sq = x * x
        summed = lax.reduce_window(
            sq, 0.0, lax.add,
            window_dimensions=(1, 1, 1, win),
            window_strides=(1, 1, 1, 1),
            padding=((0, 0), (0, 0), (0, 0), (half, half)),
        )
        return x / jnp.power(self.k + self.alpha * summed, self.beta), state
