"""Normalization layers: BatchNormalization, LocalResponseNormalization.

Parity surface: reference ``nn/conf/layers/BatchNormalization.java`` +
``nn/layers/normalization/BatchNormalization.java:57`` (helper hook; cuDNN
path CudnnBatchNormalizationHelper.java) and
``LocalResponseNormalization.java`` (+ CudnnLocalResponseNormalizationHelper).

TPU-native: one fused traced expression; the running-stat buffers live in the
layer *state* pytree (non-trainable), updated functionally inside the jitted
train step — no mutable INDArray views.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import BaseLayer, Layer, register_layer


@register_layer
@dataclasses.dataclass(frozen=True)
class BatchNormalization(BaseLayer):
    """Batch norm over the channel/feature axis (last axis in both the
    (batch, features) and NHWC layouts). Reference defaults: decay=0.9,
    eps=1e-5, lockGammaBeta=false (BatchNormalization.java conf)."""

    decay: float = 0.9
    eps: float = 1e-5
    lock_gamma_beta: bool = False
    gamma: float = 1.0  # fixed value when locked
    beta: float = 0.0

    def regularizable(self):
        return ()

    def init(self, rng, it: InputType, dtype=jnp.float32):
        n = it.channels if it.kind == "cnn" else it.flat_size()
        params = {}
        if not self.lock_gamma_beta:
            params = {"gamma": jnp.full((n,), self.gamma, dtype),
                      "beta": jnp.full((n,), self.beta, dtype)}
        state = {"mean": jnp.zeros((n,), dtype), "var": jnp.ones((n,), dtype)}
        return params, state

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        axes = tuple(range(x.ndim - 1))  # all but channel/feature axis
        # batch statistics and the running buffers stay float32 regardless of
        # the compute dtype (bf16 stats lose precision); the normalization
        # itself runs in x's dtype so bf16 activations stay bf16 end to end
        if train:
            # upcast ONLY low-precision compute dtypes (f64 gradcheck runs
            # must keep their precision)
            xf = (x.astype(jnp.float32)
                  if x.dtype in (jnp.bfloat16, jnp.float16) else x)
            mean32 = jnp.mean(xf, axis=axes)
            var32 = jnp.var(xf, axis=axes)
            new_state = {
                "mean": self.decay * state["mean"] + (1.0 - self.decay) * mean32,
                "var": self.decay * state["var"] + (1.0 - self.decay) * var32,
            }
        else:
            mean32, var32 = state["mean"], state["var"]
            new_state = state
        mean = mean32.astype(x.dtype)
        var = var32.astype(x.dtype)
        xhat = (x - mean) * lax.rsqrt(var + jnp.asarray(self.eps, x.dtype))
        if self.lock_gamma_beta:
            out = self.gamma * xhat + self.beta
        else:
            out = params["gamma"] * xhat + params["beta"]
        return out, new_state


@register_layer
@dataclasses.dataclass(frozen=True)
class LocalResponseNormalization(Layer):
    """Cross-channel LRN (reference nn/conf/layers/LocalResponseNormalization.java;
    defaults k=2, n=5, alpha=1e-4, beta=0.75 as in the reference conf)."""

    k: float = 2.0
    n: int = 5
    alpha: float = 1e-4
    beta: float = 0.75

    def input_kind(self):
        return "cnn"

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        # sum of squares over a centred window of 2*(n//2)+1 channels — the
        # reference loops i=1..n/2 on both sides of the centre
        # (LocalResponseNormalization.java halfN), so even n covers n+1 channels
        half = self.n // 2
        win = 2 * half + 1
        sq = x * x
        summed = lax.reduce_window(
            sq, 0.0, lax.add,
            window_dimensions=(1, 1, 1, win),
            window_strides=(1, 1, 1, 1),
            padding=((0, 0), (0, 0), (0, 0), (half, half)),
        )
        return x / jnp.power(self.k + self.alpha * summed, self.beta), state
