"""Dropout variants, parameter constraints, weight noise.

Parity surface: reference ``nn/conf/dropout/`` (Dropout.java,
AlphaDropout.java, GaussianDropout.java, GaussianNoise.java — the IDropout
SPI applied to layer inputs), ``nn/conf/constraint/`` (MaxNormConstraint,
MinMaxNormConstraint, UnitNormConstraint, NonNegativeConstraint — applied to
parameters after each update, BaseConstraint.applyConstraint), and
``nn/conf/weightnoise/`` (DropConnect.java, WeightNoise.java — applied to
weights during the training forward pass).

All three families are frozen dataclasses living in the layer config, so
they trace into the jitted train step (no host round trips) and serialize
with the layer JSON.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.layers import register_layer, layer_to_dict
from deeplearning4j_tpu.nn.initializers import Distribution


# ------------------------------------------------------------------ dropout
@dataclasses.dataclass(frozen=True)
class IDropout:
    """Dropout SPI (reference nn/conf/dropout/IDropout.java): transforms the
    layer INPUT at train time."""

    def apply(self, x, rng, train: bool):
        raise NotImplementedError

    def to_dict(self):
        return layer_to_dict(self)


@register_layer
@dataclasses.dataclass(frozen=True)
class Dropout(IDropout):
    """Inverted dropout; ``p`` is the RETAIN probability (DL4J 0.9
    semantics, Dropout.java)."""

    p: float = 0.5

    def apply(self, x, rng, train):
        if not train or rng is None or self.p >= 1.0 or self.p <= 0.0:
            return x
        m = jax.random.bernoulli(rng, self.p, x.shape)
        return jnp.where(m, x / self.p, 0.0).astype(x.dtype)


@register_layer
@dataclasses.dataclass(frozen=True)
class AlphaDropout(IDropout):
    """SELU-preserving dropout (reference AlphaDropout.java): dropped units
    take the negative saturation value alpha', and an affine correction
    keeps zero mean / unit variance. ``p`` is the retain probability."""

    p: float = 0.95
    # fixed SELU constants (AlphaDropout.java: DEFAULT_ALPHA/LAMBDA product)
    _ALPHA_PRIME = -1.7580993408473766

    def apply(self, x, rng, train):
        if not train or rng is None or self.p >= 1.0 or self.p <= 0.0:
            return x
        p, ap = self.p, self._ALPHA_PRIME
        a = (p + ap * ap * p * (1 - p)) ** -0.5
        b = -a * (1 - p) * ap
        m = jax.random.bernoulli(rng, p, x.shape)
        return (a * jnp.where(m, x, ap) + b).astype(x.dtype)


@register_layer
@dataclasses.dataclass(frozen=True)
class GaussianDropout(IDropout):
    """Multiplicative gaussian noise N(1, rate/(1-rate)) (reference
    GaussianDropout.java)."""

    rate: float = 0.1

    def __post_init__(self):
        if not 0.0 <= self.rate < 1.0:
            raise ValueError(f"GaussianDropout rate must be in [0, 1); got "
                             f"{self.rate}")

    def apply(self, x, rng, train):
        if not train or rng is None or self.rate <= 0.0:
            return x
        stddev = (self.rate / (1.0 - self.rate)) ** 0.5
        noise = 1.0 + stddev * jax.random.normal(rng, x.shape, x.dtype)
        return x * noise


@register_layer
@dataclasses.dataclass(frozen=True)
class GaussianNoise(IDropout):
    """Additive gaussian noise N(0, stddev) at train time (reference
    GaussianNoise.java)."""

    stddev: float = 0.1

    def apply(self, x, rng, train):
        if not train or rng is None or self.stddev <= 0.0:
            return x
        return x + self.stddev * jax.random.normal(rng, x.shape, x.dtype)


# -------------------------------------------------------------- constraints
@dataclasses.dataclass(frozen=True, kw_only=True)
class BaseConstraint:
    """Parameter constraint applied AFTER each update (reference
    nn/conf/constraint/BaseConstraint.java). Norms reduce over every axis
    but the last (per output unit: columns of dense W, filters of conv
    kernels — the reference's default dimension handling)."""

    apply_to_weights: bool = True
    apply_to_biases: bool = False

    def apply(self, param):
        raise NotImplementedError

    def to_dict(self):
        return layer_to_dict(self)

    @staticmethod
    def _norms(w):
        axes = tuple(range(w.ndim - 1)) if w.ndim > 1 else (0,)
        return jnp.sqrt(jnp.sum(w * w, axis=axes, keepdims=True) + 1e-12)


@register_layer
@dataclasses.dataclass(frozen=True)
class MaxNormConstraint(BaseConstraint):
    """Rescale units whose L2 norm exceeds max_norm (MaxNormConstraint.java)."""

    max_norm: float = 2.0

    def apply(self, param):
        n = self._norms(param)
        return param * (jnp.minimum(n, self.max_norm) / n)


@register_layer
@dataclasses.dataclass(frozen=True)
class MinMaxNormConstraint(BaseConstraint):
    """Clamp unit norms into [min_norm, max_norm] with blending ``rate``
    (MinMaxNormConstraint.java)."""

    min_norm: float = 0.0
    max_norm: float = 2.0
    rate: float = 1.0

    def apply(self, param):
        n = self._norms(param)
        clipped = jnp.clip(n, self.min_norm, self.max_norm)
        scale = self.rate * (clipped / n) + (1.0 - self.rate)
        return param * scale


@register_layer
@dataclasses.dataclass(frozen=True)
class UnitNormConstraint(BaseConstraint):
    """Force unit L2 norms (UnitNormConstraint.java)."""

    def apply(self, param):
        return param / self._norms(param)


@register_layer
@dataclasses.dataclass(frozen=True)
class NonNegativeConstraint(BaseConstraint):
    """Clamp params at zero (NonNegativeConstraint.java)."""

    def apply(self, param):
        return jnp.maximum(param, 0.0)


# ------------------------------------------------------------- weight noise
@dataclasses.dataclass(frozen=True, kw_only=True)
class IWeightNoise:
    """Weight-noise SPI (reference nn/conf/weightnoise/IWeightNoise.java):
    transforms WEIGHTS during the training forward pass."""

    apply_to_bias: bool = False

    def apply_to_param(self, w, rng):
        raise NotImplementedError

    def to_dict(self):
        return layer_to_dict(self)


@register_layer
@dataclasses.dataclass(frozen=True)
class DropConnect(IWeightNoise):
    """Bernoulli weight dropout (reference DropConnect.java); ``p`` is the
    retain probability, inverted-scaled so expectations match at test time."""

    p: float = 0.5

    def apply_to_param(self, w, rng):
        if self.p >= 1.0 or self.p <= 0.0:
            return w
        m = jax.random.bernoulli(rng, self.p, w.shape)
        return jnp.where(m, w / self.p, 0.0).astype(w.dtype)


@register_layer
@dataclasses.dataclass(frozen=True)
class WeightNoise(IWeightNoise):
    """Additive or multiplicative noise drawn from ``dist`` (reference
    WeightNoise.java)."""

    dist: Optional[Distribution] = None
    additive: bool = True
    stddev: float = 0.01  # used when dist is None: N(0, stddev)

    def apply_to_param(self, w, rng):
        if self.dist is not None:
            noise = self.dist.sample(rng, w.shape, w.dtype)
        else:
            noise = self.stddev * jax.random.normal(rng, w.shape, w.dtype)
        return w + noise if self.additive else w * noise
