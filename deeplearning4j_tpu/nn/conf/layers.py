"""Layer configurations + implementations (feed-forward core).

Parity surface: reference ``nn/conf/layers/*`` (declarative configs) together
with ``nn/layers/*`` (imperative impls). In the TPU rebuild the conf/impl split
collapses: each config dataclass carries pure ``init``/``apply`` functions that
JAX traces into one XLA program — the per-layer interpretive loop of
``MultiLayerNetwork.feedForwardToLayer`` disappears at compile time.

Contract (every layer):
- ``output_type(input_type) -> InputType``      shape inference
  (reference: ``Layer.getOutputType`` in nn/conf/layers/Layer.java)
- ``init(rng, input_type, dtype) -> (params, state)``   params is a dict of
  arrays; state is a dict for non-trainable buffers (batchnorm running stats)
  (reference: the ``nn/params/*ParamInitializer`` classes)
- ``apply(params, state, x, *, train, rng, mask) -> (out, new_state)``
  (reference: ``Layer.activate`` — nn/api/Layer.java:114-166; backprop is jax
  autodiff instead of ``Layer.backpropGradient``)

Dropout field semantics follow DL4J 0.9: ``dropout`` is the *retain*
probability applied to the layer's input when training (0 disables).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.initializers import Distribution, init_weights
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn import lossfunctions
from deeplearning4j_tpu.optimize.updaters import Updater

LAYER_REGISTRY = {}


def register_layer(cls):
    LAYER_REGISTRY[cls.__name__] = cls
    return cls


def layer_to_dict(conf) -> dict:
    d = {"@class": type(conf).__name__}
    for f in dataclasses.fields(conf):
        v = getattr(conf, f.name)
        if v is None:
            continue
        if isinstance(v, (Updater,)):
            v = v.to_dict()
        elif isinstance(v, Distribution):
            v = v.to_dict()
        elif isinstance(v, InputType):
            v = v.to_dict()
        elif dataclasses.is_dataclass(v) and hasattr(v, "to_dict"):
            v = v.to_dict()
        elif isinstance(v, (tuple, list)):
            v = [e.to_dict() if dataclasses.is_dataclass(e)
                 and hasattr(e, "to_dict") else e for e in v]
        d[f.name] = v
    return d


def layer_from_dict(d: dict):
    d = dict(d)
    cls = LAYER_REGISTRY[d.pop("@class")]
    names = {f.name for f in dataclasses.fields(cls)}
    kwargs = {}
    for k, v in d.items():
        if k not in names:
            continue
        if k == "updater" and isinstance(v, dict):
            v = Updater.from_dict(v)
        elif k == "dist" and isinstance(v, dict):
            v = Distribution.from_dict(v)
        elif isinstance(v, dict) and "@class" in v:  # nested layer (e.g. Bidirectional)
            v = layer_from_dict(v)
        elif isinstance(v, list):  # JSON has no tuples
            v = tuple(layer_from_dict(e)
                      if isinstance(e, dict) and "@class" in e else e
                      for e in v)
        kwargs[k] = v
    return cls(**kwargs)


def resolve_param_path(params: dict, key: str):
    """Resolve a possibly-nested '/'-separated param key (wrapper layers like
    Bidirectional expose 'fwd/W'-style paths). Returns the array or None."""
    node = params
    for part in key.split("/"):
        if isinstance(node, dict) and part in node:
            node = node[part]
        else:
            return None
    return node


def regularization_coefficients(layer):
    """(l1, l2, l1_bias, l2_bias) for a layer; wrapper layers (those with a
    nested ``layer`` field) fall back to the inner layer's coefficients when
    their own are all zero — matching the reference, where the wrapped layer's
    conf carries the regularization."""
    vals = (getattr(layer, "l1", 0.0) or 0.0, getattr(layer, "l2", 0.0) or 0.0,
            getattr(layer, "l1_bias", 0.0) or 0.0,
            getattr(layer, "l2_bias", 0.0) or 0.0)
    inner = getattr(layer, "layer", None)
    if inner is not None and not any(vals):
        return regularization_coefficients(inner)
    return vals


def dropout_input(x, dropout, train: bool, rng):
    """Inverted dropout on layer input (reference: Dropout.applyDropout via
    BaseLayer.applyDropOutIfNecessary; retain-prob semantics of DL4J 0.9).
    ``dropout`` may be a plain retain probability or an IDropout object
    (AlphaDropout/GaussianDropout/GaussianNoise — nn/conf/regularization)."""
    if not dropout:  # None / 0.0: disabled
        return x
    if not hasattr(dropout, "apply"):
        from deeplearning4j_tpu.nn.conf.regularization import Dropout
        dropout = Dropout(float(dropout))  # single implementation of the math
    return dropout.apply(x, rng, train)


def _set_param_path(params: dict, key: str, value):
    """Set a possibly-nested '/'-separated param key in place."""
    node = params
    parts = key.split("/")
    for part in parts[:-1]:
        node = node[part]
    node[parts[-1]] = value


def reg_object(layer, attr: str):
    """Resolve ``constraints``/``weight_noise`` on a layer, falling back to
    the wrapped layer for wrapper configs (Bidirectional etc.) — same
    fallthrough as regularization_coefficients."""
    v = getattr(layer, attr, None)
    if v is None:
        inner = getattr(layer, "layer", None)
        if inner is not None:
            return reg_object(inner, attr)
    return v


def _bias_keys(layer, params: dict) -> list:
    """Bias param paths: top-level 'b' plus the sibling of every nested
    weight path (e.g. 'fwd/W' -> 'fwd/b' for wrapper layers)."""
    keys = []
    if resolve_param_path(params, "b") is not None:
        keys.append("b")
    for wk in layer.regularizable():
        if "/" in wk:
            bk = wk.rsplit("/", 1)[0] + "/b"
            if bk not in keys and resolve_param_path(params, bk) is not None:
                keys.append(bk)
    return keys


def _constraint_keys(layer, params: dict, c) -> list:
    keys = []
    if getattr(c, "apply_to_weights", True):
        keys.extend(k for k in layer.regularizable()
                    if resolve_param_path(params, k) is not None)
    if getattr(c, "apply_to_biases", False):
        keys.extend(_bias_keys(layer, params))
    return keys


def apply_constraints(layer, params):
    """Apply the layer's parameter constraints after an update (reference
    BaseConstraint.applyConstraint, called from BaseMultiLayerUpdater).
    ``params`` must be a freshly-built dict (it is mutated in place inside
    the traced step)."""
    cons = reg_object(layer, "constraints")
    if not cons:
        return params
    for c in cons:
        for key in _constraint_keys(layer, params, c):
            _set_param_path(params, key,
                            c.apply(resolve_param_path(params, key)))
    return params


def apply_layer(layer, params, state, x, *, train, rng, mask, extra=None):
    """The networks' single entry into ``layer.apply``: lowers the layer
    through ``jax.checkpoint`` when its ``remat=`` knob is set (policy names
    in perf/fusion.py), so the backward pass recomputes instead of saving
    what the policy excludes. ``extra`` carries optional additional traced
    inputs (the fused residual-add input in ComputationGraph)."""
    extra = extra or {}
    if getattr(layer, "remat", None):
        from deeplearning4j_tpu.perf.fusion import remat_policy
        policy = remat_policy(layer.remat)

        def run(p, s, xx, kk, mm, ee):
            return layer.apply(p, s, xx, train=train, rng=kk, mask=mm, **ee)

        return jax.checkpoint(run, policy=policy)(params, state, x, rng,
                                                  mask, extra)
    return layer.apply(params, state, x, train=train, rng=rng, mask=mask,
                       **extra)


def noisy_params(layer, params, rng, train: bool):
    """Apply the layer's weight noise for a training forward pass (reference
    BaseLayer.getParamWithNoise via IWeightNoise). Uses a stream folded off
    the layer's dropout key so the two draws are independent."""
    wn = reg_object(layer, "weight_noise")
    if wn is None or not train or rng is None:
        return params
    out = dict(params)
    keys = [k for k in layer.regularizable()
            if resolve_param_path(params, k) is not None]
    if wn.apply_to_bias:
        keys.extend(_bias_keys(layer, params))
    for i, key in enumerate(keys):
        sub = jax.random.fold_in(rng, 7919 + i)
        if "/" in key:  # nested (wrapper layers): rebuild the nested dicts
            top, restk = key.split("/", 1)
            inner = dict(out[top])
            inner[restk] = wn.apply_to_param(inner[restk], sub)
            out[top] = inner
        else:
            out[key] = wn.apply_to_param(out[key], sub)
    return out


@dataclasses.dataclass(frozen=True)
class Layer:
    """Base of all layer configs (reference nn/conf/layers/Layer.java)."""

    name: Optional[str] = None
    dropout: float = 0.0
    # per-layer rematerialization: lower this layer's apply through
    # jax.checkpoint with the named policy (perf/fusion.py REMAT_POLICIES:
    # 'full' recomputes everything in the backward; 'dots_saveable' keeps
    # matmul/conv outputs; ...). None = normal autodiff saving. Validated
    # by analysis/validation.py; visible in conf.memory_report().
    remat: Optional[str] = None

    # ---- shape inference ----
    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    # ---- params ----
    def init(self, rng, input_type: InputType, dtype=jnp.float32):
        return {}, {}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        raise NotImplementedError

    # which param keys get l1/l2 (weights only, like DL4J's regularization-by-param-type)
    def regularizable(self) -> Tuple[str, ...]:
        return ()

    def is_output_layer(self) -> bool:
        return False

    def is_recurrent(self) -> bool:
        return False

    def input_kind(self) -> str:
        """Preferred input family for automatic preprocessor insertion:
        'ff' | 'cnn' | 'rnn' | 'any' (reference: each layer conf's
        getPreProcessorForInputType)."""
        return "any"

    def with_n_in(self, n_in: int):
        """Fill in n_in during config wiring (reference
        MultiLayerConfiguration's preProcess/setNIn pass)."""
        if hasattr(self, "n_in") and getattr(self, "n_in") in (None, 0):
            return dataclasses.replace(self, n_in=n_in)
        return self

    def to_dict(self):
        return layer_to_dict(self)


@dataclasses.dataclass(frozen=True)
class BaseLayer(Layer):
    """Layers with weights (reference nn/conf/layers/BaseLayer.java): carry
    activation, weight init, regularization and per-layer updater override."""

    activation: str = "identity"
    weight_init: str = "xavier"
    dist: Optional[Distribution] = None
    bias_init: float = 0.0
    l1: float = 0.0
    l2: float = 0.0
    l1_bias: float = 0.0
    l2_bias: float = 0.0
    updater: Optional[Updater] = None
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: float = 1.0
    # post-update parameter constraints (nn/conf/regularization.py;
    # reference nn/conf/constraint/)
    constraints: Optional[tuple] = None
    # training-forward weight noise (reference nn/conf/weightnoise/)
    weight_noise: Optional[object] = None

    def regularizable(self):
        return ("W",)


@register_layer
@dataclasses.dataclass(frozen=True)
class DenseLayer(BaseLayer):
    """Fully connected layer (reference nn/conf/layers/DenseLayer.java +
    nn/layers/feedforward/dense/DenseLayer.java). y = act(x @ W + b).

    The matmul is MXU-shaped: (batch, n_in) @ (n_in, n_out)."""

    n_in: Optional[int] = None
    n_out: int = 0
    has_bias: bool = True

    def input_kind(self):
        return "ff"

    def output_type(self, input_type):
        if input_type.kind == "rnn":  # dense broadcasts over time natively
            return InputType.recurrent(self.n_out, input_type.timeseries_length)
        return InputType.feed_forward(self.n_out)

    def init(self, rng, input_type, dtype=jnp.float32):
        n_in = self.n_in or input_type.flat_size()
        k_w, _ = jax.random.split(rng)
        params = {
            "W": init_weights(k_w, (n_in, self.n_out), n_in, self.n_out,
                              self.weight_init, self.dist, dtype)
        }
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return params, {}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = dropout_input(x, self.dropout, train, rng)
        z = x @ params["W"]
        if self.has_bias:
            z = z + params["b"]
        return get_activation(self.activation)(z), state


@register_layer
@dataclasses.dataclass(frozen=True)
class ActivationLayer(Layer):
    """Pure activation (reference nn/conf/layers/ActivationLayer.java).
    ``activation_param`` feeds parameterized activations (LeakyReLU alpha,
    ELU alpha, ThresholdedReLU theta — the Keras advanced-activation layer
    classes lower to this)."""

    activation: str = "relu"
    activation_param: Optional[float] = None

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        fn = get_activation(self.activation)
        if self.activation_param is not None:
            return fn(x, self.activation_param), state
        return fn(x), state


@register_layer
@dataclasses.dataclass(frozen=True)
class PReLULayer(BaseLayer):
    """Parametric ReLU with learnable negative slope (reference
    nn/conf/layers/PReLULayer — Keras advanced_activations.PReLU).
    ``shared_axes`` lists 1-based input axes sharing one alpha (Keras
    convention: shared_axes=[1, 2] gives per-channel alpha on NHWC)."""

    shared_axes: Optional[Tuple[int, ...]] = None

    def input_kind(self):
        return "any"

    def output_type(self, input_type):
        return input_type

    def _alpha_shape(self, input_type):
        if input_type.kind == "cnn":
            shape = [input_type.height, input_type.width, input_type.channels]
        elif input_type.kind in ("rnn", "cnn1d"):
            shape = [input_type.timeseries_length or 1, input_type.size]
        else:
            shape = [input_type.flat_size()]
        for ax in self.shared_axes or ():
            shape[ax - 1] = 1
        return tuple(shape)

    def init(self, rng, input_type, dtype=jnp.float32):
        return {"alpha": jnp.zeros(self._alpha_shape(input_type), dtype)}, {}

    def regularizable(self):
        return ()

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        alpha = params["alpha"]
        return jnp.where(x >= 0, x, alpha * x), state


@register_layer
@dataclasses.dataclass(frozen=True)
class DropoutLayer(Layer):
    """Standalone dropout (reference nn/conf/layers/DropoutLayer.java).
    ``dropout`` = retain probability."""

    dropout: float = 0.5

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return dropout_input(x, self.dropout, train, rng), state


@dataclasses.dataclass(frozen=True)
class BaseOutputLayer(BaseLayer):
    """Common machinery for loss-bearing layers (reference
    nn/conf/layers/BaseOutputLayer.java + nn/layers/BaseOutputLayer.java).

    ``apply`` returns post-activation predictions; ``pre_output`` returns the
    pre-activation z used for the numerically-stable fused loss; ``score``
    computes the mask-aware mean loss."""

    loss: str = "mcxent"
    loss_weights: Optional[Tuple[float, ...]] = None

    def is_output_layer(self):
        return True

    def pre_output(self, params, x):
        """May return a pytree for layers whose score needs more than the
        logits (CenterLoss carries features+centers; YOLO the raw grid)."""
        z = x @ params["W"]
        if "b" in params:
            z = z + params["b"]
        return z

    def output_activations(self, preout):
        """preout -> network predictions (the networks call this instead of
        applying ``activation`` directly, so structured preouts work)."""
        return get_activation(self.activation)(preout)

    def compute_score(self, labels, preout, mask=None):
        return lossfunctions.score(self.loss, labels, preout, self.activation,
                                   mask, self.loss_weights)

    def compute_score_array(self, labels, preout, mask=None):
        return lossfunctions.score_array(self.loss, labels, preout,
                                         self.activation, mask, self.loss_weights)


@register_layer
@dataclasses.dataclass(frozen=True)
class OutputLayer(BaseOutputLayer):
    """Dense + loss (reference nn/conf/layers/OutputLayer.java)."""

    n_in: Optional[int] = None
    n_out: int = 0
    has_bias: bool = True
    activation: str = "softmax"

    def input_kind(self):
        return "ff"

    def output_type(self, input_type):
        return InputType.feed_forward(self.n_out)

    def init(self, rng, input_type, dtype=jnp.float32):
        n_in = self.n_in or input_type.flat_size()
        k_w, _ = jax.random.split(rng)
        params = {
            "W": init_weights(k_w, (n_in, self.n_out), n_in, self.n_out,
                              self.weight_init, self.dist, dtype)
        }
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return params, {}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = dropout_input(x, self.dropout, train, rng)
        return get_activation(self.activation)(self.pre_output(params, x)), state


@register_layer
@dataclasses.dataclass(frozen=True)
class CenterLossOutputLayer(OutputLayer):
    """Softmax output + center loss (reference
    nn/conf/layers/CenterLossOutputLayer.java: alpha=0.05, lambda=2e-4;
    nn/layers/training/CenterLossOutputLayer.java:35).

    Loss = interclass(labels, softmax) + lambda/2 * mean ||f - c_y||^2 where
    f is the layer input (the embedding) and c_y the per-class center.

    Center updates mirror the reference's hand-crafted rule (centers move
    toward the class mean of the features with rate alpha, normalized by
    class count + 1 — CenterLossOutputLayer.java:209-224): that direction is
    injected as the autodiff gradient of a value-neutral pseudo-term, so any
    updater works on the other params while centers follow the reference
    dynamics."""

    alpha: float = 0.05
    lamda: float = 2e-4   # "lambda" is a Python keyword; JSON key is "lamda"
    # reference's gradientCheck flag (CenterLossOutputLayer.java:218): centers
    # take the TRUE loss gradient instead of the alpha EMA direction, so
    # finite-difference checks pass
    gradient_check: bool = False

    def init(self, rng, input_type, dtype=jnp.float32):
        params, state = super().init(rng, input_type, dtype)
        n_in = self.n_in or input_type.flat_size()
        # centers start at zero (reference CenterLossParamInitializer)
        params["cL"] = jnp.zeros((self.n_out, n_in), dtype)
        return params, state

    def pre_output(self, params, x):
        z = x @ params["W"]
        if "b" in params:
            z = z + params["b"]
        # score needs the features and centers too: carry them as a pytree
        return {"z": z, "f": x, "cL": params["cL"]}

    def output_activations(self, preout):
        return get_activation(self.activation)(preout["z"])

    def compute_score(self, labels, preout, mask=None):
        inter = lossfunctions.score(self.loss, labels, preout["z"],
                                    self.activation, mask, self.loss_weights)
        if self.gradient_check:
            centers_y = labels @ preout["cL"]             # true gradient mode
            diff = preout["f"] - centers_y
            return inter + 0.5 * self.lamda * jnp.mean(jnp.sum(diff * diff, -1))
        centers_y = labels @ jax.lax.stop_gradient(preout["cL"])  # (B, n_in)
        diff = preout["f"] - centers_y
        intra = 0.5 * self.lamda * jnp.mean(jnp.sum(diff * diff, -1))
        # value-neutral term whose gradient w.r.t. centers reproduces the
        # reference's alpha * sum(c_y - f) / (count_y + 1) update direction
        counts = jnp.sum(labels, 0)                       # (n_out,)
        w_per_ex = labels @ (1.0 / (counts + 1.0))        # (B,)
        cdiff = labels @ preout["cL"] - jax.lax.stop_gradient(preout["f"])
        pseudo = 0.5 * self.alpha * jnp.sum(
            w_per_ex[:, None] * cdiff * cdiff)
        pseudo = pseudo - jax.lax.stop_gradient(pseudo)   # grad only, no value
        return inter + intra + pseudo

    def compute_score_array(self, labels, preout, mask=None):
        inter = lossfunctions.score_array(self.loss, labels, preout["z"],
                                          self.activation, mask,
                                          self.loss_weights)
        centers_y = labels @ preout["cL"]
        intra = 0.5 * self.lamda * jnp.sum((preout["f"] - centers_y) ** 2, -1)
        return inter + intra


@register_layer
@dataclasses.dataclass(frozen=True)
class LossLayer(BaseOutputLayer):
    """Loss without weights (reference nn/conf/layers/LossLayer.java)."""

    activation: str = "identity"

    def regularizable(self):
        return ()

    def pre_output(self, params, x):
        return x

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return get_activation(self.activation)(x), state
