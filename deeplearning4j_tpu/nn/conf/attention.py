"""Self-attention layers: long context as a first-class LAYER API.

Parity surface: the reference line's successor API — deeplearning4j
1.0.0-beta ``nn/conf/layers/SelfAttentionLayer.java`` /
``LearnedSelfAttentionLayer.java`` (DL4J 0.9.x itself predates attention;
these layers complete the sequence-model family the way the project's own
later releases did). TPU-native: the score math runs through the Pallas
flash-attention kernel on TPU (``parallel/ring_attention.py`` — tiled
online softmax, no (T, T) materialization) when shapes satisfy the kernel's
block constraints; padded batches, tiny sequences, and off-TPU runs use the
masked dense path (``reference_attention``, shared with the ring/Ulysses
parity tests so there is exactly ONE dense implementation). For sequences
beyond one chip, the same math shards over the mesh via
``ring_self_attention`` / ``ulysses_self_attention`` (parallel/).

Param layout: nested ``{"q": {"W", "b"}, "k": ..., "v": ..., "o": ...}``
(plus ``ff1``/``ff2`` in the encoder block) so the framework's bias-aware
machinery — l1_bias/l2_bias regularization, bias constraints, weight noise
``apply_to_bias`` — discovers the biases through the standard
``<prefix>/b`` sibling rule (layers.py ``_bias_keys``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    BaseLayer, dropout_input, register_layer,
)
from deeplearning4j_tpu.nn.initializers import init_weights


def _heads(x, h):
    b, t, d = x.shape
    return x.reshape(b, t, h, d // h).transpose(0, 2, 1, 3)


def _unheads(x):
    b, h, t, dh = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, t, h * dh)


def _proj(p, x):
    z = x @ p["W"]
    return z + p["b"] if "b" in p else z


def _attend(params, x, mask, n_heads: int, causal: bool):
    """Shared multi-head attention core over nested q/k/v/o param groups.
    Uses the Pallas flash kernel when the shapes meet its block constraints
    and there is no padding mask; the dense path is reference_attention.

    Which path the compiled program took is observable: every trace bumps a
    ``perf.CompileWatch`` counter (``attention.flash`` /
    ``attention.flash_fallback`` / ``attention.dense``) via
    ``bump_active`` — landing on the owning model's watch when traced
    inside one of its jitted programs, and on ``GLOBAL`` always — surfaced
    by ``ParallelInference.stats()``. A serving fleet silently running the
    dense path instead of the Pallas kernel shows up in its stats rather
    than only as a latency regression. Counters tick at TRACE time (once
    per compiled program), not per dispatch."""
    import jax

    from deeplearning4j_tpu.parallel.ring_attention import (
        flash_self_attention, reference_attention,
    )
    from deeplearning4j_tpu.perf.compile_watch import bump_active

    q = _heads(_proj(params["q"], x), n_heads)
    k = _heads(_proj(params["k"], x), n_heads)
    v = _heads(_proj(params["v"], x), n_heads)
    out = None
    if mask is None and q.shape[2] >= 128:
        on_tpu = jax.default_backend() == "tpu"
        try:
            out = flash_self_attention(q, k, v, causal=causal)
            bump_active("attention.flash" if on_tpu
                        else "attention.flash_unavailable")
        except ValueError:
            # kernel block constraints (shape-dependent): the silent perf
            # cliff this counter exists for — the Pallas kernel was
            # eligible but got skipped
            bump_active("attention.flash_fallback")
            out = None
    else:
        bump_active("attention.dense")  # masked/short sequence: by design
    if out is None:
        out = reference_attention(q, k, v, causal=causal, key_mask=mask)
    return _proj(params["o"], _unheads(out))


def _qkvo_params(rng, n_in: int, d: int, layer, dtype):
    ks = jax.random.split(rng, 4)
    out = {}
    for key, k_, din, dout in (("q", ks[0], n_in, d), ("k", ks[1], n_in, d),
                               ("v", ks[2], n_in, d), ("o", ks[3], d, d)):
        g = {"W": init_weights(k_, (din, dout), din, dout, layer.weight_init,
                               layer.dist, dtype)}
        if layer.has_bias:
            g["b"] = jnp.full((dout,), layer.bias_init, dtype)
        out[key] = g
    return out


@register_layer
@dataclasses.dataclass(frozen=True)
class SelfAttentionLayer(BaseLayer):
    """Multi-head self-attention over (batch, time, features).

    ``n_out`` is the model width (divisible by ``n_heads``); Q/K/V and the
    output projection are learned. ``causal=True`` gives autoregressive
    masking; the framework's feature masks become key padding masks and
    masked timesteps emit zeros (the recurrent-layer output contract).
    """

    n_in: Optional[int] = None
    n_out: int = 0
    n_heads: int = 4
    causal: bool = False
    has_bias: bool = True
    activation: str = "identity"

    def input_kind(self):
        return "rnn"

    def is_recurrent(self):
        return True

    supports_stateful = False  # full-sequence layer: no rnn_time_step carry

    def regularizable(self):
        return ("q/W", "k/W", "v/W", "o/W")

    def output_type(self, it: InputType) -> InputType:
        if self.n_out % self.n_heads:
            raise ValueError(
                f"n_out {self.n_out} not divisible by n_heads {self.n_heads}")
        return InputType.recurrent(self.n_out, it.timeseries_length)

    def init(self, rng, it: InputType, dtype=jnp.float32):
        n_in = self.n_in or it.size
        return _qkvo_params(rng, n_in, self.n_out, self, dtype), {}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = dropout_input(x, self.dropout, train, rng)
        out = get_activation(self.activation)(
            _attend(params, x, mask, self.n_heads, self.causal))
        if mask is not None:  # masked steps emit zeros, post-activation
            out = out * mask[..., None].astype(out.dtype)
        return out, state


@register_layer
@dataclasses.dataclass(frozen=True)
class TransformerEncoderBlock(BaseLayer):
    """Pre-LN transformer block: LN -> MHA -> residual, LN -> FFN(gelu) ->
    residual. Width-preserving (n_out == n_in); stack for depth. Shares the
    attention core with :class:`SelfAttentionLayer` (flash kernel on TPU)."""

    n_in: Optional[int] = None
    n_out: int = 0              # model width; inferred from input when 0
    n_heads: int = 4
    ff_size: int = 0            # defaults to 4*width
    causal: bool = False
    has_bias: bool = True
    ff_activation: str = "gelu"
    activation: str = "identity"

    def input_kind(self):
        return "rnn"

    def is_recurrent(self):
        return True

    supports_stateful = False

    def regularizable(self):
        return ("q/W", "k/W", "v/W", "o/W", "ff1/W", "ff2/W")

    def _width(self, it: InputType) -> int:
        return self.n_out or self.n_in or it.size

    def output_type(self, it: InputType) -> InputType:
        d = self._width(it)
        if it.size and d != it.size:
            raise ValueError(
                f"TransformerEncoderBlock is residual: width {d} must match "
                f"input size {it.size}")
        if d % self.n_heads:
            raise ValueError(
                f"width {d} not divisible by n_heads {self.n_heads}")
        return InputType.recurrent(d, it.timeseries_length)

    def init(self, rng, it: InputType, dtype=jnp.float32):
        d = self._width(it)
        ff = self.ff_size or 4 * d
        k_attn, k1, k2 = jax.random.split(rng, 3)
        params = _qkvo_params(k_attn, d, d, self, dtype)
        for key, k_, din, dout in (("ff1", k1, d, ff), ("ff2", k2, ff, d)):
            g = {"W": init_weights(k_, (din, dout), din, dout,
                                   self.weight_init, self.dist, dtype)}
            if self.has_bias:
                g["b"] = jnp.full((dout,), self.bias_init, dtype)
            params[key] = g
        params["ln1_g"] = jnp.ones((d,), dtype)
        params["ln1_b"] = jnp.zeros((d,), dtype)
        params["ln2_g"] = jnp.ones((d,), dtype)
        params["ln2_b"] = jnp.zeros((d,), dtype)
        return params, {}

    @staticmethod
    def _ln(x, g, b, eps=1e-5):
        mu = jnp.mean(x, -1, keepdims=True)
        var = jnp.var(x, -1, keepdims=True)
        return (x - mu) * jax.lax.rsqrt(var + eps) * g + b

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = dropout_input(x, self.dropout, train, rng)
        att_in = self._ln(x, params["ln1_g"], params["ln1_b"])
        x = x + _attend(params, att_in, mask, self.n_heads, self.causal)
        ff_in = self._ln(x, params["ln2_g"], params["ln2_b"])
        h = get_activation(self.ff_activation)(_proj(params["ff1"], ff_in))
        x = get_activation(self.activation)(x + _proj(params["ff2"], h))
        if mask is not None:  # masked steps emit zeros, post-activation
            x = x * mask[..., None].astype(x.dtype)
        return x, state


__all__ = ["SelfAttentionLayer", "TransformerEncoderBlock"]
