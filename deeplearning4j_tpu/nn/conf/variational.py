"""Variational autoencoder layer.

Parity surface: reference
``nn/conf/layers/variational/VariationalAutoencoder.java`` (builder:
encoderLayerSizes/decoderLayerSizes, pzxActivationFunction, numSamples,
reconstruction distribution) and
``nn/layers/variational/VariationalAutoencoder.java:68`` (1,163 LoC of
hand-written forward/backward); reconstruction distributions
``variational/BernoulliReconstructionDistribution.java`` and
``GaussianReconstructionDistribution.java``.

TPU-native redesign: the reference hand-derives every gradient of the ELBO
through encoder, reparameterization and decoder; here ``pretrain_loss`` is a
~30-line traced expression (reparameterized sample + closed-form KL) and
autodiff does the rest. In a supervised stack the layer's ``apply`` returns
the mean of q(z|x) — identical to the reference's ``activate``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import BaseLayer, register_layer
from deeplearning4j_tpu.nn.initializers import init_weights


def _mlp_init(rng, sizes, weight_init, dist, bias_init, dtype, prefix):
    params = {}
    for i, (a, b) in enumerate(zip(sizes[:-1], sizes[1:])):
        rng, k = jax.random.split(rng)
        params[f"{prefix}{i}W"] = init_weights(k, (a, b), a, b, weight_init,
                                               dist, dtype)
        params[f"{prefix}{i}b"] = jnp.full((b,), bias_init, dtype)
    return params, rng


def _mlp_apply(params, x, n, act, prefix):
    for i in range(n):
        x = act(x @ params[f"{prefix}{i}W"] + params[f"{prefix}{i}b"])
    return x


@register_layer
@dataclasses.dataclass(frozen=True)
class VariationalAutoencoder(BaseLayer):
    """VAE as a layer: supervised forward = mean of q(z|x); unsupervised
    pretraining maximizes the ELBO (see module docstring).

    ``reconstruction``: 'bernoulli' (sigmoid + binary cross-entropy — data in
    [0,1]) or 'gaussian' (identity mean + learned diagonal log-variance) —
    the two reference ReconstructionDistributions that cover the test suite.
    ``pzx_activation``: activation on the q(z|x) mean/logvar pre-outs
    (reference pzxActivationFunction, default identity).
    """

    n_in: Optional[int] = None
    n_out: int = 0                       # latent size
    encoder_layer_sizes: Tuple[int, ...] = (100,)
    decoder_layer_sizes: Tuple[int, ...] = (100,)
    pzx_activation: str = "identity"
    reconstruction: str = "bernoulli"
    num_samples: int = 1
    activation: str = "tanh"             # encoder/decoder hidden activation

    def input_kind(self):
        return "ff"

    def is_pretrainable(self):
        return True

    def output_type(self, input_type):
        return InputType.feed_forward(self.n_out)

    # ------------------------------------------------------------------ init
    def init(self, rng, input_type, dtype=jnp.float32):
        n_in = self.n_in or input_type.flat_size()
        enc = (n_in,) + tuple(self.encoder_layer_sizes)
        dec = (self.n_out,) + tuple(self.decoder_layer_sizes)
        params, rng = _mlp_init(rng, enc, self.weight_init, self.dist,
                                self.bias_init, dtype, "e")
        dparams, rng = _mlp_init(rng, dec, self.weight_init, self.dist,
                                 self.bias_init, dtype, "d")
        params.update(dparams)
        eh = enc[-1]
        dh = dec[-1]
        recon_out = n_in if self.reconstruction == "bernoulli" else 2 * n_in
        for name, (a, b) in (("pzxMean", (eh, self.n_out)),
                             ("pzxLogStd2", (eh, self.n_out)),
                             ("pxz", (dh, recon_out))):
            rng, k = jax.random.split(rng)
            params[name + "W"] = init_weights(k, (a, b), a, b,
                                              self.weight_init, self.dist, dtype)
            params[name + "b"] = jnp.full((b,), self.bias_init, dtype)
        return params, {}

    def regularizable(self):
        return tuple(k for k in
                     [f"e{i}W" for i in range(len(self.encoder_layer_sizes))]
                     + [f"d{i}W" for i in range(len(self.decoder_layer_sizes))]
                     + ["pzxMeanW", "pzxLogStd2W", "pxzW"])

    # --------------------------------------------------------------- forward
    def _encode(self, params, x):
        act = get_activation(self.activation)
        h = _mlp_apply(params, x, len(self.encoder_layer_sizes), act, "e")
        pzx_act = get_activation(self.pzx_activation)
        mean = pzx_act(h @ params["pzxMeanW"] + params["pzxMeanb"])
        logvar = pzx_act(h @ params["pzxLogStd2W"] + params["pzxLogStd2b"])
        return mean, logvar

    def _decode(self, params, z):
        act = get_activation(self.activation)
        h = _mlp_apply(params, z, len(self.decoder_layer_sizes), act, "d")
        return h @ params["pxzW"] + params["pxzb"]

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        """Supervised forward: mean of q(z|x) (reference activate :804)."""
        mean, _ = self._encode(params, x)
        return mean, state

    # -------------------------------------------------------------- pretrain
    def pretrain_loss(self, params, state, x, rng):
        """Negative ELBO, averaged over the minibatch (reference
        computeGradientAndScore with numSamples reparameterized draws):
        E_q[-log p(x|z)] + KL(q(z|x) || N(0, I))."""
        mean, logvar = self._encode(params, x)
        # closed-form KL per example: -0.5 * sum(1 + log s2 - m^2 - s2)
        kl = -0.5 * jnp.sum(1.0 + logvar - mean ** 2 - jnp.exp(logvar), -1)
        recon = 0.0
        for s in range(self.num_samples):
            rng, k = jax.random.split(rng)
            eps = jax.random.normal(k, mean.shape, mean.dtype)
            z = mean + jnp.exp(0.5 * logvar) * eps
            p = self._decode(params, z)
            if self.reconstruction == "bernoulli":
                # sigmoid + binary CE, numerically fused on logits
                nll = jnp.sum(jnp.maximum(p, 0) - p * x +
                              jnp.log1p(jnp.exp(-jnp.abs(p))), -1)
            elif self.reconstruction == "gaussian":
                mu, lv = jnp.split(p, 2, axis=-1)
                nll = 0.5 * jnp.sum(lv + (x - mu) ** 2 / jnp.exp(lv)
                                    + jnp.log(2 * jnp.pi), -1)
            else:
                raise ValueError(self.reconstruction)
            recon = recon + nll
        recon = recon / self.num_samples
        return jnp.mean(recon + kl)

    # ------------------------------------------------------------- utilities
    def reconstruction_probability(self, params, x, rng, num_samples=5):
        """Monte-carlo estimate of log p(x) (reference
        reconstructionLogProbability — used for anomaly detection)."""
        mean, logvar = self._encode(params, x)
        total = None
        for s in range(num_samples):
            rng, k = jax.random.split(rng)
            eps = jax.random.normal(k, mean.shape, mean.dtype)
            z = mean + jnp.exp(0.5 * logvar) * eps
            p = self._decode(params, z)
            if self.reconstruction == "bernoulli":
                logp = -jnp.sum(jnp.maximum(p, 0) - p * x +
                                jnp.log1p(jnp.exp(-jnp.abs(p))), -1)
            else:
                mu, lv = jnp.split(p, 2, axis=-1)
                logp = -0.5 * jnp.sum(lv + (x - mu) ** 2 / jnp.exp(lv)
                                      + jnp.log(2 * jnp.pi), -1)
            total = logp if total is None else jnp.logaddexp(total, logp)
        return total - jnp.log(float(num_samples))

    def generate_at_mean_given_z(self, params, z):
        """Decoder mean for a latent (reference generateAtMeanGivenZ)."""
        p = self._decode(params, z)
        if self.reconstruction == "bernoulli":
            return jax.nn.sigmoid(p)
        mu, _ = jnp.split(p, 2, axis=-1)
        return mu
