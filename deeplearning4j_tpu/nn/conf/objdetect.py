"""YOLOv2 object-detection output layer.

Parity surface: reference
``nn/conf/layers/objdetect/Yolo2OutputLayer.java`` (builder: boundingBoxes
priors, lambdaCoord=5, lambdaNoObj=0.5, L2 position/class losses) and
``nn/layers/objdetect/Yolo2OutputLayer.java:63`` (721 LoC — the box
assignment loss of YOLO9000/YOLOv2), plus ``objdetect/DetectedObject.java``
and the YoloUtils prediction decoding.

TPU-native redesign: the reference hand-writes both the loss and its
gradient with per-box Java loops and ND4J broadcasts; here the whole loss is
one vectorized jnp expression over a (mb, H, W, B, 5+C) tensor — autodiff
produces the backward pass, and XLA fuses the box algebra into the
surrounding program. Layout is NHWC throughout (channels-last is the TPU
conv layout), so labels are (mb, H, W, 4+C) where the reference uses
(mb, 4+C, H, W); the depth order [x1,y1,x2,y2,class...] in *grid units* is
identical.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import Layer, register_layer


def _split_grid(x, n_boxes: int):
    """(mb, H, W, B*(5+C)) -> (mb, H, W, B, 5+C)."""
    mb, h, w, d = x.shape
    per = d // n_boxes
    return x.reshape(mb, h, w, n_boxes, per)


@register_layer
@dataclasses.dataclass(frozen=True)
class Yolo2OutputLayer(Layer):
    """YOLOv2 loss layer.

    ``boxes``: tuple of (w, h) anchor priors in grid units (reference
    boundingBoxes). Labels (mb, H, W, 4+C): [x1, y1, x2, y2] box corners in
    grid units plus one-hot class (all-zero = no object in that cell — masks
    are inferred from the labels exactly as the reference does).
    """

    boxes: Tuple[Tuple[float, float], ...] = ()
    lambda_coord: float = 5.0
    lambda_no_obj: float = 0.5

    def is_output_layer(self):
        return True

    def input_kind(self):
        return "cnn"

    def output_type(self, input_type):
        return input_type

    def regularizable(self):
        return ()

    # ------------------------------------------------------------- forward
    def pre_output(self, params, x):
        return x

    def output_activations(self, preout):
        """Apply the YOLO activations (reference Yolo2OutputLayer.activate
        :329): sigmoid xy + conf, prior*exp wh, softmax classes. Returned in
        the same (mb, H, W, B*(5+C)) layout."""
        b = len(self.boxes)
        t = _split_grid(preout, b)
        priors = jnp.asarray(self.boxes, t.dtype)            # (B, 2)
        xy = jax.nn.sigmoid(t[..., 0:2])
        wh = priors * jnp.exp(t[..., 2:4])
        conf = jax.nn.sigmoid(t[..., 4:5])
        cls = jax.nn.softmax(t[..., 5:], axis=-1)
        out = jnp.concatenate([xy, wh, conf, cls], axis=-1)
        return out.reshape(preout.shape)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return self.output_activations(x), state

    # ---------------------------------------------------------------- loss
    def compute_score(self, labels, preout, mask=None):
        """YOLOv2 loss (reference computeBackpropGradientAndScore): summed
        components / minibatch. All five steps of the reference collapse into
        one traced expression; the hand-derived gradient becomes autodiff."""
        b = len(self.boxes)
        t = _split_grid(preout, b)                           # (mb,H,W,B,5+C)
        mb, H, W = t.shape[0], t.shape[1], t.shape[2]
        priors = jnp.asarray(self.boxes, t.dtype)

        cls_labels = labels[..., 4:]                         # (mb,H,W,C)
        obj = (jnp.sum(cls_labels, -1) > 0).astype(t.dtype)  # (mb,H,W)

        tl = labels[..., 0:2]
        br = labels[..., 2:4]
        center = 0.5 * (tl + br)
        center_in_cell = center - jnp.floor(center)          # (mb,H,W,2)
        label_wh = br - tl
        label_wh_sqrt = jnp.sqrt(jnp.maximum(label_wh, 0.0))

        pred_xy = jax.nn.sigmoid(t[..., 0:2])                # in-cell (0,1)
        pred_wh = priors * jnp.exp(t[..., 2:4])              # grid units
        pred_wh_sqrt = jnp.sqrt(pred_wh)
        pred_conf = jax.nn.sigmoid(t[..., 4])                # (mb,H,W,B)

        # absolute predicted box: cell origin + in-cell offset
        gx = jnp.arange(W, dtype=t.dtype)[None, None, :, None]
        gy = jnp.arange(H, dtype=t.dtype)[None, :, None, None]
        grid = jnp.stack(
            [jnp.broadcast_to(gx, (1, H, W, 1)),
             jnp.broadcast_to(gy, (1, H, W, 1))], axis=-1)   # (1,H,W,1,2)
        pred_center = pred_xy + grid
        p_tl = pred_center - 0.5 * pred_wh
        p_br = pred_center + 0.5 * pred_wh

        # IoU vs the cell's label box (reference calculateIOULabelPredicted)
        l_tl = tl[:, :, :, None, :]
        l_br = br[:, :, :, None, :]
        inter_tl = jnp.maximum(p_tl, l_tl)
        inter_br = jnp.minimum(p_br, l_br)
        inter_wh = jnp.maximum(inter_br - inter_tl, 0.0)
        inter = inter_wh[..., 0] * inter_wh[..., 1]          # (mb,H,W,B)
        area_p = pred_wh[..., 0] * pred_wh[..., 1]
        area_l = (label_wh[..., 0] * label_wh[..., 1])[:, :, :, None]
        union = area_p + area_l - inter
        iou = jnp.where(union > 0, inter / jnp.maximum(union, 1e-12), 0.0)

        # 1_ij^obj: box with max IoU in an object cell (reference IsMax)
        responsible = jax.nn.one_hot(jnp.argmax(iou, -1), b, dtype=t.dtype)
        m_obj = responsible * obj[..., None]                 # (mb,H,W,B)
        m_noobj = 1.0 - m_obj

        conf_target = jax.lax.stop_gradient(iou) * m_obj

        # L2 losses, summed like the reference (LossL2, average=false)
        pos = jnp.sum(m_obj[..., None] *
                      (pred_xy - center_in_cell[:, :, :, None, :]) ** 2)
        size = jnp.sum(m_obj[..., None] *
                       (pred_wh_sqrt - label_wh_sqrt[:, :, :, None, :]) ** 2)
        conf = (jnp.sum(m_obj * (pred_conf - conf_target) ** 2)
                + self.lambda_no_obj *
                jnp.sum(m_noobj * (pred_conf - conf_target) ** 2))
        # class predictions: softmax + L2 (the reference's default
        # lossClassPredictions = LossL2 applied to softmax output)
        cls_pred = jax.nn.softmax(t[..., 5:], axis=-1)
        cls_l = cls_labels[:, :, :, None, :]
        cls_loss = jnp.sum(m_obj[..., None] * (cls_pred - cls_l) ** 2)

        total = (self.lambda_coord * (pos + size) + conf + cls_loss)
        return total / mb

    def compute_score_array(self, labels, preout, mask=None):
        # per-example scores: re-run with batch kept (used by score calcs)
        def one(lab, po):
            return self.compute_score(lab[None], po[None])
        return jax.vmap(one)(labels, preout)


class DetectedObject:
    """One decoded detection (reference objdetect/DetectedObject.java):
    center x/y + w/h in grid units, confidence, class distribution."""

    def __init__(self, example: int, cx: float, cy: float, w: float, h: float,
                 confidence: float, class_probs: np.ndarray):
        self.example = example
        self.center_x = cx
        self.center_y = cy
        self.width = w
        self.height = h
        self.confidence = confidence
        self.class_probs = class_probs

    @property
    def predicted_class(self) -> int:
        return int(np.argmax(self.class_probs))

    def top_left(self):
        return (self.center_x - self.width / 2, self.center_y - self.height / 2)

    def bottom_right(self):
        return (self.center_x + self.width / 2, self.center_y + self.height / 2)

    def __repr__(self):
        return (f"DetectedObject(ex={self.example}, cls={self.predicted_class},"
                f" conf={self.confidence:.3f}, xywh=({self.center_x:.2f},"
                f"{self.center_y:.2f},{self.width:.2f},{self.height:.2f}))")


def get_predicted_objects(activations, n_boxes: int,
                          threshold: float = 0.5) -> List[DetectedObject]:
    """Decode YOLO activations (as produced by output_activations) into
    DetectedObjects above a confidence threshold (reference
    YoloUtils.getPredictedObjects)."""
    a = np.asarray(activations)
    mb, H, W, d = a.shape
    per = d // n_boxes
    a5 = a.reshape(mb, H, W, n_boxes, per)
    out: List[DetectedObject] = []
    ex, ys, xs, bs = np.where(a5[..., 4] >= threshold)
    for e, y, x, bi in zip(ex, ys, xs, bs):
        v = a5[e, y, x, bi]
        out.append(DetectedObject(int(e), float(x + v[0]), float(y + v[1]),
                                  float(v[2]), float(v[3]), float(v[4]),
                                  v[5:].copy()))
    return out
