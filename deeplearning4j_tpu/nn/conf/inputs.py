"""Input types for shape inference.

Parity surface: reference ``nn/conf/inputs/InputType.java`` — the declarative
shape-inference system used by ``MultiLayerConfiguration``/
``ComputationGraphConfiguration`` to wire n_in automatically and to insert
input preprocessors between layer families.

TPU-first convention: convolutional activations are **NHWC** (batch, height,
width, channels) — the layout XLA:TPU tiles best — instead of DL4J's NCHW;
recurrent activations are (batch, time, size) instead of DL4J's (batch, size,
time).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class InputType:
    kind: str  # "ff" | "rnn" | "cnn" | "cnn_flat" | "cnn1d"
    size: int = 0  # ff/rnn feature size; cnn1d channels
    height: int = 0
    width: int = 0
    channels: int = 0
    timeseries_length: Optional[int] = None

    # ---- factories (InputType.feedForward etc. in the reference) ----
    @staticmethod
    def feed_forward(size: int) -> "InputType":
        return InputType("ff", size=size)

    @staticmethod
    def recurrent(size: int, timeseries_length: Optional[int] = None) -> "InputType":
        return InputType("rnn", size=size, timeseries_length=timeseries_length)

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputType":
        return InputType("cnn", height=height, width=width, channels=channels)

    @staticmethod
    def convolutional_flat(height: int, width: int, channels: int) -> "InputType":
        return InputType("cnn_flat", height=height, width=width, channels=channels)

    @staticmethod
    def recurrent1d(channels: int, length: Optional[int] = None) -> "InputType":
        return InputType("cnn1d", size=channels, timeseries_length=length)

    # ---- helpers ----
    def flat_size(self) -> int:
        if self.kind == "ff":
            return self.size
        if self.kind in ("cnn", "cnn_flat"):
            return self.height * self.width * self.channels
        if self.kind == "rnn":
            return self.size
        if self.kind == "cnn1d":
            return self.size
        raise ValueError(self.kind)

    def example_shape(self, batch: int = 1) -> Tuple[int, ...]:
        """Concrete array shape for one batch of this input type."""
        if self.kind in ("ff", "cnn_flat"):
            return (batch, self.flat_size())
        if self.kind == "rnn":
            t = self.timeseries_length or 1
            return (batch, t, self.size)
        if self.kind == "cnn":
            return (batch, self.height, self.width, self.channels)
        if self.kind == "cnn1d":
            t = self.timeseries_length or 1
            return (batch, t, self.size)
        raise ValueError(self.kind)

    def to_dict(self):
        return {k: v for k, v in dataclasses.asdict(self).items() if v not in (None,)}

    @staticmethod
    def from_dict(d):
        return InputType(**d)
