"""Computation-graph configuration: vertices + DAG wiring.

Parity surface: reference ``nn/conf/ComputationGraphConfiguration.java``
(GraphBuilder), graph vertex configs in ``nn/conf/graph/`` and impls in
``nn/graph/vertex/impl/`` (14 classes + rnn/): MergeVertex,
ElementWiseVertex, StackVertex, UnstackVertex, SubsetVertex, ReshapeVertex,
ScaleVertex, ShiftVertex, L2NormalizeVertex, L2Vertex, PreprocessorVertex,
LastTimeStepVertex, DuplicateToTimeSeriesVertex.

TPU-native: a vertex is a pure function of its input activations; the whole
DAG is traced in topological order into ONE XLA program (the reference's
runtime topo-order loop — ComputationGraph.java:1440-1513 — happens once at
trace time, not per batch).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import Layer, layer_from_dict, layer_to_dict
from deeplearning4j_tpu.optimize.updaters import Updater, Sgd

_VERTEX_REGISTRY = {}


def register_vertex(cls):
    _VERTEX_REGISTRY[cls.__name__] = cls
    return cls


def vertex_to_dict(v):
    d = dataclasses.asdict(v)
    d["@class"] = type(v).__name__
    return d


def vertex_from_dict(d):
    d = dict(d)
    cls = _VERTEX_REGISTRY[d.pop("@class")]
    names = {f.name for f in dataclasses.fields(cls)}
    return cls(**{k: (tuple(v) if isinstance(v, list) else v)
                  for k, v in d.items() if k in names})


class GraphVertex:
    """Parameterless DAG node (reference nn/graph/vertex/GraphVertex.java)."""

    def output_type(self, *input_types: InputType) -> InputType:
        return input_types[0]

    def apply(self, *inputs):
        raise NotImplementedError

    def to_dict(self):
        return vertex_to_dict(self)


@register_vertex
@dataclasses.dataclass(frozen=True)
class MergeVertex(GraphVertex):
    """Concatenate along the feature axis (reference nn/conf/graph/MergeVertex.java)."""

    def output_type(self, *its):
        total = sum(it.flat_size() for it in its)
        base = its[0]
        if base.kind == "rnn":
            return InputType.recurrent(sum(it.size for it in its), base.timeseries_length)
        if base.kind == "cnn":
            return InputType.convolutional(base.height, base.width,
                                           sum(it.channels for it in its))
        return InputType.feed_forward(total)

    def apply(self, *inputs):
        return jnp.concatenate(inputs, axis=-1)


@register_vertex
@dataclasses.dataclass(frozen=True)
class ElementWiseVertex(GraphVertex):
    """add|subtract|product|average|max (reference ElementWiseVertex.java)."""

    op: str = "add"

    def apply(self, *inputs):
        op = self.op.lower()
        if op == "add":
            out = inputs[0]
            for x in inputs[1:]:
                out = out + x
            return out
        if op == "subtract":
            if len(inputs) != 2:
                raise ValueError("subtract requires exactly 2 inputs")
            return inputs[0] - inputs[1]
        if op in ("product", "mul"):
            out = inputs[0]
            for x in inputs[1:]:
                out = out * x
            return out
        if op in ("average", "avg"):
            return sum(inputs) / float(len(inputs))
        if op == "max":
            out = inputs[0]
            for x in inputs[1:]:
                out = jnp.maximum(out, x)
            return out
        raise ValueError(f"Unknown elementwise op '{self.op}'")


@register_vertex
@dataclasses.dataclass(frozen=True)
class StackVertex(GraphVertex):
    """Stack along the batch axis (reference StackVertex.java)."""

    def apply(self, *inputs):
        return jnp.concatenate(inputs, axis=0)


@register_vertex
@dataclasses.dataclass(frozen=True)
class UnstackVertex(GraphVertex):
    """Take slice ``from_index`` of ``stack_size`` along batch (reference
    UnstackVertex.java)."""

    from_index: int = 0
    stack_size: int = 1

    def apply(self, *inputs):
        x = inputs[0]
        step = x.shape[0] // self.stack_size
        return x[self.from_index * step:(self.from_index + 1) * step]


@register_vertex
@dataclasses.dataclass(frozen=True)
class SubsetVertex(GraphVertex):
    """Feature range [from_index, to_index] inclusive (reference SubsetVertex.java)."""

    from_index: int = 0
    to_index: int = 0

    def output_type(self, *its):
        n = self.to_index - self.from_index + 1
        it = its[0]
        if it.kind == "rnn":
            return InputType.recurrent(n, it.timeseries_length)
        return InputType.feed_forward(n)

    def apply(self, *inputs):
        return inputs[0][..., self.from_index:self.to_index + 1]


@register_vertex
@dataclasses.dataclass(frozen=True)
class ReshapeVertex(GraphVertex):
    """Reshape to (batch, *shape) (reference ReshapeVertex.java)."""

    shape: Tuple[int, ...] = ()

    def output_type(self, *its):
        if len(self.shape) == 1:
            return InputType.feed_forward(self.shape[0])
        if len(self.shape) == 3:
            return InputType.convolutional(*self.shape)
        if len(self.shape) == 2:
            return InputType.recurrent(self.shape[1], self.shape[0])
        return its[0]

    def apply(self, *inputs):
        x = inputs[0]
        return x.reshape((x.shape[0],) + tuple(self.shape))


@register_vertex
@dataclasses.dataclass(frozen=True)
class ScaleVertex(GraphVertex):
    """x * scale (reference ScaleVertex.java)."""

    scale: float = 1.0

    def apply(self, *inputs):
        return inputs[0] * self.scale


@register_vertex
@dataclasses.dataclass(frozen=True)
class ShiftVertex(GraphVertex):
    """x + shift (reference ShiftVertex.java)."""

    shift: float = 0.0

    def apply(self, *inputs):
        return inputs[0] + self.shift


@register_vertex
@dataclasses.dataclass(frozen=True)
class L2NormalizeVertex(GraphVertex):
    """x / ||x||_2 over feature axes (reference L2NormalizeVertex.java)."""

    eps: float = 1e-8

    def apply(self, *inputs):
        x = inputs[0]
        axes = tuple(range(1, x.ndim))
        n = jnp.sqrt(jnp.sum(x * x, axis=axes, keepdims=True) + self.eps)
        return x / n


@register_vertex
@dataclasses.dataclass(frozen=True)
class L2Vertex(GraphVertex):
    """Pairwise L2 distance of two inputs -> (batch, 1) (reference L2Vertex.java)."""

    eps: float = 1e-8

    def output_type(self, *its):
        return InputType.feed_forward(1)

    def apply(self, *inputs):
        a, b = inputs
        d = a.reshape(a.shape[0], -1) - b.reshape(b.shape[0], -1)
        return jnp.sqrt(jnp.sum(d * d, axis=1, keepdims=True) + self.eps)


@register_vertex
@dataclasses.dataclass(frozen=True)
class PreprocessorVertex(GraphVertex):
    """Wrap an InputPreProcessor as a vertex (reference PreprocessorVertex.java)."""

    preprocessor: Optional[object] = None

    def output_type(self, *its):
        return self.preprocessor.output_type(its[0])

    def apply(self, *inputs):
        out, _ = self.preprocessor.apply(inputs[0], None)
        return out

    def to_dict(self):
        from deeplearning4j_tpu.nn.conf.preprocessors import preprocessor_to_dict
        return {"@class": "PreprocessorVertex",
                "preprocessor": preprocessor_to_dict(self.preprocessor)}


@register_vertex
@dataclasses.dataclass(frozen=True)
class LastTimeStepVertex(GraphVertex):
    """(b, t, s) -> (b, s) last unmasked step (reference
    nn/graph/vertex/impl/rnn/LastTimeStepVertex.java). Mask handling is done
    by the graph runtime (passes the relevant input mask)."""

    mask_input: Optional[str] = None

    def output_type(self, *its):
        return InputType.feed_forward(its[0].size)

    def apply(self, *inputs, mask=None):
        x = inputs[0]
        if mask is None:
            return x[:, -1, :]
        lengths = jnp.sum(mask, axis=1).astype(jnp.int32)
        idx = jnp.maximum(lengths - 1, 0)
        return jnp.take_along_axis(x, idx[:, None, None], axis=1)[:, 0, :]


@register_vertex
@dataclasses.dataclass(frozen=True)
class DuplicateToTimeSeriesVertex(GraphVertex):
    """(b, s) -> (b, t, s) broadcast over the time length of a reference input
    (reference rnn/DuplicateToTimeSeriesVertex.java)."""

    reference_input: Optional[str] = None

    def output_type(self, *its):
        return InputType.recurrent(its[0].flat_size())

    def apply(self, *inputs, time_steps=None):
        x = inputs[0]
        return jnp.broadcast_to(x[:, None, :], (x.shape[0], time_steps, x.shape[1]))


@dataclasses.dataclass(frozen=True)
class ComputationGraphConfiguration:
    """DAG config (reference nn/conf/ComputationGraphConfiguration.java).

    ``vertices`` maps name -> (Layer | GraphVertex, input names). Network
    inputs are named in ``network_inputs`` with types in ``input_types``.
    """

    network_inputs: Tuple[str, ...]
    vertices: Dict[str, Tuple[object, Tuple[str, ...]]]
    network_outputs: Tuple[str, ...]
    input_types: Tuple[InputType, ...] = ()
    seed: int = 12345
    dtype: str = "float32"
    updater: Updater = Sgd(learning_rate=0.1)
    backprop_type: str = "standard"
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20

    def __post_init__(self):
        if self.backprop_type not in ("standard", "tbptt"):
            raise ValueError(
                f"Unknown backprop_type '{self.backprop_type}' "
                "(expected 'standard' or 'tbptt')")
        if (self.backprop_type == "tbptt"
                and self.tbptt_fwd_length != self.tbptt_back_length):
            # _fit_tbptt steps and truncates by fwd_length only (same
            # constraint as MultiLayerConfiguration.__post_init__)
            raise ValueError(
                "tbptt_back_length != tbptt_fwd_length is not supported: got "
                f"fwd={self.tbptt_fwd_length}, back={self.tbptt_back_length}. "
                "Use equal lengths")

    # ---- topology (reference ComputationGraph.topologicalSortOrder :1190) ----
    def topological_order(self) -> List[str]:
        indeg = {}
        children = {n: [] for n in list(self.vertices) + list(self.network_inputs)}
        for name, (_, inputs) in self.vertices.items():
            indeg[name] = len(inputs)
            for i in inputs:
                if i not in children:
                    raise ValueError(f"Vertex '{name}' references unknown input '{i}'")
                children[i].append(name)
        order = []
        frontier = list(self.network_inputs)
        while frontier:
            cur = frontier.pop()
            if cur in self.vertices:
                order.append(cur)
            for ch in children[cur]:
                indeg[ch] -= 1
                if indeg[ch] == 0:
                    frontier.append(ch)
        if len(order) != len(self.vertices):
            cyc = set(self.vertices) - set(order)
            raise ValueError(f"Graph has a cycle or unreachable vertices: {sorted(cyc)}")
        return order

    # ---- shape inference over the DAG ----
    def _infer(self):
        """Walk the DAG once: per-vertex input types (post-preprocessor) and
        automatically inserted preprocessors for layer vertices (same
        infer_preprocessor logic the sequential config uses — the reference
        ComputationGraphConfiguration also auto-adds preprocessors)."""
        from deeplearning4j_tpu.nn.conf.preprocessors import infer_preprocessor
        if len(self.input_types) != len(self.network_inputs):
            raise ValueError("input_types must be set for all network inputs")
        known: Dict[str, InputType] = dict(zip(self.network_inputs, self.input_types))
        types = {}
        pres = {}
        for name in self.topological_order():
            obj, inputs = self.vertices[name]
            its = tuple(known[i] for i in inputs)
            if isinstance(obj, Layer):
                pre = infer_preprocessor(its[0], obj)
                if pre is not None:
                    pres[name] = pre
                    its = (pre.output_type(its[0]),) + its[1:]
                types[name] = its
                known[name] = obj.output_type(its[0])
            else:
                types[name] = its
                known[name] = obj.output_type(*its)
        return types, pres, known

    def vertex_input_types(self) -> Dict[str, Tuple[InputType, ...]]:
        return self._infer()[0]

    def resolved_vertex_preprocessors(self):
        return self._infer()[1]

    def vertex_output_types(self) -> Dict[str, InputType]:
        """Output InputType of every vertex (and network input) — used by
        transfer learning to type the frozen boundary."""
        return self._infer()[2]

    def wired_vertices(self) -> Dict[str, Tuple[object, Tuple[str, ...]]]:
        types = self.vertex_input_types()
        out = {}
        for name, (obj, inputs) in self.vertices.items():
            if isinstance(obj, Layer):
                obj = obj.with_n_in(types[name][0].flat_size())
            out[name] = (obj, inputs)
        return out

    # ---- static analysis (analysis/validation.py) ----
    def validate(self, *, eval_shape_check: bool = False, batch: int = 2,
                 labels_shapes=None, raise_on_error: bool = True):
        """Ahead-of-compile DAG validation: cycle / dangling-vertex /
        unknown-reference detection, merge/element-wise rank+shape
        agreement, per-layer shape inference with vertex-named messages.
        ``eval_shape_check=True`` cross-checks against ``jax.eval_shape``
        of the traced DAG. Returns the issue list; raises
        :class:`analysis.ConfigValidationError` on errors unless
        ``raise_on_error=False``."""
        from deeplearning4j_tpu.analysis.validation import (
            ConfigValidationError, validate_graph)
        issues = validate_graph(
            self, eval_shape_check=eval_shape_check, batch=batch,
            labels_shapes=labels_shapes)
        errors = [i for i in issues if i.severity == "error"]
        if errors and raise_on_error:
            raise ConfigValidationError(errors)
        return issues

    def memory_report(self, minibatch: int = 32):
        """Analytic per-vertex parameter + activation memory (no device
        allocation), plus the measured training-activation-bytes line
        (jaxpr-derived residual set of the real train step). See
        nn/memory.py::conf_memory_report."""
        from deeplearning4j_tpu.nn.memory import conf_memory_report
        return conf_memory_report(self, minibatch=minibatch)

    def fused(self) -> "ComputationGraphConfiguration":
        """Conv→BN→Act(→residual-add) fusion rewrite of this DAG
        (perf/fusion.py). Matched chains — including the residual
        bottleneck pattern — become FusedConvBNActivation vertices."""
        from deeplearning4j_tpu.perf.fusion import fuse
        return fuse(self)

    # ---- serde ----
    def to_json(self) -> str:
        d = {
            "network_inputs": list(self.network_inputs),
            "network_outputs": list(self.network_outputs),
            "input_types": [t.to_dict() for t in self.input_types],
            "seed": self.seed,
            "dtype": self.dtype,
            "updater": self.updater.to_dict(),
            "backprop_type": self.backprop_type,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_back_length": self.tbptt_back_length,
            "vertices": {
                name: {"node": (layer_to_dict(obj) if isinstance(obj, Layer)
                                else obj.to_dict()),
                       "is_layer": isinstance(obj, Layer),
                       "inputs": list(inputs)}
                for name, (obj, inputs) in self.vertices.items()
            },
        }
        return json.dumps(d, indent=2)

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        from deeplearning4j_tpu.nn.conf.preprocessors import preprocessor_from_dict
        d = json.loads(s)
        vertices = {}
        for name, vd in d["vertices"].items():
            node = vd["node"]
            if vd["is_layer"]:
                obj = layer_from_dict(node)
            elif node["@class"] == "PreprocessorVertex":
                obj = PreprocessorVertex(preprocessor_from_dict(node["preprocessor"]))
            else:
                obj = vertex_from_dict(node)
            vertices[name] = (obj, tuple(vd["inputs"]))
        return ComputationGraphConfiguration(
            network_inputs=tuple(d["network_inputs"]),
            vertices=vertices,
            network_outputs=tuple(d["network_outputs"]),
            input_types=tuple(InputType.from_dict(t) for t in d["input_types"]),
            seed=d.get("seed", 12345),
            dtype=d.get("dtype", "float32"),
            updater=Updater.from_dict(d["updater"]),
            backprop_type=d.get("backprop_type", "standard"),
            tbptt_fwd_length=d.get("tbptt_fwd_length", 20),
            tbptt_back_length=d.get("tbptt_back_length", 20),
        )


class GraphBuilder:
    """Fluent DAG builder (reference ComputationGraphConfiguration.GraphBuilder,
    used by every zoo model — e.g. ResNet50.java:173 graphBuilder)."""

    def __init__(self, parent=None):
        self._parent = parent  # NeuralNetConfiguration Builder for defaults
        self._inputs: List[str] = []
        self._outputs: List[str] = []
        self._vertices: Dict[str, Tuple[object, Tuple[str, ...]]] = {}
        self._input_types: List[InputType] = []
        self._backprop_type = "standard"
        self._tbptt_length = 20

    def add_inputs(self, *names: str) -> "GraphBuilder":
        self._inputs.extend(names)
        return self

    def add_layer(self, name: str, layer: Layer, *inputs: str) -> "GraphBuilder":
        from deeplearning4j_tpu.nn.conf.network import _apply_layer_defaults
        if self._parent is not None:
            layer = _apply_layer_defaults(layer, self._parent._defaults)
        self._vertices[name] = (layer, tuple(inputs))
        return self

    def add_vertex(self, name: str, vertex: GraphVertex, *inputs: str) -> "GraphBuilder":
        self._vertices[name] = (vertex, tuple(inputs))
        return self

    def set_outputs(self, *names: str) -> "GraphBuilder":
        self._outputs = list(names)
        return self

    def set_input_types(self, *types: InputType) -> "GraphBuilder":
        self._input_types = list(types)
        return self

    def backprop_type(self, kind: str, fwd_length: int = 20,
                      back_length: Optional[int] = None) -> "GraphBuilder":
        """reference GraphBuilder.backpropType(...).tBPTTForwardLength(...);
        back_length must equal fwd_length (windows step by fwd_length)."""
        if kind not in ("standard", "tbptt"):
            raise ValueError(f"Unknown backprop_type '{kind}' "
                             "(expected 'standard' or 'tbptt')")
        if back_length is not None and back_length != fwd_length:
            raise ValueError(
                "tbptt back_length != fwd_length is not supported: got "
                f"fwd={fwd_length}, back={back_length}")
        self._backprop_type = kind
        self._tbptt_length = fwd_length
        return self

    def build(self) -> ComputationGraphConfiguration:
        seed = self._parent._seed if self._parent else 12345
        dtype = self._parent._dtype if self._parent else "float32"
        updater = self._parent._updater if self._parent else Sgd(learning_rate=0.1)
        conf = ComputationGraphConfiguration(
            network_inputs=tuple(self._inputs),
            vertices=dict(self._vertices),
            network_outputs=tuple(self._outputs),
            input_types=tuple(self._input_types),
            seed=seed, dtype=dtype, updater=updater,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_length,
            tbptt_back_length=self._tbptt_length,
        )
        conf.topological_order()  # validate DAG early
        return conf
