"""Activation functions.

Parity surface: ND4J ``org.nd4j.linalg.activations.Activation`` (external to the
reference repo but referenced from every layer config, e.g.
deeplearning4j-nn/.../nn/conf/layers/Layer.java activation fields). Each entry
is a pure jax function; autodiff replaces the hand-written backprop() of the
ND4J activation classes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _identity(x):
    return x


def _leakyrelu(x, alpha=0.01):
    return jnp.where(x >= 0, x, alpha * x)


def _thresholdedrelu(x, theta=1.0):
    return jnp.where(x > theta, x, 0.0)


def _rationaltanh(x):
    # ND4J RationalTanh: 1.7159 * tanh_approx(2x/3) with Padé-style approx;
    # we use the exact form the approximation targets.
    return 1.7159 * jnp.tanh(2.0 * x / 3.0)


def _rectifiedtanh(x):
    return jnp.maximum(0.0, jnp.tanh(x))


def _hardsigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


def _cube(x):
    return x ** 3


def _swish(x):
    return x * jax.nn.sigmoid(x)


ACTIVATIONS = {
    "identity": _identity,
    "linear": _identity,
    "relu": jax.nn.relu,
    "relu6": lambda x: jnp.clip(x, 0.0, 6.0),
    "leakyrelu": _leakyrelu,
    "thresholdedrelu": _thresholdedrelu,
    "elu": jax.nn.elu,
    "selu": jax.nn.selu,
    "gelu": jax.nn.gelu,
    "swish": _swish,
    "sigmoid": jax.nn.sigmoid,
    "hardsigmoid": _hardsigmoid,
    "tanh": jnp.tanh,
    "hardtanh": lambda x: jnp.clip(x, -1.0, 1.0),
    "rationaltanh": _rationaltanh,
    "rectifiedtanh": _rectifiedtanh,
    "softmax": lambda x: jax.nn.softmax(x, axis=-1),
    "logsoftmax": lambda x: jax.nn.log_softmax(x, axis=-1),
    "softplus": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "cube": _cube,
}


def get_activation(name):
    """Resolve an activation by name (case-insensitive) or pass through a callable."""
    if callable(name):
        return name
    key = str(name).lower()
    if key not in ACTIVATIONS:
        raise ValueError(f"Unknown activation '{name}'. Known: {sorted(ACTIVATIONS)}")
    return ACTIVATIONS[key]
