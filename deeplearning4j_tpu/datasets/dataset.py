"""DataSet / MultiDataSet containers.

Parity surface: ND4J ``org.nd4j.linalg.dataset.DataSet`` / ``MultiDataSet``
(external to the reference repo, but the currency of every ``fit``/iterator
API, e.g. MultiLayerNetwork.fit(DataSetIterator) —
deeplearning4j-nn/.../nn/multilayer/MultiLayerNetwork.java:1156).

Host-side arrays are numpy; transfer to device happens once per step inside
the jitted train program (minimising host->HBM traffic).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np


@dataclasses.dataclass
class DataSet:
    features: np.ndarray
    labels: np.ndarray
    features_mask: Optional[np.ndarray] = None
    labels_mask: Optional[np.ndarray] = None

    def num_examples(self) -> int:
        return int(self.features.shape[0])

    def shuffle(self, rng: np.random.Generator):
        idx = rng.permutation(self.num_examples())
        self.features = self.features[idx]
        self.labels = self.labels[idx]
        if self.features_mask is not None:
            self.features_mask = self.features_mask[idx]
        if self.labels_mask is not None:
            self.labels_mask = self.labels_mask[idx]

    def split(self, batch_size: int):
        n = self.num_examples()
        out = []
        for i in range(0, n, batch_size):
            sl = slice(i, min(i + batch_size, n))
            out.append(DataSet(
                self.features[sl], self.labels[sl],
                None if self.features_mask is None else self.features_mask[sl],
                None if self.labels_mask is None else self.labels_mask[sl],
            ))
        return out

    @staticmethod
    def merge(datasets: Sequence["DataSet"]) -> "DataSet":
        f = np.concatenate([d.features for d in datasets], axis=0)
        l = np.concatenate([d.labels for d in datasets], axis=0)
        fm = None
        lm = None
        if datasets[0].features_mask is not None:
            fm = np.concatenate([d.features_mask for d in datasets], axis=0)
        if datasets[0].labels_mask is not None:
            lm = np.concatenate([d.labels_mask for d in datasets], axis=0)
        return DataSet(f, l, fm, lm)


@dataclasses.dataclass
class MultiDataSet:
    """Multi-input/multi-output dataset (ComputationGraph currency)."""

    features: Sequence[np.ndarray]
    labels: Sequence[np.ndarray]
    features_masks: Optional[Sequence[Optional[np.ndarray]]] = None
    labels_masks: Optional[Sequence[Optional[np.ndarray]]] = None

    def num_examples(self) -> int:
        return int(self.features[0].shape[0])

    @staticmethod
    def from_dataset(ds: DataSet) -> "MultiDataSet":
        return MultiDataSet(
            [ds.features], [ds.labels],
            [ds.features_mask] if ds.features_mask is not None else None,
            [ds.labels_mask] if ds.labels_mask is not None else None,
        )
