from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet  # noqa: F401
from deeplearning4j_tpu.datasets.iterators import (  # noqa: F401
    DataSetIterator,
    ListDataSetIterator,
    AsyncDataSetIterator,
    IrisDataSetIterator,
    MnistDataSetIterator,
    EarlyTerminationDataSetIterator,
    CifarDataSetIterator,
    EmnistDataSetIterator,
    SvhnDataSetIterator,
    TinyImageNetDataSetIterator,
    LFWDataSetIterator,
    ExistingDataSetIterator,
    IteratorDataSetIterator,
    SamplingDataSetIterator,
    MultipleEpochsIterator,
    MultiDataSetIterator,
    ListMultiDataSetIterator,
    MultiDataSetIteratorAdapter,
    MultiDataSetWrapperIterator,
    JointMultiDataSetIterator,
    AsyncMultiDataSetIterator,
    EarlyTerminationMultiDataSetIterator,
)
from deeplearning4j_tpu.datasets.streaming import (  # noqa: F401
    StreamingDataSetIterator,
    StreamingHttpReceiver,
)
from deeplearning4j_tpu.datasets.sharded import (  # noqa: F401
    DataLeaseError,
    DataLeaseTimeout,
    StaleDataLeaseError,
    ShardedDataset,
    ShardedReader,
    ShardLeaseBoard,
    LedgerReport,
    reconcile_ledger,
)
from deeplearning4j_tpu.datasets.records import (  # noqa: F401
    RecordReader,
    CollectionRecordReader,
    CSVRecordReader,
    CSVSequenceRecordReader,
    CSVShardSource,
    RecordReaderDataSetIterator,
    RecordSource,
    SequenceRecordReaderDataSetIterator,
    ShardFileSource,
    write_shards,
)
from deeplearning4j_tpu.datasets.preprocessing import (  # noqa: F401
    DataSetPreProcessor,
    NormalizerStandardize,
    NormalizerMinMaxScaler,
    ImagePreProcessingScaler,
    CombinedPreProcessor,
)
