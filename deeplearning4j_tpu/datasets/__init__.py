from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet  # noqa: F401
from deeplearning4j_tpu.datasets.iterators import (  # noqa: F401
    DataSetIterator,
    ListDataSetIterator,
    AsyncDataSetIterator,
    IrisDataSetIterator,
    MnistDataSetIterator,
)
