"""DataSet iterators.

Parity surface: ND4J ``DataSetIterator`` + the reference's canonical iterators
(deeplearning4j-core/.../datasets/iterator/impl/: MnistDataSetIterator,
IrisDataSetIterator, ...) and the async prefetch wrapper
(deeplearning4j-nn/.../datasets/iterator/AsyncDataSetIterator.java).

Iterators are plain Python iterables of :class:`DataSet` with ``reset()``;
``AsyncDataSetIterator`` prefetches on a background thread so host ETL overlaps
device compute (same role as the reference's prefetch thread wrapped around
fit() at MultiLayerNetwork.java:1161).
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Iterator, List, Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.fetchers import iris_data, mnist_data


class DataSetIterator:
    """Base iterator (parity: org.nd4j.linalg.dataset.api.iterator.DataSetIterator)."""

    def __iter__(self) -> Iterator[DataSet]:
        self.reset()
        return self._generate()

    def _generate(self):
        raise NotImplementedError

    def reset(self):
        pass

    def batch_size(self) -> int:
        raise NotImplementedError

    # optional metadata used by networks for shape checks
    def input_columns(self) -> Optional[int]:
        return None

    def total_outcomes(self) -> Optional[int]:
        return None


class ListDataSetIterator(DataSetIterator):
    """Iterate over a pre-split list of DataSets (parity:
    org.nd4j.linalg.dataset.api.iterator.impl ListDataSetIterator)."""

    def __init__(self, data, batch: Optional[int] = None):
        if isinstance(data, DataSet):
            data = data.split(batch or data.num_examples())
        self._data: List[DataSet] = list(data)
        self._batch = batch or (self._data[0].num_examples() if self._data else 0)

    def _generate(self):
        yield from self._data

    def batch_size(self):
        return self._batch

    def input_columns(self):
        f = self._data[0].features
        return int(np.prod(f.shape[1:]))

    def total_outcomes(self):
        return int(self._data[0].labels.shape[-1])


class IrisDataSetIterator(ListDataSetIterator):
    """Iris fixture iterator (reference
    deeplearning4j-core/.../datasets/iterator/impl/IrisDataSetIterator.java).
    Data embedded (150 examples, 4 features, 3 one-hot classes), normalized."""

    def __init__(self, batch: int = 150, num_examples: int = 150, shuffle_seed: Optional[int] = 42):
        x, y = iris_data()
        if shuffle_seed is not None:
            rng = np.random.default_rng(shuffle_seed)
            idx = rng.permutation(len(x))
            x, y = x[idx], y[idx]
        x = x[:num_examples]
        y = y[:num_examples]
        super().__init__(DataSet(x, y), batch)


class MnistDataSetIterator(ListDataSetIterator):
    """MNIST iterator (reference
    deeplearning4j-core/.../datasets/iterator/impl/MnistDataSetIterator.java +
    fetchers/MnistDataFetcher.java).

    Features are flat (batch, 784) float32 in [0,1] like the reference's
    binarize=false path. In zero-egress environments (no download), a
    deterministic synthetic MNIST-shaped dataset is generated instead
    (class-conditional patterns + noise) so training/tests remain meaningful.
    """

    def __init__(self, batch: int = 128, num_examples: int = 60000, train: bool = True,
                 seed: int = 123):
        x, y = mnist_data(num_examples, train=train, seed=seed)
        super().__init__(DataSet(x, y), batch)

    def input_columns(self):
        return 784

    def total_outcomes(self):
        return 10


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch (reference AsyncDataSetIterator.java).

    On TPU the host ETL / device compute overlap matters just as it did for
    GPUs; a small bounded queue keeps memory in check.
    """

    _END = object()

    def __init__(self, base: DataSetIterator, queue_size: int = 4):
        self._base = base
        self._queue_size = queue_size

    def reset(self):
        if hasattr(self._base, "reset"):
            self._base.reset()

    def _generate(self):
        q: "queue.Queue" = queue.Queue(maxsize=self._queue_size)
        err = []

        def worker():
            try:
                for ds in self._base:
                    q.put(ds)
            except BaseException as e:  # propagate to consumer
                err.append(e)
            finally:
                q.put(self._END)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is self._END:
                break
            yield item
        t.join()
        if err:
            raise err[0]

    def batch_size(self):
        return self._base.batch_size()

    def input_columns(self):
        return self._base.input_columns()

    def total_outcomes(self):
        return self._base.total_outcomes()


class EarlyTerminationDataSetIterator(DataSetIterator):
    """Cap the number of minibatches (reference
    deeplearning4j-nn/.../datasets/iterator/EarlyTerminationDataSetIterator.java)."""

    def __init__(self, base: DataSetIterator, max_batches: int):
        self._base = base
        self._max = max_batches

    def reset(self):
        self._base.reset()

    def _generate(self):
        for i, ds in enumerate(self._base):
            if i >= self._max:
                break
            yield ds

    def batch_size(self):
        return self._base.batch_size()

    def input_columns(self):
        return self._base.input_columns()

    def total_outcomes(self):
        return self._base.total_outcomes()
