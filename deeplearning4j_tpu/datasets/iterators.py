"""DataSet iterators.

Parity surface: ND4J ``DataSetIterator`` + the reference's canonical iterators
(deeplearning4j-core/.../datasets/iterator/impl/: MnistDataSetIterator,
IrisDataSetIterator, ...) and the async prefetch wrapper
(deeplearning4j-nn/.../datasets/iterator/AsyncDataSetIterator.java).

Iterators are plain Python iterables of :class:`DataSet` with ``reset()``;
``AsyncDataSetIterator`` prefetches on a background thread so host ETL overlaps
device compute (same role as the reference's prefetch thread wrapped around
fit() at MultiLayerNetwork.java:1161).

``AsyncDataSetIterator`` covers only the HOST half of the overlap; the
device half — issuing batch N+1's ``jax.device_put`` while step N runs —
is :class:`~deeplearning4j_tpu.perf.prefetch.DevicePrefetchIterator`
(re-exported here lazily). The two compose, Async innermost::

    it = DevicePrefetchIterator(AsyncDataSetIterator(raw_iterator))
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Iterator, List, Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.fetchers import iris_data, mnist_data


class DataSetIterator:
    """Base iterator (parity: org.nd4j.linalg.dataset.api.iterator.DataSetIterator).

    ``set_pre_processor`` attaches a ``DataSet -> DataSet`` callable applied
    to every emitted batch (reference DataSetIterator.setPreProcessor)."""

    pre_processor = None

    def __iter__(self) -> Iterator[DataSet]:
        self.reset()
        gen = self._generate()
        if self.pre_processor is None:
            return gen
        return (self.pre_processor(ds) for ds in gen)

    def set_pre_processor(self, pre_processor):
        self.pre_processor = pre_processor
        return self

    def _generate(self):
        raise NotImplementedError

    def reset(self):
        pass

    def batch_size(self) -> int:
        raise NotImplementedError

    # optional metadata used by networks for shape checks
    def input_columns(self) -> Optional[int]:
        return None

    def total_outcomes(self) -> Optional[int]:
        return None


class ListDataSetIterator(DataSetIterator):
    """Iterate over a pre-split list of DataSets (parity:
    org.nd4j.linalg.dataset.api.iterator.impl ListDataSetIterator)."""

    def __init__(self, data, batch: Optional[int] = None):
        if isinstance(data, DataSet):
            data = data.split(batch or data.num_examples())
        self._data: List[DataSet] = list(data)
        self._batch = batch or (self._data[0].num_examples() if self._data else 0)

    def _generate(self):
        yield from self._data

    def batch_size(self):
        return self._batch

    def input_columns(self):
        f = self._data[0].features
        return int(np.prod(f.shape[1:]))

    def total_outcomes(self):
        return int(self._data[0].labels.shape[-1])


class IrisDataSetIterator(ListDataSetIterator):
    """Iris fixture iterator (reference
    deeplearning4j-core/.../datasets/iterator/impl/IrisDataSetIterator.java).
    Data embedded (150 examples, 4 features, 3 one-hot classes), normalized."""

    def __init__(self, batch: int = 150, num_examples: int = 150, shuffle_seed: Optional[int] = 42):
        x, y = iris_data()
        if shuffle_seed is not None:
            rng = np.random.default_rng(shuffle_seed)
            idx = rng.permutation(len(x))
            x, y = x[idx], y[idx]
        x = x[:num_examples]
        y = y[:num_examples]
        super().__init__(DataSet(x, y), batch)


class MnistDataSetIterator(ListDataSetIterator):
    """MNIST iterator (reference
    deeplearning4j-core/.../datasets/iterator/impl/MnistDataSetIterator.java +
    fetchers/MnistDataFetcher.java).

    Features are flat (batch, 784) float32 in [0,1] like the reference's
    binarize=false path. In zero-egress environments (no download), a
    deterministic synthetic MNIST-shaped dataset is generated instead
    (class-conditional patterns + noise) so training/tests remain meaningful.
    """

    def __init__(self, batch: int = 128, num_examples: int = 60000, train: bool = True,
                 seed: int = 123):
        x, y = mnist_data(num_examples, train=train, seed=seed)
        super().__init__(DataSet(x, y), batch)

    def input_columns(self):
        return 784

    def total_outcomes(self):
        return 10


def _async_generate(base, queue_size, end_sentinel):
    """Shared producer/consumer core for the async prefetch iterators.

    The producer checks a stop flag around every blocking put so an
    early-exiting consumer (break / EarlyTermination wrapper) releases the
    thread instead of leaving it blocked on a full queue holding the base
    iterator mid-stream."""
    q: "queue.Queue" = queue.Queue(maxsize=queue_size)
    stop = threading.Event()
    err = []

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for item in base:
                if not _put(item):
                    return
        except BaseException as e:  # propagate to consumer
            err.append(e)
        finally:
            _put(end_sentinel)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is end_sentinel:
                break
            yield item
    finally:
        stop.set()
        t.join()
    if err:
        raise err[0]


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch (reference AsyncDataSetIterator.java).

    On TPU the host ETL / device compute overlap matters just as it did for
    GPUs; a small bounded queue keeps memory in check.
    """

    _END = object()

    def __init__(self, base: DataSetIterator, queue_size: int = 4):
        self._base = base
        self._queue_size = queue_size

    def reset(self):
        if hasattr(self._base, "reset"):
            self._base.reset()

    def _generate(self):
        yield from _async_generate(self._base, self._queue_size, self._END)

    def batch_size(self):
        return self._base.batch_size()

    def input_columns(self):
        return self._base.input_columns()

    def total_outcomes(self):
        return self._base.total_outcomes()

    # seekable/epoch-aware base (datasets/sharded.py ShardedReader):
    # forward the resume/seek surface so exact-step resume still seeks
    # without materializing when the reader is wrapped for prefetch.
    # Via __getattr__ (not plain methods) so hasattr() on the wrapper
    # reflects whether the BASE actually supports seeking.
    def __getattr__(self, name):
        if name == "bind_epoch":
            base_bind = getattr(self._base, name)  # AttributeError if not

            def bind_epoch(provider):
                base_bind(provider)
                return self
            return bind_epoch
        if name == "iter_from":
            base_iter_from = getattr(self._base, name)

            def iter_from(start_batch):
                gen = _async_generate(base_iter_from(start_batch),
                                      self._queue_size, self._END)
                # a pre_processor set on THIS wrapper must apply on the
                # seek path exactly as __iter__ applies it
                if self.pre_processor is None:
                    return gen
                return (self.pre_processor(ds) for ds in gen)
            return iter_from
        raise AttributeError(
            f"{type(self).__name__!r} object has no attribute {name!r}")


class EarlyTerminationDataSetIterator(DataSetIterator):
    """Cap the number of minibatches (reference
    deeplearning4j-nn/.../datasets/iterator/EarlyTerminationDataSetIterator.java)."""

    def __init__(self, base: DataSetIterator, max_batches: int):
        self._base = base
        self._max = max_batches

    def reset(self):
        self._base.reset()

    def _generate(self):
        for i, ds in enumerate(self._base):
            if i >= self._max:
                break
            yield ds

    def batch_size(self):
        return self._base.batch_size()

    def input_columns(self):
        return self._base.input_columns()

    def total_outcomes(self):
        return self._base.total_outcomes()


# ------------------------------------------------------------- image corpora
class CifarDataSetIterator(ListDataSetIterator):
    """CIFAR-10 NHWC (reference datasets/iterator/impl/CifarDataSetIterator.java;
    real data when cached locally, see fetchers.cifar10_data)."""

    def __init__(self, batch: int = 128, num_examples: int = 50000,
                 train: bool = True, seed: int = 321):
        from deeplearning4j_tpu.datasets.fetchers import cifar10_data
        x, y = cifar10_data(num_examples, train=train, seed=seed)
        super().__init__(DataSet(x, y), batch)

    def total_outcomes(self):
        return 10


class EmnistDataSetIterator(ListDataSetIterator):
    """EMNIST splits (reference datasets/iterator/impl/EmnistDataSetIterator.java:53
    — COMPLETE/MERGE/BALANCED/LETTERS/DIGITS/MNIST sets)."""

    def __init__(self, split: str = "balanced", batch: int = 128,
                 num_examples: int = 10000, train: bool = True, seed: int = 555):
        from deeplearning4j_tpu.datasets.fetchers import emnist_data, emnist_num_classes
        x, y = emnist_data(split, num_examples, train=train, seed=seed)
        self.split = split
        self._classes = emnist_num_classes(split)
        super().__init__(DataSet(x, y), batch)

    @staticmethod
    def num_labels(split: str) -> int:
        from deeplearning4j_tpu.datasets.fetchers import emnist_num_classes
        return emnist_num_classes(split)

    def input_columns(self):
        return 784

    def total_outcomes(self):
        return self._classes


class SvhnDataSetIterator(ListDataSetIterator):
    """SVHN cropped digits (reference datasets/fetchers/SvhnDataFetcher.java)."""

    def __init__(self, batch: int = 128, num_examples: int = 10000,
                 train: bool = True, seed: int = 777):
        from deeplearning4j_tpu.datasets.fetchers import svhn_data
        x, y = svhn_data(num_examples, train=train, seed=seed)
        super().__init__(DataSet(x, y), batch)

    def total_outcomes(self):
        return 10


class TinyImageNetDataSetIterator(ListDataSetIterator):
    """TinyImageNet 64x64x3, 200 classes (reference TinyImageNetFetcher.java)."""

    def __init__(self, batch: int = 128, num_examples: int = 5000,
                 train: bool = True, seed: int = 999):
        from deeplearning4j_tpu.datasets.fetchers import tiny_imagenet_data
        x, y = tiny_imagenet_data(num_examples, train=train, seed=seed)
        super().__init__(DataSet(x, y), batch)

    def total_outcomes(self):
        return 200


class LFWDataSetIterator(ListDataSetIterator):
    """LFW faces (reference datasets/iterator/impl/LFWDataSetIterator.java)."""

    def __init__(self, batch: int = 64, num_examples: int = 1000,
                 train: bool = True, seed: int = 1111):
        from deeplearning4j_tpu.datasets.fetchers import lfw_data
        x, y = lfw_data(num_examples, train=train, seed=seed)
        self._classes = y.shape[1]
        super().__init__(DataSet(x, y), batch)

    def total_outcomes(self):
        return self._classes


# --------------------------------------------------- more generic adapters
class ExistingDataSetIterator(DataSetIterator):
    """Wrap any iterable of DataSets (reference ExistingDataSetIterator.java)."""

    def __init__(self, iterable):
        self._iterable = iterable

    def _generate(self):
        yield from self._iterable

    def reset(self):
        if hasattr(self._iterable, "reset"):
            self._iterable.reset()


class IteratorDataSetIterator(DataSetIterator):
    """Re-batch a stream of (possibly ragged) DataSets to a fixed minibatch
    size (reference IteratorDataSetIterator.java)."""

    def __init__(self, base, batch: int):
        self._base = base
        self._batch = batch

    def reset(self):
        if hasattr(self._base, "reset"):
            self._base.reset()

    def batch_size(self):
        return self._batch

    @staticmethod
    def _take(chunks: list, n: int) -> DataSet:
        """Assemble n rows from the head of the chunk queue; partial chunks
        stay as zero-copy views, so total copying is O(total rows)."""
        need = n
        fx, fy, ffm, flm = [], [], [], []
        while need > 0:
            x, y, fm, lm = chunks[0]
            take = min(need, len(x))
            fx.append(x[:take])
            fy.append(y[:take])
            ffm.append(None if fm is None else fm[:take])
            flm.append(None if lm is None else lm[:take])
            if take == len(x):
                chunks.pop(0)
            else:
                chunks[0] = (x[take:], y[take:],
                             None if fm is None else fm[take:],
                             None if lm is None else lm[take:])
            need -= take

        def cat(parts):
            if all(p is None for p in parts):
                return None
            if any(p is None for p in parts):
                raise ValueError(
                    "Cannot re-batch a mix of masked and unmasked DataSets")
            return np.concatenate(parts)

        return DataSet(np.concatenate(fx), np.concatenate(fy),
                       cat(ffm), cat(flm))

    def _generate(self):
        chunks, count = [], 0
        for ds in self._base:
            chunks.append((ds.features, ds.labels,
                           ds.features_mask, ds.labels_mask))
            count += ds.num_examples()
            while count >= self._batch:
                yield self._take(chunks, self._batch)
                count -= self._batch
        if count:
            yield self._take(chunks, count)


class SamplingDataSetIterator(DataSetIterator):
    """Sample minibatches with replacement from a source DataSet (reference
    SamplingDataSetIterator.java)."""

    def __init__(self, source: DataSet, batch: int, num_samples: int,
                 seed: int = 123):
        self._source = source
        self._batch = batch
        self._num_samples = num_samples
        self._seed = seed
        self._pass = 0  # distinct draws every epoch

    def batch_size(self):
        return self._batch

    def _generate(self):
        rng = np.random.default_rng(self._seed + self._pass)
        self._pass += 1
        n = self._source.num_examples()
        # ceil: emit at least num_samples samples
        for _ in range(-(-self._num_samples // self._batch)):
            idx = rng.integers(0, n, self._batch)
            yield DataSet(
                self._source.features[idx], self._source.labels[idx],
                None if self._source.features_mask is None
                else self._source.features_mask[idx],
                None if self._source.labels_mask is None
                else self._source.labels_mask[idx])


class MultipleEpochsIterator(DataSetIterator):
    """Replay a base iterator N times as one pass (reference
    MultipleEpochsIterator.java)."""

    def __init__(self, epochs: int, base: DataSetIterator):
        self._epochs = epochs
        self._base = base

    def reset(self):
        self._base.reset()

    def batch_size(self):
        return self._base.batch_size()

    def _generate(self):
        for _ in range(self._epochs):
            self._base.reset()
            yield from self._base


# ------------------------------------------------------ MultiDataSet family
class MultiDataSetIterator:
    """Base multi-input/multi-output iterator (parity:
    org.nd4j.linalg.dataset.api.iterator.MultiDataSetIterator — the currency
    of ComputationGraph.fit)."""

    def __iter__(self):
        return self._generate()

    def _generate(self):
        raise NotImplementedError

    def reset(self):
        pass


class ListMultiDataSetIterator(MultiDataSetIterator):
    """Minibatch a MultiDataSet or list of them."""

    def __init__(self, data, batch: Optional[int] = None):
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet
        if isinstance(data, MultiDataSet):
            if batch is None:
                data = [data]
            else:
                n = data.num_examples()
                data = [
                    MultiDataSet(
                        [f[i:i + batch] for f in data.features],
                        [l[i:i + batch] for l in data.labels],
                        None if data.features_masks is None else
                        [None if m is None else m[i:i + batch]
                         for m in data.features_masks],
                        None if data.labels_masks is None else
                        [None if m is None else m[i:i + batch]
                         for m in data.labels_masks])
                    for i in range(0, n, batch)]
        self._data = list(data)

    def _generate(self):
        yield from self._data


class MultiDataSetIteratorAdapter(MultiDataSetIterator):
    """DataSetIterator → MultiDataSetIterator (reference
    MultiDataSetIteratorAdapter.java)."""

    def __init__(self, base: DataSetIterator):
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet
        self._base = base
        self._mds = MultiDataSet

    def reset(self):
        self._base.reset()

    def _generate(self):
        for ds in self._base:
            yield self._mds.from_dataset(ds)


class MultiDataSetWrapperIterator(DataSetIterator):
    """MultiDataSetIterator → DataSetIterator for single-in/single-out graphs
    (reference MultiDataSetWrapperIterator.java)."""

    def __init__(self, base: MultiDataSetIterator):
        self._base = base

    def reset(self):
        self._base.reset()

    def _generate(self):
        for mds in self._base:
            if len(mds.features) != 1 or len(mds.labels) != 1:
                raise ValueError(
                    "MultiDataSetWrapperIterator needs single-input/"
                    f"single-output data; got {len(mds.features)} inputs")
            fm = mds.features_masks[0] if mds.features_masks else None
            lm = mds.labels_masks[0] if mds.labels_masks else None
            yield DataSet(mds.features[0], mds.labels[0], fm, lm)


class JointMultiDataSetIterator(MultiDataSetIterator):
    """Zip several DataSetIterators into one MultiDataSet stream (reference
    JointMultiDataSetIterator.java): input i / label i come from iterator i;
    with ``output_index`` set, labels come from that single iterator."""

    def __init__(self, *iterators: DataSetIterator,
                 output_index: Optional[int] = None):
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet
        self._its = iterators
        self._out = output_index
        self._mds = MultiDataSet

    def reset(self):
        for it in self._its:
            it.reset()

    def _generate(self):
        for group in zip(*self._its):
            feats = [ds.features for ds in group]
            fmasks = [ds.features_mask for ds in group]
            if self._out is None:
                labels = [ds.labels for ds in group]
                lmasks = [ds.labels_mask for ds in group]
            else:
                labels = [group[self._out].labels]
                lmasks = [group[self._out].labels_mask]
            any_fm = any(m is not None for m in fmasks)
            any_lm = any(m is not None for m in lmasks)
            yield self._mds(feats, labels,
                            fmasks if any_fm else None,
                            lmasks if any_lm else None)


class AsyncMultiDataSetIterator(MultiDataSetIterator):
    """Background prefetch for MultiDataSets (reference
    AsyncMultiDataSetIterator.java) — same bounded-queue overlap as
    AsyncDataSetIterator."""

    _END = object()

    def __init__(self, base: MultiDataSetIterator, queue_size: int = 4):
        self._base = base
        self._queue_size = queue_size

    def reset(self):
        self._base.reset()

    def _generate(self):
        yield from _async_generate(self._base, self._queue_size, self._END)


class EarlyTerminationMultiDataSetIterator(MultiDataSetIterator):
    """Cap minibatches (reference EarlyTerminationMultiDataSetIterator.java)."""

    def __init__(self, base: MultiDataSetIterator, max_batches: int):
        self._base = base
        self._max = max_batches

    def reset(self):
        self._base.reset()

    def _generate(self):
        for i, mds in enumerate(self._base):
            if i >= self._max:
                break
            yield mds


def __getattr__(name):
    # lazy re-export (PEP 562): perf.prefetch imports DataSetIterator from
    # this module, so an eager import here would be circular
    if name == "DevicePrefetchIterator":
        from deeplearning4j_tpu.perf.prefetch import DevicePrefetchIterator
        return DevicePrefetchIterator
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
