"""Streaming data plane: sharded, lease-based ETL with fleet-true
exactly-once resume.

Training input used to be per-process host iterators: kill-and-resume
relied on ``ResumeState`` skipping consumed batches *within one process*,
so an elastic N→M reshard could silently replay or drop records. This
module makes the input tier a first-class distributed service — the
TPU-native equivalent of the reference's DataVec + Spark distributed
record readers feeding cluster DP (SURVEY L3 / §3.4):

- **Deterministic distributed shuffle.** Records are grouped into
  ``num_shards`` contiguous shards; an epoch's global order is a seeded
  permutation of shard indices (plus a per-shard seeded permutation
  within each shard), derived ONLY from ``(seed, epoch)`` — never from
  the world size, the worker, or global RNG state. The same seed
  therefore yields a bitwise-identical epoch record order at ANY world
  size, which is what makes an elastic N→M reshard mathematically
  invisible to training: global batch ``b`` holds the same records
  whether 1, 2 or 4 workers slice it.

- **Record-range leases over the StorageBackend.** Each worker claims a
  lease on the row-range it is about to consume (per
  ``lease_batches``-sized chunk of the epoch), through the SAME storage
  medium and freshness-under-TTL idiom as the elastic membership
  protocol (parallel/elastic.py) — read-back convergence, no
  compare-and-swap required, idempotent under ``RetryingBackend``
  retries (a retried put rewrites OUR claim; the read-back confirms it,
  so a transient fault can never double-claim a range). A fresh foreign
  lease whose row-slice overlaps ours means contention: a claim from a
  LATER generation proves we are the stale side of a membership change
  (:class:`StaleDataLeaseError` — the data-plane analogue of the
  checkpoint generation fence), an equal-or-older one is waited out
  bounded by the TTL (a SIGKILLed worker's lease simply expires).

- **Fleet-true exactly-once resume.** The reader is SEEKABLE:
  ``iter_from(batch)`` starts an epoch pass at any global batch index
  without materializing, staging or transferring the skipped records.
  ``checkpoint.manager.skip_consumed_batches`` uses it automatically, so
  a restore at ``(epoch e, batch k)`` — recorded by every checkpoint as
  ``batch_in_epoch`` — resumes by *seeking*, replaying ZERO consumed
  batches even when the restoring fleet has a different world size.
  ``bind_epoch`` ties the shuffle epoch to ``model.epoch`` (every fit
  wire-in binds it), so a restored model's reader reproduces exactly the
  interrupted epoch's order.

- **Per-record consumption ledger** (optional, chaos proof): each
  yielded batch writes an idempotent, keyed ledger object naming the
  exact records handed to the training loop.
  :func:`reconcile_ledger` reassembles the authoritative per-epoch
  record sequence (highest generation wins for a batch whose first
  training attempt was rolled back by a restore) and reports duplicates,
  gaps and contested batches — the artifact the 4→3 SIGKILL acceptance
  test asserts "no record seen twice / none dropped" against.

Stall attribution rides the existing ``train.data_wait`` spans (every
fit loop wraps its stream); the reader additionally exports lease-claim
latency, conflict counts and records-consumed through the obs registry.

Composition: a :class:`ShardedReader` is an ordinary
``DataSetIterator`` — wrap it in ``AsyncDataSetIterator`` for
host-thread prefetch and/or ``DevicePrefetchIterator`` for device
staging (both forward ``iter_from``/``bind_epoch``); build the dataset
from any live feed with :meth:`ShardedDataset.from_iterator` (e.g. a
``StreamingDataSetIterator`` segment pushed by an external producer).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import time
import uuid
from collections import OrderedDict
from typing import Callable, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator

log = logging.getLogger(__name__)

DATA_LEASE_PREFIX = "dlease-"
LEDGER_PREFIX = "dledger-"

__all__ = [
    "DataLeaseError", "DataLeaseTimeout", "StaleDataLeaseError",
    "ShardedDataset", "ShardedReader", "ShardLeaseBoard",
    "LedgerReport", "reconcile_ledger",
    "DATA_LEASE_PREFIX", "LEDGER_PREFIX",
]


class DataLeaseError(RuntimeError):
    """Base class for data-plane lease failures."""


class DataLeaseTimeout(DataLeaseError):
    """A conflicting fresh lease did not clear within the claim deadline
    (a live foreign worker is consuming our range — systematic
    double-assignment, not a transient)."""


class StaleDataLeaseError(DataLeaseError):
    """A LATER-generation worker holds an overlapping range: this worker
    is the stale side of a membership change and must stop consuming —
    the data-plane analogue of the checkpoint generation fence."""


def _block_nbytes(block: dict) -> int:
    return sum(a.nbytes for a in block.values() if a is not None)


# ---------------------------------------------------------------- the plan
def _epoch_rng(*entropy: int) -> np.random.Generator:
    # seeded, instance-scoped RNG only: global-state shuffles here are the
    # deterministic-epoch hazard lint rule DLT011 exists to catch
    return np.random.default_rng([0xD17A, *[int(e) for e in entropy]])


class ShardedDataset:
    """Sharded view over an in-memory OR file-backed record source (see
    module docstring). In-memory: ``features``/``labels`` are indexable
    row arrays and ``num_shards`` defaults to about one shard per batch.
    File-backed: pass ``source=`` (a ``datasets.records.RecordSource`` —
    shard files in any StorageBackend, the lake included) and the shard
    layout IS the source's file layout; shard blocks are loaded lazily
    into an LRU of at most ``max_resident_shards``, so host RAM is
    bounded by in-flight shards, not the corpus
    (``peak_resident_bytes``/``resident_bytes()`` account for it). Both
    modes produce the identical epoch plan for the same
    ``(seed, epoch, shard layout)`` — shuffle, leases, seek and ledger
    semantics operate on row indices and do not know where rows live.

    ``store`` (any checkpoint/storage.py backend, or a directory path)
    enables the lease protocol; ``ledger=True`` additionally writes the
    per-record consumption ledger (chaos/audit runs — one small object
    put per batch per worker). Without a store the reader is a plain
    deterministic sharded iterator.

    ``fetch_hook(epoch, batch)`` — when set — runs before a batch is
    sliced, ledgered or yielded: the chaos tests SIGKILL the process
    there, the exact "between steps" shape of a real preemption."""

    def __init__(self, features=None, labels=None, *, batch_size: int,
                 num_shards: Optional[int] = None, seed: int = 0,
                 shuffle_within_shard: bool = True,
                 store=None, ledger: bool = False,
                 lease_batches: int = 8, lease_ttl_s: float = 10.0,
                 lease_wait_s: float = 30.0,
                 features_mask=None, labels_mask=None,
                 source=None, max_resident_shards: int = 8,
                 clock: Callable[[], float] = time.time):
        self.source = source
        self._resident: "OrderedDict[int, dict]" = OrderedDict()
        self.max_resident_shards = max(1, int(max_resident_shards))
        self.shard_loads = 0
        self.shard_hits = 0
        self.shard_evictions = 0
        self.peak_resident_bytes = 0
        self._resident_bytes = 0
        if source is not None:
            if features is not None or labels is not None:
                raise ValueError("pass arrays OR source=, not both")
            if num_shards is not None:
                raise ValueError("with source=, the shard layout IS the "
                                 "source's file layout — num_shards is "
                                 "not a free parameter")
            self.features = None
            self.labels = None
            self.features_mask = None
            self.labels_mask = None
            sizes = [int(s) for s in source.shard_sizes]
            if not sizes or any(s < 1 for s in sizes):
                raise ValueError(f"source has invalid shard sizes {sizes}")
            n = sum(sizes)
            self.num_shards = len(sizes)
            self._offsets = np.cumsum([0] + sizes).astype(np.int64)
            self._shards = [np.arange(self._offsets[i], self._offsets[i + 1],
                                      dtype=np.int64)
                            for i in range(len(sizes))]
        else:
            self.features = np.asarray(features)
            self.labels = None if labels is None else np.asarray(labels)
            self.features_mask = (None if features_mask is None
                                  else np.asarray(features_mask))
            self.labels_mask = (None if labels_mask is None
                                else np.asarray(labels_mask))
            n = int(self.features.shape[0])
        if batch_size < 1 or batch_size > n:
            raise ValueError(f"batch_size {batch_size} must be in [1, {n}]")
        self.batch_size = int(batch_size)
        self.num_records = n
        if source is None:
            # one shard ≈ one batch by default: shard-level permutation
            # then moves whole batch-sized blocks, the classic shuffle
            # granularity
            self.num_shards = int(num_shards) if num_shards is not None \
                else max(1, n // self.batch_size)
            if not (1 <= self.num_shards <= n):
                raise ValueError(f"num_shards {self.num_shards} must be in "
                                 f"[1, {n}]")
            self._shards = np.array_split(np.arange(n, dtype=np.int64),
                                          self.num_shards)
        self.seed = int(seed)
        self.shuffle_within_shard = bool(shuffle_within_shard)
        self.lease_batches = max(1, int(lease_batches))
        self.lease_ttl_s = float(lease_ttl_s)
        self.lease_wait_s = float(lease_wait_s)
        self.ledger = bool(ledger)
        self.clock = clock
        self.fetch_hook: Optional[Callable[[int, int], None]] = None
        if store is None:
            self.store = None
        else:
            from deeplearning4j_tpu.checkpoint.storage import as_backend
            self.store = as_backend(store)
        if self.ledger and self.store is None:
            raise ValueError("ledger=True needs a store to write it to")

    # ------------------------------------------------------------ builders
    @classmethod
    def from_dataset(cls, ds: DataSet, **kwargs) -> "ShardedDataset":
        return cls(ds.features, ds.labels,
                   features_mask=ds.features_mask,
                   labels_mask=ds.labels_mask, **kwargs)

    @classmethod
    def from_iterator(cls, iterator, **kwargs) -> "ShardedDataset":
        """Drain any DataSet iterable (a ``StreamingDataSetIterator``
        segment included) into an indexable record source — the bridge
        from push-driven ingestion to the seekable sharded plan."""
        fx, fy, ffm, flm = [], [], [], []
        for ds in iterator:
            fx.append(np.asarray(ds.features))
            fy.append(None if ds.labels is None else np.asarray(ds.labels))
            ffm.append(None if ds.features_mask is None
                       else np.asarray(ds.features_mask))
            flm.append(None if ds.labels_mask is None
                       else np.asarray(ds.labels_mask))
        if not fx:
            raise ValueError("from_iterator drained an empty stream")

        def cat(parts):
            if all(p is None for p in parts):
                return None
            if any(p is None for p in parts):
                raise ValueError("from_iterator got a mix of present and "
                                 "absent labels/masks across batches")
            return np.concatenate(parts)

        return cls(np.concatenate(fx), cat(fy), features_mask=cat(ffm),
                   labels_mask=cat(flm), **kwargs)

    @classmethod
    def from_source(cls, source, **kwargs) -> "ShardedDataset":
        """A lazily-loaded dataset over shard files
        (``datasets.records.RecordSource``) — the data-lake entry point:
        ``from_source(ShardFileSource(cloud_backend, "corpus/"), ...)``."""
        return cls(source=source, **kwargs)

    # ------------------------------------------------------------- shapes
    @property
    def feature_shape(self) -> tuple:
        """Per-record feature shape, known without loading any shard."""
        if self.source is not None:
            return tuple(self.source.feature_shape)
        return tuple(self.features.shape[1:])

    @property
    def label_width(self) -> Optional[int]:
        """Trailing label dimension, or None for an unlabeled corpus."""
        if self.source is not None:
            shape = self.source.label_shape
            return None if shape is None else int(shape[-1])
        if self.labels is None:
            return None
        return int(self.labels.shape[-1])

    # ---------------------------------------------------------- residency
    def resident_bytes(self) -> int:
        """Host bytes currently pinned by loaded shard blocks — the
        number the >RSS-budget acceptance test asserts stays a small
        multiple of shard size while the corpus is orders larger."""
        return self._resident_bytes

    def _shard_block(self, shard: int) -> dict:
        shard = int(shard)
        blk = self._resident.get(shard)
        if blk is not None:
            self._resident.move_to_end(shard)
            self.shard_hits += 1
            return blk
        blk = self.source.load_shard(shard)
        self.shard_loads += 1
        self._resident[shard] = blk
        self._resident_bytes += _block_nbytes(blk)
        while len(self._resident) > self.max_resident_shards:
            _, old = self._resident.popitem(last=False)
            self._resident_bytes -= _block_nbytes(old)
            self.shard_evictions += 1
        self.peak_resident_bytes = max(self.peak_resident_bytes,
                                       self._resident_bytes)
        return blk

    def _take_lazy(self, records: np.ndarray) -> DataSet:
        """Gather rows spanning shard files, preserving record order. A
        batch under the shard-block shuffle touches ~⌈batch/shard⌉+1
        shards, so the LRU makes this sequential-ish I/O, not random."""
        recs = np.asarray(records, dtype=np.int64)
        shard_ids = np.searchsorted(self._offsets, recs, side="right") - 1
        local = recs - self._offsets[shard_ids]
        fields: Dict[str, Optional[np.ndarray]] = {}
        for shard in np.unique(shard_ids):
            blk = self._shard_block(int(shard))
            mask = shard_ids == shard
            rows = local[mask]
            for f in ("features", "labels", "features_mask", "labels_mask"):
                src = blk.get(f)
                if src is None:
                    if fields.get(f) is not None:
                        raise ValueError(
                            f"shard {shard} of {self.source.describe()} "
                            f"lacks {f} that earlier shards have")
                    fields.setdefault(f, None)
                    continue
                out = fields.get(f)
                if out is None:
                    out = fields[f] = np.empty(
                        (len(recs),) + src.shape[1:], dtype=src.dtype)
                out[mask] = src[rows]
        return DataSet(fields["features"], fields.get("labels"),
                       features_mask=fields.get("features_mask"),
                       labels_mask=fields.get("labels_mask"))

    # ---------------------------------------------------------------- plan
    @property
    def num_batches(self) -> int:
        """Full global batches per epoch (a ragged tail is dropped — the
        static-shape contract; pad upstream via perf.bucketing to keep a
        tail)."""
        return self.num_records // self.batch_size

    def epoch_order(self, epoch: int) -> np.ndarray:
        """The epoch's global record order — a pure function of
        ``(seed, epoch)``, identical at any world size."""
        perm = _epoch_rng(self.seed, epoch).permutation(self.num_shards)
        parts = []
        for s in perm:
            idx = self._shards[int(s)]
            if self.shuffle_within_shard:
                idx = idx[_epoch_rng(self.seed, epoch, int(s))
                          .permutation(len(idx))]
            parts.append(idx)
        return np.concatenate(parts)

    def batch_records(self, epoch: int, batch: int) -> np.ndarray:
        order = self.epoch_order(epoch)
        return order[batch * self.batch_size:(batch + 1) * self.batch_size]

    # -------------------------------------------------------------- reader
    def reader(self, rank: int = 0, world: int = 1,
               worker_id: Optional[str] = None,
               generation: int = 0) -> "ShardedReader":
        """This worker's view of the plan: the ``rank``-th row-slice of
        every global batch, lease-claimed chunk by chunk when a store is
        configured."""
        return ShardedReader(self, rank=rank, world=world,
                             worker_id=worker_id, generation=generation)

    def take(self, records: np.ndarray) -> DataSet:
        if self.source is not None:
            return self._take_lazy(records)
        return DataSet(
            self.features[records],
            None if self.labels is None else self.labels[records],
            features_mask=None if self.features_mask is None
            else self.features_mask[records],
            labels_mask=None if self.labels_mask is None
            else self.labels_mask[records])


# ================================================================== leases
def _slices_overlap(r1: int, w1: int, r2: int, w2: int) -> bool:
    """Whether rank r1's slice of a batch at world w1 intersects rank
    r2's at world w2 (exact integer cross-multiplication on the
    [r/w, (r+1)/w) fractions)."""
    return r1 * w2 < (r2 + 1) * w1 and r2 * w1 < (r1 + 1) * w2


class ShardLeaseBoard:
    """Record-range claims over the store (same lease idiom as
    parallel/elastic.py's LeaseBoard: freshness under a TTL, read-back
    convergence, no compare-and-swap).

    A claim is ``dlease-e<epoch>-c<chunk>-<worker>`` holding
    ``{worker, incarnation, rank, world, generation, time}``; claiming
    lists the chunk's prefix and treats any FRESH foreign lease whose
    row-slice overlaps ours as contention (wait bounded by
    ``wait_s``; a later-generation claimant raises
    :class:`StaleDataLeaseError` immediately). Puts are idempotent per
    worker — a ``RetryingBackend`` retry rewrites the same claim and the
    read-back confirms it, so transient storage faults cannot
    double-claim a range."""

    def __init__(self, store, worker_id: str, *, ttl_s: float = 10.0,
                 wait_s: float = 30.0, poll_s: float = 0.05,
                 clock: Callable[[], float] = time.time,
                 sleep: Callable[[float], None] = time.sleep):
        from deeplearning4j_tpu.checkpoint.storage import as_backend
        self.store = as_backend(store)
        self.worker_id = str(worker_id)
        self.incarnation = uuid.uuid4().hex[:12]
        self.ttl_s = float(ttl_s)
        self.wait_s = float(wait_s)
        self.poll_s = float(poll_s)
        self.clock = clock
        self.sleep = sleep
        self._held: Dict[str, str] = {}  # name -> chunk key, for release
        self.claims = 0
        self.conflicts_waited = 0
        # obs: lease-claim latency is the data plane's availability cost;
        # conflicts are the signal a reshard (or a zombie) is in flight
        from deeplearning4j_tpu.obs.registry import get_registry
        reg = get_registry()
        self._m_claim_ms = reg.histogram(
            "data_plane_lease_claim_ms", unit="ms",
            help="wall time to claim one record-range lease (list + "
                 "conflict scan + put + read-back)")
        self._m_conflicts = reg.counter(
            "data_plane_lease_conflicts_total", unit="conflicts",
            help="fresh overlapping foreign leases encountered while "
                 "claiming record ranges")

    @staticmethod
    def _chunk_prefix(epoch: int, chunk: int) -> str:
        return f"{DATA_LEASE_PREFIX}e{epoch:04d}-c{chunk:06d}-"

    def _fresh(self, rec: dict) -> bool:
        return (self.clock() - float(rec.get("time", 0))) <= self.ttl_s

    def _conflicts(self, epoch: int, chunk: int, rank: int, world: int,
                   generation: int) -> List[dict]:
        out = []
        prefix = self._chunk_prefix(epoch, chunk)
        for name in self.store.list(prefix=prefix):
            try:
                rec = json.loads(self.store.get(name).decode())
            except Exception as e:
                # unreadable lease = expired/absent (elastic.py precedent)
                log.warning("unreadable data lease %s (%s: %s)", name,
                            type(e).__name__, e)
                continue
            if rec.get("worker") == self.worker_id:
                continue  # our own claim (or an older incarnation of us)
            if not self._fresh(rec):
                continue
            if _slices_overlap(rank, world, int(rec.get("rank", 0)),
                               int(rec.get("world", 1))):
                if int(rec.get("generation", 0)) > generation:
                    raise StaleDataLeaseError(
                        f"{self.worker_id} (gen {generation}) found a "
                        f"gen-{rec.get('generation')} lease by "
                        f"{rec.get('worker')} on epoch {epoch} chunk "
                        f"{chunk} — this worker is stale; stop consuming")
                out.append(rec)
        return out

    def claim(self, epoch: int, chunk: int, rank: int, world: int,
              generation: int = 0) -> str:
        """Claim ``(epoch, chunk)`` for our row-slice; returns the lease
        object name. Blocks (bounded) while a fresh overlapping foreign
        lease exists — a dead claimant's lease simply expires."""
        t0 = time.perf_counter()
        deadline = self.clock() + self.wait_s
        waited = False
        while True:
            others = self._conflicts(epoch, chunk, rank, world, generation)
            if not others:
                break
            if not waited:
                waited = True
                self.conflicts_waited += 1
                self._m_conflicts.inc()
                from deeplearning4j_tpu.obs.trace import get_tracer
                get_tracer().event(
                    "data_plane.lease_wait", epoch=epoch, chunk=chunk,
                    holders=[o.get("worker") for o in others])
            if self.clock() > deadline:
                raise DataLeaseTimeout(
                    f"{self.worker_id}: record-range lease for epoch "
                    f"{epoch} chunk {chunk} still held by "
                    f"{[o.get('worker') for o in others]} after "
                    f"{self.wait_s:.0f}s — overlapping LIVE consumers "
                    "mean the fleet double-assigned a range")
            self.sleep(self.poll_s)
        name = self._chunk_prefix(epoch, chunk) + self.worker_id
        rec = {"worker": self.worker_id, "incarnation": self.incarnation,
               "rank": int(rank), "world": int(world),
               "generation": int(generation), "time": self.clock()}
        self.store.put(name, json.dumps(rec).encode())
        # read-back convergence: confirm the store holds OUR claim (a
        # retried put that actually landed twice is still just ours)
        back = json.loads(self.store.get(name).decode())
        if back.get("worker") != self.worker_id:
            raise DataLeaseError(
                f"lease read-back for {name} returned a claim by "
                f"{back.get('worker')!r}")
        self._held[name] = f"e{epoch}c{chunk}"
        self.claims += 1
        self._m_claim_ms.observe((time.perf_counter() - t0) * 1000.0)
        return name

    def release(self, name: str):
        self._held.pop(name, None)
        try:
            self.store.delete(name)
        except Exception as e:
            log.warning("data lease release %s failed (%s: %s)", name,
                        type(e).__name__, e)

    def release_all(self):
        """Best-effort release of every lease this board still holds —
        peers need not wait a TTL after a clean generation end."""
        for name in list(self._held):
            self.release(name)


# ================================================================== reader
class ShardedReader(DataSetIterator):
    """One worker's lease-claimed, seekable view of a
    :class:`ShardedDataset` (see module docstring). Yields the
    ``rank``-th row-slice of every global batch of the current epoch;
    re-iterating yields the next epoch (or whatever ``bind_epoch``'s
    provider says the epoch now is)."""

    def __init__(self, dataset: ShardedDataset, rank: int = 0,
                 world: int = 1, worker_id: Optional[str] = None,
                 generation: int = 0):
        if world < 1 or not (0 <= rank < world):
            raise ValueError(f"rank {rank} out of range for world {world}")
        if dataset.batch_size % world:
            raise ValueError(
                f"global batch {dataset.batch_size} not divisible by "
                f"world {world} — every worker must take an equal "
                "row-slice (the ClusterTrainer equal-shard contract)")
        self.dataset = dataset
        self.rank = int(rank)
        self.world = int(world)
        self.generation = int(generation)
        self.worker_id = (str(worker_id) if worker_id is not None
                          else f"r{rank:03d}of{world:03d}-"
                               f"{uuid.uuid4().hex[:8]}")
        self._epoch_provider: Optional[Callable[[], int]] = None
        self._auto_epoch = 0
        self.batches_yielded = 0
        self.records_yielded = 0
        self.leases = None
        if dataset.store is not None:
            self.leases = ShardLeaseBoard(
                dataset.store, self.worker_id, ttl_s=dataset.lease_ttl_s,
                wait_s=dataset.lease_wait_s, clock=dataset.clock)
        from deeplearning4j_tpu.obs.registry import get_registry
        reg = get_registry()
        self._m_records = reg.counter(
            "data_plane_records_total", unit="records",
            help="records handed to the training loop by sharded readers "
                 "(process-local rows)")
        self._m_batches = reg.counter(
            "data_plane_batches_total", unit="batches",
            help="local batches yielded by sharded readers")
        self._m_ledger_writes = reg.counter(
            "data_plane_ledger_writes_total", unit="writes",
            help="consumption-ledger objects written (ledger-enabled "
                 "runs only)")

    # ----------------------------------------------------------- epoching
    def bind_epoch(self, provider: Callable[[], int]) -> "ShardedReader":
        """Tie the shuffle epoch to an external counter — every fit
        wire-in binds ``lambda: model.epoch``, so a restored model's
        reader reproduces the interrupted epoch exactly."""
        self._epoch_provider = provider
        return self

    def current_epoch(self) -> int:
        if self._epoch_provider is not None:
            return int(self._epoch_provider())
        return self._auto_epoch

    # ---------------------------------------------------------- iteration
    def batch_size(self) -> int:
        return self.dataset.batch_size // self.world

    def input_columns(self):
        return int(np.prod(self.dataset.feature_shape))

    def total_outcomes(self):
        return self.dataset.label_width

    def _generate(self):
        # raw stream: DataSetIterator.__iter__ applies pre_processor
        return self._iter_raw(0)

    def iter_from(self, start_batch: int):
        """One epoch pass beginning at global batch ``start_batch`` —
        the seek primitive exact-step resume uses: nothing before
        ``start_batch`` is fetched, sliced, ledgered or transferred.
        Applies the reader's ``pre_processor`` exactly like plain
        iteration does, so a resumed epoch's remainder sees the same
        transform as every other epoch."""
        gen = self._iter_raw(start_batch)
        if self.pre_processor is None:
            return gen
        return (self.pre_processor(d) for d in gen)

    def _iter_raw(self, start_batch: int):
        ds = self.dataset
        nb = ds.num_batches
        if start_batch > nb:
            raise ValueError(
                f"cannot seek to batch {start_batch}: the epoch has only "
                f"{nb} full batches — the resume cursor outran the data "
                "(changed dataset between runs?)")
        epoch = self.current_epoch()
        order = ds.epoch_order(epoch)
        local = self.batch_size()
        lo = self.rank * local
        held: Optional[str] = None
        try:
            for b in range(start_batch, nb):
                if self.leases is not None \
                        and (held is None or b % ds.lease_batches == 0):
                    prev, held = held, self.leases.claim(
                        epoch, b // ds.lease_batches, self.rank,
                        self.world, self.generation)
                    if prev is not None:
                        self.leases.release(prev)
                if ds.fetch_hook is not None:
                    ds.fetch_hook(epoch, b)
                recs = order[b * ds.batch_size + lo:
                             b * ds.batch_size + lo + local]
                if ds.ledger:
                    self._write_ledger(epoch, b, recs)
                self.batches_yielded += 1
                self.records_yielded += len(recs)
                self._m_batches.inc()
                self._m_records.inc(len(recs))
                yield ds.take(recs)
        finally:
            if held is not None and self.leases is not None:
                self.leases.release(held)
        if self._epoch_provider is None:
            self._auto_epoch += 1

    def _write_ledger(self, epoch: int, batch: int, records: np.ndarray):
        """Keyed, idempotent consumption record: re-training a batch that
        was rolled back by a restore overwrites the same slot at a newer
        generation instead of duplicating it."""
        name = (f"{LEDGER_PREFIX}e{epoch:04d}-b{batch:06d}-"
                f"r{self.rank:03d}of{self.world:03d}")
        self.dataset.store.put(name, json.dumps({
            "epoch": int(epoch), "batch": int(batch),
            "rank": self.rank, "world": self.world,
            "generation": self.generation, "worker": self.worker_id,
            "records": [int(r) for r in records],
            "time": self.dataset.clock(),
        }).encode())
        self._m_ledger_writes.inc()

    def release_all(self):
        if self.leases is not None:
            self.leases.release_all()


# ================================================================== ledger
@dataclasses.dataclass
class LedgerReport:
    """What the consumption ledger proves (see :func:`reconcile_ledger`)."""
    epochs: Dict[int, List[int]]       # epoch -> authoritative record order
    duplicates: List[tuple]            # (epoch, record) seen twice
    gaps: List[tuple]                  # (epoch, batch) with a torn cover
    contested: List[tuple]             # (epoch, batch, sorted generations)

    @property
    def clean(self) -> bool:
        return not self.duplicates and not self.gaps


def reconcile_ledger(store, batch_size: int) -> LedgerReport:
    """Reassemble the authoritative per-epoch record sequence from the
    ledger objects in ``store``.

    For each ``(epoch, batch)`` the entries of the HIGHEST generation
    present are authoritative — the storage-backed mirror of checkpoint
    rollback semantics: if a batch's first training attempt died before
    its step committed, the restore rolled those updates back and the
    re-training (at a newer generation, possibly a different world size)
    is the one that counts. Authoritative covers must tile the batch
    exactly (every rank of one world, ``batch_size`` records total);
    anything else lands in ``gaps``. ``contested`` lists batches whose
    slots hold more than one generation — the acceptance test
    cross-checks those against the checkpoint journal to prove no
    CONSUMED (committed) batch was ever replayed."""
    from deeplearning4j_tpu.checkpoint.storage import as_backend
    backend = as_backend(store)
    entries: Dict[tuple, List[dict]] = {}
    for name in backend.list(prefix=LEDGER_PREFIX):
        try:
            rec = json.loads(backend.get(name).decode())
            entries.setdefault(
                (int(rec["epoch"]), int(rec["batch"])), []).append(rec)
        except Exception as e:
            log.warning("unreadable ledger object %s (%s: %s)", name,
                        type(e).__name__, e)
    per_epoch: Dict[int, Dict[int, List[int]]] = {}
    gaps: List[tuple] = []
    contested: List[tuple] = []
    for (epoch, batch), recs in sorted(entries.items()):
        gens = sorted({int(r.get("generation", 0)) for r in recs})
        if len(gens) > 1:
            contested.append((epoch, batch, gens))
        top = [r for r in recs if int(r.get("generation", 0)) == gens[-1]]
        worlds = {int(r["world"]) for r in top}
        if len(worlds) != 1:
            gaps.append((epoch, batch))
            continue
        world = worlds.pop()
        by_rank = {int(r["rank"]): r for r in top}
        if sorted(by_rank) != list(range(world)):
            gaps.append((epoch, batch))
            continue
        seq: List[int] = []
        for r in range(world):
            seq.extend(int(x) for x in by_rank[r]["records"])
        if len(seq) != batch_size:
            gaps.append((epoch, batch))
            continue
        per_epoch.setdefault(epoch, {})[batch] = seq
    epochs: Dict[int, List[int]] = {}
    duplicates: List[tuple] = []
    for epoch, batches in per_epoch.items():
        seen: Dict[int, int] = {}
        order: List[int] = []
        for b in sorted(batches):
            for rec_id in batches[b]:
                if rec_id in seen:
                    duplicates.append((epoch, rec_id))
                seen[rec_id] = b
                order.append(rec_id)
        epochs[epoch] = order
    return LedgerReport(epochs=epochs, duplicates=duplicates, gaps=gaps,
                        contested=contested)
