"""Dataset fetchers.

Parity surface: reference deeplearning4j-core/.../datasets/fetchers/
(MnistDataFetcher, IrisDataFetcher, ...). The reference downloads + caches
archives; this environment is zero-egress, so:

- Iris comes from scikit-learn's bundled copy (real Fisher data, no network),
  with a deterministic synthetic fallback.
- MNIST loads from a local IDX cache directory if present
  (``$DL4J_TPU_DATA_DIR`` or ``~/.deeplearning4j_tpu/mnist``), else generates a
  deterministic synthetic MNIST-shaped dataset: each class is a bright patch at
  a class-specific location plus noise — linearly separable enough that LeNet
  converges, so end-to-end training tests remain meaningful.
"""

from __future__ import annotations

import gzip
import logging
import os
import struct
from typing import Tuple

import numpy as np

log = logging.getLogger(__name__)


def _one_hot(y: np.ndarray, n: int) -> np.ndarray:
    out = np.zeros((len(y), n), np.float32)
    out[np.arange(len(y)), y] = 1.0
    return out


def iris_data() -> Tuple[np.ndarray, np.ndarray]:
    """(150, 4) features normalized to [0,1] per column, (150, 3) one-hot."""
    try:
        from sklearn.datasets import load_iris  # bundled csv, no network
        d = load_iris()
        x = d.data.astype(np.float32)
        y = d.target.astype(np.int64)
    except Exception:
        rng = np.random.default_rng(6)
        means = np.array([[5.0, 3.4, 1.5, 0.2], [5.9, 2.8, 4.3, 1.3], [6.6, 3.0, 5.6, 2.0]],
                         np.float32)
        x = np.concatenate([m + 0.3 * rng.standard_normal((50, 4)).astype(np.float32)
                            for m in means])
        y = np.repeat(np.arange(3), 50)
    x = (x - x.min(0)) / (x.max(0) - x.min(0))
    return x.astype(np.float32), _one_hot(y, 3)


def _data_dir() -> str:
    return os.environ.get("DL4J_TPU_DATA_DIR",
                          os.path.join(os.path.expanduser("~"), ".deeplearning4j_tpu"))


def _read_idx_images(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        assert magic == 2051, f"bad idx image magic {magic}"
        return np.frombuffer(f.read(n * rows * cols), np.uint8).reshape(n, rows * cols)


def _read_idx_labels(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        assert magic == 2049, f"bad idx label magic {magic}"
        return np.frombuffer(f.read(n), np.uint8)


def _find_mnist_files(train: bool):
    base = os.path.join(_data_dir(), "mnist")
    stem = "train" if train else "t10k"
    for ext in ("", ".gz"):
        img = os.path.join(base, f"{stem}-images-idx3-ubyte{ext}")
        lab = os.path.join(base, f"{stem}-labels-idx1-ubyte{ext}")
        if os.path.exists(img) and os.path.exists(lab):
            return img, lab
    return None


def synthetic_mnist(num_examples: int, seed: int = 123) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic MNIST-shaped learnable dataset (see module docstring)."""
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 10, num_examples)
    x = rng.uniform(0.0, 0.25, (num_examples, 28, 28)).astype(np.float32)
    # class k lights a 8x8 patch anchored on a 2x5 grid + a class-scaled stripe
    rows = (y // 5) * 12 + 2
    cols = (y % 5) * 5 + 1
    for i in range(num_examples):
        r, c = rows[i], cols[i]
        x[i, r:r + 8, c:c + 8] += 0.7
    x = np.clip(x, 0.0, 1.0)
    return x.reshape(num_examples, 784), _one_hot(y, 10)


def mnist_data(num_examples: int = 60000, train: bool = True,
               seed: int = 123) -> Tuple[np.ndarray, np.ndarray]:
    """(n, 784) float32 in [0,1] + (n, 10) one-hot, real if cached locally."""
    found = _find_mnist_files(train)
    if found is not None:
        x = _read_idx_images(found[0]).astype(np.float32) / 255.0
        y = _read_idx_labels(found[1])
        n = min(num_examples, len(x))
        return x[:n], _one_hot(y[:n], 10)
    n = min(num_examples, 60000 if train else 10000)
    return synthetic_mnist(n, seed=seed if train else seed + 1)


# --------------------------------------------------------------------------
# Image dataset fetchers beyond MNIST (reference datasets/fetchers/:
# EmnistDataFetcher, SvhnDataFetcher, TinyImageNetFetcher and
# datasets/iterator/impl/CifarDataSetIterator, LFWDataSetIterator). Same
# zero-egress contract as MNIST: load from a local cache directory when
# present, else generate a deterministic class-conditional synthetic set that
# is learnable so end-to-end tests stay meaningful.

def synthetic_images(num_examples: int, side: int, channels: int,
                     num_classes: int, seed: int) -> Tuple[np.ndarray, np.ndarray]:
    """(n, side, side, channels) float32 NHWC in [0,1] + one-hot labels.
    Class k lights a patch whose position and channel mix are k-specific."""
    rng = np.random.default_rng(seed)
    y = rng.integers(0, num_classes, num_examples)
    x = rng.uniform(0.0, 0.25,
                    (num_examples, side, side, channels)).astype(np.float32)
    grid = max(int(np.ceil(np.sqrt(num_classes))), 1)
    patch = max(side // (grid + 1), 3)
    cell = max((side - patch) // max(grid - 1, 1), 1)
    for i in range(num_examples):
        k = y[i]
        r = (k // grid) * cell
        c = (k % grid) * cell
        ch = k % channels
        x[i, r:r + patch, c:c + patch, ch] += 0.7
    return np.clip(x, 0.0, 1.0), _one_hot(y, num_classes)


def _read_cifar_bin(paths) -> Tuple[np.ndarray, np.ndarray]:
    xs, ys = [], []
    for p in paths:
        raw = np.frombuffer(open(p, "rb").read(), np.uint8).reshape(-1, 3073)
        ys.append(raw[:, 0])
        # stored CHW planar -> NHWC
        xs.append(raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
    x = np.concatenate(xs).astype(np.float32) / 255.0
    return x, _one_hot(np.concatenate(ys), 10)


def cifar10_data(num_examples: int = 50000, train: bool = True,
                 seed: int = 321) -> Tuple[np.ndarray, np.ndarray]:
    """(n, 32, 32, 3) + (n, 10); real CIFAR-10 if the binary batches are
    cached under ``$DL4J_TPU_DATA_DIR/cifar10/cifar-10-batches-bin``."""
    base = os.path.join(_data_dir(), "cifar10", "cifar-10-batches-bin")
    names = ([f"data_batch_{i}.bin" for i in range(1, 6)] if train
             else ["test_batch.bin"])
    paths = [os.path.join(base, n) for n in names]
    if all(os.path.exists(p) for p in paths):
        x, y = _read_cifar_bin(paths)
        n = min(num_examples, len(x))
        return x[:n], y[:n]
    n = min(num_examples, 50000 if train else 10000)
    return synthetic_images(n, 32, 3, 10, seed if train else seed + 1)


_EMNIST_CLASSES = {"complete": 62, "merge": 47, "balanced": 47,
                   "letters": 26, "digits": 10, "mnist": 10}


def emnist_data(split: str = "balanced", num_examples: int = 10000,
                train: bool = True, seed: int = 555) -> Tuple[np.ndarray, np.ndarray]:
    """EMNIST (reference EmnistDataFetcher): (n, 784) + one-hot over the
    split's class count. Real data from IDX files under
    ``$DL4J_TPU_DATA_DIR/emnist`` (``emnist-<split>-train-images-idx3-ubyte``)."""
    if split not in _EMNIST_CLASSES:
        raise ValueError(f"Unknown EMNIST split {split!r}; "
                         f"one of {sorted(_EMNIST_CLASSES)}")
    n_classes = _EMNIST_CLASSES[split]
    stem = "train" if train else "test"
    base = os.path.join(_data_dir(), "emnist")
    for ext in ("", ".gz"):
        img = os.path.join(base, f"emnist-{split}-{stem}-images-idx3-ubyte{ext}")
        lab = os.path.join(base, f"emnist-{split}-{stem}-labels-idx1-ubyte{ext}")
        if os.path.exists(img) and os.path.exists(lab):
            x = _read_idx_images(img).astype(np.float32) / 255.0
            y = _read_idx_labels(lab).astype(np.int64)
            # letters split is 1-indexed in the source files
            if split == "letters" and y.min() == 1:
                y = y - 1
            n = min(num_examples, len(x))
            return x[:n], _one_hot(y[:n], n_classes)
    x, y = synthetic_images(num_examples, 28, 1, n_classes,
                            seed if train else seed + 1)
    return x.reshape(len(x), 784), y


def emnist_num_classes(split: str) -> int:
    return _EMNIST_CLASSES[split]


def svhn_data(num_examples: int = 10000, train: bool = True,
              seed: int = 777) -> Tuple[np.ndarray, np.ndarray]:
    """SVHN cropped-digits (reference SvhnDataFetcher): (n, 32, 32, 3) +
    (n, 10). Real data from ``$DL4J_TPU_DATA_DIR/svhn/{train,test}_32x32.mat``."""
    path = os.path.join(_data_dir(), "svhn",
                        ("train" if train else "test") + "_32x32.mat")
    if os.path.exists(path):
        try:
            from scipy.io import loadmat
            m = loadmat(path)
            x = m["X"].transpose(3, 0, 1, 2).astype(np.float32) / 255.0
            y = m["y"].reshape(-1).astype(np.int64) % 10  # label "10" is digit 0
            n = min(num_examples, len(x))
            return x[:n], _one_hot(y[:n], 10)
        except Exception as e:
            log.warning("SVHN cache at %s exists but failed to load (%s); "
                        "falling back to synthetic data", path, e)
    return synthetic_images(num_examples, 32, 3, 10,
                            seed if train else seed + 1)


def tiny_imagenet_data(num_examples: int = 5000, train: bool = True,
                       seed: int = 999) -> Tuple[np.ndarray, np.ndarray]:
    """TinyImageNet (reference TinyImageNetFetcher): (n, 64, 64, 3) + 200
    classes. Real data requires the unpacked ``tiny-imagenet-200`` directory
    under the cache dir; otherwise synthetic."""
    base = os.path.join(_data_dir(), "tiny-imagenet-200")
    if os.path.isdir(base):
        try:
            return _load_tiny_imagenet_dir(base, num_examples, train)
        except Exception as e:
            log.warning("TinyImageNet cache at %s exists but failed to load "
                        "(%s); falling back to synthetic data", base, e)
    return synthetic_images(num_examples, 64, 3, 200,
                            seed if train else seed + 1)


def _load_tiny_imagenet_dir(base, num_examples, train):
    # JPEG decoding without PIL/tf: defer to numpy-readable .npy cache the
    # user can produce once; the raw-archive path needs an image decoder this
    # environment does not ship.
    x = np.load(os.path.join(base, "train_x.npy" if train else "val_x.npy"))
    y = np.load(os.path.join(base, "train_y.npy" if train else "val_y.npy"))
    n = min(num_examples, len(x))
    return (x[:n].astype(np.float32) / (255.0 if x.max() > 1.5 else 1.0),
            _one_hot(y[:n].astype(np.int64), 200))


def lfw_data(num_examples: int = 1000, train: bool = True, side: int = 40,
             num_classes: int = 5749, seed: int = 1111) -> Tuple[np.ndarray, np.ndarray]:
    """LFW faces (reference LFWDataSetIterator): (n, side, side, 3). Real
    data via sklearn's fetch_lfw_people cache if present locally; else
    synthetic."""
    try:
        from sklearn.datasets import fetch_lfw_people
        d = fetch_lfw_people(color=True, download_if_missing=False)
        x = d.images.astype(np.float32)
        if x.max() > 1.5:
            x = x / 255.0
        # nearest-neighbor resize to the requested square side
        h, w = x.shape[1], x.shape[2]
        ri = np.clip((np.arange(side) * h) // side, 0, h - 1)
        ci = np.clip((np.arange(side) * w) // side, 0, w - 1)
        x = x[:, ri][:, :, ci]
        y = d.target.astype(np.int64)
        # deterministic 80/20 train/test split
        cut = int(len(x) * 0.8)
        x, y = (x[:cut], y[:cut]) if train else (x[cut:], y[cut:])
        n = min(num_examples, len(x))
        return x[:n], _one_hot(y[:n], int(d.target.max()) + 1)
    except Exception as e:
        if not isinstance(e, ImportError) and "download_if_missing" not in str(e):
            log.warning("LFW load failed (%s); falling back to synthetic", e)
    return synthetic_images(num_examples, side, 3, min(num_classes, 64),
                            seed if train else seed + 1)
