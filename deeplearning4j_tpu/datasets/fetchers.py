"""Dataset fetchers.

Parity surface: reference deeplearning4j-core/.../datasets/fetchers/
(MnistDataFetcher, IrisDataFetcher, ...). The reference downloads + caches
archives; this environment is zero-egress, so:

- Iris comes from scikit-learn's bundled copy (real Fisher data, no network),
  with a deterministic synthetic fallback.
- MNIST loads from a local IDX cache directory if present
  (``$DL4J_TPU_DATA_DIR`` or ``~/.deeplearning4j_tpu/mnist``), else generates a
  deterministic synthetic MNIST-shaped dataset: each class is a bright patch at
  a class-specific location plus noise — linearly separable enough that LeNet
  converges, so end-to-end training tests remain meaningful.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Tuple

import numpy as np


def _one_hot(y: np.ndarray, n: int) -> np.ndarray:
    out = np.zeros((len(y), n), np.float32)
    out[np.arange(len(y)), y] = 1.0
    return out


def iris_data() -> Tuple[np.ndarray, np.ndarray]:
    """(150, 4) features normalized to [0,1] per column, (150, 3) one-hot."""
    try:
        from sklearn.datasets import load_iris  # bundled csv, no network
        d = load_iris()
        x = d.data.astype(np.float32)
        y = d.target.astype(np.int64)
    except Exception:
        rng = np.random.default_rng(6)
        means = np.array([[5.0, 3.4, 1.5, 0.2], [5.9, 2.8, 4.3, 1.3], [6.6, 3.0, 5.6, 2.0]],
                         np.float32)
        x = np.concatenate([m + 0.3 * rng.standard_normal((50, 4)).astype(np.float32)
                            for m in means])
        y = np.repeat(np.arange(3), 50)
    x = (x - x.min(0)) / (x.max(0) - x.min(0))
    return x.astype(np.float32), _one_hot(y, 3)


def _data_dir() -> str:
    return os.environ.get("DL4J_TPU_DATA_DIR",
                          os.path.join(os.path.expanduser("~"), ".deeplearning4j_tpu"))


def _read_idx_images(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        assert magic == 2051, f"bad idx image magic {magic}"
        return np.frombuffer(f.read(n * rows * cols), np.uint8).reshape(n, rows * cols)


def _read_idx_labels(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        assert magic == 2049, f"bad idx label magic {magic}"
        return np.frombuffer(f.read(n), np.uint8)


def _find_mnist_files(train: bool):
    base = os.path.join(_data_dir(), "mnist")
    stem = "train" if train else "t10k"
    for ext in ("", ".gz"):
        img = os.path.join(base, f"{stem}-images-idx3-ubyte{ext}")
        lab = os.path.join(base, f"{stem}-labels-idx1-ubyte{ext}")
        if os.path.exists(img) and os.path.exists(lab):
            return img, lab
    return None


def synthetic_mnist(num_examples: int, seed: int = 123) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic MNIST-shaped learnable dataset (see module docstring)."""
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 10, num_examples)
    x = rng.uniform(0.0, 0.25, (num_examples, 28, 28)).astype(np.float32)
    # class k lights a 8x8 patch anchored on a 2x5 grid + a class-scaled stripe
    rows = (y // 5) * 12 + 2
    cols = (y % 5) * 5 + 1
    for i in range(num_examples):
        r, c = rows[i], cols[i]
        x[i, r:r + 8, c:c + 8] += 0.7
    x = np.clip(x, 0.0, 1.0)
    return x.reshape(num_examples, 784), _one_hot(y, 10)


def mnist_data(num_examples: int = 60000, train: bool = True,
               seed: int = 123) -> Tuple[np.ndarray, np.ndarray]:
    """(n, 784) float32 in [0,1] + (n, 10) one-hot, real if cached locally."""
    found = _find_mnist_files(train)
    if found is not None:
        x = _read_idx_images(found[0]).astype(np.float32) / 255.0
        y = _read_idx_labels(found[1])
        n = min(num_examples, len(x))
        return x[:n], _one_hot(y[:n], 10)
    n = min(num_examples, 60000 if train else 10000)
    return synthetic_mnist(n, seed=seed if train else seed + 1)
