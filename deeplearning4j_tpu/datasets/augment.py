"""On-device data augmentation: pure-jnp crop/flip/normalize INSIDE the
jitted train step.

Host-side augmentation (numpy per-batch transforms in the input pipeline)
pays a full host pass over every image plus the transfer of the augmented
copy; on a TPU the same ops are bandwidth-trivial next to the conv work
already on device. ``ImageAugmentation`` is a frozen config whose
``apply(x, rng)`` runs inside the traced loss: MultiLayerNetwork /
ComputationGraph thread a key split off the STEP rng into it, so
augmentation is deterministic given the training seed (the same
reproducibility contract as dropout), replays bitwise across
checkpoint-resume, and costs zero host work.

Because augmentation runs inside the forward, it changes the
forward→backward residual set — ``perf.fusion.training_activation_bytes``
and the HBM planner (``perf/planner.py``) take an ``augmentation=`` knob so
the planned memory accounts for it.

This is the PR 11 leftover (on-device augmentation was independent of the
lease/resume machinery); reference analogue: DataVec's ImageTransform
pipeline, which runs on the host.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ImageAugmentation:
    """Random crop / horizontal flip / per-channel normalize for NHWC
    batches, as pure traced ops.

    ``crop_padding``: zero-pad H and W by this much, then take a random
    H×W crop per example (the CIFAR recipe); 0 disables.
    ``flip_prob``: per-example probability of a horizontal (width-axis)
    flip; 0 disables.
    ``mean``/``std``: per-channel normalize ``(x - mean) / std`` applied
    AFTER the geometric ops; None disables.

    Frozen and hashable — the networks key their jit caches on it, so
    changing the augmentation mints a fresh compiled step instead of
    silently reusing the old one."""

    crop_padding: int = 0
    flip_prob: float = 0.0
    mean: Optional[Tuple[float, ...]] = None
    std: Optional[Tuple[float, ...]] = None

    def __post_init__(self):
        if self.crop_padding < 0:
            raise ValueError(f"crop_padding must be >= 0, got "
                             f"{self.crop_padding}")
        if not 0.0 <= self.flip_prob <= 1.0:
            raise ValueError(f"flip_prob must be in [0, 1], got "
                             f"{self.flip_prob}")
        if (self.mean is None) != (self.std is None):
            raise ValueError("mean and std must be set together")

    def to_dict(self) -> dict:
        return {
            "crop_padding": self.crop_padding,
            "flip_prob": self.flip_prob,
            "mean": None if self.mean is None else list(self.mean),
            "std": None if self.std is None else list(self.std),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ImageAugmentation":
        return cls(
            crop_padding=int(d.get("crop_padding", 0)),
            flip_prob=float(d.get("flip_prob", 0.0)),
            mean=(None if d.get("mean") is None
                  else tuple(float(v) for v in d["mean"])),
            std=(None if d.get("std") is None
                 else tuple(float(v) for v in d["std"])),
        )

    def apply(self, x, rng):
        """Augment one NHWC batch under ``rng`` (a jax PRNG key). Pure and
        shape-preserving: output shape == input shape, so bucket ladders
        and compiled-step shapes are untouched."""
        if x.ndim != 4:
            raise ValueError(
                f"ImageAugmentation.apply expects NHWC (batch, h, w, c); "
                f"got rank-{x.ndim} input")
        n, h, w, _ = x.shape
        k_oy, k_ox, k_flip = jax.random.split(rng, 3)
        if self.crop_padding:
            p = int(self.crop_padding)
            padded = jnp.pad(x, ((0, 0), (p, p), (p, p), (0, 0)))
            oy = jax.random.randint(k_oy, (n,), 0, 2 * p + 1)
            ox = jax.random.randint(k_ox, (n,), 0, 2 * p + 1)

            def crop_one(img, y0, x0):
                return jax.lax.dynamic_slice(
                    img, (y0, x0, 0), (h, w, img.shape[-1]))

            x = jax.vmap(crop_one)(padded, oy, ox)
        if self.flip_prob:
            flip = jax.random.bernoulli(k_flip, self.flip_prob, (n,))
            x = jnp.where(flip[:, None, None, None], x[:, :, ::-1, :], x)
        if self.mean is not None:
            mean = jnp.asarray(self.mean, x.dtype)
            std = jnp.asarray(self.std, x.dtype)
            x = (x - mean) / std
        return x
