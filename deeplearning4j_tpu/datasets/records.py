"""Record readers + the record→DataSet bridge.

Parity surface: DataVec's ``RecordReader`` SPI (datavec-api, external to the
reference repo) and the reference's
``deeplearning4j-core/.../datasets/datavec/RecordReaderDataSetIterator.java:51``
(labelIndex / labelIndexFrom-To / regression modes) and
``SequenceRecordReaderDataSetIterator.java`` (sequence + alignment modes).

TPU-native design: records are plain Python lists of values; batch assembly
produces contiguous numpy arrays once per minibatch (a single host->device
transfer per step inside the jitted program). The Writable type hierarchy
dissolves — numpy dtype promotion does the converter's job.

The FILE-BACKED tier (DataVec's distributed record readers, SURVEY L3):
:class:`RecordSource` is the lazy counterpart of the in-RAM arrays a
``ShardedDataset`` is normally built from — a corpus laid out as shard
objects in ANY ``StorageBackend`` (local dir, in-process bucket,
``CloudObjectBackend`` over the wire), loaded one shard at a time.
:class:`ShardFileSource` reads the native ``.npz`` shard layout
(:func:`write_shards` produces it); :class:`CSVShardSource` reads a
prefix of CSV shard objects through the same column/label conventions as
:class:`RecordReaderDataSetIterator`. ``ShardedDataset(source=...)``
keeps its deterministic shuffle / lease / exactly-once semantics
unchanged — those operate on row indices, which a source serves lazily
with RAM bounded by the in-flight shard set.
"""

from __future__ import annotations

import csv
import io
import json
import os
from typing import Iterable, List, Optional, Sequence, Union

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.fetchers import _one_hot as _one_hot_int
from deeplearning4j_tpu.datasets.iterators import DataSetIterator


def _one_hot(idx: np.ndarray, n: int) -> np.ndarray:
    return _one_hot_int(np.asarray(idx).astype(np.int64), n)


class RecordReader:
    """Iterable of records; a record is a list of values (DataVec
    ``RecordReader.next()`` → List<Writable>)."""

    def __iter__(self):
        raise NotImplementedError

    def reset(self):
        pass


class CollectionRecordReader(RecordReader):
    """In-memory records (DataVec CollectionRecordReader)."""

    def __init__(self, records: Sequence[Sequence]):
        self.records = list(records)

    def __iter__(self):
        return iter(self.records)


class CSVRecordReader(RecordReader):
    """CSV file/string reader (DataVec CSVRecordReader): ``skip_lines``
    header rows, custom delimiter, numeric fields parsed to float, other
    fields kept as strings."""

    def __init__(self, source: Union[str, Iterable[str]], skip_lines: int = 0,
                 delimiter: str = ","):
        self.source = source
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def _lines(self):
        if isinstance(self.source, str):
            if os.path.exists(self.source):
                with open(self.source, "r", encoding="utf-8") as f:
                    yield from f
            else:
                yield from io.StringIO(self.source)
        else:
            yield from self.source

    def __iter__(self):
        reader = csv.reader(self._lines(), delimiter=self.delimiter)
        for i, row in enumerate(reader):
            if i < self.skip_lines or not row:
                continue
            yield [self._parse(v) for v in row]

    def numeric_matrix(self) -> Optional[np.ndarray]:
        """All-numeric fast path: the native one-pass parser
        (deeplearning4j_tpu.native.parse_csv_numeric) turns the whole source
        into a float32 matrix without per-row Python objects. None when the
        native lib is absent or the data has strings/ragged rows — callers
        fall back to row iteration."""
        from deeplearning4j_tpu.native import parse_csv_numeric
        if not isinstance(self.source, str):
            # a generator/file-object source may be one-shot: consuming it
            # here would leave the fallback row path empty, so the fast path
            # only applies to path/string sources
            return None
        if os.path.exists(self.source):
            with open(self.source, "rb") as f:
                data = f.read()
        else:
            data = self.source.encode("utf-8")
        return parse_csv_numeric(data, self.delimiter, self.skip_lines)

    @staticmethod
    def _parse(v: str):
        v = v.strip()
        try:
            return float(v)
        except ValueError:
            return v


class CSVSequenceRecordReader(RecordReader):
    """Sequence CSV reader (DataVec CSVSequenceRecordReader): each source —
    file path or list of lines — is one sequence; yields one list-of-records
    per sequence."""

    def __init__(self, sources: Sequence[Union[str, Sequence[str]]],
                 skip_lines: int = 0, delimiter: str = ","):
        self.sources = list(sources)
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def __iter__(self):
        for src in self.sources:
            yield list(CSVRecordReader(src, self.skip_lines, self.delimiter))


class RecordReaderDataSetIterator(DataSetIterator):
    """records → DataSet minibatches (reference
    RecordReaderDataSetIterator.java:51).

    - classification: ``label_index`` column holds the class id,
      ``num_possible_labels`` sets one-hot width
    - regression: ``regression=True`` with ``label_index`` (single target) or
      ``label_index_from``/``label_index_to`` (inclusive range of targets)
    - ``max_num_batches`` caps iteration (reference maxNumBatches)
    """

    def __init__(self, record_reader: RecordReader, batch_size: int,
                 label_index: int = -1, num_possible_labels: int = -1,
                 regression: bool = False,
                 label_index_from: int = -1, label_index_to: int = -1,
                 max_num_batches: int = -1):
        self.reader = record_reader
        self._batch = batch_size
        self.label_index = label_index
        self.num_possible_labels = num_possible_labels
        self.regression = regression
        self.label_index_from = label_index_from
        self.label_index_to = label_index_to
        self.max_num_batches = max_num_batches
        if (not regression and label_index >= 0 and label_index_from < 0
                and num_possible_labels <= 0):
            # per-batch inference would give inconsistent one-hot widths
            raise ValueError(
                "Classification mode needs num_possible_labels (the one-hot "
                "width must be fixed across minibatches)")
        self._label_map: dict = {}  # string label -> class index

    def reset(self):
        self.reader.reset()

    def batch_size(self):
        return self._batch

    def total_outcomes(self):
        if self.regression:
            if self.label_index_from >= 0:
                return self.label_index_to - self.label_index_from + 1
            return 1
        return self.num_possible_labels if self.num_possible_labels > 0 else None

    def _to_float(self, rows, what: str) -> np.ndarray:
        try:
            return np.asarray(rows, np.float32)
        except (ValueError, TypeError):
            bad = next((v for row in rows
                        for v in (row if isinstance(row, (list, tuple)) else [row])
                        if isinstance(v, str)), None)
            if bad is not None:
                raise ValueError(
                    f"Non-numeric value {bad!r} in {what}; map string fields "
                    "to numbers before batching (string class labels in the "
                    "label column are mapped automatically)") from None
            widths = sorted({len(r) for r in rows
                             if isinstance(r, (list, tuple))})
            raise ValueError(
                f"Cannot assemble {what} into an array"
                + (f": ragged record lengths {widths}" if len(widths) > 1
                   else "")) from None

    def _split(self, rows: List[list]):
        li = self.label_index
        if (not self.regression and li >= 0 and len(rows)
                and isinstance(rows[0][li], str)):
            # auto-map string class labels to stable indices in order of
            # first appearance (the common 'species name' CSV case)
            rows = [list(r) for r in rows]
            for r in rows:
                label = r[li]
                if label not in self._label_map:
                    if len(self._label_map) >= self.num_possible_labels:
                        raise ValueError(
                            f"More than num_possible_labels="
                            f"{self.num_possible_labels} distinct labels "
                            f"(new: {label!r})")
                    self._label_map[label] = len(self._label_map)
                r[li] = self._label_map[label]
        arr = self._to_float(rows, "record batch")
        if self.label_index_from >= 0:  # regression target range
            lo, hi = self.label_index_from, self.label_index_to
            labels = arr[:, lo:hi + 1]
            feats = np.concatenate([arr[:, :lo], arr[:, hi + 1:]], axis=1)
        elif self.label_index >= 0:
            labels = arr[:, self.label_index:self.label_index + 1]
            feats = np.concatenate(
                [arr[:, :self.label_index], arr[:, self.label_index + 1:]],
                axis=1)
            if not self.regression:
                labels = _one_hot(labels[:, 0], self.num_possible_labels)
        else:  # no labels: features only (autoencoder style — labels=features)
            feats = labels = arr
        return DataSet(feats, labels.astype(np.float32))

    def _generate(self):
        # native bulk path: one C++ pass over the bytes, then pure slicing
        mat = (self.reader.numeric_matrix()
               if hasattr(self.reader, "numeric_matrix") else None)
        if mat is not None:
            for k, s in enumerate(range(0, len(mat), self._batch)):
                if 0 < self.max_num_batches <= k:
                    return
                yield self._split(mat[s:s + self._batch])
            return
        rows, batches = [], 0
        for rec in self.reader:
            rows.append(rec)
            if len(rows) == self._batch:
                yield self._split(rows)
                rows, batches = [], batches + 1
                if 0 < self.max_num_batches <= batches:
                    return
        if rows:
            yield self._split(rows)


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """sequence records → (batch, time, features) DataSets (reference
    SequenceRecordReaderDataSetIterator.java). Sequences in a batch are
    padded to the longest with features/labels masks (ALIGN_END of the
    reference's alignment modes)."""

    def __init__(self, reader: CSVSequenceRecordReader, batch_size: int,
                 label_index: int = -1, num_possible_labels: int = -1,
                 regression: bool = False):
        self.reader = reader
        self._batch = batch_size
        self.label_index = label_index
        self.num_possible_labels = num_possible_labels
        self.regression = regression
        if not regression and label_index >= 0 and num_possible_labels <= 0:
            raise ValueError(
                "Sequence classification mode needs num_possible_labels")

    def reset(self):
        self.reader.reset()

    def batch_size(self):
        return self._batch

    def _assemble(self, seqs: List[np.ndarray]):
        T = max(s.shape[0] for s in seqs)
        li = self.label_index
        n_feat = seqs[0].shape[1] - (1 if li >= 0 else 0)
        n_lab = (self.num_possible_labels if not self.regression and li >= 0
                 else (1 if li >= 0 else n_feat))
        B = len(seqs)
        x = np.zeros((B, T, n_feat), np.float32)
        y = np.zeros((B, T, n_lab), np.float32)
        mask = np.zeros((B, T), np.float32)
        for i, s in enumerate(seqs):
            t = s.shape[0]
            mask[i, :t] = 1.0
            if li >= 0:
                feats = np.concatenate([s[:, :li], s[:, li + 1:]], axis=1)
                lab = s[:, li]
                x[i, :t] = feats
                if self.regression:
                    y[i, :t, 0] = lab
                else:
                    y[i, :t] = _one_hot(lab, n_lab)
            else:
                x[i, :t] = s
                y[i, :t] = s
        full = mask.all()
        return DataSet(x, y, None if full else mask, None if full else mask)

    def _generate(self):
        seqs = []
        for seq in self.reader:
            seqs.append(np.asarray(seq, np.float32))
            if len(seqs) == self._batch:
                yield self._assemble(seqs)
                seqs = []
        if seqs:
            yield self._assemble(seqs)


# ===================================================== file-backed sources
SHARD_META_NAME = "meta.json"
_SHARD_FIELDS = ("features", "labels", "features_mask", "labels_mask")


class RecordSource:
    """A corpus as an ordered list of shard files in a StorageBackend.

    The contract ``ShardedDataset(source=...)`` builds on:

    - ``shard_sizes``: rows per shard, fixed at construction (global row
      ``r`` lives at offset ``r - sum(sizes[:i])`` of shard ``i``);
    - ``load_shard(i)`` → ``{"features": arr, "labels": arr|None,
      "features_mask": ..., "labels_mask": ...}`` with exactly
      ``shard_sizes[i]`` rows — loaded on demand, never retained here
      (residency is the dataset's LRU's job);
    - ``feature_shape``/``label_shape``: per-record trailing shapes, known
      WITHOUT loading any shard (readers size their models from these).
    """

    shard_sizes: List[int]
    feature_shape: tuple
    label_shape: Optional[tuple]

    @property
    def num_shards(self) -> int:
        return len(self.shard_sizes)

    @property
    def num_records(self) -> int:
        return sum(self.shard_sizes)

    def load_shard(self, index: int) -> dict:
        raise NotImplementedError

    def describe(self) -> str:
        return type(self).__name__


def _shard_key(prefix: str, index: int) -> str:
    return f"{prefix}shard-{index:05d}.npz"


def write_shards(store, prefix: str, features, labels=None, *,
                 records_per_shard: int, features_mask=None,
                 labels_mask=None) -> "ShardFileSource":
    """Lay a corpus out as the native shard format: one ``.npz`` object
    per ``records_per_shard`` rows plus a trailing ``meta.json`` under
    ``prefix`` in any backend. The meta object is written LAST — it is
    the commit point a :class:`ShardFileSource` discovers the corpus
    through, so a writer that dies mid-layout leaves nothing readable."""
    from deeplearning4j_tpu.checkpoint.storage import as_backend
    backend = as_backend(store)
    features = np.asarray(features)
    n = int(features.shape[0])
    if records_per_shard < 1:
        raise ValueError("records_per_shard must be >= 1")
    arrays = {"features": features,
              "labels": None if labels is None else np.asarray(labels),
              "features_mask": (None if features_mask is None
                                else np.asarray(features_mask)),
              "labels_mask": (None if labels_mask is None
                              else np.asarray(labels_mask))}
    for field, arr in arrays.items():
        if arr is not None and arr.shape[0] != n:
            raise ValueError(f"{field} has {arr.shape[0]} rows, "
                             f"features has {n}")
    sizes = []
    for i, lo in enumerate(range(0, n, records_per_shard)):
        hi = min(n, lo + records_per_shard)
        buf = io.BytesIO()
        np.savez(buf, **{f: a[lo:hi] for f, a in arrays.items()
                         if a is not None})
        backend.put(_shard_key(prefix, i), buf.getvalue())
        sizes.append(hi - lo)
    meta = {"version": 1, "shard_sizes": sizes,
            "feature_shape": list(features.shape[1:]),
            "label_shape": (None if arrays["labels"] is None
                            else list(arrays["labels"].shape[1:])),
            "fields": [f for f, a in arrays.items() if a is not None]}
    backend.put(prefix + SHARD_META_NAME,
                json.dumps(meta, sort_keys=True).encode())
    return ShardFileSource(backend, prefix)


class ShardFileSource(RecordSource):
    """The native shard-file layout: ``<prefix>shard-NNNNN.npz`` objects
    described by ``<prefix>meta.json`` (see :func:`write_shards`), over
    any StorageBackend — the lake path feeds training through
    ``CloudObjectBackend`` + ``CachedBackend`` with exactly this class."""

    def __init__(self, store, prefix: str = "shards/"):
        from deeplearning4j_tpu.checkpoint.storage import as_backend
        self.store = as_backend(store)
        self.prefix = str(prefix)
        try:
            meta = json.loads(self.store.get(self.prefix +
                                             SHARD_META_NAME).decode())
        except FileNotFoundError:
            raise FileNotFoundError(
                f"no shard corpus at prefix {self.prefix!r} in "
                f"{self.store.describe()} — write_shards() commits "
                f"{SHARD_META_NAME} last; its absence means no corpus "
                "(or a writer that died mid-layout)") from None
        self.shard_sizes = [int(s) for s in meta["shard_sizes"]]
        self.feature_shape = tuple(meta["feature_shape"])
        self.label_shape = (None if meta.get("label_shape") is None
                            else tuple(meta["label_shape"]))
        self.fields = tuple(meta.get("fields", ("features",)))
        self.loads = 0
        self.bytes_loaded = 0

    def load_shard(self, index: int) -> dict:
        data = self.store.get(_shard_key(self.prefix, index))
        self.loads += 1
        self.bytes_loaded += len(data)
        with np.load(io.BytesIO(data), allow_pickle=False) as z:
            out = {f: (np.asarray(z[f]) if f in z.files else None)
                   for f in _SHARD_FIELDS}
        got = 0 if out["features"] is None else out["features"].shape[0]
        if got != self.shard_sizes[index]:
            raise ValueError(
                f"shard {index} of {self.describe()} has {got} rows, "
                f"meta says {self.shard_sizes[index]} — corpus rewritten "
                "under a live reader?")
        return out

    def describe(self) -> str:
        return f"ShardFileSource({self.store.describe()}, {self.prefix!r})"


class CSVShardSource(RecordSource):
    """CSV shard objects under a prefix (DataVec's CSV readers over an
    object store): every object ``<prefix>*`` is one shard, shards ordered
    by name. Label handling follows
    :class:`RecordReaderDataSetIterator` — ``label_index`` column one-hot
    to ``num_possible_labels`` wide (or kept scalar under
    ``regression=True``); without a ``label_index`` the rows are
    features-only. Labels must be NUMERIC class ids — string labels would
    need a first-appearance map whose order depends on shard visit order,
    which a deterministic shuffle cannot allow.

    Row counts are taken in one pass over the corpus at construction
    (each object read once — through a ``CachedBackend`` that pass also
    warms the cache); bytes are NOT retained."""

    def __init__(self, store, prefix: str, *, label_index: int = -1,
                 num_possible_labels: int = -1, regression: bool = False,
                 skip_lines: int = 0, delimiter: str = ","):
        from deeplearning4j_tpu.checkpoint.storage import as_backend
        self.store = as_backend(store)
        self.prefix = str(prefix)
        self.label_index = int(label_index)
        self.num_possible_labels = int(num_possible_labels)
        self.regression = bool(regression)
        self.skip_lines = int(skip_lines)
        self.delimiter = delimiter
        if not regression and label_index >= 0 and num_possible_labels <= 0:
            raise ValueError("Classification mode needs num_possible_labels")
        self.shard_names = [n for n in self.store.list(prefix=self.prefix)
                            if not n.endswith(SHARD_META_NAME)]
        if not self.shard_names:
            raise FileNotFoundError(
                f"no CSV shards under prefix {self.prefix!r} in "
                f"{self.store.describe()}")
        self.loads = 0
        self.bytes_loaded = 0
        sizes, widths = [], set()
        for name in self.shard_names:
            rows = self._parse(self.store.get(name), name)
            sizes.append(rows.shape[0])
            widths.add(rows.shape[1])
        if len(widths) != 1:
            raise ValueError(f"CSV shards disagree on column count: "
                             f"{sorted(widths)}")
        self.shard_sizes = sizes
        width = widths.pop()
        n_feat = width - (1 if self.label_index >= 0 else 0)
        self.feature_shape = (n_feat,)
        if self.label_index < 0:
            self.label_shape = None
        elif self.regression:
            self.label_shape = (1,)
        else:
            self.label_shape = (self.num_possible_labels,)

    def _parse(self, data: bytes, name: str) -> np.ndarray:
        self.loads += 1
        self.bytes_loaded += len(data)
        reader = CSVRecordReader(data.decode("utf-8"),
                                 skip_lines=self.skip_lines,
                                 delimiter=self.delimiter)
        mat = reader.numeric_matrix()
        if mat is None:
            rows = list(reader)
            if any(isinstance(v, str) for r in rows for v in r):
                raise ValueError(
                    f"CSV shard {name} has non-numeric fields — lake CSV "
                    "shards must be fully numeric (see class docstring)")
            mat = np.asarray(rows, np.float32)
        if mat.ndim != 2:
            raise ValueError(f"CSV shard {name} is empty or ragged")
        return mat

    def load_shard(self, index: int) -> dict:
        name = self.shard_names[index]
        mat = self._parse(self.store.get(name), name)
        li = self.label_index
        if li < 0:
            return {"features": mat, "labels": None,
                    "features_mask": None, "labels_mask": None}
        labels = mat[:, li:li + 1]
        feats = np.concatenate([mat[:, :li], mat[:, li + 1:]], axis=1)
        if not self.regression:
            labels = _one_hot(labels[:, 0], self.num_possible_labels)
        return {"features": feats, "labels": labels.astype(np.float32),
                "features_mask": None, "labels_mask": None}

    def describe(self) -> str:
        return f"CSVShardSource({self.store.describe()}, {self.prefix!r})"
