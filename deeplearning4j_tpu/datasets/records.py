"""Record readers + the record→DataSet bridge.

Parity surface: DataVec's ``RecordReader`` SPI (datavec-api, external to the
reference repo) and the reference's
``deeplearning4j-core/.../datasets/datavec/RecordReaderDataSetIterator.java:51``
(labelIndex / labelIndexFrom-To / regression modes) and
``SequenceRecordReaderDataSetIterator.java`` (sequence + alignment modes).

TPU-native design: records are plain Python lists of values; batch assembly
produces contiguous numpy arrays once per minibatch (a single host->device
transfer per step inside the jitted program). The Writable type hierarchy
dissolves — numpy dtype promotion does the converter's job.
"""

from __future__ import annotations

import csv
import io
import os
from typing import Iterable, List, Optional, Sequence, Union

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.fetchers import _one_hot as _one_hot_int
from deeplearning4j_tpu.datasets.iterators import DataSetIterator


def _one_hot(idx: np.ndarray, n: int) -> np.ndarray:
    return _one_hot_int(np.asarray(idx).astype(np.int64), n)


class RecordReader:
    """Iterable of records; a record is a list of values (DataVec
    ``RecordReader.next()`` → List<Writable>)."""

    def __iter__(self):
        raise NotImplementedError

    def reset(self):
        pass


class CollectionRecordReader(RecordReader):
    """In-memory records (DataVec CollectionRecordReader)."""

    def __init__(self, records: Sequence[Sequence]):
        self.records = list(records)

    def __iter__(self):
        return iter(self.records)


class CSVRecordReader(RecordReader):
    """CSV file/string reader (DataVec CSVRecordReader): ``skip_lines``
    header rows, custom delimiter, numeric fields parsed to float, other
    fields kept as strings."""

    def __init__(self, source: Union[str, Iterable[str]], skip_lines: int = 0,
                 delimiter: str = ","):
        self.source = source
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def _lines(self):
        if isinstance(self.source, str):
            if os.path.exists(self.source):
                with open(self.source, "r", encoding="utf-8") as f:
                    yield from f
            else:
                yield from io.StringIO(self.source)
        else:
            yield from self.source

    def __iter__(self):
        reader = csv.reader(self._lines(), delimiter=self.delimiter)
        for i, row in enumerate(reader):
            if i < self.skip_lines or not row:
                continue
            yield [self._parse(v) for v in row]

    def numeric_matrix(self) -> Optional[np.ndarray]:
        """All-numeric fast path: the native one-pass parser
        (deeplearning4j_tpu.native.parse_csv_numeric) turns the whole source
        into a float32 matrix without per-row Python objects. None when the
        native lib is absent or the data has strings/ragged rows — callers
        fall back to row iteration."""
        from deeplearning4j_tpu.native import parse_csv_numeric
        if not isinstance(self.source, str):
            # a generator/file-object source may be one-shot: consuming it
            # here would leave the fallback row path empty, so the fast path
            # only applies to path/string sources
            return None
        if os.path.exists(self.source):
            with open(self.source, "rb") as f:
                data = f.read()
        else:
            data = self.source.encode("utf-8")
        return parse_csv_numeric(data, self.delimiter, self.skip_lines)

    @staticmethod
    def _parse(v: str):
        v = v.strip()
        try:
            return float(v)
        except ValueError:
            return v


class CSVSequenceRecordReader(RecordReader):
    """Sequence CSV reader (DataVec CSVSequenceRecordReader): each source —
    file path or list of lines — is one sequence; yields one list-of-records
    per sequence."""

    def __init__(self, sources: Sequence[Union[str, Sequence[str]]],
                 skip_lines: int = 0, delimiter: str = ","):
        self.sources = list(sources)
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def __iter__(self):
        for src in self.sources:
            yield list(CSVRecordReader(src, self.skip_lines, self.delimiter))


class RecordReaderDataSetIterator(DataSetIterator):
    """records → DataSet minibatches (reference
    RecordReaderDataSetIterator.java:51).

    - classification: ``label_index`` column holds the class id,
      ``num_possible_labels`` sets one-hot width
    - regression: ``regression=True`` with ``label_index`` (single target) or
      ``label_index_from``/``label_index_to`` (inclusive range of targets)
    - ``max_num_batches`` caps iteration (reference maxNumBatches)
    """

    def __init__(self, record_reader: RecordReader, batch_size: int,
                 label_index: int = -1, num_possible_labels: int = -1,
                 regression: bool = False,
                 label_index_from: int = -1, label_index_to: int = -1,
                 max_num_batches: int = -1):
        self.reader = record_reader
        self._batch = batch_size
        self.label_index = label_index
        self.num_possible_labels = num_possible_labels
        self.regression = regression
        self.label_index_from = label_index_from
        self.label_index_to = label_index_to
        self.max_num_batches = max_num_batches
        if (not regression and label_index >= 0 and label_index_from < 0
                and num_possible_labels <= 0):
            # per-batch inference would give inconsistent one-hot widths
            raise ValueError(
                "Classification mode needs num_possible_labels (the one-hot "
                "width must be fixed across minibatches)")
        self._label_map: dict = {}  # string label -> class index

    def reset(self):
        self.reader.reset()

    def batch_size(self):
        return self._batch

    def total_outcomes(self):
        if self.regression:
            if self.label_index_from >= 0:
                return self.label_index_to - self.label_index_from + 1
            return 1
        return self.num_possible_labels if self.num_possible_labels > 0 else None

    def _to_float(self, rows, what: str) -> np.ndarray:
        try:
            return np.asarray(rows, np.float32)
        except (ValueError, TypeError):
            bad = next((v for row in rows
                        for v in (row if isinstance(row, (list, tuple)) else [row])
                        if isinstance(v, str)), None)
            if bad is not None:
                raise ValueError(
                    f"Non-numeric value {bad!r} in {what}; map string fields "
                    "to numbers before batching (string class labels in the "
                    "label column are mapped automatically)") from None
            widths = sorted({len(r) for r in rows
                             if isinstance(r, (list, tuple))})
            raise ValueError(
                f"Cannot assemble {what} into an array"
                + (f": ragged record lengths {widths}" if len(widths) > 1
                   else "")) from None

    def _split(self, rows: List[list]):
        li = self.label_index
        if (not self.regression and li >= 0 and len(rows)
                and isinstance(rows[0][li], str)):
            # auto-map string class labels to stable indices in order of
            # first appearance (the common 'species name' CSV case)
            rows = [list(r) for r in rows]
            for r in rows:
                label = r[li]
                if label not in self._label_map:
                    if len(self._label_map) >= self.num_possible_labels:
                        raise ValueError(
                            f"More than num_possible_labels="
                            f"{self.num_possible_labels} distinct labels "
                            f"(new: {label!r})")
                    self._label_map[label] = len(self._label_map)
                r[li] = self._label_map[label]
        arr = self._to_float(rows, "record batch")
        if self.label_index_from >= 0:  # regression target range
            lo, hi = self.label_index_from, self.label_index_to
            labels = arr[:, lo:hi + 1]
            feats = np.concatenate([arr[:, :lo], arr[:, hi + 1:]], axis=1)
        elif self.label_index >= 0:
            labels = arr[:, self.label_index:self.label_index + 1]
            feats = np.concatenate(
                [arr[:, :self.label_index], arr[:, self.label_index + 1:]],
                axis=1)
            if not self.regression:
                labels = _one_hot(labels[:, 0], self.num_possible_labels)
        else:  # no labels: features only (autoencoder style — labels=features)
            feats = labels = arr
        return DataSet(feats, labels.astype(np.float32))

    def _generate(self):
        # native bulk path: one C++ pass over the bytes, then pure slicing
        mat = (self.reader.numeric_matrix()
               if hasattr(self.reader, "numeric_matrix") else None)
        if mat is not None:
            for k, s in enumerate(range(0, len(mat), self._batch)):
                if 0 < self.max_num_batches <= k:
                    return
                yield self._split(mat[s:s + self._batch])
            return
        rows, batches = [], 0
        for rec in self.reader:
            rows.append(rec)
            if len(rows) == self._batch:
                yield self._split(rows)
                rows, batches = [], batches + 1
                if 0 < self.max_num_batches <= batches:
                    return
        if rows:
            yield self._split(rows)


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """sequence records → (batch, time, features) DataSets (reference
    SequenceRecordReaderDataSetIterator.java). Sequences in a batch are
    padded to the longest with features/labels masks (ALIGN_END of the
    reference's alignment modes)."""

    def __init__(self, reader: CSVSequenceRecordReader, batch_size: int,
                 label_index: int = -1, num_possible_labels: int = -1,
                 regression: bool = False):
        self.reader = reader
        self._batch = batch_size
        self.label_index = label_index
        self.num_possible_labels = num_possible_labels
        self.regression = regression
        if not regression and label_index >= 0 and num_possible_labels <= 0:
            raise ValueError(
                "Sequence classification mode needs num_possible_labels")

    def reset(self):
        self.reader.reset()

    def batch_size(self):
        return self._batch

    def _assemble(self, seqs: List[np.ndarray]):
        T = max(s.shape[0] for s in seqs)
        li = self.label_index
        n_feat = seqs[0].shape[1] - (1 if li >= 0 else 0)
        n_lab = (self.num_possible_labels if not self.regression and li >= 0
                 else (1 if li >= 0 else n_feat))
        B = len(seqs)
        x = np.zeros((B, T, n_feat), np.float32)
        y = np.zeros((B, T, n_lab), np.float32)
        mask = np.zeros((B, T), np.float32)
        for i, s in enumerate(seqs):
            t = s.shape[0]
            mask[i, :t] = 1.0
            if li >= 0:
                feats = np.concatenate([s[:, :li], s[:, li + 1:]], axis=1)
                lab = s[:, li]
                x[i, :t] = feats
                if self.regression:
                    y[i, :t, 0] = lab
                else:
                    y[i, :t] = _one_hot(lab, n_lab)
            else:
                x[i, :t] = s
                y[i, :t] = s
        full = mask.all()
        return DataSet(x, y, None if full else mask, None if full else mask)

    def _generate(self):
        seqs = []
        for seq in self.reader:
            seqs.append(np.asarray(seq, np.float32))
            if len(seqs) == self._batch:
                yield self._assemble(seqs)
                seqs = []
        if seqs:
            yield self._assemble(seqs)
