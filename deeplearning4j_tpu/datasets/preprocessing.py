"""DataSet pre-processors / normalizers.

Parity surface: ND4J's ``DataSetPreProcessor`` + normalizers
(NormalizerStandardize, NormalizerMinMaxScaler, ImagePreProcessingScaler —
the objects passed to ``DataSetIterator.setPreProcessor`` throughout the
reference, e.g. RecordReaderDataSetIterator.java setPreProcessor).

Pre-processors are callables ``DataSet -> DataSet`` (pure, not in-place —
functional style keeps them safe under async prefetch where the same source
batch may be referenced elsewhere). Normalizers additionally have
``fit(iterator_or_dataset)`` to learn statistics and ``revert_*`` inverses.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet


class DataSetPreProcessor:
    def __call__(self, ds: DataSet) -> DataSet:
        return self.pre_process(ds)

    def pre_process(self, ds: DataSet) -> DataSet:
        raise NotImplementedError


def _feature_axes(x: np.ndarray):
    # statistics per trailing feature dim; (n, f), (n, t, f) and (n, h, w, c)
    # all reduce over every axis but the last
    return tuple(range(x.ndim - 1))


class NormalizerStandardize(DataSetPreProcessor):
    """Zero-mean unit-variance feature scaling (ND4J NormalizerStandardize)."""

    def __init__(self, fit_labels: bool = False):
        self.fit_labels = fit_labels
        self.mean: Optional[np.ndarray] = None
        self.std: Optional[np.ndarray] = None
        self.label_mean: Optional[np.ndarray] = None
        self.label_std: Optional[np.ndarray] = None

    def fit(self, data):
        if isinstance(data, DataSet):
            data = [data]
        xs, ys = [], []
        for ds in data:
            xs.append(np.asarray(ds.features, np.float64)
                      .reshape(-1, ds.features.shape[-1]))
            if self.fit_labels:
                ys.append(np.asarray(ds.labels, np.float64)
                          .reshape(-1, ds.labels.shape[-1]))
        x = np.concatenate(xs)
        self.mean = x.mean(0)
        self.std = np.maximum(x.std(0), 1e-8)
        if self.fit_labels:
            y = np.concatenate(ys)
            self.label_mean = y.mean(0)
            self.label_std = np.maximum(y.std(0), 1e-8)
        return self

    def pre_process(self, ds: DataSet) -> DataSet:
        if self.mean is None:
            raise ValueError("fit() the normalizer before use")
        x = ((ds.features - self.mean) / self.std).astype(np.float32)
        y = ds.labels
        if self.fit_labels and self.label_mean is not None:
            y = ((y - self.label_mean) / self.label_std).astype(np.float32)
        return DataSet(x, y, ds.features_mask, ds.labels_mask)

    def revert_features(self, x: np.ndarray) -> np.ndarray:
        return x * self.std + self.mean

    def revert_labels(self, y: np.ndarray) -> np.ndarray:
        if self.label_mean is None:
            return y
        return y * self.label_std + self.label_mean


class NormalizerMinMaxScaler(DataSetPreProcessor):
    """Scale features into [lo, hi] (ND4J NormalizerMinMaxScaler)."""

    def __init__(self, lo: float = 0.0, hi: float = 1.0):
        self.lo, self.hi = lo, hi
        self.min: Optional[np.ndarray] = None
        self.max: Optional[np.ndarray] = None

    def fit(self, data):
        if isinstance(data, DataSet):
            data = [data]
        mins, maxs = [], []
        for ds in data:
            x = np.asarray(ds.features).reshape(-1, ds.features.shape[-1])
            mins.append(x.min(0))
            maxs.append(x.max(0))
        self.min = np.min(mins, axis=0)
        self.max = np.max(maxs, axis=0)
        return self

    def pre_process(self, ds: DataSet) -> DataSet:
        if self.min is None:
            raise ValueError("fit() the normalizer before use")
        rng = np.maximum(self.max - self.min, 1e-12)
        x = (ds.features - self.min) / rng * (self.hi - self.lo) + self.lo
        return DataSet(x.astype(np.float32), ds.labels,
                       ds.features_mask, ds.labels_mask)


class ImagePreProcessingScaler(DataSetPreProcessor):
    """uint8-range pixels → [lo, hi] without fitting (ND4J
    ImagePreProcessingScaler): x/255 * (hi-lo) + lo."""

    def __init__(self, lo: float = 0.0, hi: float = 1.0, max_pixel: float = 255.0):
        self.lo, self.hi, self.max_pixel = lo, hi, max_pixel

    def pre_process(self, ds: DataSet) -> DataSet:
        x = ds.features / self.max_pixel * (self.hi - self.lo) + self.lo
        return DataSet(x.astype(np.float32), ds.labels,
                       ds.features_mask, ds.labels_mask)


class CombinedPreProcessor(DataSetPreProcessor):
    """Chain pre-processors in order (reference CombinedPreProcessor.java)."""

    def __init__(self, *processors: DataSetPreProcessor):
        self.processors = processors

    def pre_process(self, ds: DataSet) -> DataSet:
        for p in self.processors:
            ds = p(ds)
        return ds
