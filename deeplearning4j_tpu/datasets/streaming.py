"""Streaming batch ingestion: push-driven DataSet iterators.

Parity surface: reference dl4j-streaming
(``streaming/routes/CamelKafkaRouteBuilder.java:1`` — DataVec records
arriving over Kafka/Camel feed a training loop) and
``spark/iterator/PortableDataStreamDataSetIterator``. The capability — an
EXTERNAL producer pushes batches into a live ``fit()`` — is what matters;
the Kafka/Camel fabric itself is a JVM-ecosystem integration (README
"Scope decisions").

TPU-native design: a bounded queue decouples the producer from the
device-bound training loop exactly like the AsyncDataSetIterator prefetch
path, so the training thread blocks only when the feed runs dry.
``StreamingHttpReceiver`` adds a minimal HTTP front door (POST npz batches)
for producers in other processes/languages.
"""

from __future__ import annotations

import io
import queue
import threading
from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator


class StreamingDataSetIterator(DataSetIterator):
    """Unbounded-duration, push-driven iterator.

    Producers call :meth:`push` (any thread) with a DataSet or
    (features, labels) arrays; the consumer side is an ordinary
    DataSetIterator usable with ``net.fit``. Iteration blocks waiting for
    batches and ends when a producer calls :meth:`end` (one fit pass ==
    one stream segment; a later iteration consumes the next segment from
    the same live queue).
    """

    _END = object()

    def __init__(self, queue_size: int = 16,
                 poll_timeout: Optional[float] = None):
        self._q: "queue.Queue" = queue.Queue(maxsize=queue_size)
        self._poll_timeout = poll_timeout
        self.pushed = 0
        self.consumed = 0
        self._bs: Optional[int] = None

    # ------------------------------------------------------------ producer
    def push(self, features, labels=None, features_mask=None,
             labels_mask=None, timeout: Optional[float] = None):
        """Enqueue one batch; blocks while the queue is full (backpressure).
        Accepts a DataSet or raw arrays."""
        if isinstance(features, DataSet):
            ds = features
        else:
            ds = DataSet(np.asarray(features),
                         None if labels is None else np.asarray(labels),
                         features_mask=features_mask,
                         labels_mask=labels_mask)
        self._q.put(ds, timeout=timeout)
        if self._bs is None:
            self._bs = int(ds.features.shape[0])
        self.pushed += 1
        return self

    def end(self):
        """Mark end of the current stream segment: the consuming iteration
        finishes once everything queued before this call is drained."""
        self._q.put(StreamingDataSetIterator._END)
        return self

    # ------------------------------------------------------------ consumer
    def _generate(self):
        while True:
            try:
                item = self._q.get(timeout=self._poll_timeout)
            except queue.Empty:
                return  # poll_timeout elapsed with no producer activity
            if item is StreamingDataSetIterator._END:
                return
            self.consumed += 1
            yield item

    def reset(self):  # streams have no rewind; reset is a no-op
        pass

    def batch_size(self):
        return self._bs or 0


class StreamingHttpReceiver:
    """HTTP front door for :class:`StreamingDataSetIterator`.

    ``POST /push`` with an ``.npz`` body holding ``features`` and optional
    ``labels`` / ``features_mask`` / ``labels_mask`` arrays enqueues one
    batch; ``POST /end`` closes the current segment. The reference's
    equivalent is the Camel route endpoint feeding DataVec records into
    training (CamelKafkaRouteBuilder.java:1).
    """

    def __init__(self, iterator: StreamingDataSetIterator, port: int = 0):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        it = iterator

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_POST(self):
                try:
                    if self.path == "/end":
                        it.end()
                        self._ok(b"ended")
                        return
                    if self.path != "/push":
                        self.send_error(404)
                        return
                    n = int(self.headers.get("Content-Length", 0))
                    with np.load(io.BytesIO(self.rfile.read(n))) as z:
                        it.push(z["features"],
                                z["labels"] if "labels" in z else None,
                                z["features_mask"] if "features_mask" in z
                                else None,
                                z["labels_mask"] if "labels_mask" in z
                                else None)
                    self._ok(b"ok")
                except Exception as e:  # surface to the producer
                    self.send_error(400, str(e))

            def _ok(self, body):
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


__all__ = ["StreamingDataSetIterator", "StreamingHttpReceiver"]
