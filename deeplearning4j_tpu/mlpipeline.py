"""ML-pipeline integration: scikit-learn-compatible estimators.

Parity surface: reference dl4j-spark-ml
(``deeplearning4j-scaleout/spark/dl4j-spark-ml/src/main/java/org/deeplearning4j/
spark/ml/impl/SparkDl4jNetwork.java:1`` — an Estimator whose ``fit(DataFrame)``
returns a Transformer model usable inside Spark ML Pipelines, plus the
AutoEncoder variant). The JVM-side Spark ML fabric is scoped out (README);
the CAPABILITY — drop a network into the ecosystem's standard pipeline/
grid-search machinery — maps in Python to the scikit-learn estimator
contract, which is what these wrappers implement:

* duck-typed ``get_params``/``set_params``/``fit``/``predict`` — works with
  ``sklearn.pipeline.Pipeline``, ``GridSearchCV``, ``cross_val_score``,
  ``clone`` without importing sklearn here;
* each ``fit`` builds a FRESH network from the configuration (sklearn's
  re-fit semantics), trains it minibatch-wise on the TPU path, and exposes
  the live network as ``model_``.
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet

try:  # newer sklearn requires __sklearn_tags__; inherit it when available
    from sklearn.base import BaseEstimator as _SkBase
    from sklearn.base import ClassifierMixin as _SkClf
    from sklearn.base import RegressorMixin as _SkReg
except Exception:  # sklearn absent: estimators stay pure duck-typed
    class _SkBase:  # distinct empty bases (object twice would TypeError)
        pass

    class _SkClf:
        pass

    class _SkReg:
        pass


class _BaseDL4JEstimator:
    """sklearn-contract plumbing shared by the classifier/regressor."""

    _PARAM_NAMES = ("conf", "epochs", "batch_size", "shuffle", "seed")

    def __init__(self, conf=None, epochs: int = 10, batch_size: int = 32,
                 shuffle: bool = True, seed: int = 12345):
        self.conf = conf
        self.epochs = epochs
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed

    # ------------------------------------------------------ sklearn contract
    def get_params(self, deep: bool = True):
        return {k: getattr(self, k) for k in self._PARAM_NAMES}

    def set_params(self, **params):
        for k, v in params.items():
            if k not in self._PARAM_NAMES:
                raise ValueError(
                    f"Invalid parameter {k!r} for {type(self).__name__}; "
                    f"valid: {self._PARAM_NAMES}")
            setattr(self, k, v)
        return self

    # ----------------------------------------------------------------- fit
    def _build(self):
        from deeplearning4j_tpu.nn.conf.graph import (
            ComputationGraphConfiguration,
        )
        from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        conf = self.conf() if callable(self.conf) else self.conf
        if conf is None:
            raise ValueError(
                f"{type(self).__name__} needs a network configuration: pass "
                "conf=<MultiLayerConfiguration or zero-arg factory>")
        if isinstance(conf, MultiLayerConfiguration):
            return MultiLayerNetwork(conf).init(self.seed)
        if isinstance(conf, ComputationGraphConfiguration):
            return ComputationGraph(conf).init(self.seed)
        raise TypeError(f"Unsupported configuration type {type(conf)}")

    def _fit_arrays(self, X, Y):
        net = self._build()
        X = np.asarray(X, np.float32)
        Y = np.asarray(Y, np.float32)
        rng = np.random.default_rng(self.seed)
        n = len(X)
        for _ in range(int(self.epochs)):
            order = rng.permutation(n) if self.shuffle else np.arange(n)
            for s in range(0, n, int(self.batch_size)):
                idx = order[s:s + int(self.batch_size)]
                net.fit(DataSet(X[idx], Y[idx]))
        self.model_ = net
        self.n_features_in_ = X.shape[1] if X.ndim == 2 else X.shape[1:]
        return self

    def _check_fitted(self):
        if not hasattr(self, "model_"):
            raise RuntimeError(
                f"{type(self).__name__} is not fitted yet; call fit first")

    def _output(self, X) -> np.ndarray:
        """(n, out) network output for MLN and single-input graphs alike
        (ComputationGraph.output returns a LIST of output arrays)."""
        m = self.model_
        X = np.asarray(X, np.float32)
        if hasattr(m, "output_single"):
            return np.asarray(m.output_single(X))
        return np.asarray(m.output(X))


class DL4JClassifier(_BaseDL4JEstimator, _SkClf, _SkBase):
    """Classifier estimator (reference SparkDl4jNetwork classification use).

    ``y`` may be integer class labels or one-hot rows; classes are stored in
    ``classes_`` and predictions are mapped back to the original labels.

    Example::

        clf = DL4JClassifier(conf=my_conf_factory, epochs=30)
        clf.fit(X, y).predict(X2)                 # sklearn semantics
        Pipeline([("scale", StandardScaler()), ("net", clf)]).fit(X, y)
    """

    def fit(self, X, y):
        y = np.asarray(y)
        if y.ndim == 2 and y.shape[1] > 1:          # one-hot given
            self.classes_ = np.arange(y.shape[1])
            onehot = y.astype(np.float32)
        else:
            self.classes_, inv = np.unique(y.ravel(), return_inverse=True)
            onehot = np.eye(len(self.classes_), dtype=np.float32)[inv]
        return self._fit_arrays(X, onehot)

    def predict_proba(self, X) -> np.ndarray:
        self._check_fitted()
        return self._output(X)

    def predict(self, X) -> np.ndarray:
        self._check_fitted()
        return self.classes_[np.argmax(self.predict_proba(X), axis=-1)]

    def score(self, X, y) -> float:
        """Mean accuracy (sklearn classifier contract); accepts the same
        label formats as fit (integer/str labels or one-hot rows)."""
        y = np.asarray(y)
        if y.ndim == 2 and y.shape[1] > 1:
            y = self.classes_[np.argmax(y, axis=-1)]
        else:
            y = y.ravel()
        return float(np.mean(self.predict(X) == y))


class DL4JRegressor(_BaseDL4JEstimator, _SkReg, _SkBase):
    """Regressor estimator (reference SparkDl4jNetwork regression use)."""

    def fit(self, X, y):
        y = np.asarray(y, np.float32)
        if y.ndim == 1:
            y = y[:, None]
        self._y_cols = y.shape[1]
        return self._fit_arrays(X, y)

    def predict(self, X) -> np.ndarray:
        self._check_fitted()
        out = self._output(X)
        return out.ravel() if self._y_cols == 1 else out

    def score(self, X, y) -> float:
        """R^2 (sklearn regressor contract)."""
        y = np.asarray(y, np.float32)
        pred = self.predict(X)
        ss_res = float(np.sum((y.ravel() - pred.ravel()) ** 2))
        ss_tot = float(np.sum((y.ravel() - np.mean(y)) ** 2))
        return 1.0 - ss_res / max(ss_tot, 1e-12)


__all__ = ["DL4JClassifier", "DL4JRegressor"]
