"""Overload-safe HTTP model server in front of ``ParallelInference``.

Parity surface: the reference ships real serving fronts (SURVEY §2.9
``NearestNeighborsServer``, §2.10 the Play-based UI server); this is the
model-inference analogue, stdlib ``ThreadingHTTPServer`` in the house
style of ``clustering/server.py`` / ``ui/server.py``.

Robustness under overload is the design center, not an adapter detail:

- **Continuous batching** — every HTTP handler thread ``submit()``s into
  one ``ParallelInference`` per model; its worker coalesces whatever is
  queued at dispatch time into the pow2 bucket ladder. No fixed
  microbatches: cross-client requests share device batches whenever they
  overlap in the queue.
- **Admission control / load shedding** — the per-model queue is BOUNDED
  (``ParallelInference(queue_depth=...)``); over capacity the server
  answers **429 + Retry-After immediately** instead of queueing without
  limit. A burst beyond sustainable load degrades to fast rejections,
  never to unbounded memory or forever-waiting clients.
- **Deadlines** — each request carries ``deadline_ms`` (body field,
  ``X-Deadline-Ms`` header, or the endpoint default); it propagates into
  batch formation, where expired requests are evicted BEFORE device
  dispatch and answered **504** — a request never occupies a batch slot
  it cannot use.
- **Circuit breaker** — a burst of model-dispatch failures OPENS the
  per-model :class:`~deeplearning4j_tpu.serving.breaker.CircuitBreaker`;
  while open the server answers **503 fast** with Retry-After, then
  half-open probes feel for recovery.
- **Graceful drain** — ``drain()`` (and ``stop()``) sheds new arrivals
  with 503 while every in-flight request completes: zero dropped, which
  also makes checkpoint hot-swap + restart under load safe end to end.
- **Readiness** — ``/readyz`` stays 503 until every endpoint's warmup
  ladder has compiled (no live request ever pays a multi-second XLA
  compile); ``/healthz`` reports process liveness.
- **Observability** — shed/expired/breaker counters, end-to-end
  ``serving_request_ms``, in-flight gauge and per-``ParallelInference``
  queue-depth/occupancy instruments all land in the obs registry,
  scrapeable at this server's own ``/metrics``.

Routes::

    GET  /healthz                     process liveness (+ drain flag)
    GET  /readyz                      200 only when warmed and not draining
    GET  /metrics                     Prometheus exposition (obs registry)
    GET  /v1/models                   model list + serving stats
    GET  /v1/models/<name>            one model's stats (pi + breaker)
    POST /v1/models/<name>:predict    {"inputs": [[...], ...],
                                       "deadline_ms": 250}  (optional)
    POST /v1/models/<name>:generate   {"prompt_ids": [...], "max_tokens":
                                       64, "stream": true}  (serving/decode)

Generate streams tokens as Server-Sent Events over chunked HTTP/1.1
(``event: token`` / ``done`` / ``error`` frames); ``stream: false``
collects the whole generation into one JSON response. Admission rides
the same taxonomy as predict — 429 when every decode session slot is
held, 503 draining/stopped, and 504 when the FIRST token misses
``deadline_ms`` (time-to-first-token). After streaming starts the
status is already 200, so a later token missing ``token_deadline_ms``
terminates the stream with a typed in-band ``error`` event instead —
a stream never silently stalls.

Predict bodies carry the tensor either as a JSON float list (``inputs``)
or as the BINARY wire format — base64-encoded little-endian raw array
bytes::

    {"x_b64": "<base64>", "dtype": "float32", "shape": [4, 784]}

which cuts the payload to ~⅓ of the JSON float encoding (measured in
``bench_serving_load``). ``dtype`` is ``"float32"`` (the native serving
dtype), ``"float64"`` (accepted, downcast to f32 on decode), or ``"int8"``
— the latter on QUANTIZED endpoints only (``quant/``): the payload is
interpreted on the endpoint's calibrated input grid (``x ≈ xq *
input_scale``, the scale reported in the endpoint's stats) — another 4x
fewer bytes on the wire.

Predict responses: 200 ``{"outputs": ...}``; 400 malformed; 404 unknown
model; 413 oversized body; 429 shed (queue full); 503 breaker open or
draining; 504 deadline expired — all errors are structured JSON with an
``"error"`` message and a ``"reason"`` tag.
"""

from __future__ import annotations

import json
import logging
import math
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Sequence
from urllib.parse import urlparse

import numpy as np

from deeplearning4j_tpu.parallel.inference import (DeadlineExpiredError,
                                                   ParallelInference,
                                                   QueueFullError)
from deeplearning4j_tpu.serving.breaker import CircuitBreaker
from deeplearning4j_tpu.serving.decode import (DecodeEngine,
                                               EngineStoppedError,
                                               SessionLimitError)
from deeplearning4j_tpu.serving.wire import decode_array, encode_array
from deeplearning4j_tpu.utils.http import parse_content_length

log = logging.getLogger(__name__)

__all__ = ["ModelEndpoint", "GenerateEndpoint", "ModelServer",
           "BreakerOpenError", "ModelDispatchError"]


class BreakerOpenError(RuntimeError):
    """The endpoint's circuit breaker is open (or probing): fast 503."""

    def __init__(self, retry_after_s: float):
        super().__init__("circuit breaker open")
        self.retry_after_s = float(retry_after_s)


class ModelDispatchError(RuntimeError):
    """The model dispatch itself failed (counted against the breaker)."""


class ModelEndpoint:
    """One served model: a ``ParallelInference`` plus its admission,
    deadline and breaker policy. Build through
    :meth:`ModelServer.add_model` (which owns construction defaults), or
    directly around an existing ``ParallelInference``."""

    def __init__(self, name: str, pi: ParallelInference, *,
                 default_deadline_ms: float = 1000.0,
                 breaker: Optional[CircuitBreaker] = None,
                 warmup_example=None, warmup_buckets=None,
                 owns_pi: bool = False):
        if pi.inference_mode != "batched":
            raise ValueError(
                f"endpoint '{name}' needs a batched-mode ParallelInference "
                "(continuous batching is the serving contract)")
        self.name = name
        self.pi = pi
        self.default_deadline_ms = float(default_deadline_ms)
        self.breaker = breaker if breaker is not None else CircuitBreaker()
        self.warmup_example = warmup_example
        self.warmup_buckets = warmup_buckets
        self.owns_pi = owns_pi
        # feature-shape guard from the warmup example: a wrong-shaped
        # request is a CLIENT error (400) and must never reach dispatch,
        # where its failure would count against the model's breaker
        self.feature_shape = (None if warmup_example is None
                              else tuple(np.asarray(warmup_example).shape[1:]))
        # warmed==True means /readyz may pass: either the ladder compiled
        # or no example was given (caller accepts first-request compiles)
        self.warmed = warmup_example is None
        self._warmup_lock = threading.Lock()
        # quantized serving (quant/): the flag is surfaced per endpoint in
        # stats(), and input_scale is the calibrated grid int8 wire
        # payloads are decoded on (None ⇒ int8 payloads rejected 400)
        from deeplearning4j_tpu.quant.lowering import (input_quant_scale,
                                                       is_quantized)
        self.quantized = is_quantized(pi.model)
        self.input_scale = input_quant_scale(pi.model)

    def warmup(self):
        """Compile the bucket ladder; flips the readiness gate."""
        with self._warmup_lock:
            if self.warmup_example is not None:
                self.pi.warmup(self.warmup_example,
                               buckets=self.warmup_buckets)
            self.warmed = True
        return self

    def predict(self, arr: np.ndarray,
                deadline_ms: Optional[float] = None) -> np.ndarray:
        """Admission → (deadline-aware) batch formation → dispatch.
        Raises the typed errors the HTTP layer maps to 429/503/504/500."""
        if not self.breaker.allow():
            raise BreakerOpenError(self.breaker.retry_after())
        dl_ms = (self.default_deadline_ms if deadline_ms is None
                 else float(deadline_ms))
        deadline = (time.monotonic() + dl_ms / 1000.0
                    if dl_ms and dl_ms > 0 else None)
        obs = self.pi.submit(arr, deadline=deadline)  # QueueFullError ⇒ 429
        try:
            # the extra beat past the deadline covers a batch already ON
            # the device when the deadline passed — eviction only happens
            # at batch formation, so a dispatched request may still answer
            out = obs.get(timeout=(dl_ms / 1000.0 + 5.0)
                          if deadline is not None else None)
        except DeadlineExpiredError:
            raise
        except TimeoutError:
            # result never materialized inside deadline + slack: to the
            # client this is the same 504; not proven to be a model fault,
            # so it does not feed the breaker
            raise DeadlineExpiredError(
                "result not ready within deadline (+5s dispatch slack)")
        except BaseException as e:
            self.breaker.record_failure()
            raise ModelDispatchError(f"{type(e).__name__}: {e}") from e
        self.breaker.record_success()
        if self.feature_shape is None:
            # learned from the first success: later wrong-shaped requests
            # become 400s at the guard instead of dispatch failures that
            # count against the model's breaker
            self.feature_shape = tuple(arr.shape[1:])
        if deadline is not None and time.monotonic() > deadline:
            # dispatched in time but finished late (e.g. a slow batch
            # already on the device when the deadline passed): the answer
            # is worthless to the caller — 504, so a 200 ALWAYS means the
            # deadline was met (the model stays healthy: no breaker hit)
            raise DeadlineExpiredError("result completed after the "
                                       "deadline; discarded")
        return out

    def stats(self) -> dict:
        st = self.pi.stats()
        return {
            "requests_served": st["requests_served"],
            "batches_dispatched": st["batches_dispatched"],
            "queue": st["queue"],
            "batch_size": st["batch_size"],
            "hot_swap": st["hot_swap"],
            "warmed": self.warmed,
            "quantized": self.quantized,
            "input_scale": self.input_scale,
            "breaker": self.breaker.as_dict(),
            "default_deadline_ms": self.default_deadline_ms,
        }


class GenerateEndpoint:
    """One generative model behind ``POST /v1/models/<name>:generate``:
    a :class:`~deeplearning4j_tpu.serving.decode.DecodeEngine` plus the
    HTTP-facing policy — token-budget cap, time-to-first-token and
    per-token deadline defaults, and the optional vocab that lets
    clients send ``"prompt"`` strings instead of ``"prompt_ids"``.
    Build through :meth:`ModelServer.add_generator`."""

    def __init__(self, name: str, engine: DecodeEngine, *,
                 default_max_tokens: int = 64,
                 max_max_tokens: int = 1024,
                 default_deadline_ms: float = 1000.0,
                 default_token_deadline_ms: float = 10000.0):
        self.name = name
        self.engine = engine
        self.default_max_tokens = int(default_max_tokens)
        self.max_max_tokens = int(max_max_tokens)
        self.default_deadline_ms = float(default_deadline_ms)
        self.default_token_deadline_ms = float(default_token_deadline_ms)
        self._stoi = (None if engine.vocab is None
                      else {c: i for i, c in enumerate(engine.vocab)})

    @property
    def warmed(self) -> bool:
        return self.engine.readiness()[0]

    def warmup(self):
        """Compile the decode slot ladder + prefill buckets and run the
        priming wave; flips this generator's readiness gate."""
        self.engine.warmup()
        return self

    def encode_prompt(self, prompt: str):
        if self._stoi is None:
            raise ValueError(
                f"generator '{self.name}' has no vocab — send "
                "'prompt_ids' (a list of token ids) instead of 'prompt'")
        try:
            return [self._stoi[c] for c in prompt]
        except KeyError as e:
            raise ValueError(f"prompt character {e} is not in generator "
                             f"'{self.name}'s vocab") from e

    def stats(self) -> dict:
        return {
            **self.engine.stats(),
            "default_max_tokens": self.default_max_tokens,
            "max_max_tokens": self.max_max_tokens,
            "default_deadline_ms": self.default_deadline_ms,
            "default_token_deadline_ms": self.default_token_deadline_ms,
            "has_vocab": self._stoi is not None,
        }

    def shutdown(self, drain: bool = False, drain_timeout_s: float = 10.0):
        self.engine.stop(drain=drain, drain_timeout_s=drain_timeout_s)


def _decode_inputs(body: dict, ep: "ModelEndpoint") -> np.ndarray:
    """Predict-body tensor decode: JSON ``inputs`` float lists, or the
    binary wire format ``{"x_b64", "dtype", "shape"}`` (serving/wire.py —
    base64 of raw little-endian array bytes). int8 payloads are only
    meaningful on a quantized endpoint, where they are decoded on the
    model's calibrated input grid. Raises KeyError (no tensor at all) or
    ValueError (malformed) — the HTTP layer maps both to 400."""
    if "inputs" in body:
        return np.asarray(body["inputs"], dtype=np.float32)
    if "x_b64" not in body:
        raise KeyError("inputs")
    return decode_array(
        body, int8_scale=ep.input_scale, allow_explicit_scale=False,
        int8_hint=f"model '{ep.name}' is not quantized (or its first "
                  "layer is not) — int8 payloads need the endpoint's "
                  "calibrated input scale; send float32")


class _Handler(BaseHTTPRequestHandler):
    server_ref: Optional["ModelServer"] = None
    # HTTP/1.1 so :generate can stream with chunked transfer encoding;
    # every non-stream response still carries Content-Length, so plain
    # keep-alive request/response traffic is unaffected
    protocol_version = "HTTP/1.1"
    # slow-client guard: a peer that stops sending mid-request times out
    # and frees its handler thread instead of holding it forever
    timeout = 30.0

    def log_message(self, fmt, *args):  # quiet
        pass

    def _body(self, body: bytes, content_type: str, code: int = 200,
              retry_after_s: Optional[float] = None):
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        if retry_after_s is not None:
            self.send_header("Retry-After",
                             str(max(1, math.ceil(retry_after_s))))
        if code >= 400:
            # error paths may answer before consuming the request body
            # (404/413/...), which under keep-alive would poison the next
            # request on the reused connection — close it instead
            self.send_header("Connection", "close")
            self.close_connection = True
        self.end_headers()
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass  # client gave up; the server must not care

    def _json(self, obj, code: int = 200,
              retry_after_s: Optional[float] = None):
        self._body(json.dumps(obj).encode(), "application/json", code,
                   retry_after_s=retry_after_s)

    def _error(self, code: int, reason: str, message: str,
               retry_after_s: Optional[float] = None):
        self._json({"error": message, "reason": reason}, code,
                   retry_after_s=retry_after_s)

    # ----------------------------------------------------------------- GET
    def do_GET(self):
        srv = type(self).server_ref
        path = urlparse(self.path).path
        if path == "/healthz":
            self._json({"ok": True, "draining": srv.draining,
                        "models": sorted(srv.endpoints),
                        "indexes": sorted(srv.indexes),
                        "generators": sorted(srv.generators)})
        elif path == "/readyz":
            ready, reasons = srv.readiness()
            if ready:
                self._json({"ready": True})
            else:
                self._json({"ready": False, "reasons": reasons}, 503)
        elif path == "/metrics":
            from deeplearning4j_tpu.obs.exporters import prometheus_text
            self._body(prometheus_text().encode(),
                       "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/v1/models":
            self._json({"models": {n: ep.stats()
                                   for n, ep in srv.endpoints.items()},
                        "generators": {n: g.stats()
                                       for n, g in srv.generators.items()}})
        elif path == "/v1/indexes":
            self._json({"indexes": {n: ep.stats()
                                    for n, ep in srv.indexes.items()}})
        elif path.startswith("/v1/models/"):
            name = path[len("/v1/models/"):]
            ep = srv.endpoints.get(name)
            if ep is not None:
                self._json({"model": name, **ep.stats()})
            elif name in srv.generators:
                self._json({"model": name,
                            **srv.generators[name].stats()})
            else:
                self._error(404, "unknown_model", f"no model '{name}'")
        elif path.startswith("/v1/indexes/"):
            name = path[len("/v1/indexes/"):]
            ep = srv.indexes.get(name)
            if ep is None:
                self._error(404, "unknown_index", f"no index '{name}'")
            else:
                self._json({"index": name, **ep.stats()})
        else:
            self._error(404, "not_found", "not found")

    # ---------------------------------------------------------------- POST
    def do_POST(self):
        srv = type(self).server_ref
        path = urlparse(self.path).path
        if path.startswith("/v1/models/") and path.endswith(":predict"):
            self._do_predict(srv, path)
        elif path.startswith("/v1/models/") and path.endswith(":generate"):
            self._do_generate(srv, path)
        elif path.startswith("/v1/indexes/") and path.endswith(":query"):
            self._do_query(srv, path)
        else:
            self._error(404, "not_found", "not found")

    def _do_predict(self, srv, path):
        name = path[len("/v1/models/"):-len(":predict")]
        ep = srv.endpoints.get(name)
        if ep is None:
            self._error(404, "unknown_model", f"no model '{name}'")
            return
        length, err = parse_content_length(self.headers, srv.max_body_bytes)
        if err is not None:
            code, message = err
            self._error(code, "bad_request" if code == 400
                        else "body_too_large", message)
            return
        srv._m_requests.inc()
        # drain check + in-flight enter are ATOMIC: drain() observes a
        # complete count — a request is either shed or tracked, never
        # silently in between
        if not srv._enter_request():
            srv._m_drain_rejected.inc()
            self._error(503, "draining",
                        "server is draining; retry against another replica",
                        retry_after_s=srv.retry_after_s)
            return
        t0 = time.perf_counter()
        try:
            try:
                body = json.loads(self.rfile.read(length) or b"{}")
                if not isinstance(body, dict):
                    raise ValueError("body must be a JSON object")
                arr = _decode_inputs(body, ep)
                deadline_ms = body.get(
                    "deadline_ms", self.headers.get("X-Deadline-Ms"))
                if deadline_ms is not None:
                    deadline_ms = float(deadline_ms)
            except KeyError:
                self._error(400, "bad_request", "body needs an 'inputs' "
                            "array ({\"inputs\": [[...], ...]}) or the "
                            "binary form {\"x_b64\", \"dtype\", \"shape\"}")
                return
            except (ValueError, TypeError) as e:
                self._error(400, "bad_request", f"malformed request: {e}")
                return
            if arr.ndim < 2 or arr.shape[0] < 1:
                self._error(400, "bad_request",
                            "'inputs' needs a leading batch axis "
                            f"(got shape {arr.shape})")
                return
            if ep.feature_shape is not None and \
                    tuple(arr.shape[1:]) != ep.feature_shape:
                self._error(400, "bad_request",
                            f"model '{name}' takes features of shape "
                            f"{ep.feature_shape}; got {tuple(arr.shape[1:])}")
                return
            try:
                out = ep.predict(arr, deadline_ms=deadline_ms)
            except QueueFullError as e:
                srv._m_shed.inc()
                self._error(429, "shed", str(e),
                            retry_after_s=srv.retry_after_s)
                return
            except BreakerOpenError as e:
                srv._m_breaker_rejected.inc()
                self._error(503, "breaker_open",
                            f"model '{name}' is failing; breaker open",
                            retry_after_s=e.retry_after_s)
                return
            except DeadlineExpiredError as e:
                srv._m_expired.inc()
                srv._m_request_ms.observe((time.perf_counter() - t0) * 1e3)
                self._error(504, "deadline_expired", str(e))
                return
            except ModelDispatchError as e:
                srv._m_errors.inc()
                self._error(500, "dispatch_failed",
                            f"inference failed: {e}")
                return
            srv._m_request_ms.observe((time.perf_counter() - t0) * 1e3)
            self._json({
                "model": name,
                "outputs": np.asarray(out).tolist(),
                "checkpoint_step": ep.pi.current_checkpoint_step,
            })
        finally:
            srv._exit_request()

    def _do_generate(self, srv, path):
        """``POST /v1/models/<name>:generate`` — admit a generative
        session on the model's DecodeEngine and deliver its tokens,
        either streamed as SSE over chunked HTTP or collected into one
        JSON body. The 429/503/504 taxonomy applies up to the first
        token; afterwards deadline faults become typed in-band events."""
        name = path[len("/v1/models/"):-len(":generate")]
        gep = srv.generators.get(name)
        if gep is None:
            self._error(404, "unknown_model", f"no generator '{name}'")
            return
        length, err = parse_content_length(self.headers, srv.max_body_bytes)
        if err is not None:
            code, message = err
            self._error(code, "bad_request" if code == 400
                        else "body_too_large", message)
            return
        srv._m_requests.inc()
        if not srv._enter_request():
            srv._m_drain_rejected.inc()
            self._error(503, "draining",
                        "server is draining; retry against another replica",
                        retry_after_s=srv.retry_after_s)
            return
        t0 = time.perf_counter()
        sess = None
        try:
            try:
                body = json.loads(self.rfile.read(length) or b"{}")
                if not isinstance(body, dict):
                    raise ValueError("body must be a JSON object")
                if "prompt_ids" in body:
                    prompt_ids = [int(i) for i in body["prompt_ids"]]
                elif "prompt" in body:
                    prompt_ids = gep.encode_prompt(str(body["prompt"]))
                else:
                    raise ValueError(
                        "body needs 'prompt_ids' (a list of token ids) "
                        "or 'prompt' (a string, on generators with a "
                        "vocab)")
                max_tokens = int(body.get("max_tokens",
                                          gep.default_max_tokens))
                if not 1 <= max_tokens <= gep.max_max_tokens:
                    raise ValueError(f"max_tokens must be in "
                                     f"[1, {gep.max_max_tokens}]; "
                                     f"got {max_tokens}")
                temperature = float(body.get("temperature", 1.0))
                if not (temperature >= 0.0):  # also rejects NaN
                    raise ValueError(
                        f"temperature must be >= 0; got {temperature}")
                top_k = int(body.get("top_k", 0))
                eos_id = body.get("eos_id")
                stream = bool(body.get("stream", True))
                deadline_ms = body.get(
                    "deadline_ms", self.headers.get("X-Deadline-Ms"))
                deadline_ms = (gep.default_deadline_ms if deadline_ms
                               is None else float(deadline_ms))
                token_deadline_ms = float(body.get(
                    "token_deadline_ms", gep.default_token_deadline_ms))
            except (ValueError, TypeError, KeyError) as e:
                self._error(400, "bad_request", f"malformed request: {e}")
                return
            try:
                sess = gep.engine.open_session(
                    prompt_ids, max_tokens=max_tokens,
                    temperature=temperature, top_k=top_k,
                    eos_id=None if eos_id is None else int(eos_id))
            except SessionLimitError as e:
                srv._m_shed.inc()
                self._error(429, "shed", str(e),
                            retry_after_s=srv.retry_after_s)
                return
            except EngineStoppedError as e:
                srv._m_drain_rejected.inc()
                self._error(503, "draining", str(e),
                            retry_after_s=srv.retry_after_s)
                return
            except ValueError as e:
                self._error(400, "bad_request", f"malformed request: {e}")
                return
            # time-to-first-token deadline: nothing has been written yet,
            # so a miss still gets a proper 504 status line
            first = sess.next_event(
                timeout_s=deadline_ms / 1000.0 if deadline_ms > 0 else None)
            if first is None:
                sess.cancel()
                srv._m_expired.inc()
                srv._m_request_ms.observe((time.perf_counter() - t0) * 1e3)
                self._error(504, "deadline_expired",
                            f"no first token within {deadline_ms:.0f}ms")
                return
            if first["type"] == "error":
                srv._m_errors.inc()
                self._error(503 if first.get("error") == "engine_stopped"
                            else 500, first.get("error", "decode_failed"),
                            first.get("message", "decode failed"),
                            retry_after_s=srv.retry_after_s)
                return
            token_deadline_s = (token_deadline_ms / 1000.0
                                if token_deadline_ms > 0 else None)
            if stream:
                self._stream_generate(srv, name, sess, first,
                                      token_deadline_s, t0)
            else:
                self._collect_generate(srv, name, gep, sess, first,
                                       token_deadline_s, t0)
        finally:
            if sess is not None and not sess.finished:
                sess.cancel()  # free the slot at the next token boundary
            srv._exit_request()

    def _stream_generate(self, srv, name, sess, first, token_deadline_s,
                         t0):
        """SSE over chunked HTTP/1.1: one ``event:``/``data:`` frame per
        engine event, each its own chunk so tokens flush as they land.
        The terminal frame is always ``done`` or a typed ``error``."""
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Transfer-Encoding", "chunked")
        self.send_header("Connection", "close")
        self.close_connection = True
        self.end_headers()

        def send_event(ev: dict):
            payload = json.dumps({k: v for k, v in ev.items()
                                  if k != "type"})
            data = f"event: {ev['type']}\ndata: {payload}\n\n".encode()
            self.wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")
            self.wfile.flush()

        terminal = None
        try:
            send_event({"type": "meta", "model": name,
                        "session": sess.id})
            send_event(first)
            if first["type"] in ("done", "error"):
                terminal = first
            else:
                for ev in sess.events(token_deadline_s):
                    send_event(ev)
                    if ev["type"] in ("done", "error"):
                        terminal = ev
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            sess.cancel()  # client went away mid-stream: free the slot
            return
        if terminal is not None and terminal["type"] == "error":
            if terminal.get("error") == "token_deadline_expired":
                srv._m_expired.inc()
            else:
                srv._m_errors.inc()
        srv._m_request_ms.observe((time.perf_counter() - t0) * 1e3)

    def _collect_generate(self, srv, name, gep, sess, first,
                          token_deadline_s, t0):
        """``stream: false`` — drain the whole generation, answer once."""
        events = [first]
        if first["type"] not in ("done", "error"):
            events.extend(sess.events(token_deadline_s))
        terminal = events[-1]
        if terminal["type"] == "error":
            if terminal.get("error") == "token_deadline_expired":
                srv._m_expired.inc()
                self._error(504, "deadline_expired",
                            terminal.get("message", "token deadline"))
            else:
                srv._m_errors.inc()
                self._error(503 if terminal.get("error") == "engine_stopped"
                            else 500,
                            terminal.get("error", "decode_failed"),
                            terminal.get("message", "decode failed"),
                            retry_after_s=srv.retry_after_s)
            return
        toks = [ev for ev in events if ev["type"] == "token"]
        srv._m_request_ms.observe((time.perf_counter() - t0) * 1e3)
        out = {"model": name, "session": sess.id,
               "token_ids": [ev["id"] for ev in toks],
               "tokens": len(toks), "reason": terminal.get("reason")}
        if gep.engine.vocab is not None:
            out["text"] = "".join(ev.get("text") or "" for ev in toks)
        self._json(out)

    def _do_query(self, srv, path):
        """``POST /v1/indexes/<name>:query`` — batched vector k-NN with
        the full serving contract (429 shed / 503 breaker / 504 deadline
        / drain), sharing the admission gate and SLO metrics with the
        predict route. Queries arrive as JSON ``{"queries": [[...]]}`` or
        the binary wire form ``{"x_b64","dtype","shape"}`` (int8 decoded
        on the index's table grid, or an explicit ``"scale"``); pass
        ``"b64": true`` to get ``indices_b64``/``distances_b64`` binary
        responses back."""
        from deeplearning4j_tpu.parallel.inference import \
            DeadlineExpiredError as _Expired
        from deeplearning4j_tpu.retrieval.service import IndexDispatchError

        name = path[len("/v1/indexes/"):-len(":query")]
        ep = srv.indexes.get(name)
        if ep is None:
            self._error(404, "unknown_index", f"no index '{name}'")
            return
        length, err = parse_content_length(self.headers, srv.max_body_bytes)
        if err is not None:
            code, message = err
            self._error(code, "bad_request" if code == 400
                        else "body_too_large", message)
            return
        srv._m_requests.inc()
        if not srv._enter_request():
            srv._m_drain_rejected.inc()
            self._error(503, "draining",
                        "server is draining; retry against another replica",
                        retry_after_s=srv.retry_after_s)
            return
        t0 = time.perf_counter()
        try:
            try:
                body = json.loads(self.rfile.read(length) or b"{}")
                if not isinstance(body, dict):
                    raise ValueError("body must be a JSON object")
                if "queries" in body:
                    q = np.asarray(body["queries"], dtype=np.float32)
                elif "x_b64" in body:
                    ix = ep.index
                    # any index that PUBLISHES a wire grid (int8 and
                    # int4 tables — queries stay on the int8 grid
                    # regardless of table codec) decodes int8 payloads
                    # on it; PQ/fp32 indexes publish none
                    scale = ix.scale
                    q = decode_array(
                        body, int8_scale=(float(body["scale"])
                                          if "scale" in body else scale),
                        int8_hint=f"index '{name}' publishes no int8 "
                                  "wire grid — int8 query payloads need "
                                  "a 'scale' field (or an int8/int4 "
                                  "index, whose table grid is used); "
                                  "send float32")
                else:
                    raise ValueError(
                        "body needs a 'queries' array ({\"queries\": "
                        "[[...], ...]}) or the binary form "
                        "{\"x_b64\", \"dtype\", \"shape\"}")
                if q.ndim == 1:
                    q = q[None, :]
                k = int(body.get("k", ep.k_default))
                deadline_ms = body.get(
                    "deadline_ms", self.headers.get("X-Deadline-Ms"))
                if deadline_ms is not None:
                    deadline_ms = float(deadline_ms)
                if q.ndim != 2 or q.shape[0] < 1 \
                        or q.shape[1] != ep.index.dim:
                    raise ValueError(
                        f"index '{name}' takes (b, {ep.index.dim}) "
                        f"queries; got shape {tuple(q.shape)}")
                if not 1 <= k <= ep.k_max:
                    raise ValueError(
                        f"k must be in [1, {ep.k_max}]; got {k}")
                if q.shape[0] > ep.max_query_rows:
                    raise ValueError(
                        f"batch of {q.shape[0]} queries exceeds this "
                        f"endpoint's max_query_rows={ep.max_query_rows}; "
                        "split the batch")
            except (ValueError, TypeError, KeyError) as e:
                self._error(400, "bad_request", f"malformed request: {e}")
                return
            try:
                idx, dist = ep.query(q, k, deadline_ms=deadline_ms)
            except QueueFullError as e:
                srv._m_shed.inc()
                self._error(429, "shed", str(e),
                            retry_after_s=srv.retry_after_s)
                return
            except BreakerOpenError as e:
                srv._m_breaker_rejected.inc()
                self._error(503, "breaker_open",
                            f"index '{name}' is failing; breaker open",
                            retry_after_s=e.retry_after_s)
                return
            except _Expired as e:
                srv._m_expired.inc()
                srv._m_request_ms.observe((time.perf_counter() - t0) * 1e3)
                self._error(504, "deadline_expired", str(e))
                return
            except IndexDispatchError as e:
                srv._m_errors.inc()
                self._error(500, "dispatch_failed", f"query failed: {e}")
                return
            except ValueError as e:
                # admission-time validation (shape/k/rows drift between
                # the HTTP checks and submit, e.g. across a hot-swap):
                # still a caller error — 400, never a dead handler
                self._error(400, "bad_request", f"malformed request: {e}")
                return
            srv._m_request_ms.observe((time.perf_counter() - t0) * 1e3)
            srv._m_requests_retrieval.inc()
            out = {"index": name, "k": k}
            labels = ep.index.labels
            if body.get("b64"):
                # fixed response dtypes: indices int32 LE, distances
                # float32 LE, both of the stated shape
                out["indices_b64"] = encode_array(
                    np.asarray(idx, np.int32), "indices_b64")["indices_b64"]
                out["distances_b64"] = encode_array(
                    np.asarray(dist, np.float32),
                    "distances_b64")["distances_b64"]
                out["shape"] = [int(s) for s in np.asarray(idx).shape]
            else:
                out["indices"] = np.asarray(idx).tolist()
                out["distances"] = np.asarray(dist).tolist()
                if labels is not None:
                    out["labels"] = [[labels[i] if 0 <= i < len(labels)
                                      else None for i in row]
                                     for row in np.asarray(idx)]
            self._json(out)
        finally:
            srv._exit_request()


class ModelServer:
    """Multi-model HTTP serving front (see module docstring).

    ``ModelServer({"iris": net}).start()`` builds a batched
    ``ParallelInference`` per model (bounded queue, immediate shed) and
    serves them behind one port; pass a ``ParallelInference`` instead of
    a model to control batching/bucketing/hot-swap yourself, or a
    :class:`ModelEndpoint` to control everything."""

    def __init__(self, models: Optional[Dict[str, object]] = None, *,
                 port: int = 0, bind_address: str = "127.0.0.1",
                 max_body_bytes: int = 8 << 20,
                 default_deadline_ms: float = 1000.0,
                 retry_after_s: float = 1.0,
                 queue_depth: int = 256, batch_limit: int = 32,
                 compile_cache_dir: Optional[str] = None):
        # loopback by default, like the UI/kNN servers: exposing an
        # unauthenticated predict endpoint beyond the host is an opt-in
        if compile_cache_dir is not None:
            from deeplearning4j_tpu.perf.compile_cache import \
                enable_compilation_cache
            enable_compilation_cache(compile_cache_dir)
        self.compile_cache_dir = compile_cache_dir
        self.port = port
        self.bind_address = bind_address
        self.max_body_bytes = int(max_body_bytes)
        self.default_deadline_ms = float(default_deadline_ms)
        self.retry_after_s = float(retry_after_s)
        self._default_queue_depth = int(queue_depth)
        self._default_batch_limit = int(batch_limit)
        self.endpoints: Dict[str, ModelEndpoint] = {}
        self.indexes: Dict[str, object] = {}  # name -> IndexEndpoint
        self.generators: Dict[str, GenerateEndpoint] = {}
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._warmup_thread: Optional[threading.Thread] = None
        self._state = threading.Condition()
        self._inflight = 0
        self._draining = False
        from deeplearning4j_tpu.obs.registry import (absorb_model_server,
                                                     get_registry)
        reg = get_registry()
        self._m_requests = reg.counter(
            "serving_http_requests", unit="requests",
            help="predict requests received over HTTP")
        self._m_shed = reg.counter(
            "serving_requests_shed", unit="requests",
            help="requests shed with 429 because the bounded admission "
                 "queue was full (load shedding, never unbounded growth)")
        self._m_expired = reg.counter(
            "serving_requests_expired", unit="requests",
            help="requests answered 504 after their deadline expired "
                 "(evicted before device dispatch)")
        self._m_breaker_rejected = reg.counter(
            "serving_breaker_rejected", unit="requests",
            help="requests answered 503 fast while a model's circuit "
                 "breaker was open")
        self._m_drain_rejected = reg.counter(
            "serving_drain_rejected", unit="requests",
            help="requests shed with 503 while the server drained")
        self._m_errors = reg.counter(
            "serving_request_errors", unit="requests",
            help="predict requests that failed in model dispatch (500)")
        self._m_requests_retrieval = reg.counter(
            "serving_retrieval_requests", unit="requests",
            help="retrieval :query requests answered 200 over HTTP")
        self._m_request_ms = reg.histogram(
            "serving_request_ms", unit="ms",
            help="end-to-end HTTP predict latency for admitted requests "
                 "(queue wait + batch formation + dispatch)")
        self._m_inflight = reg.gauge(
            "serving_inflight_requests", unit="requests",
            help="predict requests currently inside the server "
                 "(admitted, not yet answered)")
        absorb_model_server(reg, self)
        for name, m in (models or {}).items():
            self.add_model(name, m)

    # ---------------------------------------------------------- model mgmt
    def add_model(self, name: str, model, *, warmup_example=None,
                  warmup_buckets=None, breaker: Optional[CircuitBreaker]
                  = None, default_deadline_ms: Optional[float] = None,
                  queue_depth: Optional[int] = None,
                  batch_limit: Optional[int] = None,
                  fold_bn: bool = False, quantize=None,
                  checkpoint_manager=None,
                  checkpoint_poll_secs: Optional[float] = None,
                  tuning=None) -> ModelEndpoint:
        """Register a model (several nets behind one server, each with its
        own ``ParallelInference``, queue and breaker). ``quantize`` takes a
        ``quant.CalibrationRecord``: the endpoint serves the int8 lowering
        (``ParallelInference(quantize=)``) — re-applied on every checkpoint
        hot-swap — and accepts int8 binary predict payloads. ``tuning``
        takes a ``perf.autotune.TuningRecord``: the endpoint serves on the
        record's bucket ladder, warmed at registration
        (``ParallelInference(tuning=)``), so it compiles nothing at serve
        time."""
        if name in self.endpoints:
            raise ValueError(f"model '{name}' already registered")
        if (quantize is not None or tuning is not None) \
                and isinstance(model, (ModelEndpoint, ParallelInference)):
            # a pre-built PI/endpoint already owns its serving graph —
            # silently dropping the record would serve untuned/fp32 while
            # the caller believes the record is applied
            raise ValueError(
                "add_model(quantize=/tuning=) needs the raw network — pass "
                "the model itself, or build the ParallelInference with "
                "quantize=/tuning= and register that")
        if isinstance(model, ModelEndpoint):
            ep = model
            ep.name = name
        elif isinstance(model, ParallelInference):
            ep = ModelEndpoint(
                name, model, warmup_example=warmup_example,
                warmup_buckets=warmup_buckets, breaker=breaker,
                default_deadline_ms=(self.default_deadline_ms
                                     if default_deadline_ms is None
                                     else default_deadline_ms))
        else:
            pi = ParallelInference(
                model,
                batch_limit=(self._default_batch_limit if batch_limit is None
                             else batch_limit),
                queue_depth=(self._default_queue_depth if queue_depth is None
                             else queue_depth),
                queue_put_timeout_ms=0.0,  # over capacity ⇒ IMMEDIATE 429
                fold_bn=fold_bn, quantize=quantize, tuning=tuning,
                checkpoint_manager=checkpoint_manager,
                checkpoint_poll_secs=checkpoint_poll_secs)
            ep = ModelEndpoint(
                name, pi, warmup_example=warmup_example,
                warmup_buckets=warmup_buckets, breaker=breaker,
                default_deadline_ms=(self.default_deadline_ms
                                     if default_deadline_ms is None
                                     else default_deadline_ms),
                owns_pi=True)
        self.endpoints[name] = ep
        return ep

    def add_generator(self, name: str, model, *,
                      max_sessions: int = 64, min_slots: int = 8,
                      prefill_buckets: Sequence[int] = (16, 64, 256),
                      vocab: Optional[Sequence[str]] = None, seed: int = 0,
                      default_max_tokens: int = 64,
                      max_max_tokens: int = 1024,
                      default_deadline_ms: Optional[float] = None,
                      default_token_deadline_ms: float = 10000.0,
                      checkpoint_manager=None,
                      checkpoint_poll_secs: Optional[float] = None,
                      hot_swap_policy: str = "carry") -> GenerateEndpoint:
        """Register a generative (autoregressive) model behind
        ``POST /v1/models/<name>:generate``. Builds a
        :class:`~deeplearning4j_tpu.serving.decode.DecodeEngine` (pass
        one directly to control the slot ladder yourself) and starts its
        decode worker; the slot-ladder warmup rides the server's warmup
        pass and gates ``/readyz``. ``checkpoint_manager`` enables
        mid-generation hot-swap (``hot_swap_policy`` "carry" keeps
        session carries across the param swap, "reprefill" rebuilds them
        from prompt + generated history under the new params)."""
        if name in self.generators:
            raise ValueError(f"generator '{name}' already registered")
        if isinstance(model, DecodeEngine):
            engine = model
        else:
            engine = DecodeEngine(model, max_sessions=max_sessions,
                                  min_slots=min_slots,
                                  prefill_buckets=prefill_buckets,
                                  seed=seed, vocab=vocab)
        engine.start()
        if checkpoint_manager is not None:
            engine.start_hot_swap(
                checkpoint_manager,
                poll_secs=(5.0 if checkpoint_poll_secs is None
                           else checkpoint_poll_secs),
                policy=hot_swap_policy)
        gep = GenerateEndpoint(
            name, engine, default_max_tokens=default_max_tokens,
            max_max_tokens=max_max_tokens,
            default_deadline_ms=(self.default_deadline_ms
                                 if default_deadline_ms is None
                                 else default_deadline_ms),
            default_token_deadline_ms=default_token_deadline_ms)
        self.generators[name] = gep
        return gep

    def add_index(self, name: str, index, *, k_default: int = 10,
                  k_max: int = 128,
                  default_deadline_ms: Optional[float] = None,
                  queue_depth: Optional[int] = None,
                  batch_limit: int = 64,
                  breaker: Optional[CircuitBreaker] = None,
                  warmup_queries: int = 256):
        """Register a vector index (``retrieval/``) behind
        ``POST /v1/indexes/<name>:query`` with the SAME serving contract
        as models: bounded admission (429), per-request deadlines (504),
        circuit breaker (503), drain, warmup-gated readiness and the SLO
        metrics. Pass a ``retrieval.IndexEndpoint`` to control batching
        yourself, or any index (BruteForceIndex/IVFIndex) for the
        defaults. Hot-swap a rebuilt index under load via the returned
        endpoint's ``swap_index()``."""
        from deeplearning4j_tpu.retrieval.service import IndexEndpoint

        if name in self.indexes:
            raise ValueError(f"index '{name}' already registered")
        if isinstance(index, IndexEndpoint):
            ep = index
            ep.name = name
        else:
            ep = IndexEndpoint(
                name, index, k_default=k_default, k_max=k_max,
                default_deadline_ms=(self.default_deadline_ms
                                     if default_deadline_ms is None
                                     else default_deadline_ms),
                queue_depth=(self._default_queue_depth if queue_depth is None
                             else queue_depth),
                batch_limit=batch_limit, breaker=breaker,
                warmup_queries=warmup_queries)
        self.indexes[name] = ep
        return ep

    # ------------------------------------------------------------ lifecycle
    def start(self, warmup: bool = True,
              warmup_async: bool = True) -> "ModelServer":
        """Bind and serve. ``warmup=True`` compiles every endpoint's
        bucket ladder (async by default — the port answers immediately,
        ``/readyz`` flips to 200 when compilation finishes; pass
        ``warmup_async=False`` to block until ready)."""
        handler = type("BoundServingHandler", (_Handler,),
                       {"server_ref": self})
        # socketserver's default listen backlog is 5: a burst of
        # simultaneous connects (far-above-capacity offered load — exactly
        # what this tier exists to absorb) can then overflow the TCP
        # accept queue and surface as kernel connection RESETS instead of
        # the admission layer's typed 429s. Deepen the backlog so sheds
        # happen in OUR code, with Retry-After, not in the kernel's.
        server_cls = type("BacklogThreadingHTTPServer",
                          (ThreadingHTTPServer,),
                          {"request_queue_size": 128})
        self._httpd = server_cls((self.bind_address, self.port), handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="model-server", daemon=True)
        self._thread.start()
        if warmup:
            if warmup_async:
                self._warmup_thread = threading.Thread(
                    target=self.warmup, name="serving-warmup", daemon=True)
                self._warmup_thread.start()
            else:
                self.warmup()
        return self

    def warmup(self):
        """Compile every endpoint's warmup ladder (gates ``/readyz``) —
        model bucket ladders, index (bucket × k-rung) ladders and decode
        slot ladders alike."""
        for ep in (list(self.endpoints.values())
                   + list(self.indexes.values())
                   + list(self.generators.values())):
            try:
                ep.warmup()
            except Exception:
                log.exception("warmup failed for endpoint '%s'; it "
                              "stays not-ready", ep.name)
        return self

    def readiness(self):
        unwarmed = sorted(n for n, ep in self.endpoints.items()
                          if not ep.warmed)
        unwarmed_ix = sorted(n for n, ep in self.indexes.items()
                             if not ep.warmed)
        unwarmed_gen = sorted(n for n, g in self.generators.items()
                              if not g.warmed)
        reasons = []
        if unwarmed:
            reasons.append(f"warmup pending: {unwarmed}")
        if unwarmed_ix:
            reasons.append(f"index warmup pending: {unwarmed_ix}")
        if unwarmed_gen:
            reasons.append(f"decode warmup pending: {unwarmed_gen}")
        if self.draining:
            reasons.append("draining")
        return (not reasons, reasons)

    @property
    def draining(self) -> bool:
        with self._state:
            return self._draining

    @property
    def inflight(self) -> int:
        with self._state:
            return self._inflight

    def _enter_request(self) -> bool:
        with self._state:
            if self._draining:
                return False
            self._inflight += 1
            self._m_inflight.set(self._inflight)
            return True

    def _exit_request(self):
        with self._state:
            self._inflight -= 1
            self._m_inflight.set(self._inflight)
            self._state.notify_all()

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Graceful drain: new arrivals shed (503), every in-flight
        request completes — zero dropped. Returns whether in-flight hit
        zero inside the timeout. Idempotent; ``undrain()`` reverses it
        (e.g. after a hot-swap rollout step)."""
        deadline = time.monotonic() + timeout_s
        with self._state:
            self._draining = True
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._state.wait(remaining)
            return True

    def undrain(self):
        with self._state:
            self._draining = False

    def stop(self, drain: bool = True, drain_timeout_s: float = 30.0):
        """Drain (unless told not to), stop the listener, shut down every
        endpoint's ``ParallelInference`` this server built."""
        if drain:
            self.drain(timeout_s=drain_timeout_s)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        for ep in self.endpoints.values():
            if ep.owns_pi:
                ep.pi.shutdown()
        for iep in self.indexes.values():
            iep.shutdown()
        for gep in self.generators.values():
            # server-level drain above already waited out live streams;
            # this stops the decode worker (bounded) and error-terminates
            # anything still stuck
            gep.shutdown(drain=drain, drain_timeout_s=drain_timeout_s)

    @property
    def address(self) -> str:
        return f"http://{self.bind_address}:{self.port}"
