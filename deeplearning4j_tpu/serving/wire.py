"""Binary wire format for tensor payloads over the HTTP tiers.

One encoding, three servers (model predict, kNN, retrieval query):
base64 of raw little-endian array bytes plus enough JSON to rebuild the
array::

    {"x_b64": "<base64>", "dtype": "float32", "shape": [4, 784]}

- ``float32`` — the native serving dtype (~3× smaller than JSON float
  lists, measured in ``bench_serving_load``).
- ``float64`` — accepted, downcast to f32 on decode.
- ``int8`` — another 4× fewer bytes; only meaningful against a known
  symmetric grid, so decode requires a scale: the endpoint's calibrated
  input grid (quantized models), the index's table grid (int8 retrieval
  indexes), or an explicit ``"scale"`` field (the host kNN server, which
  has no calibration to fall back on). ``x ≈ x_int8 * scale``.

Responses can carry arrays the same way (``encode_array``): retrieval
endpoints answer ``indices_b64``/``distances_b64`` when the client asks
for ``"b64": true`` — bulk top-k batches are int32/float32 matrices,
exactly the payloads JSON float-bloats worst.
"""

from __future__ import annotations

import base64
from typing import Optional

import numpy as np

__all__ = ["WIRE_DTYPES", "decode_array", "encode_array"]

WIRE_DTYPES = ("float32", "float64", "int8")


def decode_array(body: dict, *, field: str = "x_b64",
                 int8_scale: Optional[float] = None,
                 allow_explicit_scale: bool = True,
                 int8_hint: str = "int8 payloads need a quantized "
                                  "endpoint; send float32") -> np.ndarray:
    """Decode ``{field, "dtype", "shape"}`` from a JSON body into a
    float32 array. ``int8_scale`` is the symmetric grid int8 payloads are
    decoded on; when None an explicit ``"scale"`` field is honored
    (unless ``allow_explicit_scale=False`` — quantized model endpoints
    own their grid) and its absence raises ``ValueError(int8_hint)`` —
    the HTTP layers map that to a structured 400."""
    dtype = str(body.get("dtype", "float32"))
    if dtype not in WIRE_DTYPES:
        raise ValueError(f"unsupported wire dtype '{dtype}' "
                         f"(supported: {list(WIRE_DTYPES)})")
    shape = body.get("shape")
    if (not isinstance(shape, (list, tuple)) or not shape
            or not all(isinstance(d, int) and d > 0 for d in shape)):
        raise ValueError("binary payloads need 'shape': a non-empty list "
                         "of positive ints")
    raw = base64.b64decode(str(body[field]), validate=True)
    dt = np.dtype(dtype).newbyteorder("<")
    expected = int(np.prod(shape)) * dt.itemsize
    if len(raw) != expected:
        raise ValueError(
            f"payload is {len(raw)} bytes but shape {list(shape)} of "
            f"{dtype} needs {expected}")
    arr = np.frombuffer(raw, dtype=dt).reshape(shape)
    if dtype == "int8":
        scale = int8_scale
        if scale is None and allow_explicit_scale and "scale" in body:
            scale = float(body["scale"])
        if scale is None:
            raise ValueError(int8_hint)
        return arr.astype(np.float32) * np.float32(scale)
    return np.ascontiguousarray(arr, dtype=np.float32)


def encode_array(arr: np.ndarray, field: str = "x_b64") -> dict:
    """The response-side encoding: little-endian raw bytes, base64."""
    a = np.ascontiguousarray(arr)
    le = a.astype(a.dtype.newbyteorder("<"), copy=False)
    return {field: base64.b64encode(le.tobytes()).decode("ascii"),
            "dtype": str(a.dtype), "shape": list(a.shape)}
