"""Generative decode tier: device-resident session state, continuous
session batching, token streaming.

The reference's ``rnnTimeStep`` decode API serves ONE session per call
with a host round-trip per token. This module turns the same recurrent
step into a serving tier:

- **Device-resident session state.** Every live session owns one slot in
  a device-resident state block: the LSTM carries (h, c — the KV-cache
  shape, LSTM edition) with leading axis = slot capacity, plus per-slot
  last-token / temperature / top-k / active lanes. Capacity is a pow2
  **slot ladder** (min_slots, 2·min_slots, ... ≥ max_sessions): growth
  jumps to the next rung, and :meth:`DecodeEngine.warmup` pre-compiles
  every rung so growth never compiles at serve time.
- **Continuous session batching.** ONE jitted step advances every active
  slot per dispatch. Sessions join/leave only at token boundaries, by
  scatter-writing (join) or flag-clearing (clear) their slot — both are
  themselves warmed jitted programs with the slot index traced, so the
  steady state compiles NOTHING (CompileWatch-asserted in tests).
- **On-device sampling.** Temperature/top-k sampling runs inside the
  step off a device PRNG key that never leaves the device; the only
  host transfer per dispatch is the bulk (S,) sampled-token vector
  (trace_check-asserted: syncs scale with steps, not sessions×tokens).
  ``temperature <= 0`` means argmax-greedy — deterministic, used by the
  parity tests against sequential ``rnn_time_step``.
- **Prefill buckets.** Prompts run through right-padded pow2 length
  buckets with a feature mask; the LSTM mask semantics hold the carry
  through padded steps, so the final carry equals the carry after the
  real prompt. Longer prompts chunk through the largest bucket with the
  carry threaded — the same path re-prefills a session after a
  checkpoint hot-swap under ``policy="reprefill"``.
- **Checkpoint hot-swap.** :meth:`start_hot_swap` polls a
  CheckpointManager like ``ParallelInference``; a newer checkpoint is
  restored OFF-PATH and the swap is applied by the decode worker
  between dispatches — sessions either carry their state across the
  swap (``policy="carry"``, default) or are re-prefilled from
  prompt+generated under the new params (``policy="reprefill"``).

Host-side rule (lint DLT020): nothing in the per-token path reads the
device. The worker fetches the sampled-token vector once per dispatch
(:func:`_host_read`) and all delivery/bookkeeping below that point
iterates over host numpy.
"""

from __future__ import annotations

import itertools
import logging
import queue
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.parallel.inference import QueueFullError

log = logging.getLogger(__name__)

__all__ = ["DecodeEngine", "DecodeSession", "SessionLimitError",
           "EngineStoppedError"]

_session_ids = itertools.count(1)


class SessionLimitError(QueueFullError):
    """Admission refused: every slot the engine may grow to is occupied.
    Subclasses QueueFullError so the server's 429 mapping applies."""


class EngineStoppedError(RuntimeError):
    """open_session() on a stopped or draining engine — maps to 503."""


def _pow2_at_least(n: int) -> int:
    return 1 << max(0, (int(n) - 1).bit_length())


def _sample_next(logits, temps, topks, key):
    """In-graph temperature/top-k sampling, one row per slot. Pure jnp:
    top-k threshold via a descending sort, -inf mask, temperature
    scaling, then ``jax.random.categorical`` (independent per row).
    ``topk <= 0`` disables the top-k cut; ``temp <= 0`` selects argmax."""
    v = logits.shape[-1]
    desc = jnp.sort(logits, axis=-1)[:, ::-1]
    k_idx = jnp.clip(jnp.where(topks > 0, topks, v), 1, v) - 1
    kth = jnp.take_along_axis(desc, k_idx[:, None], axis=-1)
    masked = jnp.where(logits < kth, -jnp.inf, logits)
    scaled = masked / jnp.maximum(temps, 1e-6)[:, None]
    sampled = jax.random.categorical(key, scaled, axis=-1)
    greedy = jnp.argmax(logits, axis=-1)
    return jnp.where(temps > 0.0, sampled, greedy).astype(jnp.int32)


def _host_read(arr) -> np.ndarray:
    """The ONE host transfer per decode dispatch: the bulk (S,) sampled
    vector. Everything below this point in the delivery path is host
    numpy — never a per-session device read."""
    return np.asarray(arr)


class DecodeSession:
    """One generative stream: admission parameters, the generated-id
    history, and a bounded event queue the transport drains.

    Events are dicts: ``{"type": "token", "id": int, "index": int,
    "text": str|None}``, ``{"type": "done", "reason": str, "tokens":
    int}`` or ``{"type": "error", "error": str, "message": str}``. A
    terminal event (done/error) is always the last one delivered —
    a stream never silently stalls."""

    def __init__(self, prompt_ids: Sequence[int], *, max_tokens: int,
                 temperature: float, top_k: int, eos_id: Optional[int],
                 engine: "DecodeEngine"):
        self.id = f"s{next(_session_ids)}"
        self.prompt_ids = [int(i) for i in prompt_ids]
        self.max_tokens = int(max_tokens)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.eos_id = eos_id if eos_id is None else int(eos_id)
        self.generated: List[int] = []
        self.opened_at = time.monotonic()
        self.first_token_at: Optional[float] = None
        self._last_token_at: Optional[float] = None
        self._engine = engine
        self._cancelled = False
        self._finished = False
        self._synthetic = False  # warmup priming: excluded from metrics
        self.slot: Optional[int] = None
        # bounded (DLT008): max_tokens token events + one terminal event
        self._events: "queue.Queue[dict]" = queue.Queue(
            maxsize=self.max_tokens + 8)

    # ------------------------------------------------------------- consumer
    def next_event(self, timeout_s: Optional[float] = None) -> Optional[dict]:
        """Blocking read of the next event; ``None`` means the timeout
        elapsed with the engine silent — the caller owns the deadline
        semantics (the HTTP layer turns it into a typed error event)."""
        try:
            return self._events.get(timeout=timeout_s)
        except queue.Empty:
            return None

    def events(self, token_deadline_s: Optional[float] = None):
        """Iterate events until the terminal one. A token that misses
        ``token_deadline_s`` terminates the iteration with a typed
        ``error`` event (and cancels the session) — never a silent
        stall."""
        while True:
            ev = self.next_event(token_deadline_s)
            if ev is None:
                self.cancel()
                yield {"type": "error", "error": "token_deadline_expired",
                       "message": f"no token within {token_deadline_s}s"}
                return
            yield ev
            if ev["type"] in ("done", "error"):
                return

    def cancel(self):
        """Mark for retirement; the decode worker clears the slot at the
        next token boundary. Idempotent, callable from any thread."""
        self._cancelled = True
        self._engine._nudge()

    @property
    def finished(self) -> bool:
        return self._finished

    # ----------------------------------------------- engine-side delivery
    def _emit(self, ev: dict):
        try:
            self._events.put_nowait(ev)
        except queue.Full:  # consumer gone; retire via cancel path
            self._cancelled = True

    def _finish(self, reason: str):
        self._finished = True
        self._emit({"type": "done", "reason": reason,
                    "tokens": len(self.generated)})


class DecodeEngine:
    """Continuous-batching autoregressive decode over one network.

    The engine owns the device session block and a single worker thread
    that admits pending sessions, dispatches the jitted step, and
    delivers sampled tokens — all device-state mutation happens on that
    thread, so joins/leaves/swaps land exactly at token boundaries."""

    def __init__(self, net, *, max_sessions: int = 64, min_slots: int = 8,
                 prefill_buckets: Sequence[int] = (16, 64, 256),
                 seed: int = 0, vocab: Optional[Sequence[str]] = None):
        self._net = net
        self._step_fn = net.decode_step_fn()
        self._watch = net.compile_watch
        self.vocab_size = net.decode_vocab_size()
        n_out = getattr(net.layers[-1], "n_out", None)
        if n_out is not None and int(n_out) != self.vocab_size:
            raise ValueError(
                f"closed-loop decode needs n_out == input vocab; got "
                f"n_out={n_out} vs vocab={self.vocab_size}")
        self.vocab = list(vocab) if vocab is not None else None
        self.max_sessions = int(max_sessions)
        min_slots = _pow2_at_least(min(min_slots, self.max_sessions))
        self._rungs: List[int] = []
        s = min_slots
        while True:
            self._rungs.append(s)
            if s >= self.max_sessions:
                break
            s *= 2
        self._buckets = sorted(_pow2_at_least(b) for b in prefill_buckets)
        self._params = net.params
        self._state = net.state
        self._carry1 = net._zero_carries(1)
        self._key = jax.random.PRNGKey(seed)

        self._lock = threading.RLock()
        self._work = threading.Condition(self._lock)
        self._cap_idx = 0
        self._dstate = self._init_dstate(self._rungs[0])
        self._free: List[int] = list(range(self._rungs[0]))
        self._slots: Dict[int, DecodeSession] = {}
        self._pending: "deque[DecodeSession]" = deque()
        self._sessions: Dict[str, DecodeSession] = {}
        self._running = False
        self._draining = False
        self._warmed = False
        self._worker: Optional[threading.Thread] = None
        self._steps = 0

        # hot-swap
        self._swap_cm = None
        self._swap_policy = "carry"
        self._swap_seen_step: Optional[int] = None
        self._pending_swap: Optional[Tuple[object, object, int]] = None
        self._swap_count = 0
        self._swap_thread: Optional[threading.Thread] = None
        self._swap_stop = threading.Event()

        self._progs: Dict[str, object] = {}
        self._init_metrics()

    # ------------------------------------------------------------- metrics
    def _init_metrics(self):
        from deeplearning4j_tpu.obs.registry import get_registry
        reg = get_registry()
        self._m_active = reg.gauge(
            "decode_sessions_active", unit="sessions",
            help="live decode sessions holding a device slot")
        self._m_tokens = reg.counter(
            "decode_tokens_total", unit="tokens",
            help="tokens sampled and delivered across all decode sessions")
        self._m_steps = reg.counter(
            "decode_steps_total", unit="dispatches",
            help="batched decode step dispatches (all active slots "
                 "advance one token per dispatch)")
        self._m_ttft = reg.histogram(
            "decode_ttft_ms", unit="ms",
            help="time to first token: session open to first delivery")
        self._m_itl = reg.histogram(
            "decode_itl_ms", unit="ms",
            help="inter-token latency between consecutive deliveries "
                 "of one session")
        self._m_swaps = reg.counter(
            "decode_hot_swaps_total", unit="swaps",
            help="checkpoint hot-swaps applied at a token boundary")

    # -------------------------------------------------------- device state
    def _init_dstate(self, cap: int):
        carries = self._net._zero_carries(cap)
        return (carries,
                jnp.zeros((cap,), dtype=jnp.int32),
                jnp.ones((cap,), dtype=jnp.float32),
                jnp.zeros((cap,), dtype=jnp.int32),
                jnp.zeros((cap,), dtype=jnp.bool_))

    @property
    def capacity(self) -> int:
        return self._rungs[self._cap_idx]

    # ------------------------------------------------------ jitted programs
    def _prog(self, kind: str):
        """One wrapped jitted program per kind; rung/bucket shapes are
        plain shape specializations of the same program, pre-compiled by
        warmup so neither growth nor any steady-state dispatch compiles."""
        fn = self._progs.get(kind)
        if fn is not None:
            return fn
        step_fn = self._step_fn
        if kind == "step":
            def prog(params, state, dstate, key):
                carries, tokens, temps, topks, active = dstate
                logits, new_carries = step_fn(params, state, carries, tokens)
                key, sub = jax.random.split(key)
                nxt = _sample_next(logits, temps, topks, sub)
                return ((new_carries, nxt, temps, topks, active), nxt, key)
        elif kind == "join":
            def prog(dstate, slot, carry, token, temp, topk):
                carries, tokens, temps, topks, active = dstate
                nc = jax.tree_util.tree_map(
                    lambda a, b: a.at[slot].set(b[0]), carries, carry)
                return (nc, tokens.at[slot].set(token),
                        temps.at[slot].set(temp),
                        topks.at[slot].set(topk),
                        active.at[slot].set(True))
        elif kind == "clear":
            def prog(dstate, slot):
                carries, tokens, temps, topks, active = dstate
                return (carries, tokens, temps, topks,
                        active.at[slot].set(False))
        elif kind == "grow":
            def prog(dstate):
                def pad(a):
                    return jnp.concatenate([a, jnp.zeros_like(a)], axis=0)
                carries, tokens, temps, topks, active = dstate
                return (jax.tree_util.tree_map(pad, carries), pad(tokens),
                        pad(temps), pad(topks), pad(active))
        elif kind == "prefill":
            net = self._net
            index_seq = getattr(net.layers[0], "takes_index_sequence", False)
            n_in = self.vocab_size

            def prog(params, state, ids, length, carry, temp, topk, key):
                t = ids.shape[1]
                x = ids if index_seq else jax.nn.one_hot(
                    ids, n_in, dtype=jnp.float32)
                fmask = (jnp.arange(t)[None, :] < length).astype(jnp.float32)
                _, preout, _, _, new_carries = net._forward(
                    params, state, x, False, None, fmask, carry)
                idx = jnp.reshape(length - 1, (1, 1, 1)).astype(jnp.int32)
                last = jnp.take_along_axis(
                    preout, idx, axis=1)[:, 0, :].astype(jnp.float32)
                key, sub = jax.random.split(key)
                tok = _sample_next(last, temp[None], topk[None], sub)[0]
                return new_carries, tok, key
        else:
            raise KeyError(kind)
        fn = self._watch.wrap(jax.jit(prog), f"decode.{kind}")
        self._progs[kind] = fn
        return fn

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "DecodeEngine":
        with self._work:
            if self._running:
                return self
            self._running = True
        self._worker = threading.Thread(target=self._run_loop,
                                        name="decode-worker", daemon=True)
        self._worker.start()
        return self

    def warmup(self):
        """Compile the full slot ladder (step/join/clear at every rung,
        grow at every rung transition) and every prefill bucket, then run
        a synthetic priming wave through the live worker — max_sessions
        short sessions that grow the ladder to its top rung end-to-end.
        After priming, capacity sits at the top rung (it is a high-water
        mark and never shrinks), so every serve-time dispatch — step,
        join, clear, prefill — replays a program the warmup already
        compiled: the steady state compiles NOTHING. Priming sessions
        are marked synthetic and excluded from the serving metrics."""
        params, state = self._params, self._state
        key = jax.random.PRNGKey(0)
        step, join, clear, grow = (self._prog("step"), self._prog("join"),
                                   self._prog("clear"), self._prog("grow"))
        pf = self._prog("prefill")
        # prefill first: its carry/token outputs are the exact arguments
        # the serve-time join receives, so the join signature warmed here
        # is the one admission dispatches
        carry, tok = self._carry1, None
        for b in self._buckets:
            ids = jnp.zeros((1, b), dtype=jnp.int32)
            carry, tok, key = pf(params, state, ids, np.int32(1),
                                 self._carry1, np.float32(1.0),
                                 np.int32(0), key)
        ds = self._init_dstate(self._rungs[0])
        for i, cap in enumerate(self._rungs):
            ds2 = join(ds, np.int32(0), carry, tok,
                       np.float32(1.0), np.int32(0))
            ds2, toks, key = step(params, state, ds2, key)
            ds2 = clear(ds2, np.int32(0))
            jax.block_until_ready(toks)
            if i + 1 < len(self._rungs):
                ds = grow(ds2)
        # priming wave: the live path end-to-end, worker thread included
        self.start()
        prime = []
        with self._work:
            if not self._draining:
                for _ in range(self.max_sessions):
                    sess = DecodeSession([0], max_tokens=2, temperature=1.0,
                                         top_k=2, eos_id=None, engine=self)
                    sess._synthetic = True
                    self._sessions[sess.id] = sess
                    self._pending.append(sess)
                    prime.append(sess)
                self._work.notify_all()
        for sess in prime:
            for _ in sess.events(token_deadline_s=120.0):
                pass
        with self._work:
            self._warmed = True

    def readiness(self) -> Tuple[bool, List[str]]:
        reasons = []
        if not self._warmed:
            reasons.append("decode slot ladder not warmed")
        if not self._running:
            reasons.append("decode worker not running")
        return (not reasons), reasons

    def stop(self, drain: bool = False, drain_timeout_s: float = 10.0):
        """Stop the worker. ``drain=True`` first refuses new sessions and
        waits (bounded) for active ones to finish; anything still live at
        the deadline gets a terminal error event."""
        with self._work:
            self._draining = True
            self._work.notify_all()
        if drain:
            deadline = time.monotonic() + drain_timeout_s
            while time.monotonic() < deadline:
                with self._work:
                    if not self._slots and not self._pending:
                        break
                time.sleep(0.01)
        with self._work:
            self._running = False
            self._work.notify_all()
            leftovers = list(self._slots.values()) + list(self._pending)
            self._slots.clear()
            self._pending.clear()
        for sess in leftovers:
            if not sess._finished:
                sess._finished = True
                sess._emit({"type": "error", "error": "engine_stopped",
                            "message": "decode engine stopped"})
        self._swap_stop.set()
        if self._worker is not None:
            self._worker.join(timeout=5.0)
        if self._swap_thread is not None:
            self._swap_thread.join(timeout=5.0)
        self._m_active.set(0)

    # ------------------------------------------------------------ admission
    def open_session(self, prompt_ids: Sequence[int], *,
                     max_tokens: int = 64, temperature: float = 1.0,
                     top_k: int = 0, eos_id: Optional[int] = None
                     ) -> DecodeSession:
        """Admit a generative stream, or refuse: SessionLimitError (429)
        when every slot the ladder may grow to is held, EngineStoppedError
        (503) when stopping/draining. Admission itself happens on the
        decode worker at the next token boundary."""
        prompt_ids = list(prompt_ids)
        if not prompt_ids:
            raise ValueError("empty prompt: decode needs >= 1 prompt token")
        bad = [i for i in prompt_ids
               if not (0 <= int(i) < self.vocab_size)]
        if bad:
            raise ValueError(
                f"prompt ids out of range [0, {self.vocab_size}): {bad[:5]}")
        if max_tokens < 1:
            raise ValueError("max_tokens must be >= 1")
        with self._work:
            if not self._running or self._draining:
                raise EngineStoppedError("decode engine is not accepting "
                                         "sessions (stopped or draining)")
            if len(self._sessions) >= self.max_sessions:
                raise SessionLimitError(
                    f"all {self.max_sessions} decode sessions in use")
            sess = DecodeSession(prompt_ids, max_tokens=max_tokens,
                                 temperature=temperature, top_k=top_k,
                                 eos_id=eos_id, engine=self)
            self._sessions[sess.id] = sess
            self._pending.append(sess)
            self._work.notify_all()
        return sess

    def _nudge(self):
        with self._work:
            self._work.notify_all()

    # ----------------------------------------------------------- the worker
    def _run_loop(self):
        """The decode loop: admit → step → deliver, forever. Every device
        state mutation (join/clear/grow/swap) happens here, between step
        dispatches — the token-boundary contract."""
        step = self._prog("step")
        while True:
            with self._work:
                if not self._running:
                    return
                if self._pending_swap is not None:
                    self._apply_swap_locked()
                self._admit_pending_locked()
                if not self._slots:
                    self._work.wait(0.05)
                    continue
                params, state = self._params, self._state
                dstate, key = self._dstate, self._key
                occupied = dict(self._slots)
            try:
                dstate, toks_dev, key = step(params, state, dstate, key)
                toks = _host_read(toks_dev)
            except Exception as e:  # pragma: no cover - device failure
                log.exception("decode step failed; terminating sessions")
                self._fail_all(e)
                return
            with self._work:
                self._dstate, self._key = dstate, key
                self._steps += 1
                self._m_steps.inc()
                self._deliver_locked(toks, occupied)

    def _admit_pending_locked(self):
        join = self._prog("join")
        grow = self._prog("grow")
        while self._pending:
            sess = self._pending[0]
            if sess._cancelled:
                self._pending.popleft()
                self._sessions.pop(sess.id, None)
                if not sess._finished:
                    sess._finish("cancelled")
                continue
            if not self._free:
                if self._cap_idx + 1 >= len(self._rungs):
                    break  # ladder maxed; admission gate should prevent this
                old = self.capacity
                self._dstate = grow(self._dstate)
                self._cap_idx += 1
                self._free.extend(range(old, self.capacity))
            self._pending.popleft()
            slot = self._free.pop()
            carry, first_tok, self._key = self._run_prefill(
                sess.prompt_ids, sess.temperature, sess.top_k, self._key)
            self._dstate = join(self._dstate, np.int32(slot), carry,
                                first_tok, np.float32(sess.temperature),
                                np.int32(sess.top_k))
            sess.slot = slot
            self._slots[slot] = sess
            self._m_active.set(len(self._slots))
            self._deliver_one_locked(sess, int(_host_read(first_tok)))

    def _run_prefill(self, ids: List[int], temp: float, topk: int, key):
        """Right-padded bucketed prefill; prompts longer than the top
        bucket chunk through it with the carry threaded. Returns the
        batch-1 carry after the full prompt plus the sampled first
        token (device scalars — no host read here)."""
        pf = self._prog("prefill")
        params, state = self._params, self._state
        carry = self._carry1
        top = self._buckets[-1]
        pos = 0
        tok = None
        while pos < len(ids):
            rem = len(ids) - pos
            if rem > top:
                n, bucket = top, top
            else:
                n = rem
                bucket = next(b for b in self._buckets if b >= rem)
            chunk = np.zeros((1, bucket), dtype=np.int32)
            chunk[0, :n] = ids[pos:pos + n]
            carry, tok, key = pf(params, state, jnp.asarray(chunk),
                                 np.int32(n), carry, np.float32(temp),
                                 np.int32(topk), key)
            pos += n
        return carry, tok, key

    def _deliver_locked(self, toks: np.ndarray, occupied: Dict[int, "DecodeSession"]):
        for slot, sess in occupied.items():
            if sess._cancelled:
                self._retire_locked(sess, "cancelled")
                continue
            self._deliver_one_locked(sess, int(toks[slot]))

    def _deliver_one_locked(self, sess: DecodeSession, tok: int):
        now = time.monotonic()
        sess.generated.append(tok)
        if sess.first_token_at is None:
            sess.first_token_at = now
            if not sess._synthetic:
                self._m_ttft.observe((now - sess.opened_at) * 1e3)
        elif sess._last_token_at is not None and not sess._synthetic:
            self._m_itl.observe((now - sess._last_token_at) * 1e3)
        sess._last_token_at = now
        if not sess._synthetic:
            self._m_tokens.inc()
        text = None
        if self.vocab is not None and 0 <= tok < len(self.vocab):
            text = self.vocab[tok]
        sess._emit({"type": "token", "id": tok,
                    "index": len(sess.generated) - 1, "text": text})
        if sess.eos_id is not None and tok == sess.eos_id:
            self._retire_locked(sess, "eos")
        elif len(sess.generated) >= sess.max_tokens:
            self._retire_locked(sess, "max_tokens")

    def _retire_locked(self, sess: DecodeSession, reason: str):
        slot = sess.slot
        if slot is not None and self._slots.get(slot) is sess:
            self._dstate = self._prog("clear")(self._dstate, np.int32(slot))
            del self._slots[slot]
            self._free.append(slot)
        sess.slot = None
        self._sessions.pop(sess.id, None)
        self._m_active.set(len(self._slots))
        if not sess._finished:
            sess._finish(reason)
        self._work.notify_all()

    def _fail_all(self, err: Exception):
        with self._work:
            self._running = False
            sessions = list(self._sessions.values())
            self._sessions.clear()
            self._slots.clear()
            self._pending.clear()
        for sess in sessions:
            sess._finished = True
            sess._emit({"type": "error", "error": "engine_failure",
                        "message": str(err)})

    # ------------------------------------------------------------- hot swap
    def start_hot_swap(self, checkpoint_manager, poll_secs: float = 5.0,
                       policy: str = "carry"):
        """Poll for newer checkpoints and apply them at a token boundary.
        ``policy="carry"``: sessions keep their device carries across the
        param swap. ``policy="reprefill"``: each live session's carry is
        rebuilt under the new params from prompt + generated history."""
        if policy not in ("carry", "reprefill"):
            raise ValueError(f"unknown hot-swap policy {policy!r}")
        self._swap_cm = checkpoint_manager
        self._swap_policy = policy
        self._swap_seen_step = checkpoint_manager.latest_step()
        self._swap_thread = threading.Thread(
            target=self._swap_loop, args=(poll_secs,),
            name="decode-hot-swap", daemon=True)
        self._swap_thread.start()

    def _swap_loop(self, poll_secs: float):
        errors = 0
        while not self._swap_stop.wait(poll_secs * (1 + min(errors, 5))):
            try:
                self.poll_checkpoint()
                errors = 0
            except Exception:
                errors += 1
                log.exception("decode hot-swap poll failed (%d)", errors)

    def poll_checkpoint(self) -> bool:
        """One poll: restore a strictly newer checkpoint off-path, check
        the param structure matches, then hand it to the decode worker to
        swap between dispatches. Returns True when a swap was staged."""
        cm = self._swap_cm
        if cm is None:
            return False
        cm.refresh()
        refresh_err = getattr(cm, "last_refresh_error", None)
        if refresh_err is not None:
            # the journal re-read failed: this probe learned nothing —
            # surface the fault so the poll loop backs off
            raise refresh_err
        latest = cm.latest_step()
        if latest is None or (self._swap_seen_step is not None
                              and latest <= self._swap_seen_step):
            return False
        net = cm.restore_latest(load_updater=False)
        if net is None:
            return False
        # restore_latest may fall back past a torn newest entry to a
        # checkpoint at-or-before the one being served — don't downgrade
        restored_step = getattr(getattr(net, "_restored_from", None),
                                "step", latest)
        if self._swap_seen_step is not None \
                and restored_step <= self._swap_seen_step:
            return False
        old_td = jax.tree_util.tree_structure(self._params)
        new_td = jax.tree_util.tree_structure(net.params)
        if old_td != new_td:
            log.warning("hot-swap refused: checkpoint param structure "
                        "changed (%s != %s)", new_td, old_td)
            self._swap_seen_step = latest
            return False
        with self._work:
            self._pending_swap = (net.params, net.state, latest)
            self._swap_seen_step = latest
            self._work.notify_all()
        return True

    def _apply_swap_locked(self):
        params, state, ckpt_step = self._pending_swap
        self._pending_swap = None
        self._params, self._state = params, state
        self._swap_count += 1
        self._m_swaps.inc()
        log.info("decode hot-swap applied at step boundary (checkpoint "
                 "step %s, policy=%s, %d live sessions)", ckpt_step,
                 self._swap_policy, len(self._slots))
        if self._swap_policy != "reprefill":
            return
        join = self._prog("join")
        for slot, sess in list(self._slots.items()):
            history = sess.prompt_ids + sess.generated[:-1]
            last = sess.generated[-1] if sess.generated else None
            if last is None:  # not yet delivered anything: plain re-admit
                history, last = sess.prompt_ids, 0
            carry, _, self._key = self._run_prefill(
                history, sess.temperature, sess.top_k, self._key)
            self._dstate = join(self._dstate, np.int32(slot), carry,
                                jnp.asarray(last, dtype=jnp.int32),
                                np.float32(sess.temperature),
                                np.int32(sess.top_k))

    # ---------------------------------------------------------------- stats
    @property
    def active_sessions(self) -> int:
        with self._lock:
            return len(self._slots)

    def stats(self) -> dict:
        with self._lock:
            return {
                "active": len(self._slots),
                "pending": len(self._pending),
                "capacity": self.capacity,
                "max_sessions": self.max_sessions,
                "steps": self._steps,
                "hot_swaps": self._swap_count,
                "warmed": self._warmed,
                "compiles": {k: self._watch.compiles(f"decode.{k}")
                             for k in ("step", "join", "clear", "grow",
                                       "prefill")},
            }
