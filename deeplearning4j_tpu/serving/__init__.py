"""Overload-safe model serving tier.

An HTTP front-end over ``parallel.ParallelInference`` where robustness
under overload is the headline: continuous batching into the pow2 bucket
ladder, bounded admission with load shedding (429), per-request deadlines
honored at batch formation (504 before dispatch, never a wasted batch
slot), a per-model circuit breaker (fast 503 + half-open probing),
graceful drain (zero dropped in-flight), warmup-gated readiness and a
``/metrics`` scrape of every control point. See
:mod:`~deeplearning4j_tpu.serving.server` for the route table and
:mod:`~deeplearning4j_tpu.serving.breaker` for the breaker state machine.

The generative tier (:mod:`~deeplearning4j_tpu.serving.decode`) adds
continuous-batching autoregressive decode behind
``POST /v1/models/<name>:generate``: per-session recurrent state lives
device-resident in a pow2 session-slot ladder, one jitted step advances
every active session per dispatch, and tokens stream back as SSE with
the same admission taxonomy.
"""

from deeplearning4j_tpu.serving.breaker import CircuitBreaker  # noqa: F401
from deeplearning4j_tpu.serving.decode import (  # noqa: F401
    DecodeEngine,
    DecodeSession,
    EngineStoppedError,
    SessionLimitError,
)
from deeplearning4j_tpu.serving.server import (  # noqa: F401
    BreakerOpenError,
    GenerateEndpoint,
    ModelDispatchError,
    ModelEndpoint,
    ModelServer,
)
from deeplearning4j_tpu.serving.wire import (  # noqa: F401
    decode_array,
    encode_array,
)
