"""Circuit breaker for the serving tier.

A model that starts failing every dispatch (poisoned checkpoint, OOM'd
device, a bug tripped by a particular input mix) must not drag every
client through a full queue wait + dispatch + failure: after
``failure_threshold`` failures inside ``window_s`` the breaker OPENS and
the server answers 503 immediately — the fast-fail half of graceful
degradation. After ``cooldown_s`` one request is let through as a
half-open PROBE; its success closes the breaker, its failure re-opens it.

States (the classic three):

- ``closed``  — healthy; failures are counted in a sliding window;
- ``open``    — rejecting everything until the cooldown elapses;
- ``half_open`` — exactly one probe in flight; everyone else still
  rejected. A probe that never resolves (caller died, deadline expired
  before dispatch) is abandoned after ``probe_timeout_s`` so the breaker
  can never wedge half-open forever.

Thread-safe; the clock is injectable (``clock=``) so tests drive the
cooldown deterministically instead of sleeping.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Deque

__all__ = ["CircuitBreaker"]


class CircuitBreaker:
    """``allow()`` before dispatch; ``record_success()`` /
    ``record_failure()`` after. See module docstring for the state
    machine. Counters (``opens``, ``rejections``, ``probes``) feed the
    obs registry through the server's absorb bridge."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, failure_threshold: int = 5, window_s: float = 10.0,
                 cooldown_s: float = 5.0, probe_timeout_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        if window_s <= 0 or cooldown_s < 0 or probe_timeout_s <= 0:
            raise ValueError("window_s/probe_timeout_s must be > 0 and "
                             "cooldown_s >= 0")
        self.failure_threshold = int(failure_threshold)
        self.window_s = float(window_s)
        self.cooldown_s = float(cooldown_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CircuitBreaker.CLOSED
        self._failures: Deque[float] = deque()
        self._opened_at = 0.0
        self._probe_at = 0.0
        self._probe_in_flight = False
        self.opens = 0
        self.rejections = 0
        self.probes = 0

    # ------------------------------------------------------------- queries
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def retry_after(self) -> float:
        """Seconds until a request is worth retrying (the 503 Retry-After
        value): remaining cooldown when open, a short beat otherwise."""
        with self._lock:
            if self._state == CircuitBreaker.OPEN:
                return max(0.0, self._opened_at + self.cooldown_s
                           - self._clock())
            return 1.0

    # ------------------------------------------------------------ protocol
    def allow(self) -> bool:
        """May this request proceed to dispatch? Open → False (fast 503)
        until the cooldown elapses, then exactly one half-open probe."""
        with self._lock:
            now = self._clock()
            if self._state == CircuitBreaker.CLOSED:
                return True
            if self._state == CircuitBreaker.OPEN:
                if now < self._opened_at + self.cooldown_s:
                    self.rejections += 1
                    return False
                self._state = CircuitBreaker.HALF_OPEN
                self._probe_in_flight = False  # fall through: claim probe
            # half-open: admit one probe; re-claim an abandoned one
            if self._probe_in_flight and \
                    now < self._probe_at + self.probe_timeout_s:
                self.rejections += 1
                return False
            self._probe_in_flight = True
            self._probe_at = now
            self.probes += 1
            return True

    def record_success(self):
        with self._lock:
            if self._state == CircuitBreaker.HALF_OPEN:
                self._state = CircuitBreaker.CLOSED
                self._probe_in_flight = False
                self._failures.clear()
            elif self._state == CircuitBreaker.CLOSED:
                # healthy traffic ages failures out of the window anyway;
                # clearing eagerly keeps a slow drip below threshold
                self._prune(self._clock())

    def record_failure(self):
        with self._lock:
            now = self._clock()
            if self._state == CircuitBreaker.HALF_OPEN:
                # the probe failed: full cooldown again
                self._state = CircuitBreaker.OPEN
                self._opened_at = now
                self._probe_in_flight = False
                self.opens += 1
                return
            if self._state == CircuitBreaker.OPEN:
                # stragglers admitted before the open must not extend it
                return
            self._failures.append(now)
            self._prune(now)
            if len(self._failures) >= self.failure_threshold:
                self._state = CircuitBreaker.OPEN
                self._opened_at = now
                self._failures.clear()
                self.opens += 1

    def _prune(self, now: float):
        while self._failures and self._failures[0] < now - self.window_s:
            self._failures.popleft()

    def as_dict(self) -> dict:
        with self._lock:
            return {"state": self._state, "opens": self.opens,
                    "rejections": self.rejections, "probes": self.probes,
                    "window_failures": len(self._failures)}
