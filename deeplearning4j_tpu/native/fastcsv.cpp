// Native CSV -> float32 matrix parser.
//
// Parity surface: DataVec's native record reading underpinning
// RecordReaderDataSetIterator (the reference's ETL hot path runs through
// JavaCC/opencsv on the JVM; libnd4j handles buffer creation). Here the hot
// path is one C++ pass over the byte buffer producing a dense float32
// matrix that numpy wraps zero-copy; non-numeric fields abort so the
// caller can fall back to the general Python reader.
//
// Build: g++ -O3 -shared -fPIC -o _fastcsv.so fastcsv.cpp   (no deps)
#include <cstdint>
#include <cstdlib>
#include <cstring>

extern "C" {

// ABI tag checked by the ctypes loader: bump whenever any exported
// signature or behavioral contract changes, so a stale committed-elsewhere
// .so can never be bound to mismatched expectations on a toolchain-less
// machine (it degrades to the Python fallback instead).
int64_t fastcsv_abi_version(void) { return 2; }

// First pass: count rows/columns. Returns 0 on success, -1 on ragged rows.
// Rows are '\n'-separated; a trailing newline is allowed; empty lines and
// the first skip_lines lines are ignored.
int64_t csv_shape(const char* buf, int64_t len, char delim, int64_t skip_lines,
                  int64_t* out_rows, int64_t* out_cols) {
    int64_t rows = 0, cols = -1, cur_cols = 1, line = 0;
    bool any = false;
    for (int64_t i = 0; i <= len; ++i) {
        bool eol = (i == len) || buf[i] == '\n';
        if (eol) {
            bool empty = !any;
            if (!empty && line >= skip_lines) {
                if (cols == -1) cols = cur_cols;
                else if (cols != cur_cols) return -1;
                rows++;
            }
            if (!empty || i < len) line++;
            cur_cols = 1;
            any = false;
            continue;
        }
        if (buf[i] == delim) cur_cols++;
        else if (buf[i] != '\r' && buf[i] != ' ') any = true;
    }
    *out_rows = rows;
    *out_cols = cols == -1 ? 0 : cols;
    return 0;
}

// Second pass: fill a preallocated rows*cols float32 buffer.
// Returns 0 on success, -2 on a non-numeric field (caller falls back).
int64_t csv_parse(const char* buf, int64_t len, char delim, int64_t skip_lines,
                  float* out, int64_t rows, int64_t cols) {
    int64_t r = 0, line = 0, i = 0;
    while (i < len && r < rows) {
        // find end of line
        int64_t eol = i;
        while (eol < len && buf[eol] != '\n') eol++;
        // empty line?
        bool any = false;
        for (int64_t j = i; j < eol; ++j)
            if (buf[j] != '\r' && buf[j] != ' ') { any = true; break; }
        if (!any || line < skip_lines) {
            line++;
            i = eol + 1;
            continue;
        }
        int64_t c = 0, field_start = i;
        for (int64_t j = i; j <= eol; ++j) {
            if (j == eol || buf[j] == delim) {
                if (c >= cols) return -1;
                char tmp[64];
                int64_t flen = j - field_start;
                if (flen <= 0 || flen >= (int64_t)sizeof(tmp)) return -2;
                memcpy(tmp, buf + field_start, flen);
                tmp[flen] = '\0';
                char* end = nullptr;
                double v = strtod(tmp, &end);
                // strip trailing ws/\r from validity check
                while (end && (*end == ' ' || *end == '\r')) end++;
                if (!end || *end != '\0') return -2;
                out[r * cols + c] = (float)v;
                c++;
                field_start = j + 1;
            }
        }
        if (c != cols) return -1;
        r++;
        line++;
        i = eol + 1;
    }
    return r == rows ? 0 : -1;
}

}  // extern "C"
