"""Native (C++) runtime components.

Parity surface: the reference's native layer — libnd4j buffer handling and
DataVec's record-reading hot path. The TPU compute path is XLA; these
components cover the HOST side of the pipeline where C++ genuinely beats
Python (byte-level parsing feeding the async iterators).

Components load via ctypes from shared objects compiled in-tree
(``build_native()`` invokes g++ — no pip, no pybind11). Every entry point
has a pure-Python fallback, so the package works without a toolchain.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from typing import Optional

import numpy as np

log = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "_fastcsv.so")
# must match fastcsv_abi_version() in fastcsv.cpp — a loaded .so whose tag
# differs (stale binary with surviving symbols) degrades to Python fallbacks
_ABI_VERSION = 2
_lib = None
_tried = False


def build_native(force: bool = False) -> bool:
    """Compile the native components in-tree (g++). Returns success.

    Rebuilds whenever the C++ source is newer than the shared object, so
    source edits always take effect (the .so itself is never committed)."""
    src = os.path.join(_DIR, "fastcsv.cpp")
    if os.path.exists(_SO) and not force:
        if (not os.path.exists(src)
                or os.path.getmtime(_SO) >= os.path.getmtime(src)):
            return True
    try:
        subprocess.run(["g++", "-O3", "-shared", "-fPIC", "-o", _SO, src],
                       check=True, capture_output=True)
        return True
    except Exception as e:
        log.info("Native build unavailable (%s); using Python fallbacks", e)
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    # builds when missing OR stale vs fastcsv.cpp; if the rebuild fails but
    # a (possibly stale) .so is already on disk, still load it — a working
    # fast path beats a silent fallback on toolchain-less machines
    if not build_native() and not os.path.exists(_SO):
        return None
    try:
        lib = ctypes.CDLL(_SO)
        lib.fastcsv_abi_version.restype = ctypes.c_int64
        lib.fastcsv_abi_version.argtypes = []
        got = lib.fastcsv_abi_version()
        if got != _ABI_VERSION:
            log.info("Native library ABI %d != expected %d; "
                     "using Python fallbacks", got, _ABI_VERSION)
            return None
        lib.csv_shape.restype = ctypes.c_int64
        lib.csv_shape.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_char, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64)]
        lib.csv_parse.restype = ctypes.c_int64
        lib.csv_parse.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_char, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_int64]
        _lib = lib
    except (OSError, AttributeError) as e:
        # AttributeError: a stale .so loaded via the fallback above may
        # predate a symbol this binding expects — degrade to Python
        log.info("Native library load failed (%s); using Python fallbacks", e)
    return _lib


def native_available() -> bool:
    return _load() is not None


def parse_csv_numeric(data: bytes, delimiter: str = ",",
                      skip_lines: int = 0) -> Optional[np.ndarray]:
    """Parse an all-numeric CSV byte buffer to a float32 (rows, cols) array
    in one native pass. Returns None when the native library is missing or
    the data has non-numeric / ragged fields (caller falls back to the
    Python reader)."""
    lib = _load()
    if lib is None:
        return None
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    d = delimiter.encode()[0:1]
    rc = lib.csv_shape(data, len(data), d, skip_lines,
                       ctypes.byref(rows), ctypes.byref(cols))
    if rc != 0 or rows.value == 0 or cols.value == 0:
        return None
    out = np.empty((rows.value, cols.value), np.float32)
    rc = lib.csv_parse(data, len(data), d, skip_lines,
                       out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                       rows.value, cols.value)
    if rc != 0:
        return None
    return out


__all__ = ["build_native", "native_available", "parse_csv_numeric"]
