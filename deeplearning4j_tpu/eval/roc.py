"""ROC / AUC.

Parity surface: reference eval/ROC.java (706 LoC; exact mode with
thresholdSteps=0 and thresholded mode), ROCBinary.java, ROCMultiClass.java.

This implementation accumulates raw (score, label) pairs (the reference's
"exact" mode, the default since 0.9.x) and computes AUROC by rank statistics
and AUPRC by trapezoidal integration of the PR curve.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np


class ROC:
    """Binary ROC: positive class probability vs binary label.

    ``threshold_steps=0`` (default) is the reference's exact mode: raw
    (score, label) pairs are retained and AUROC is computed by rank
    statistics. ``threshold_steps=N`` is the thresholded mode
    (ROC.java:163 pre-0.9.x default): scores are histogrammed into N
    equal-width bins so memory stays O(N) regardless of eval-set size —
    use it for very large evaluations.
    """

    def __init__(self, threshold_steps: int = 0):
        self.threshold_steps = int(threshold_steps)
        self._scores: List[np.ndarray] = []
        self._labels: List[np.ndarray] = []
        if self.threshold_steps > 0:
            self._pos_hist = np.zeros(self.threshold_steps, np.int64)
            self._neg_hist = np.zeros(self.threshold_steps, np.int64)

    def eval(self, labels, predictions, mask=None):
        """labels: (n,) {0,1} or one-hot (n,2) (positive = column 1);
        predictions: same shape of probabilities."""
        labels = np.asarray(labels)
        preds = np.asarray(predictions)
        if labels.ndim > 1 and labels.shape[-1] == 2:
            labels = labels[..., 1]
            preds = preds[..., 1]
        labels = labels.reshape(-1)
        preds = preds.reshape(-1)
        if mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            labels, preds = labels[m], preds[m]
        if self.threshold_steps > 0:
            idx = np.clip((preds * self.threshold_steps).astype(np.int64),
                          0, self.threshold_steps - 1)
            pos = labels > 0.5
            np.add.at(self._pos_hist, idx[pos], 1)
            np.add.at(self._neg_hist, idx[~pos], 1)
            return
        self._labels.append(labels.astype(np.float64))
        self._scores.append(preds.astype(np.float64))

    def _collect(self) -> Tuple[np.ndarray, np.ndarray]:
        return np.concatenate(self._scores), np.concatenate(self._labels)

    def _thresholded_rates(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(thresholds, fpr, tpr) from the histograms. ``>= threshold``
        counts are suffix sums of the bin histograms."""
        pos_ge = np.concatenate([np.cumsum(self._pos_hist[::-1])[::-1], [0]])
        neg_ge = np.concatenate([np.cumsum(self._neg_hist[::-1])[::-1], [0]])
        thresholds = np.linspace(0.0, 1.0, self.threshold_steps + 1)
        tpr = pos_ge / max(self._pos_hist.sum(), 1)
        fpr = neg_ge / max(self._neg_hist.sum(), 1)
        return thresholds, fpr, tpr

    def calculate_auc(self) -> float:
        """AUROC via the Mann-Whitney U statistic (rank sum), equivalent to
        the reference's exact-mode trapezoidal AUC. In thresholded mode,
        trapezoidal area under the binned curve."""
        if self.threshold_steps > 0:
            _, fpr, tpr = self._thresholded_rates()
            order = np.argsort(fpr, kind="mergesort")
            return float(np.trapezoid(tpr[order], fpr[order]))
        s, y = self._collect()
        pos = s[y > 0.5]
        neg = s[y <= 0.5]
        if len(pos) == 0 or len(neg) == 0:
            return float("nan")
        order = np.argsort(np.concatenate([pos, neg]), kind="mergesort")
        ranks = np.empty(len(order))
        ranks[order] = np.arange(1, len(order) + 1)
        # average ranks for ties
        allv = np.concatenate([pos, neg])
        sorted_v = allv[order]
        i = 0
        while i < len(sorted_v):
            j = i
            while j + 1 < len(sorted_v) and sorted_v[j + 1] == sorted_v[i]:
                j += 1
            if j > i:
                avg = (i + 1 + j + 1) / 2.0
                ranks[order[i:j + 1]] = avg
            i = j + 1
        r_pos = ranks[:len(pos)].sum()
        u = r_pos - len(pos) * (len(pos) + 1) / 2.0
        return float(u / (len(pos) * len(neg)))

    def calculate_auprc(self) -> float:
        if self.threshold_steps > 0:
            pos_ge = np.cumsum(self._pos_hist[::-1])[::-1]
            neg_ge = np.cumsum(self._neg_hist[::-1])[::-1]
            total = pos_ge + neg_ge
            # precision=1 for thresholds above every score (nothing predicted
            # positive) — same anchor convention as the exact path below
            precision = np.where(total == 0, 1.0,
                                 pos_ge / np.maximum(total, 1))
            recall = pos_ge / max(self._pos_hist.sum(), 1)
            # ascending recall, anchored at (recall=0, precision=1)
            recall = np.concatenate([[0.0], recall[::-1]])
            precision = np.concatenate([[1.0], precision[::-1]])
            return float(np.trapezoid(precision, recall))
        s, y = self._collect()
        order = np.argsort(-s, kind="mergesort")
        y = y[order] > 0.5
        tp = np.cumsum(y)
        fp = np.cumsum(~y)
        precision = tp / np.maximum(tp + fp, 1)
        recall = tp / max(y.sum(), 1)
        # prepend (recall=0, precision=1)
        recall = np.concatenate([[0.0], recall])
        precision = np.concatenate([[1.0], precision])
        return float(np.trapezoid(precision, recall))

    def merge(self, other: "ROC"):
        """reference ROC.merge (distributed aggregation). Exact mode
        concatenates retained arrays; thresholded mode adds histograms."""
        if self.threshold_steps != other.threshold_steps:
            raise ValueError("Cannot merge ROCs with different threshold_steps")
        if self.threshold_steps > 0:
            self._pos_hist += other._pos_hist
            self._neg_hist += other._neg_hist
        else:
            self._scores.extend(other._scores)
            self._labels.extend(other._labels)
        return self

    def get_roc_curve(self, num_points: int = 101):
        """(fpr, tpr) arrays at score thresholds (reference curves/RocCurve)."""
        if self.threshold_steps > 0:
            _, fpr, tpr = self._thresholded_rates()
            return fpr[::-1], tpr[::-1]
        s, y = self._collect()
        thresholds = np.linspace(1.0, 0.0, num_points)
        pos = max((y > 0.5).sum(), 1)
        neg = max((y <= 0.5).sum(), 1)
        tpr = [(s[y > 0.5] >= t).sum() / pos for t in thresholds]
        fpr = [(s[y <= 0.5] >= t).sum() / neg for t in thresholds]
        return np.asarray(fpr), np.asarray(tpr)

    def export_roc_curve(self, num_points: int = 101) -> "RocCurve":
        """Exportable curve object (reference ROC.getRocCurve -> RocCurve)."""
        from deeplearning4j_tpu.eval.curves import RocCurve
        if self.threshold_steps > 0:
            thresholds, fpr, tpr = self._thresholded_rates()
            return RocCurve(thresholds=[float(t) for t in thresholds],
                            fpr=[float(v) for v in fpr],
                            tpr=[float(v) for v in tpr])
        thresholds = np.linspace(1.0, 0.0, num_points)
        fpr, tpr = self.get_roc_curve(num_points)
        return RocCurve(thresholds=[float(t) for t in thresholds],
                        fpr=[float(v) for v in fpr],
                        tpr=[float(v) for v in tpr])

    def export_precision_recall_curve(self, num_points: int = 101) -> "PrecisionRecallCurve":
        """reference ROC.getPrecisionRecallCurve -> PrecisionRecallCurve."""
        from deeplearning4j_tpu.eval.curves import PrecisionRecallCurve
        if self.threshold_steps > 0:
            thresholds = np.linspace(0.0, 1.0, self.threshold_steps + 1)
            pos_ge = np.concatenate([np.cumsum(self._pos_hist[::-1])[::-1], [0]])
            neg_ge = np.concatenate([np.cumsum(self._neg_hist[::-1])[::-1], [0]])
            total = pos_ge + neg_ge
            # precision=1 where nothing is predicted positive (reference
            # PrecisionRecallCurve zero-recall anchor), keeping the exported
            # curve's AUPRC consistent with calculate_auprc()
            prec = np.where(total == 0, 1.0, pos_ge / np.maximum(total, 1))
            rec = pos_ge / max(self._pos_hist.sum(), 1)
            return PrecisionRecallCurve(
                thresholds=[float(t) for t in thresholds],
                precision=[float(v) for v in prec],
                recall=[float(v) for v in rec])
        s, y = self._collect()
        thresholds = np.linspace(0.0, 1.0, num_points)
        ypos = y > 0.5
        npos = max(ypos.sum(), 1)
        prec, rec = [], []
        for t in thresholds:
            sel = s >= t
            tp = (ypos & sel).sum()
            prec.append(float(tp / sel.sum()) if sel.sum() else 1.0)
            rec.append(float(tp / npos))
        return PrecisionRecallCurve(thresholds=[float(t) for t in thresholds],
                                    precision=prec, recall=rec)


class ROCMultiClass:
    """One-vs-all ROC per class (reference ROCMultiClass.java)."""

    def __init__(self):
        self._rocs: Optional[List[ROC]] = None

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        preds = np.asarray(predictions)
        n = labels.shape[-1]
        if self._rocs is None:
            self._rocs = [ROC() for _ in range(n)]
        lab2 = labels.reshape(-1, n)
        pr2 = preds.reshape(-1, n)
        if mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            lab2, pr2 = lab2[m], pr2[m]
        for i in range(n):
            self._rocs[i].eval(lab2[:, i], pr2[:, i])

    def calculate_auc(self, cls: int) -> float:
        return self._rocs[cls].calculate_auc()

    def merge(self, other: "ROCMultiClass"):
        """reference ROCMultiClass.merge: delegate per class."""
        if other._rocs is None:
            return self
        if self._rocs is None:
            self._rocs = [ROC() for _ in other._rocs]
        if len(self._rocs) != len(other._rocs):
            raise ValueError(
                f"Cannot merge {len(other._rocs)}-class into "
                f"{len(self._rocs)}-class ROCMultiClass")
        for mine, theirs in zip(self._rocs, other._rocs):
            mine.merge(theirs)
        return self

    def calculate_average_auc(self) -> float:
        vals = [r.calculate_auc() for r in self._rocs]
        vals = [v for v in vals if not np.isnan(v)]
        return float(np.mean(vals)) if vals else float("nan")


class ROCBinary:
    """Independent binary ROC per output column, for multi-label sigmoid
    outputs (reference eval/ROCBinary.java:43). Differs from ROCMultiClass
    in that columns are independent binary problems, not one-vs-all over a
    softmax."""

    def __init__(self, threshold_steps: int = 0):
        self.threshold_steps = threshold_steps
        self._rocs: Optional[List[ROC]] = None

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        preds = np.asarray(predictions)
        n = labels.shape[-1]
        if self._rocs is None:
            self._rocs = [ROC(self.threshold_steps) for _ in range(n)]
        elif n != len(self._rocs):
            raise ValueError(
                f"Batch has {n} outputs; previous batches had {len(self._rocs)}")
        lab2 = labels.reshape(-1, n)
        pr2 = preds.reshape(-1, n)
        if mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            lab2, pr2 = lab2[m], pr2[m]
        for i in range(n):
            self._rocs[i].eval(lab2[:, i], pr2[:, i])

    def num_outputs(self) -> int:
        return 0 if self._rocs is None else len(self._rocs)

    def calculate_auc(self, output: int) -> float:
        return self._rocs[output].calculate_auc()

    def calculate_auprc(self, output: int) -> float:
        return self._rocs[output].calculate_auprc()

    def merge(self, other: "ROCBinary"):
        """reference ROCBinary.merge: delegate per output column."""
        if other._rocs is None:
            return self
        if self.threshold_steps != other.threshold_steps:
            raise ValueError("Cannot merge ROCBinary with different "
                             "threshold_steps")
        if self._rocs is None:
            self._rocs = [ROC(self.threshold_steps) for _ in other._rocs]
        if len(self._rocs) != len(other._rocs):
            raise ValueError(
                f"Cannot merge {len(other._rocs)}-output into "
                f"{len(self._rocs)}-output ROCBinary")
        for mine, theirs in zip(self._rocs, other._rocs):
            mine.merge(theirs)
        return self

    def calculate_average_auc(self) -> float:
        vals = [r.calculate_auc() for r in self._rocs]
        vals = [v for v in vals if not np.isnan(v)]
        return float(np.mean(vals)) if vals else float("nan")
