"""Regression metrics.

Parity surface: reference eval/RegressionEvaluation.java — per-column MSE,
MAE, RMSE, RSE (relative squared error), PC (Pearson correlation), R^2.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class RegressionEvaluation:
    def __init__(self, n_columns: Optional[int] = None):
        self.n = 0
        self.sum_err2 = None
        self.sum_abs = None
        self.sum_label = None
        self.sum_label2 = None
        self.sum_pred = None
        self.sum_pred2 = None
        self.sum_lp = None
        if n_columns is not None:
            self._alloc(n_columns)

    def _alloc(self, c: int):
        self.sum_err2 = np.zeros(c)
        self.sum_abs = np.zeros(c)
        self.sum_label = np.zeros(c)
        self.sum_label2 = np.zeros(c)
        self.sum_pred = np.zeros(c)
        self.sum_pred2 = np.zeros(c)
        self.sum_lp = np.zeros(c)

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels, np.float64)
        preds = np.asarray(predictions, np.float64)
        labels = labels.reshape(-1, labels.shape[-1])
        preds = preds.reshape(-1, preds.shape[-1])
        if mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            labels, preds = labels[m], preds[m]
        if self.sum_err2 is None:
            self._alloc(labels.shape[-1])
        elif labels.shape[-1] != len(self.sum_err2):
            raise ValueError(
                f"Batch has {labels.shape[-1]} columns; evaluation was "
                f"initialized with {len(self.sum_err2)}")
        e = preds - labels
        self.n += labels.shape[0]
        self.sum_err2 += (e * e).sum(0)
        self.sum_abs += np.abs(e).sum(0)
        self.sum_label += labels.sum(0)
        self.sum_label2 += (labels * labels).sum(0)
        self.sum_pred += preds.sum(0)
        self.sum_pred2 += (preds * preds).sum(0)
        self.sum_lp += (labels * preds).sum(0)

    def mean_squared_error(self, col: int) -> float:
        return float(self.sum_err2[col] / self.n)

    def mean_absolute_error(self, col: int) -> float:
        return float(self.sum_abs[col] / self.n)

    def root_mean_squared_error(self, col: int) -> float:
        return float(np.sqrt(self.mean_squared_error(col)))

    def relative_squared_error(self, col: int) -> float:
        mean_label = self.sum_label[col] / self.n
        denom = self.sum_label2[col] - 2 * mean_label * self.sum_label[col] \
            + self.n * mean_label ** 2
        return float(self.sum_err2[col] / denom) if denom else 0.0

    def pearson_correlation(self, col: int) -> float:
        n = self.n
        num = n * self.sum_lp[col] - self.sum_label[col] * self.sum_pred[col]
        d1 = n * self.sum_label2[col] - self.sum_label[col] ** 2
        d2 = n * self.sum_pred2[col] - self.sum_pred[col] ** 2
        d = np.sqrt(d1 * d2)
        return float(num / d) if d else 0.0

    def r_squared(self, col: int) -> float:
        return 1.0 - self.relative_squared_error(col)

    def average_mean_squared_error(self) -> float:
        return float(np.mean(self.sum_err2 / self.n))

    def merge(self, other: "RegressionEvaluation"):
        """reference RegressionEvaluation.merge (distributed aggregation):
        all accumulators are sums, so merging is elementwise addition."""
        if other.sum_err2 is None:
            return self
        if self.sum_err2 is None:
            self._alloc(len(other.sum_err2))
        elif len(self.sum_err2) != len(other.sum_err2):
            raise ValueError("Column-count mismatch in merge")
        self.n += other.n
        for name in ("sum_err2", "sum_abs", "sum_label", "sum_label2",
                     "sum_pred", "sum_pred2", "sum_lp"):
            setattr(self, name, getattr(self, name) + getattr(other, name))
        return self

    def stats(self) -> str:
        c = len(self.sum_err2)
        lines = ["Column    MSE            MAE            RMSE           RSE            PC             R^2"]
        for i in range(c):
            lines.append(
                f"col_{i:<5} {self.mean_squared_error(i):<14.6g} "
                f"{self.mean_absolute_error(i):<14.6g} "
                f"{self.root_mean_squared_error(i):<14.6g} "
                f"{self.relative_squared_error(i):<14.6g} "
                f"{self.pearson_correlation(i):<14.6g} "
                f"{self.r_squared(i):<14.6g}")
        return "\n".join(lines)
