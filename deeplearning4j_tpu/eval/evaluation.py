"""Classification evaluation.

Parity surface: reference deeplearning4j-nn/.../eval/Evaluation.java
(:285 eval(realOutcomes, guesses), :499 stats(), :1031 f1(), :1138 accuracy()),
ConfusionMatrix.java, EvaluationBinary.java.

Metric accumulation is a host-side numpy confusion matrix (cheap); the heavy
part — the forward pass producing predictions — runs jit-compiled on device.
Mask-aware for time-series (reference: time-series eval with label masks).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


class ConfusionMatrix:
    """reference eval/ConfusionMatrix.java"""

    def __init__(self, n_classes: int):
        self.matrix = np.zeros((n_classes, n_classes), np.int64)

    def add(self, actual: np.ndarray, predicted: np.ndarray):
        np.add.at(self.matrix, (actual, predicted), 1)

    def get_count(self, actual: int, predicted: int) -> int:
        return int(self.matrix[actual, predicted])


class Evaluation:
    """Accuracy / precision / recall / F1 / confusion matrix (see module doc)."""

    def __init__(self, n_classes: Optional[int] = None, labels: Optional[List[str]] = None,
                 top_n: int = 1):
        self.n_classes = n_classes
        self.label_names = labels
        self.top_n = max(1, int(top_n))
        self._top_n_correct = 0
        self._top_n_total = 0
        self.confusion: Optional[ConfusionMatrix] = None

    def _ensure(self, n):
        if self.confusion is None:
            if self.n_classes is not None and self.n_classes != n:
                raise ValueError(
                    f"Batch has {n} classes; evaluation was constructed with "
                    f"n_classes={self.n_classes}")
            self.n_classes = n
            self.confusion = ConfusionMatrix(n)
        elif n != self.n_classes:
            raise ValueError(
                f"Batch has {n} classes; previous batches had {self.n_classes}")

    def eval(self, labels, predictions, mask=None):
        """Accumulate a batch (reference Evaluation.eval :285). ``labels`` is
        one-hot (batch, n) or (batch, time, n); ``predictions`` are
        probabilities of the same shape; ``mask`` (batch,) or (batch, time)."""
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        self._ensure(labels.shape[-1])
        actual = np.argmax(labels.reshape(-1, labels.shape[-1]), axis=-1)
        pred = np.argmax(predictions.reshape(-1, predictions.shape[-1]), axis=-1)
        flat_preds = predictions.reshape(-1, predictions.shape[-1])
        if mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            actual, pred = actual[m], pred[m]
            flat_preds = flat_preds[m]
        self.confusion.add(actual, pred)
        if self.top_n > 1:
            # top-N accuracy (reference Evaluation.java topNCorrectCount,
            # constructor Evaluation(List<String> labels, int topN))
            k = min(self.top_n, flat_preds.shape[-1])
            topk = np.argpartition(-flat_preds, k - 1, axis=-1)[:, :k]
            self._top_n_correct += int((topk == actual[:, None]).any(axis=-1).sum())
            self._top_n_total += len(actual)

    # ---- metrics ----
    def _tp(self, i):
        return self.confusion.matrix[i, i]

    def _fp(self, i):
        return self.confusion.matrix[:, i].sum() - self._tp(i)

    def _fn(self, i):
        return self.confusion.matrix[i, :].sum() - self._tp(i)

    def accuracy(self) -> float:
        m = self.confusion.matrix
        total = m.sum()
        return float(np.trace(m) / total) if total else 0.0

    def precision(self, cls: Optional[int] = None) -> float:
        if cls is not None:
            d = self._tp(cls) + self._fp(cls)
            return float(self._tp(cls) / d) if d else 0.0
        vals = [self.precision(i) for i in range(self.n_classes)
                if (self.confusion.matrix[i, :].sum() + self.confusion.matrix[:, i].sum()) > 0]
        return float(np.mean(vals)) if vals else 0.0

    def recall(self, cls: Optional[int] = None) -> float:
        if cls is not None:
            d = self._tp(cls) + self._fn(cls)
            return float(self._tp(cls) / d) if d else 0.0
        vals = [self.recall(i) for i in range(self.n_classes)
                if self.confusion.matrix[i, :].sum() > 0]
        return float(np.mean(vals)) if vals else 0.0

    def f1(self, cls: Optional[int] = None) -> float:
        """reference Evaluation.f1 :1031"""
        p = self.precision(cls)
        r = self.recall(cls)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def top_n_accuracy(self) -> float:
        """Fraction of examples whose true class is in the top-N predicted
        probabilities (reference Evaluation.topNAccuracy :1187)."""
        if self.top_n == 1:
            return self.accuracy()
        return (self._top_n_correct / self._top_n_total
                if self._top_n_total else 0.0)

    def false_positive_rate(self, cls: int) -> float:
        m = self.confusion.matrix
        tn = m.sum() - m[cls, :].sum() - m[:, cls].sum() + m[cls, cls]
        fp = self._fp(cls)
        return float(fp / (fp + tn)) if (fp + tn) else 0.0

    def merge(self, other: "Evaluation"):
        """Accumulate another Evaluation (reference Evaluation.merge :1392 —
        the distributed/Spark aggregation contract: per-host evals merge
        into one). A fresh accumulator adopts the other's configuration."""
        if other.confusion is None:  # other never evaluated anything
            return self
        if self.confusion is None:
            if self.top_n == 1:  # unconfigured default adopts other's
                self.top_n = other.top_n
            self._ensure(other.n_classes)
        elif self.n_classes != other.n_classes:
            raise ValueError(
                f"Cannot merge {other.n_classes}-class into "
                f"{self.n_classes}-class Evaluation")
        if self.top_n != other.top_n:
            raise ValueError(
                f"Cannot merge top_n={other.top_n} stats into top_n="
                f"{self.top_n} (top-N counts would be incoherent)")
        if self.label_names is None:  # direction-independent stats() output
            self.label_names = other.label_names
        self.confusion.matrix += other.confusion.matrix
        self._top_n_correct += other._top_n_correct
        self._top_n_total += other._top_n_total
        return self

    def stats(self) -> str:
        """Human-readable summary (reference Evaluation.stats :499)."""
        names = self.label_names or [str(i) for i in range(self.n_classes)]
        lines = ["========================Evaluation Metrics========================",
                 f" # of classes:    {self.n_classes}",
                 f" Accuracy:        {self.accuracy():.4f}",
                 f" Precision:       {self.precision():.4f}",
                 f" Recall:          {self.recall():.4f}",
                 f" F1 Score:        {self.f1():.4f}",
                 "", "=========================Confusion Matrix=========================="]
        m = self.confusion.matrix
        header = "      " + " ".join(f"{n:>6}" for n in names)
        lines.append(header)
        for i, row in enumerate(m):
            lines.append(f"{names[i]:>6}" + " ".join(f"{v:>6}" for v in row))
        lines.append("===================================================================")
        return "\n".join(lines)


class EvaluationBinary:
    """Per-output binary metrics for multi-label outputs (reference
    eval/EvaluationBinary.java)."""

    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold
        self.tp = None

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels).reshape(-1, np.asarray(labels).shape[-1])
        preds = (np.asarray(predictions).reshape(labels.shape) >= self.threshold)
        lab = labels >= 0.5
        if mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            lab, preds = lab[m], preds[m]
        if self.tp is None:
            n = labels.shape[-1]
            self.tp = np.zeros(n, np.int64)
            self.fp = np.zeros(n, np.int64)
            self.tn = np.zeros(n, np.int64)
            self.fn = np.zeros(n, np.int64)
        self.tp += (lab & preds).sum(0)
        self.fp += (~lab & preds).sum(0)
        self.tn += (~lab & ~preds).sum(0)
        self.fn += (lab & ~preds).sum(0)

    def merge(self, other: "EvaluationBinary"):
        """reference EvaluationBinary.merge (distributed aggregation)."""
        if other.tp is None:
            return self
        if self.threshold != other.threshold:
            raise ValueError(
                f"Cannot merge threshold={other.threshold} stats into "
                f"threshold={self.threshold} (counts would be incoherent)")
        if self.tp is None:
            self.tp = other.tp.copy()
            self.fp = other.fp.copy()
            self.tn = other.tn.copy()
            self.fn = other.fn.copy()
            return self
        if len(self.tp) != len(other.tp):
            raise ValueError(
                f"Cannot merge {len(other.tp)}-output stats into "
                f"{len(self.tp)}-output EvaluationBinary")
        self.tp += other.tp
        self.fp += other.fp
        self.tn += other.tn
        self.fn += other.fn
        return self

    def accuracy(self, i: int) -> float:
        tot = self.tp[i] + self.fp[i] + self.tn[i] + self.fn[i]
        return float((self.tp[i] + self.tn[i]) / tot) if tot else 0.0

    def precision(self, i: int) -> float:
        d = self.tp[i] + self.fp[i]
        return float(self.tp[i] / d) if d else 0.0

    def recall(self, i: int) -> float:
        d = self.tp[i] + self.fn[i]
        return float(self.tp[i] / d) if d else 0.0

    def f1(self, i: int) -> float:
        p, r = self.precision(i), self.recall(i)
        return 2 * p * r / (p + r) if (p + r) else 0.0
