from deeplearning4j_tpu.eval.evaluation import Evaluation, EvaluationBinary  # noqa: F401
from deeplearning4j_tpu.eval.regression import RegressionEvaluation  # noqa: F401
from deeplearning4j_tpu.eval.roc import ROC, ROCBinary, ROCMultiClass  # noqa: F401
from deeplearning4j_tpu.eval.calibration import EvaluationCalibration  # noqa: F401
from deeplearning4j_tpu.eval.curves import (  # noqa: F401
    Histogram, PrecisionRecallCurve, ReliabilityDiagram, RocCurve,
)
