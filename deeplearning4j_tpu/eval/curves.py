"""Exportable evaluation curves.

Parity surface: reference deeplearning4j-nn/.../eval/curves/
(RocCurve.java, PrecisionRecallCurve.java, Histogram.java,
ReliabilityDiagram.java, BaseCurve.java:toJson/fromJson).

Curves are plain frozen dataclasses with JSON round-trip so they can be
persisted next to StatsStorage files and rendered by the UI module.
"""

from __future__ import annotations

import dataclasses
import json
from typing import List

import numpy as np

_CURVE_REGISTRY = {}


def _register(cls):
    _CURVE_REGISTRY[cls.__name__] = cls
    return cls


@dataclasses.dataclass(frozen=True)
class BaseCurve:
    """JSON serde shared by all curves (reference BaseCurve.java)."""

    def to_json(self) -> str:
        d = {k: (list(v) if isinstance(v, (list, tuple, np.ndarray)) else v)
             for k, v in dataclasses.asdict(self).items()}
        d["@class"] = type(self).__name__
        return json.dumps(d)

    @staticmethod
    def from_json(s: str) -> "BaseCurve":
        d = json.loads(s)
        cls = _CURVE_REGISTRY[d.pop("@class")]
        return cls(**d)


@_register
@dataclasses.dataclass(frozen=True)
class RocCurve(BaseCurve):
    """reference eval/curves/RocCurve.java:28"""

    thresholds: List[float]
    fpr: List[float]
    tpr: List[float]

    def calculate_auc(self) -> float:
        """Trapezoidal area under (fpr, tpr), reference RocCurve.calculateAUC."""
        f = np.asarray(self.fpr)
        t = np.asarray(self.tpr)
        order = np.argsort(f, kind="mergesort")
        return float(np.trapezoid(t[order], f[order]))


@_register
@dataclasses.dataclass(frozen=True)
class PrecisionRecallCurve(BaseCurve):
    """reference eval/curves/PrecisionRecallCurve.java:33"""

    thresholds: List[float]
    precision: List[float]
    recall: List[float]

    def calculate_auprc(self) -> float:
        r = np.asarray(self.recall)
        p = np.asarray(self.precision)
        # collapse duplicate recall values to their best precision before
        # integrating: trapezoid over raw points is sensitive to which tie
        # representative lands next to the adjacent recall level, and the PR
        # staircase semantics (reference PrecisionRecallCurve) take the
        # highest-precision operating point at each recall
        uniq, inv = np.unique(r, return_inverse=True)
        best = np.zeros(len(uniq))
        np.maximum.at(best, inv, p)
        return float(np.trapezoid(best, uniq))


@_register
@dataclasses.dataclass(frozen=True)
class Histogram(BaseCurve):
    """reference eval/curves/Histogram.java: equal-width bins over
    [lower, upper] with integer counts."""

    title: str
    lower: float
    upper: float
    bin_counts: List[int]

    def bin_edges(self) -> np.ndarray:
        return np.linspace(self.lower, self.upper, len(self.bin_counts) + 1)


@_register
@dataclasses.dataclass(frozen=True)
class ReliabilityDiagram(BaseCurve):
    """reference eval/curves/ReliabilityDiagram.java: mean predicted
    probability vs observed positive fraction per bin."""

    title: str
    mean_predicted_value: List[float]
    fraction_positives: List[float]
