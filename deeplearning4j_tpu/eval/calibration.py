"""Probability-calibration evaluation.

Parity surface: reference deeplearning4j-nn/.../eval/EvaluationCalibration.java
(:56 reliabilityDiagBins/histogramBins, :106 eval accumulation,
:200 getReliabilityDiagram, :241 getResidualPlot, :263 getProbabilityHistogram).

Accumulates fixed-size binned counts per class, so memory is O(classes x bins)
regardless of eval-set size. Heavy forward passes stay on device; this is
host-side bookkeeping over the returned probabilities.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from deeplearning4j_tpu.eval.curves import Histogram, ReliabilityDiagram


class EvaluationCalibration:
    """Reliability diagrams, residual plots and probability histograms."""

    def __init__(self, reliability_bins: int = 10, histogram_bins: int = 50):
        self.reliability_bins = int(reliability_bins)
        self.histogram_bins = int(histogram_bins)
        self.n_classes: Optional[int] = None
        # per (class, reliability bin): positives, totals, sum of predictions
        self._r_pos = None
        self._r_tot = None
        self._r_sum = None
        # per (class, histogram bin): residual |label - p| and probability counts
        self._resid = None
        self._prob_all = None
        self._prob_pos = None

    def _ensure(self, n: int):
        if self.n_classes is None:
            self.n_classes = n
            rb, hb = self.reliability_bins, self.histogram_bins
            self._r_pos = np.zeros((n, rb), np.int64)
            self._r_tot = np.zeros((n, rb), np.int64)
            self._r_sum = np.zeros((n, rb), np.float64)
            self._resid = np.zeros((n, hb), np.int64)
            self._prob_all = np.zeros((n, hb), np.int64)
            self._prob_pos = np.zeros((n, hb), np.int64)
        elif n != self.n_classes:
            raise ValueError(
                f"Batch has {n} classes; previous batches had {self.n_classes}")

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels, np.float64)
        preds = np.asarray(predictions, np.float64)
        n = labels.shape[-1]
        self._ensure(n)
        lab2 = labels.reshape(-1, n)
        pr2 = preds.reshape(-1, n)
        if mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            lab2, pr2 = lab2[m], pr2[m]
        rb, hb = self.reliability_bins, self.histogram_bins
        rbin = np.clip((pr2 * rb).astype(np.int64), 0, rb - 1)
        hbin = np.clip((pr2 * hb).astype(np.int64), 0, hb - 1)
        resbin = np.clip((np.abs(lab2 - pr2) * hb).astype(np.int64), 0, hb - 1)
        pos = lab2 > 0.5
        for c in range(n):
            np.add.at(self._r_tot[c], rbin[:, c], 1)
            np.add.at(self._r_pos[c], rbin[:, c][pos[:, c]], 1)
            np.add.at(self._r_sum[c], rbin[:, c], pr2[:, c])
            np.add.at(self._resid[c], resbin[:, c], 1)
            np.add.at(self._prob_all[c], hbin[:, c], 1)
            np.add.at(self._prob_pos[c], hbin[:, c][pos[:, c]], 1)

    def get_reliability_diagram(self, cls: int) -> ReliabilityDiagram:
        """reference EvaluationCalibration.getReliabilityDiagram :200 —
        empty bins are dropped."""
        tot = self._r_tot[cls]
        keep = tot > 0
        mean_pred = self._r_sum[cls][keep] / tot[keep]
        frac_pos = self._r_pos[cls][keep] / tot[keep]
        return ReliabilityDiagram(
            title=f"Reliability diagram (class {cls})",
            mean_predicted_value=[float(v) for v in mean_pred],
            fraction_positives=[float(v) for v in frac_pos])

    def expected_calibration_error(self, cls: int) -> float:
        """Weighted |confidence - accuracy| over reliability bins (standard
        ECE; the reference exposes the diagram, the scalar is a convenience)."""
        tot = self._r_tot[cls]
        total = tot.sum()
        if total == 0:
            return 0.0
        keep = tot > 0
        mean_pred = self._r_sum[cls][keep] / tot[keep]
        frac_pos = self._r_pos[cls][keep] / tot[keep]
        return float(np.sum(tot[keep] / total * np.abs(mean_pred - frac_pos)))

    def get_residual_plot(self, cls: int) -> Histogram:
        """Histogram of |label - p| (reference getResidualPlot :241)."""
        return Histogram(title=f"Residual plot (class {cls})", lower=0.0,
                         upper=1.0, bin_counts=[int(v) for v in self._resid[cls]])

    def get_probability_histogram(self, cls: int, positive_only: bool = False) -> Histogram:
        """Histogram of predicted p (reference getProbabilityHistogram :263)."""
        src = self._prob_pos if positive_only else self._prob_all
        which = "positive-label " if positive_only else ""
        return Histogram(title=f"Predicted {which}probability (class {cls})",
                         lower=0.0, upper=1.0,
                         bin_counts=[int(v) for v in src[cls]])
