"""Model serialization: save/restore networks as a single zip file.

Parity surface: reference deeplearning4j-nn/.../util/ModelSerializer.java
(:37 class, :52 writeModel — config JSON + params + updater state,
:137+ restoreMultiLayerNetwork / restoreComputationGraph).

Zip layout mirrors the reference's:
- ``configuration.json``  — network config (our JSON schema)
- ``coefficients.npz``    — flat numpy archive of all params
- ``updaterState.npz``    — optimizer state (saved when save_updater=True)
- ``metadata.json``       — model class, iteration/epoch counters, format version
"""

from __future__ import annotations

import io
import json
import zipfile
from typing import Union

import jax
import numpy as np

FORMAT_VERSION = 1


def _path_key(path) -> str:
    parts = []
    for p in path:
        for attr in ("key", "idx", "name"):
            if hasattr(p, attr):
                parts.append(str(getattr(p, attr)))
                break
        else:
            parts.append(str(p))
    return "/".join(parts)


def _flatten_with_paths(tree):
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return {_path_key(path): np.asarray(leaf) for path, leaf in flat}


def _save_npz_bytes(arrays: dict) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def _restore_into(tree, arrays: dict):
    """Rebuild a pytree with the same structure, leaves taken from arrays."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    leaves = []
    for path, leaf in flat:
        key = _path_key(path)
        if key not in arrays:
            raise ValueError(f"Missing array '{key}' in checkpoint")
        saved = arrays[key]
        if tuple(saved.shape) != tuple(np.shape(leaf)):
            raise ValueError(
                f"Shape mismatch for '{key}': checkpoint {saved.shape} vs model "
                f"{np.shape(leaf)}")
        leaves.append(saved.astype(np.asarray(leaf).dtype))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def write_model(model, path: str, save_updater: bool = True):
    """reference ModelSerializer.writeModel :52"""
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    if model.params is None:
        model.init()
    if isinstance(model, MultiLayerNetwork):
        model_type = "MultiLayerNetwork"
    elif isinstance(model, ComputationGraph):
        model_type = "ComputationGraph"
    else:
        raise TypeError(f"Cannot serialize {type(model)}")
    meta = {
        "format_version": FORMAT_VERSION,
        "model_type": model_type,
        "iteration": model.iteration,
        "epoch": model.epoch,
        "has_updater": bool(save_updater),
    }
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("configuration.json", model.conf.to_json())
        z.writestr("metadata.json", json.dumps(meta))
        z.writestr("coefficients.npz",
                   _save_npz_bytes(_flatten_with_paths([model.params, model.state])))
        if save_updater:
            z.writestr("updaterState.npz",
                       _save_npz_bytes(_flatten_with_paths(model.opt_state)))


def restore_multi_layer_network(path: str, load_updater: bool = True):
    """reference ModelSerializer.restoreMultiLayerNetwork :137"""
    return _restore(path, expect="MultiLayerNetwork", load_updater=load_updater)


def restore_computation_graph(path: str, load_updater: bool = True):
    return _restore(path, expect="ComputationGraph", load_updater=load_updater)


def restore(path: str, load_updater: bool = True):
    return _restore(path, expect=None, load_updater=load_updater)


def _restore(path, expect, load_updater):
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
    from deeplearning4j_tpu.nn.conf.graph import ComputationGraphConfiguration
    with zipfile.ZipFile(path, "r") as z:
        meta = json.loads(z.read("metadata.json"))
        if expect is not None and meta["model_type"] != expect:
            raise ValueError(
                f"Checkpoint holds a {meta['model_type']}, not a {expect}")
        cfg_json = z.read("configuration.json").decode()
        if meta["model_type"] == "MultiLayerNetwork":
            model = MultiLayerNetwork(MultiLayerConfiguration.from_json(cfg_json))
        else:
            model = ComputationGraph(ComputationGraphConfiguration.from_json(cfg_json))
        model.init()
        coeff = dict(np.load(io.BytesIO(z.read("coefficients.npz"))))
        params, state = _restore_into([model.params, model.state], coeff)
        model.params, model.state = params, state
        if load_updater and meta.get("has_updater") and "updaterState.npz" in z.namelist():
            upd = dict(np.load(io.BytesIO(z.read("updaterState.npz"))))
            model.opt_state = _restore_into(model.opt_state, upd)
        model.iteration = meta.get("iteration", 0)
        model.epoch = meta.get("epoch", 0)
    return model
