"""Model serialization: save/restore networks as a single zip file.

Parity surface: reference deeplearning4j-nn/.../util/ModelSerializer.java
(:37 class, :52 writeModel — config JSON + params + updater state,
:137+ restoreMultiLayerNetwork / restoreComputationGraph).

Zip layout mirrors the reference's:
- ``configuration.json``  — network config (our JSON schema)
- ``coefficients.npz``    — flat numpy archive of all params
- ``updaterState.npz``    — optimizer state (saved when save_updater=True)
- ``metadata.json``       — model class, iteration/epoch counters, format version
- ``quantization.json``   — quant/ calibration record (present iff the model
  is an int8-quantized serving graph; the int8 weights + scales already
  live in the config/coefficients entries, so restore rebuilds the exact
  quantized predict and this record lets serving re-apply the SAME
  lowering to newer fp32 checkpoints)
- ``tuning.json``         — perf/autotune TuningRecord (present iff the model
  carries one): the autotuned batch size / fusion / remat / serving bucket
  ladder, so training replicas and serving endpoints restoring this model
  inherit the tuned execution without re-searching

The checkpoint/ subsystem extends this layout with ``rngState.npz`` (the
training PRNG key via ``jax.random.key_data``) and extra metadata
(``batch_in_epoch``) so a restore resumes the EXACT step — same rng split
chain, same counters — making crash-resume bitwise-identical to an
uninterrupted run. ``snapshot_training_state`` / ``checkpoint_zip_bytes`` /
``restore_checkpoint`` below are that format; a checkpoint zip is a strict
superset of ``write_model``'s, so plain ``restore()`` also reads it.
"""

from __future__ import annotations

import io
import json
import zipfile
from typing import Union

import jax
import numpy as np

FORMAT_VERSION = 1


def _path_key(path) -> str:
    parts = []
    for p in path:
        for attr in ("key", "idx", "name"):
            if hasattr(p, attr):
                parts.append(str(getattr(p, attr)))
                break
        else:
            parts.append(str(p))
    return "/".join(parts)


def _flatten_with_paths(tree):
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return {_path_key(path): np.asarray(leaf) for path, leaf in flat}


def _save_npz_bytes(arrays: dict) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def _restore_into(tree, arrays: dict):
    """Rebuild a pytree with the same structure, leaves taken from arrays."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    leaves = []
    for path, leaf in flat:
        key = _path_key(path)
        if key not in arrays:
            raise ValueError(f"Missing array '{key}' in checkpoint")
        saved = arrays[key]
        if tuple(saved.shape) != tuple(np.shape(leaf)):
            raise ValueError(
                f"Shape mismatch for '{key}': checkpoint {saved.shape} vs model "
                f"{np.shape(leaf)}")
        leaves.append(saved.astype(np.asarray(leaf).dtype))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def write_model(model, path: str, save_updater: bool = True):
    """reference ModelSerializer.writeModel :52"""
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    if model.params is None:
        model.init()
    if isinstance(model, MultiLayerNetwork):
        model_type = "MultiLayerNetwork"
    elif isinstance(model, ComputationGraph):
        model_type = "ComputationGraph"
    else:
        raise TypeError(f"Cannot serialize {type(model)}")
    aug = getattr(model, "augmentation", None)
    meta = {
        "format_version": FORMAT_VERSION,
        "model_type": model_type,
        "iteration": model.iteration,
        "epoch": model.epoch,
        "has_updater": bool(save_updater),
        "augmentation": None if aug is None else aug.to_dict(),
    }
    cal = getattr(model, "_quant_calibration", None)
    tun = getattr(model, "_tuning_record", None)
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("configuration.json", model.conf.to_json())
        z.writestr("metadata.json", json.dumps(meta))
        z.writestr("coefficients.npz",
                   _save_npz_bytes(_flatten_with_paths([model.params, model.state])))
        if save_updater:
            z.writestr("updaterState.npz",
                       _save_npz_bytes(_flatten_with_paths(model.opt_state)))
        if cal is not None:
            z.writestr("quantization.json", cal.to_json())
        if tun is not None:
            z.writestr("tuning.json", tun.to_json())


def snapshot_training_state(model) -> dict:
    """Host-side snapshot of everything exact-step resume needs: params,
    layer state, updater state, the training PRNG key and the step/epoch
    counters. ``jax.device_get`` copies to HOST memory on the calling
    (training) thread, so the snapshot is immune to the train step's buffer
    donation — a checkpoint/ worker thread can serialize it later while
    training keeps mutating the live device buffers."""
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    if model.params is None:
        model.init()
    if isinstance(model, MultiLayerNetwork):
        model_type = "MultiLayerNetwork"
    elif isinstance(model, ComputationGraph):
        model_type = "ComputationGraph"
    else:
        raise TypeError(f"Cannot checkpoint {type(model)}")
    rng = model._rng
    comp = getattr(model, "grad_compression", None)
    cs = getattr(model, "compress_state", None)
    cal = getattr(model, "_quant_calibration", None)
    tun = getattr(model, "_tuning_record", None)
    return {
        # quant/ ride-along: a checkpointed QUANTIZED serving model (its
        # int8 weights are ordinary params) restores with the calibration
        # record it was lowered with
        "quant_calibration": None if cal is None else cal.to_dict(),
        # perf/autotune ride-along: the tuned execution config travels
        # with the checkpoint so restored replicas inherit it
        "tuning_record": None if tun is None else tun.to_dict(),
        # on-device augmentation ride-along (datasets/augment.py): the
        # augmented train step is part of the rng-exact resume contract —
        # a restored replica training WITHOUT it would silently diverge
        "augmentation": (None if getattr(model, "augmentation", None)
                         is None else model.augmentation.to_dict()),
        "model_type": model_type,
        "conf_json": model.conf.to_json(),
        "iteration": int(model.iteration),
        "epoch": int(model.epoch),
        "params": jax.device_get(model.params),
        "state": jax.device_get(model.state),
        "opt_state": jax.device_get(model.opt_state),
        "rng": None if rng is None else np.asarray(jax.random.key_data(rng)),
        # gradient-compression ride-along (parallel/compress.py): the
        # scheme config lands in metadata and the error-feedback state in
        # its own npz, so a restored model resumes the compressed run
        # bitwise (residuals included)
        "grad_compression": None if comp is None else comp.to_config(),
        "compress_state": None if cs is None else jax.device_get(cs),
    }


def checkpoint_zip_bytes(snap: dict, extra_meta: dict = None) -> bytes:
    """Serialize a ``snapshot_training_state`` dict to checkpoint-zip bytes
    (built in memory so the caller can hash and write them atomically).

    ZIP_STORED, not DEFLATED: the payload is float parameter data that
    deflate shrinks ~10% at ~8x the CPU, and on the checkpoint cadence the
    writer thread's GIL time interferes with the step loop — bytes are
    cheap, step-loop stalls are not. (``write_model`` stays DEFLATED; it is
    the archival format.)"""
    meta = {
        "format_version": FORMAT_VERSION,
        "model_type": snap["model_type"],
        "iteration": snap["iteration"],
        "epoch": snap["epoch"],
        "has_updater": snap["opt_state"] is not None,
        "has_rng": snap["rng"] is not None,
        "grad_compression": snap.get("grad_compression"),
        "has_compress_state": snap.get("compress_state") is not None,
        "has_quant_calibration": snap.get("quant_calibration") is not None,
        "has_tuning_record": snap.get("tuning_record") is not None,
        "augmentation": snap.get("augmentation"),
    }
    meta.update(extra_meta or {})
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_STORED) as z:
        z.writestr("configuration.json", snap["conf_json"])
        z.writestr("metadata.json", json.dumps(meta))
        z.writestr("coefficients.npz", _save_npz_bytes(
            _flatten_with_paths([snap["params"], snap["state"]])))
        if snap["opt_state"] is not None:
            z.writestr("updaterState.npz",
                       _save_npz_bytes(_flatten_with_paths(snap["opt_state"])))
        if snap["rng"] is not None:
            z.writestr("rngState.npz",
                       _save_npz_bytes({"key_data": snap["rng"]}))
        if snap.get("compress_state") is not None:
            z.writestr("compressState.npz", _save_npz_bytes(
                _flatten_with_paths(snap["compress_state"])))
        if snap.get("quant_calibration") is not None:
            z.writestr("quantization.json",
                       json.dumps(snap["quant_calibration"], sort_keys=True))
        if snap.get("tuning_record") is not None:
            z.writestr("tuning.json",
                       json.dumps(snap["tuning_record"], sort_keys=True))
    return buf.getvalue()


def restore_checkpoint(path, load_updater: bool = True):
    """Restore a checkpoint zip to ``(model, meta)`` — like ``restore`` but
    also rehydrates the training PRNG key, so continuing ``fit`` follows the
    exact rng split chain the interrupted run would have. ``path`` is a
    filesystem path or a binary file-like (the storage-backend restore path
    hands in a BytesIO of the fetched object). Zip member reads are
    CRC-checked, so a corrupted file raises rather than restoring
    silently-wrong params (the manifest layer above turns that into a
    fall-back to the previous checkpoint)."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
    from deeplearning4j_tpu.nn.conf.graph import ComputationGraphConfiguration
    with zipfile.ZipFile(path, "r") as z:
        meta = json.loads(z.read("metadata.json"))
        cfg_json = z.read("configuration.json").decode()
        if meta["model_type"] == "MultiLayerNetwork":
            model = MultiLayerNetwork(MultiLayerConfiguration.from_json(cfg_json))
        else:
            model = ComputationGraph(ComputationGraphConfiguration.from_json(cfg_json))
        model.init()
        coeff = dict(np.load(io.BytesIO(z.read("coefficients.npz"))))
        model.params, model.state = _restore_into(
            [model.params, model.state], coeff)
        if load_updater and meta.get("has_updater", True) \
                and "updaterState.npz" in z.namelist():
            upd = dict(np.load(io.BytesIO(z.read("updaterState.npz"))))
            model.opt_state = _restore_into(model.opt_state, upd)
        if meta.get("has_rng") and "rngState.npz" in z.namelist():
            rng = dict(np.load(io.BytesIO(z.read("rngState.npz"))))
            model._rng = jax.random.wrap_key_data(
                jnp.asarray(rng["key_data"]))
        if meta.get("grad_compression"):
            _restore_compression(model, meta, z)
        _restore_quant_calibration(model, z)
        _restore_tuning_record(model, z)
        _restore_augmentation(model, meta)
        model.iteration = meta.get("iteration", 0)
        model.epoch = meta.get("epoch", 0)
    return model, meta


def _restore_quant_calibration(model, z: zipfile.ZipFile):
    """Re-attach the quant/ calibration record when one rides in the zip
    (the quantized layers themselves round-trip through the config JSON +
    coefficients like any other layer)."""
    if "quantization.json" in z.namelist():
        from deeplearning4j_tpu.quant.calibrate import CalibrationRecord
        model._quant_calibration = CalibrationRecord.from_json(
            z.read("quantization.json").decode())


def _restore_tuning_record(model, z: zipfile.ZipFile):
    """Re-attach the perf/autotune TuningRecord when one rides in the zip
    (the tuned conf itself — fused layers, remat knobs — round-trips
    through the config JSON like any other configuration)."""
    if "tuning.json" in z.namelist():
        from deeplearning4j_tpu.perf.autotune import TuningRecord
        model._tuning_record = TuningRecord.from_json(
            z.read("tuning.json").decode())


def _restore_augmentation(model, meta: dict):
    """Re-enable on-device augmentation when the checkpoint metadata
    carries its config — the resumed train step must augment exactly like
    the interrupted one or the rng-exact resume silently diverges."""
    if meta.get("augmentation"):
        from deeplearning4j_tpu.datasets.augment import ImageAugmentation
        model.augmentation = ImageAugmentation.from_dict(
            meta["augmentation"])


def _restore_compression(model, meta: dict, z: zipfile.ZipFile):
    """Rebuild the gradient-compression scheme + error-feedback state from
    checkpoint metadata via the shared ride-along restore policy
    (parallel/compress.restore_compress_state)."""
    from deeplearning4j_tpu.parallel.compress import restore_compress_state
    arrays = None
    if meta.get("has_compress_state") and "compressState.npz" in z.namelist():
        arrays = dict(np.load(io.BytesIO(z.read("compressState.npz"))))
    restore_compress_state(model, meta["grad_compression"], arrays,
                           origin="checkpointed")


def restore_multi_layer_network(path: str, load_updater: bool = True):
    """reference ModelSerializer.restoreMultiLayerNetwork :137"""
    return _restore(path, expect="MultiLayerNetwork", load_updater=load_updater)


def restore_computation_graph(path: str, load_updater: bool = True):
    return _restore(path, expect="ComputationGraph", load_updater=load_updater)


def restore(path: str, load_updater: bool = True):
    return _restore(path, expect=None, load_updater=load_updater)


def _restore(path, expect, load_updater):
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
    from deeplearning4j_tpu.nn.conf.graph import ComputationGraphConfiguration
    with zipfile.ZipFile(path, "r") as z:
        meta = json.loads(z.read("metadata.json"))
        if expect is not None and meta["model_type"] != expect:
            raise ValueError(
                f"Checkpoint holds a {meta['model_type']}, not a {expect}")
        cfg_json = z.read("configuration.json").decode()
        if meta["model_type"] == "MultiLayerNetwork":
            model = MultiLayerNetwork(MultiLayerConfiguration.from_json(cfg_json))
        else:
            model = ComputationGraph(ComputationGraphConfiguration.from_json(cfg_json))
        model.init()
        coeff = dict(np.load(io.BytesIO(z.read("coefficients.npz"))))
        params, state = _restore_into([model.params, model.state], coeff)
        model.params, model.state = params, state
        if load_updater and meta.get("has_updater") and "updaterState.npz" in z.namelist():
            upd = dict(np.load(io.BytesIO(z.read("updaterState.npz"))))
            model.opt_state = _restore_into(model.opt_state, upd)
        _restore_quant_calibration(model, z)
        _restore_tuning_record(model, z)
        _restore_augmentation(model, meta)
        model.iteration = meta.get("iteration", 0)
        model.epoch = meta.get("epoch", 0)
    return model
