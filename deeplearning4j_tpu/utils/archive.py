"""Archive extraction helpers.

Parity surface: reference ``deeplearning4j-nn/.../util/ArchiveUtils.java``
(unzipFileTo for .zip/.tar/.tar.gz/.tgz/.gz), used by the dataset fetchers.
Extraction refuses entries escaping the destination (zip-slip)."""

from __future__ import annotations

import gzip
import os
import shutil
import tarfile
import zipfile


def _check_dest(dest_dir: str, target: str):
    dest = os.path.realpath(dest_dir)
    tgt = os.path.realpath(target)
    if not (tgt == dest or tgt.startswith(dest + os.sep)):
        raise ValueError(f"Archive entry escapes destination: {target}")


def unzip_file_to(archive: str, dest_dir: str):
    """Extract any supported archive into ``dest_dir`` (reference
    ArchiveUtils.unzipFileTo)."""
    os.makedirs(dest_dir, exist_ok=True)
    lower = archive.lower()
    if lower.endswith(".zip"):
        with zipfile.ZipFile(archive) as z:
            for info in z.infolist():
                _check_dest(dest_dir, os.path.join(dest_dir, info.filename))
            z.extractall(dest_dir)
    elif lower.endswith((".tar", ".tar.gz", ".tgz")):
        mode = "r:gz" if lower.endswith((".tar.gz", ".tgz")) else "r"
        with tarfile.open(archive, mode) as t:
            # filter="data" rejects symlink/absolute/device traversal that a
            # name-only check cannot catch (symlink-then-write attacks)
            t.extractall(dest_dir, filter="data")
    elif lower.endswith(".gz"):
        out = os.path.join(dest_dir,
                           os.path.basename(archive)[:-3])
        with gzip.open(archive, "rb") as f, open(out, "wb") as o:
            shutil.copyfileobj(f, o)
    else:
        raise ValueError(f"Unsupported archive format: {archive}")
