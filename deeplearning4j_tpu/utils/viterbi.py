"""Viterbi label-sequence smoothing.

Parity surface: reference ``deeplearning4j-nn/.../util/Viterbi.java`` (decode
a noisy label sequence under a metastable markov prior: emission accuracy
``p_correct``, self-transition probability ``meta_stability``; decode() takes
a binary label matrix or raw outcome indices and returns (log-likelihood,
smoothed sequence)).
"""

from __future__ import annotations

import math
from typing import Sequence, Tuple

import numpy as np


class Viterbi:
    def __init__(self, possible_labels: Sequence, meta_stability: float = 0.9,
                 p_correct: float = 0.99):
        self.possible_labels = np.asarray(possible_labels)
        self.states = int(len(self.possible_labels))
        if self.states < 2:
            raise ValueError("Need at least 2 states")
        self.meta_stability = meta_stability
        self.p_correct = p_correct
        # emission: observed == state with p_correct, else uniform leak
        self._log_emit_same = math.log(p_correct)
        self._log_emit_diff = math.log((1.0 - p_correct) / (self.states - 1))
        # transition: stay with meta_stability, else uniform leak
        self._log_stay = math.log(meta_stability)
        self._log_move = math.log((1.0 - meta_stability) / (self.states - 1))

    def decode(self, labels, binary_label_matrix: bool = True
               ) -> Tuple[float, np.ndarray]:
        """(log-likelihood, smoothed outcome sequence). ``labels`` is a
        (T, states) one-hot matrix (default) or a (T,) outcome vector."""
        labels = np.asarray(labels)
        if binary_label_matrix and labels.ndim == 2:
            observed = np.argmax(labels, axis=1)
        else:
            observed = labels.reshape(-1).astype(np.int64)
        T, S = len(observed), self.states
        emit = np.full((T, S), self._log_emit_diff)
        emit[np.arange(T), observed] = self._log_emit_same
        trans = np.full((S, S), self._log_move)
        np.fill_diagonal(trans, self._log_stay)
        # DP
        v = -math.log(S) + emit[0]
        back = np.zeros((T, S), np.int64)
        for t in range(1, T):
            scores = v[:, None] + trans          # (from, to)
            back[t] = np.argmax(scores, axis=0)
            v = scores[back[t], np.arange(S)] + emit[t]
        path = np.zeros(T, np.int64)
        path[-1] = int(np.argmax(v))
        for t in range(T - 1, 0, -1):
            path[t - 1] = back[t, path[t]]
        return float(v.max()), self.possible_labels[path]
