"""Shared hardening helpers for the stdlib HTTP servers.

Every front-end in this repo (serving/, clustering/, ui/) is a
``ThreadingHTTPServer`` in the same house style; the request-body
admission contract lives here so it cannot drift between them:
Content-Length is validated BEFORE any payload byte is read — a missing
or invalid length is a client error (400), an oversized or negative one
is 413, and either way a hostile request costs one header parse, not
server memory.
"""

from __future__ import annotations

from typing import Optional, Tuple

__all__ = ["parse_content_length"]


def parse_content_length(headers, max_body_bytes: int
                         ) -> Tuple[Optional[int],
                                    Optional[Tuple[int, str]]]:
    """Validate a request's Content-Length against a body-size cap.

    Returns ``(length, None)`` when the request may be read, or
    ``(None, (status_code, message))`` for the structured error the
    caller should answer in its own JSON shape — without having read a
    single body byte.
    """
    try:
        length = int(headers.get("Content-Length", ""))
    except ValueError:
        return None, (400, "missing or invalid Content-Length")
    if length < 0 or length > max_body_bytes:
        return None, (413, f"request body {length}B exceeds the "
                           f"{max_body_bytes}B limit")
    return length, None
