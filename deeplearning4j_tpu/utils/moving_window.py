"""Moving-window matrix extraction.

Parity surface: reference ``deeplearning4j-core/.../util/MovingWindowMatrix.java``
(windowRowSize x windowColumnSize sub-matrices of a 2-D matrix, optionally
adding 90-degree rotations — used for data augmentation of image matrices).
"""

from __future__ import annotations

from typing import List

import numpy as np


class MovingWindowMatrix:
    """All windowRows x windowCols sub-matrices of ``to_slice``, stepping by
    the window size (non-overlapping tiling, as the reference does), with
    optional rotated copies."""

    def __init__(self, to_slice, window_rows: int, window_cols: int,
                 add_rotate: bool = False):
        a = np.asarray(to_slice)
        if a.ndim != 2:
            raise ValueError("MovingWindowMatrix slices 2-D matrices")
        if window_rows < 1 or window_cols < 1:
            raise ValueError("window size must be >= 1")
        if window_rows > a.shape[0] or window_cols > a.shape[1]:
            raise ValueError(
                f"window {window_rows}x{window_cols} exceeds matrix "
                f"{a.shape[0]}x{a.shape[1]}")
        self._a = a
        self.window_rows = window_rows
        self.window_cols = window_cols
        self.add_rotate = add_rotate

    def windows(self, add_rotate: bool = None) -> List[np.ndarray]:
        """The window list (reference MovingWindowMatrix.windows())."""
        rotate = self.add_rotate if add_rotate is None else add_rotate
        out = []
        for r in range(0, self._a.shape[0] - self.window_rows + 1,
                       self.window_rows):
            for c in range(0, self._a.shape[1] - self.window_cols + 1,
                           self.window_cols):
                w = self._a[r:r + self.window_rows, c:c + self.window_cols]
                out.append(np.array(w))
                if rotate:
                    for k in (1, 2, 3):
                        out.append(np.rot90(w, k))
        return out
