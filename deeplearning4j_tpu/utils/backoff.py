"""Capped exponential backoff with jitter — the repo's single retry-delay
policy.

Shared by ``checkpoint/storage.py``'s :class:`RetryingBackend` (transient
object-store faults) and ``storage/remote.py``'s
``RemoteUIStatsStorageRouter`` (flaky UI-server posts). Both used to grow
delays linearly, which under a correlated outage (the store/server is down,
every worker retries) synchronizes retries into load spikes exactly when the
dependency is least able to absorb them; exponential growth with jitter
spreads them out (the standard AWS "exponential backoff and jitter" result).

Delay for retry ``attempt`` (0-based) is uniform in
``[jitter * d, d]`` where ``d = min(cap_s, base_s * 2**attempt)`` —
"equal-jitter"-style: bounded above by the deterministic exponential
schedule, never collapsing to zero (a zero floor can hot-spin a tight retry
loop), and fully deterministic given a seeded ``rng``.
"""

from __future__ import annotations

import random
from typing import Iterator, Optional

__all__ = ["backoff_delay", "backoff_delays"]


def backoff_delay(attempt: int, base_s: float = 0.5, cap_s: float = 30.0,
                  jitter: float = 0.5,
                  rng: Optional[random.Random] = None) -> float:
    """Seconds to sleep before retry ``attempt`` (0-based: the delay between
    the first failure and the second try is ``attempt=0``).

    ``jitter`` is the lower fraction of the window: 0.5 draws uniformly from
    ``[d/2, d]``; 1.0 disables jitter (deterministic schedule, useful in
    tests); 0.0 allows the full ``[0, d]`` spread."""
    if attempt < 0:
        raise ValueError("attempt must be >= 0")
    if not 0.0 <= jitter <= 1.0:
        raise ValueError("jitter must be in [0, 1]")
    d = min(float(cap_s), float(base_s) * (2.0 ** attempt))
    if jitter >= 1.0 or d <= 0.0:
        return max(0.0, d)
    r = (rng or random).random()
    return d * (jitter + (1.0 - jitter) * r)


def backoff_delays(retries: int, base_s: float = 0.5, cap_s: float = 30.0,
                   jitter: float = 0.5,
                   rng: Optional[random.Random] = None) -> Iterator[float]:
    """The full delay schedule for a bounded retry loop, as an iterator."""
    for attempt in range(retries):
        yield backoff_delay(attempt, base_s=base_s, cap_s=cap_s,
                            jitter=jitter, rng=rng)
