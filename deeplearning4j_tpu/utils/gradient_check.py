"""Numerical gradient checking — the correctness backbone.

Parity surface: reference
deeplearning4j-nn/.../gradientcheck/GradientCheckUtil.java:112
(checkGradients(MultiLayerNetwork, eps, maxRelError, minAbsoluteError, ...))
and the 13 test suites in deeplearning4j-core/src/test/.../gradientcheck/.

Contract kept from the reference: double precision forced (the reference sets
DataBuffer.Type.DOUBLE — GradientCheckTests.java:42), central finite
differences with ``eps``, relative error
|a - n| / max(|a|, |n|) compared to ``max_rel_error`` unless both are below
``min_abs_error``. The analytic gradient is jax autodiff of the same loss the
train step uses (instead of the reference's hand-written backpropGradient).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import enable_x64
from jax.flatten_util import ravel_pytree


def check_gradients(
    net,
    ds,
    eps: float = 1e-6,
    max_rel_error: float = 1e-3,
    min_abs_error: float = 1e-8,
    max_params_to_check: int = 4096,
    seed: int = 12345,
    print_failures: bool = True,
) -> bool:
    """Finite-difference check of d(loss)/d(params) for a MultiLayerNetwork.

    Runs entirely in float64 on the host backend. Dropout must be disabled in
    the net's config (as in the reference's gradient-check suites).
    """
    with enable_x64():
        params64 = jax.tree_util.tree_map(
            lambda a: jnp.asarray(np.asarray(a, np.float64)), net.params)
        state64 = jax.tree_util.tree_map(
            lambda a: jnp.asarray(np.asarray(a, np.float64)), net.state)
        x = jnp.asarray(np.asarray(ds.features, np.float64))
        y = jnp.asarray(np.asarray(ds.labels, np.float64))
        fm = None if ds.features_mask is None else jnp.asarray(
            np.asarray(ds.features_mask, np.float64))
        lm = None if ds.labels_mask is None else jnp.asarray(
            np.asarray(ds.labels_mask, np.float64))
        key = jax.random.key(0)

        flat0, unravel = ravel_pytree(params64)

        def loss_flat(flat):
            p = unravel(flat)
            return net._loss_fn(p, state64, x, y, key, fm, lm)[0]

        loss_jit = jax.jit(loss_flat)
        analytic = np.asarray(jax.jit(jax.grad(loss_flat))(flat0))

        n = flat0.shape[0]
        if n <= max_params_to_check:
            idxs = np.arange(n)
        else:
            idxs = np.random.default_rng(seed).choice(n, max_params_to_check, replace=False)

        flat_np = np.asarray(flat0)
        failures = 0
        max_err = 0.0
        for i in idxs:
            fp = flat_np.copy()
            fp[i] += eps
            fm_ = flat_np.copy()
            fm_[i] -= eps
            numeric = (float(loss_jit(jnp.asarray(fp))) - float(loss_jit(jnp.asarray(fm_)))) / (2 * eps)
            a = float(analytic[i])
            denom = max(abs(a), abs(numeric))
            rel = 0.0 if denom == 0 else abs(a - numeric) / denom
            max_err = max(max_err, rel)
            if rel > max_rel_error and not (abs(a) < min_abs_error and abs(numeric) < min_abs_error):
                failures += 1
                if print_failures and failures <= 10:
                    print(f"  param[{i}]: analytic={a:.8g} numeric={numeric:.8g} rel={rel:.3g}")
        if print_failures and failures:
            print(f"gradient check: {failures}/{len(idxs)} failures, max rel err {max_err:.3g}")
        return failures == 0
