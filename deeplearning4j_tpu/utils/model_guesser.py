"""Load a model from a file of unknown provenance.

Parity surface: reference
``deeplearning4j-core/.../util/ModelGuesser.java`` — ``loadModelGuess``
(native model zip vs Keras file), ``loadConfigGuess`` (MLN vs CG JSON).
"""

from __future__ import annotations

import json
import zipfile


def load_config_guess(source: str):
    """Parse a config that may be a MultiLayerConfiguration or a
    ComputationGraphConfiguration (reference ModelGuesser.loadConfigGuess
    :51). ``source`` is a JSON string or a path to one."""
    import os

    from deeplearning4j_tpu.nn.conf.graph import ComputationGraphConfiguration
    from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
    if os.path.exists(source):
        with open(source, "r", encoding="utf-8") as f:
            source = f.read()
    elif not source.lstrip().startswith("{"):
        raise ValueError(f"No such configuration file: {source!r}")
    d = json.loads(source)
    if "vertices" in d or "network_inputs" in d:
        return ComputationGraphConfiguration.from_json(source)
    if "layers" in d:
        return MultiLayerConfiguration.from_json(source)
    raise ValueError("Unrecognized configuration JSON: neither a layer list "
                     "nor a graph (no 'layers'/'vertices' key)")


def load_model_guess(path: str):
    """Load a model whose format is unknown (reference
    ModelGuesser.loadModelGuess :114): the framework's own zip (metadata.json
    + configuration.json), a Keras 3 ``.keras`` zip, or a Keras HDF5 file."""
    if zipfile.is_zipfile(path):
        with zipfile.ZipFile(path) as z:
            names = set(z.namelist())
        if "metadata.json" in names and "configuration.json" in names:
            from deeplearning4j_tpu.utils.serialization import restore
            return restore(path)
    # anything else is a Keras format — import_keras_model's archive opener
    # already dispatches .keras zips vs HDF5 and validates both
    from deeplearning4j_tpu.modelimport import (KerasImportError,
                                                import_keras_model)
    try:
        return import_keras_model(path)
    except (KerasImportError, OSError) as e:
        raise ValueError(
            f"Cannot guess the model format of {path!r}: neither a "
            f"framework model zip nor a Keras file ({e})") from e
