"""Clustering + nearest-neighbor structures.

Parity surface: reference ``deeplearning4j-nearestneighbors-parent/``
(nearestneighbor-core): ``clustering/vptree/VPTree.java:48``,
``clustering/kdtree/KDTree.java:37``, ``clustering/kmeans/
KMeansClustering.java:31`` (+ cluster/ClusterSet infrastructure).

TPU-native split: tree *construction and traversal* are host-side (pointer
chasing has no MXU mapping — same position they occupy in the reference), but
K-Means Lloyd iterations run as one jitted XLA program per step where the
distance matrix hits the MXU.
"""

from deeplearning4j_tpu.clustering.vptree import VPTree
from deeplearning4j_tpu.clustering.kdtree import KDTree
from deeplearning4j_tpu.clustering.kmeans import KMeansClustering
from deeplearning4j_tpu.clustering.server import NearestNeighborsServer

__all__ = ["VPTree", "KDTree", "KMeansClustering", "NearestNeighborsServer"]
