"""Vantage-point tree for exact metric k-NN.

Parity surface: reference
``deeplearning4j-nearestneighbors-parent/nearestneighbor-core/src/main/java/
org/deeplearning4j/clustering/vptree/VPTree.java:48`` (build + search with
"euclidean" default distance, ``search(target, k, results, distances)``).

Host-side numpy: median-split construction, best-first pruning search.
"""

from __future__ import annotations

import heapq
from typing import List, Optional, Tuple

import numpy as np


class _Node:
    __slots__ = ("index", "radius", "inside", "outside", "bucket")

    def __init__(self, index: int):
        self.index = index
        self.radius = 0.0
        self.inside: Optional["_Node"] = None
        self.outside: Optional["_Node"] = None
        self.bucket: Optional[List[int]] = None  # leaf: tied/duplicate points


class VPTree:
    """Exact k-NN under a metric (default euclidean; "cosine" supported via
    angular distance, which preserves the triangle inequality)."""

    def __init__(self, items: np.ndarray, distance: str = "euclidean",
                 seed: int = 123):
        self.items = np.asarray(items, np.float64)
        if distance not in ("euclidean", "cosine"):
            raise ValueError(f"Unsupported distance {distance!r}")
        self.distance = distance
        if distance == "cosine":
            norms = np.linalg.norm(self.items, axis=1, keepdims=True)
            self._unit = self.items / np.maximum(norms, 1e-12)
        rng = np.random.default_rng(seed)
        self._root = self._build(list(range(len(self.items))), rng)

    # ------------------------------------------------------------ distances
    def _dist_many(self, idx: List[int], point: np.ndarray) -> np.ndarray:
        if self.distance == "cosine":
            p = point / max(np.linalg.norm(point), 1e-12)
            cos = np.clip(self._unit[idx] @ p, -1.0, 1.0)
            return np.arccos(cos)  # angular distance: a true metric
        return np.linalg.norm(self.items[idx] - point, axis=1)

    # ---------------------------------------------------------------- build
    def _make_node(self, work: List[int], rng):
        """Pick a vantage point, median-split the rest. Returns
        (node, inside, outside) index lists (possibly empty)."""
        vp_pos = int(rng.integers(0, len(work)))
        work[0], work[vp_pos] = work[vp_pos], work[0]
        node = _Node(work[0])
        rest = work[1:]
        if not rest:
            return node, [], []
        d = self._dist_many(rest, self.items[node.index])
        node.radius = float(np.median(d))
        inside = [rest[i] for i in range(len(rest)) if d[i] < node.radius]
        outside = [rest[i] for i in range(len(rest)) if d[i] >= node.radius]
        if not inside:
            # radius == min distance (ties/duplicates at the median): bucket
            # ONLY the tied points; strictly-farther points keep splitting,
            # so search stays pruned even with many duplicates
            node.bucket = [rest[i] for i in range(len(rest))
                           if d[i] == node.radius]
            outside = [rest[i] for i in range(len(rest)) if d[i] > node.radius]
        return node, inside, outside

    def _build(self, idx: List[int], rng) -> Optional[_Node]:
        """Iterative construction (explicit work stack): never touches the
        Python recursion limit, even for duplicate-heavy inputs whose splits
        shed O(1) points per level."""
        if not idx:
            return None
        root, ins, outs = self._make_node(list(idx), rng)
        stack = [(ins, root, "inside"), (outs, root, "outside")]
        while stack:
            work, parent, side = stack.pop()
            if not work:
                continue
            node, ins, outs = self._make_node(work, rng)
            setattr(parent, side, node)
            stack.append((ins, node, "inside"))
            stack.append((outs, node, "outside"))
        return root

    # --------------------------------------------------------------- search
    def search(self, target, k: int) -> Tuple[List[int], List[float]]:
        """k nearest item indices + distances, ascending (reference
        VPTree.search). TIE-STABLE: equal distances resolve to the lower
        index — the result is exactly the first k of ``sorted((d_i, i))``,
        deterministic even on duplicate-heavy inputs, which is what lets
        this tree serve as the device indexes' recall oracle."""
        if k < 1:
            raise ValueError(f"k must be >= 1; got {k}")
        target = np.asarray(target, np.float64)
        # max-heap via negated (distance, index): heap[0] is the WORST
        # kept candidate under the lexicographic (d, i) order
        heap: List[Tuple[float, int]] = []
        tau = [np.inf]

        def offer(d: float, index: int):
            if len(heap) < k:
                heapq.heappush(heap, (-d, -index))
                if len(heap) == k:
                    tau[0] = -heap[0][0]
            elif (d, index) < (-heap[0][0], -heap[0][1]):
                heapq.heapreplace(heap, (-d, -index))
                tau[0] = -heap[0][0]

        # iterative near-first traversal (far side pushed with its pruning
        # test deferred to pop time, when tau is tighter)
        stack: List[Tuple[Optional[_Node], Optional[float], Optional[float]]] = [
            (self._root, None, None)]
        while stack:
            node, parent_d, parent_radius = stack.pop()
            if node is None:
                continue
            if parent_d is not None:  # deferred far-side prune
                if not (parent_d - tau[0] <= parent_radius <= parent_d + tau[0]
                        or len(heap) < k):
                    continue
            d = float(self._dist_many([node.index], target)[0])
            offer(d, node.index)
            if node.bucket:
                # tied points sit exactly at node.radius from the vantage
                # point: the scan can be skipped unless the tau-ball overlaps
                # that shell
                if len(heap) < k or abs(d - node.radius) <= tau[0]:
                    for bd, bi in zip(self._dist_many(node.bucket, target),
                                      node.bucket):
                        offer(float(bd), bi)
            near, far = ((node.inside, node.outside) if d < node.radius
                         else (node.outside, node.inside))
            stack.append((far, d, node.radius))   # popped after near subtree
            stack.append((near, None, None))
        pairs = sorted((-nd, -ni) for nd, ni in heap)
        return [i for _, i in pairs], [d for d, _ in pairs]
