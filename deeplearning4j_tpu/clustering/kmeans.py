"""K-Means clustering with jitted Lloyd iterations.

Parity surface: reference ``.../clustering/kmeans/KMeansClustering.java:31``
(setup(k, maxIter, distance) + applyTo(points) -> ClusterSet).

TPU-native design: each Lloyd iteration is ONE jitted XLA program — the
(n, k) distance matrix is a matmul-shaped op on the MXU, assignment is an
argmin, and centroid update is a segment mean via one-hot matmul (no host
loop over clusters, no per-point Java Cluster objects).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=("k", "steps"))
def _lloyd_chunk(points, centroids, tol, k: int, steps: int):
    """Up to ``steps`` Lloyd iterations as ONE device program
    (lax.while_loop), stopping early once the centroid shift drops under
    ``tol`` — the same stopping rule the host loop applies, evaluated on
    device. Returns (centroids, shift, iterations_run): the host reads
    back ONE scalar per chunk instead of one per iteration, so a large
    index build is compute-bound, not dispatch-latency-bound."""

    def cond(carry):
        i, _, shift = carry
        return jnp.logical_and(i < steps, shift >= tol)

    def body(carry):
        i, cent, _ = carry
        new_cent, _, shift, _ = _lloyd_step(points, cent, k)
        return i + 1, new_cent, shift

    init = (jnp.int32(0), centroids, jnp.float32(jnp.inf))
    i, cent, shift = jax.lax.while_loop(cond, body, init)
    return cent, shift, i


@functools.partial(jax.jit, static_argnames=("k",))
def _lloyd_step(points, centroids, k: int):
    # pairwise sq-distance via the expanded form: the x@c.T term is the MXU
    # op; full precision so near-ties assign stably (TPU matmuls default bf16)
    d2 = (jnp.sum(points**2, 1, keepdims=True)
          - 2.0 * jnp.matmul(points, centroids.T, precision="highest")
          + jnp.sum(centroids**2, 1))
    assign = jnp.argmin(d2, axis=1)
    onehot = jnp.eye(k, dtype=points.dtype)[assign]
    counts = onehot.sum(0)
    sums = onehot.T @ points
    new_centroids = jnp.where(counts[:, None] > 0,
                              sums / jnp.maximum(counts[:, None], 1.0),
                              centroids)  # keep empty clusters in place
    shift = jnp.sum((new_centroids - centroids) ** 2)
    cost = jnp.sum(jnp.min(d2, axis=1))
    return new_centroids, assign, shift, cost


class KMeansClustering:
    """Lloyd's algorithm with k-means++ seeding."""

    def __init__(self, k: int, max_iterations: int = 100, tol: float = 1e-6,
                 seed: int = 123):
        self.k = k
        self.max_iterations = max_iterations
        self.tol = tol
        self.seed = seed
        self.centroids: np.ndarray = None
        self.cost: float = float("nan")

    @staticmethod
    def setup(k: int, max_iterations: int = 100,
              distance: str = "euclidean") -> "KMeansClustering":
        """Reference factory signature (KMeansClustering.setup)."""
        if distance != "euclidean":
            raise ValueError("Only euclidean K-Means is supported")
        return KMeansClustering(k, max_iterations)

    def _seed_centroids(self, x: np.ndarray) -> np.ndarray:
        rng = np.random.default_rng(self.seed)
        first = int(rng.integers(0, len(x)))
        chosen = [first]
        d2 = np.sum((x - x[first]) ** 2, axis=1)
        for _ in range(1, self.k):
            total = d2.sum()
            if total <= 1e-12:
                # fewer distinct points than k: every point already coincides
                # with a chosen seed — fall back to uniform draws
                nxt = int(rng.integers(0, len(x)))
            else:
                nxt = int(rng.choice(len(x), p=d2 / total))
            chosen.append(nxt)
            d2 = np.minimum(d2, np.sum((x - x[nxt]) ** 2, axis=1))
        return x[chosen].copy()

    def apply_to(self, points,
                 check_every: int = 8) -> Tuple[np.ndarray, np.ndarray]:
        """Cluster; returns (assignments (n,), centroids (k, d)).
        (Reference applyTo -> ClusterSet; arrays are the TPU-native
        equivalent of the Cluster object graph.)

        ``check_every`` Lloyd iterations run as one jitted
        ``lax.while_loop`` chunk between host convergence checks: the
        per-iteration ``float(shift)`` host sync the old loop paid is now
        one readback per chunk, with the SAME iteration sequence and stop
        point (the chunk's device-side stopping rule is the host rule) —
        parity asserted in tier-1. ``check_every=1`` reproduces the old
        cadence exactly."""
        if check_every < 1:
            raise ValueError(f"check_every must be >= 1; got {check_every}")
        x32 = np.asarray(points, np.float32)
        if not np.isfinite(x32).all():
            raise ValueError("K-Means input contains non-finite values")
        x = jnp.asarray(x32)
        centroids = jnp.asarray(self._seed_centroids(x32))
        tol = jnp.float32(self.tol)
        done = 0
        self.iterations_run = 0
        while done < self.max_iterations:
            steps = min(int(check_every), self.max_iterations - done)
            centroids, shift, ran = _lloyd_chunk(x, centroids, tol,
                                                 self.k, steps)
            self.iterations_run += int(ran)
            done += steps
            if float(shift) < self.tol:
                break
        # final assignment pass against the FINAL centroids so the returned
        # (assign, centroids, cost) triple is mutually consistent
        _, assign, _, cost = _lloyd_step(x, centroids, self.k)
        self.centroids = np.asarray(centroids)
        self.cost = float(cost)
        return np.asarray(assign), self.centroids
