"""Nearest-neighbors HTTP server.

Parity surface: reference
``deeplearning4j-nearestneighbor-server/.../NearestNeighborsServer.java:44``
(serve k-NN queries over a VPTree built from a points file; POST /knn with a
vector + k, JSON results; /knnnew for vectors not in the index).

stdlib ThreadingHTTPServer like the UI server (the reference uses Play).

Wire format: both routes speak JSON, and additionally the serving tier's
binary payloads (serving/wire.py). ``/knnnew`` accepts the query
vector(s) as ``{"x_b64", "dtype", "shape"}`` — float32/float64, or int8
with an explicit ``"scale"`` (this host server has no calibrated grid to
fall back on) — including a BATCH of queries (shape ``(b, d)``), which
answers one result list per row. Any request with ``"b64": true`` gets
the result matrix back as ``indices_b64``/``distances_b64`` (int32/
float32 little-endian) instead of JSON floats — bulk query batches stop
paying the JSON float bloat (~3x, and ~12x for int8 queries). Parity
with the JSON path is bit-exact and tier-1-tested.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Sequence

import numpy as np

from deeplearning4j_tpu.clustering.vptree import VPTree
from deeplearning4j_tpu.utils.http import parse_content_length


class _Handler(BaseHTTPRequestHandler):
    server_ref = None  # type: Optional["NearestNeighborsServer"]

    def log_message(self, fmt, *args):
        pass

    def _json(self, obj, code=200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_body(self) -> bytes:
        """Size-capped body read (utils/http.py contract: a
        missing/invalid Content-Length is a structured 400, an oversized
        one a structured 413, both answered BEFORE reading the payload).
        Returns None after answering the error."""
        srv = type(self).server_ref
        length, err = parse_content_length(self.headers,
                                           srv.max_body_bytes)
        if err is not None:
            code, message = err
            self._json({"error": message}, code)
            return None
        return self.rfile.read(length)

    def do_GET(self):
        srv = type(self).server_ref
        if self.path in ("/status", "/"):
            self._json({"ok": True, "num_points": len(srv.points),
                        "dims": int(srv.points.shape[1])})
        else:
            self._json({"error": "not found"}, 404)

    def _result_payload(self, srv, rows, b64: bool, batched: bool):
        """rows: list of (indices, distances) per query row. JSON mode
        answers the reference result-object lists; b64 mode answers the
        packed int32/float32 matrices (identical numbers, ~3x fewer
        bytes — the serving/wire.py contract)."""
        if b64:
            from deeplearning4j_tpu.serving.wire import encode_array
            idx = np.asarray([r[0] for r in rows], np.int32)
            dist = np.asarray([r[1] for r in rows], np.float32)
            if not batched:
                idx, dist = idx[0], dist[0]
            return {
                "indices_b64": encode_array(idx, "indices_b64")["indices_b64"],
                "distances_b64": encode_array(
                    dist, "distances_b64")["distances_b64"],
                "shape": list(idx.shape),
            }
        def one(pairs):
            return [{"index": int(i), "distance": float(d),
                     **({"label": srv.labels[i]} if srv.labels else {})}
                    for i, d in pairs]
        if batched:
            return {"batch_results": [one(zip(*r)) for r in rows]}
        return {"results": one(zip(*rows[0]))}

    def do_POST(self):
        srv = type(self).server_ref
        raw = self._read_body()
        if raw is None:
            return
        try:
            req = json.loads(raw)
            if not isinstance(req, dict):
                self._json({"error": "request body must be a JSON object"},
                           400)
                return
            k = int(req.get("k", 1))
            if k < 1:
                self._json({"error": f"k must be >= 1; got {k}"}, 400)
                return
            if self.path == "/knn":
                # query by index of an existing point (reference /knn contract)
                idx = int(req.get("index", -1))
                if not 0 <= idx < len(srv.points):
                    self._json({"error": f"index {idx} out of range"}, 400)
                    return
                indices, dists = srv.tree.search(srv.points[idx], k + 1)
                pairs = [(i, d) for i, d in zip(indices, dists)
                         if i != idx][:k]
                rows, batched = [tuple(zip(*pairs)) if pairs
                                 else ((), ())], False
            elif self.path == "/knnnew":
                if "x_b64" in req:
                    # binary wire form (serving/wire.py); int8 needs an
                    # explicit "scale" — no calibrated grid on this server
                    from deeplearning4j_tpu.serving.wire import decode_array
                    vec = decode_array(
                        req, int8_hint="int8 query payloads need a "
                        "'scale' field on this server; send float32"
                    ).astype(np.float64)
                else:
                    vec = np.asarray(req.get("ndarray", req.get("vector")),
                                     np.float64)
                batched = vec.ndim == 2
                if (vec.ndim not in (1, 2)
                        or vec.shape[-1] != srv.points.shape[1]):
                    self._json({"error": "vector dims mismatch"}, 400)
                    return
                rows = [srv.tree.search(v, k)
                        for v in (vec if batched else [vec])]
            else:
                self._json({"error": "not found"}, 404)
                return
            payload = self._result_payload(srv, rows, bool(req.get("b64")),
                                           batched)
        except Exception as e:  # malformed request -> 400, never a dead thread
            self._json({"error": f"bad request: {e}"}, 400)
            return
        self._json(payload)


class NearestNeighborsServer:
    """``NearestNeighborsServer(points).start(port)`` then POST /knn or
    /knnnew (see module docstring)."""

    def __init__(self, points, labels: Optional[Sequence[str]] = None,
                 distance: str = "euclidean", max_body_bytes: int = 1 << 20):
        self.points = np.asarray(points, np.float64)
        if labels is not None and len(labels) != len(self.points):
            raise ValueError("labels length must match points")
        self.labels = list(labels) if labels is not None else None
        self.tree = VPTree(self.points, distance=distance)
        # a k-NN query is one vector: anything beyond ~1MB is abuse, and
        # an uncapped read lets one POST grow server memory arbitrarily
        self.max_body_bytes = int(max_body_bytes)
        self._httpd: Optional[ThreadingHTTPServer] = None

    def start(self, port: int = 9200,
              bind_address: str = "127.0.0.1") -> "NearestNeighborsServer":
        # loopback by default; pass bind_address="0.0.0.0" to serve remotely
        handler = type("BoundNNHandler", (_Handler,), {"server_ref": self})
        self._httpd = ThreadingHTTPServer((bind_address, port), handler)
        self.port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
