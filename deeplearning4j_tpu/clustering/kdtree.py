"""k-d tree for axis-aligned euclidean k-NN.

Parity surface: reference ``.../clustering/kdtree/KDTree.java:37`` (insert,
nn search; euclidean). Construction here is bulk median-split (balanced)
rather than incremental insert — same query contract.
"""

from __future__ import annotations

import heapq
from typing import List, Optional, Tuple

import numpy as np


class _KDNode:
    __slots__ = ("index", "axis", "left", "right")

    def __init__(self, index: int, axis: int):
        self.index = index
        self.axis = axis
        self.left: Optional["_KDNode"] = None
        self.right: Optional["_KDNode"] = None


class KDTree:
    def __init__(self, items: np.ndarray):
        self.items = np.asarray(items, np.float64)
        self.dims = self.items.shape[1]
        self._root = self._build(list(range(len(self.items))), 0)

    def _build(self, idx: List[int], depth: int) -> Optional[_KDNode]:
        if not idx:
            return None
        axis = depth % self.dims
        idx.sort(key=lambda i: self.items[i, axis])
        mid = len(idx) // 2
        node = _KDNode(idx[mid], axis)
        node.left = self._build(idx[:mid], depth + 1)
        node.right = self._build(idx[mid + 1:], depth + 1)
        return node

    def search(self, target, k: int) -> Tuple[List[int], List[float]]:
        """k nearest indices + euclidean distances, ascending. TIE-STABLE
        like VPTree.search: equal distances resolve to the lower index
        (the heap orders lexicographically on (d, i), and the far-side
        bound is INCLUSIVE so an equal-distance lower-index point across
        the splitting plane is still reached) — exactly the first k of
        ``sorted((d_i, i))``, deterministic on duplicate-heavy inputs."""
        if k < 1:
            raise ValueError(f"k must be >= 1; got {k}")
        target = np.asarray(target, np.float64)
        heap: List[Tuple[float, int]] = []  # (-d, -i): heap[0] = worst kept

        def visit(node: Optional[_KDNode]):
            if node is None:
                return
            d = float(np.linalg.norm(self.items[node.index] - target))
            if len(heap) < k:
                heapq.heappush(heap, (-d, -node.index))
            elif (d, node.index) < (-heap[0][0], -heap[0][1]):
                heapq.heapreplace(heap, (-d, -node.index))
            diff = target[node.axis] - self.items[node.index, node.axis]
            near, far = (node.left, node.right) if diff < 0 else (node.right, node.left)
            visit(near)
            if len(heap) < k or abs(diff) <= -heap[0][0]:
                visit(far)

        visit(self._root)
        pairs = sorted((-nd, -ni) for nd, ni in heap)
        return [i for _, i in pairs], [d for d, _ in pairs]

    def nn(self, target) -> Tuple[int, float]:
        idx, dist = self.search(target, 1)
        return idx[0], dist[0]
