"""All-to-all (Ulysses-style) sequence/context parallelism.

The second long-context scheme next to ``ring_attention``: instead of
streaming K/V blocks around a ring, ONE ``all_to_all`` re-shards the
sequence axis into the heads axis so every device runs FULL-sequence
attention for its head group, then a second ``all_to_all`` restores the
sequence sharding (DeepSpeed-Ulysses; public recipe — the reference has no
attention at all, see ring_attention.py docstring).

Trade-offs vs the ring (both kept, pick per workload):
- communication: 2 all-to-alls of activation size, independent of sequence
  length in VOLUME per device, vs n-1 ppermute rounds of K/V — Ulysses wins
  when heads >= devices and ICI all-to-all bandwidth is good;
- memory: full (t, t_local-free) attention per head group — the softmax is
  over the FULL sequence, so per-device score memory is O(t^2 * h_local),
  vs the ring's O(t_local^2 * h). Ring scales to longer t; Ulysses is
  simpler and faster at moderate t.

Requires heads % n_devices == 0 (the classic Ulysses constraint).
"""

from __future__ import annotations

import functools

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from deeplearning4j_tpu.parallel.ring_attention import reference_attention


def _ulysses_block(q, k, v, axis_name: str, causal: bool):
    """Per-device body: q/k/v arrive as (b, h, t_local, d) sequence shards,
    leave the same way. Inside, heads are sharded and time is full."""
    # (b, h, t/P, d) -> (b, h/P, t, d): split heads (axis 1), gather time
    # (axis 2). tiled=True keeps plain array semantics.
    def scatter_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def gather_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qh, kh, vh = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    o = reference_attention(qh, kh, vh, causal=causal)
    return gather_heads(o)


def ulysses_self_attention(q, k, v, mesh: Mesh, axis_name: str = "data",
                           causal: bool = False):
    """Sequence-parallel attention via head/sequence all-to-all:
    (b, h, t, d) with t sharded over ``axis_name``. Numerically equal to
    ``reference_attention`` on the gathered sequence (exact softmax — no
    online accumulation involved). heads must divide by the axis size."""
    n = mesh.shape[axis_name]
    if q.shape[1] % n != 0:
        raise ValueError(
            f"Ulysses needs heads ({q.shape[1]}) divisible by the "
            f"'{axis_name}' axis size ({n}); use ring_self_attention for "
            "head counts below the mesh size")
    spec = P(None, None, axis_name, None)
    f = jax.shard_map(
        functools.partial(_ulysses_block, axis_name=axis_name, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return f(q, k, v)
