from deeplearning4j_tpu.parallel.mesh import make_mesh, data_sharding, replicated  # noqa: F401
from deeplearning4j_tpu.parallel.trainer import (  # noqa: F401
    ClusterTrainer,
    EarlyStoppingParallelTrainer,
    ParallelWrapper,
)
from deeplearning4j_tpu.parallel.inference import (  # noqa: F401
    DeadlineExpiredError,
    ParallelInference,
    QueueFullError,
)
from deeplearning4j_tpu.parallel.sharding import (  # noqa: F401
    ShardIterator,
    UnequalShardError,
    check_equal_local_shards,
    shard_dataset_rows,
    shard_directory,
    shard_files,
    shard_iterator,
)
from deeplearning4j_tpu.parallel.elastic import (  # noqa: F401
    ElasticError,
    ElasticRestartRequired,
    ElasticRunSummary,
    ElasticRuntime,
    ElasticWorker,
    GenerationRecord,
    LeaseBoard,
    Membership,
    Rendezvous,
    RendezvousTimeout,
    StaleGenerationError,
)
from deeplearning4j_tpu.parallel.compress import (  # noqa: F401
    GradientCompression,
    Int8Compression,
    OneBitCompression,
    ThresholdCompression,
    TopKCompression,
    compression_stats,
    enable_grad_compression,
    ensure_compress_state,
    measure_compression_overhead,
)
from deeplearning4j_tpu.parallel.stats import TrainingStats  # noqa: F401
from deeplearning4j_tpu.parallel.watchdog import (  # noqa: F401
    CollectiveTimeoutError, CollectiveWatchdog,
)
from deeplearning4j_tpu.parallel.pipeline import (  # noqa: F401
    GPipeTrainer,
    make_pipeline_mesh,
    pipeline_apply,
)
from deeplearning4j_tpu.parallel.ulysses import (  # noqa: F401
    ulysses_self_attention,
)
from deeplearning4j_tpu.parallel.ring_attention import (  # noqa: F401
    flash_self_attention,
    reference_attention,
    ring_self_attention,
)
