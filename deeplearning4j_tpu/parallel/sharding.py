"""Per-process input sharding helpers for multi-host training.

Parity surface: the reference's data-side distribution story —
``spark/util/SparkUtils.java:1`` (export/repartition so each executor reads
its slice) and ``spark/data/*`` path-based RDD readers. Here the same two
capabilities are host-process-indexed functions:

* :func:`shard_iterator` — every process walks the SAME global
  DataSetIterator and takes its own row-slice of each batch; feeding these
  shards to :meth:`ClusterTrainer.fit_local_shard` (or just calling
  ``ClusterTrainer.fit`` with the global iterator, which wraps this) trains
  on exactly the global batch with zero duplication.
* :func:`shard_files` — deterministic round-robin file assignment, the
  export/read pattern for corpora too big to stream through every host.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator


class UnequalShardError(ValueError):
    """Process-local batch sizes differ across hosts. Raised BEFORE global
    batch assembly: feeding unequal local shards to
    ``jax.make_array_from_process_local_data`` fails (or hangs a peer)
    deep inside array construction with no hint of which host is off —
    this error names every host's count and the fix instead."""


def check_equal_local_shards(counts: Sequence[int]) -> None:
    """Validate one all-gathered vector of per-process local batch sizes
    (index = process index). Raises :class:`UnequalShardError` naming the
    offenders — the single definition ClusterTrainer's pre-assembly check
    uses and tests can hit directly."""
    counts = [int(c) for c in counts]
    if len(set(counts)) <= 1:
        return
    per = ", ".join(f"p{i}={c}" for i, c in enumerate(counts))
    raise UnequalShardError(
        f"process-local batch sizes differ across hosts: {per}. Every "
        "host must feed the same local batch size — shard a GLOBAL "
        "iterator with shard_iterator (equal row slices by construction), "
        "or drop/pad ragged tail batches identically on every host "
        "(masked-loss padding via perf.bucketing keeps the epoch one "
        "compiled program)")


def _process_defaults(process_index, num_processes):
    if process_index is None or num_processes is None:
        import jax
        process_index = jax.process_index() if process_index is None \
            else process_index
        num_processes = jax.process_count() if num_processes is None \
            else num_processes
    if not (0 <= process_index < num_processes):
        raise ValueError(
            f"process_index {process_index} out of range for "
            f"{num_processes} processes")
    return process_index, num_processes


def shard_dataset_rows(ds: DataSet, process_index: Optional[int] = None,
                       num_processes: Optional[int] = None) -> DataSet:
    """This process's contiguous row-slice of a global batch. The global
    batch size must divide the process count (static shapes are the TPU
    contract — no ragged per-host shards)."""
    pi, np_ = _process_defaults(process_index, num_processes)
    n = ds.num_examples()
    if n % np_:
        raise ValueError(
            f"Global batch {n} not divisible by {np_} processes")
    k = n // np_
    sl = slice(pi * k, (pi + 1) * k)

    def cut(a):
        return None if a is None else np.asarray(a)[sl]

    return DataSet(cut(ds.features), cut(ds.labels),
                   features_mask=cut(ds.features_mask),
                   labels_mask=cut(ds.labels_mask))


class ShardIterator(DataSetIterator):
    """Re-iterable view of a global iterator yielding this process's row
    shard of every batch (see module docstring)."""

    def __init__(self, base, process_index: Optional[int] = None,
                 num_processes: Optional[int] = None):
        self._base = base
        self._pi = process_index
        self._np = num_processes

    def reset(self):
        if hasattr(self._base, "reset"):
            self._base.reset()

    def _generate(self):
        for ds in self._base:
            yield shard_dataset_rows(ds, self._pi, self._np)

    def batch_size(self):
        pi, np_ = _process_defaults(self._pi, self._np)
        bs = self._base.batch_size() if hasattr(self._base, "batch_size") \
            else 0
        return bs // np_ if bs else 0

    def input_columns(self):
        return self._base.input_columns() \
            if hasattr(self._base, "input_columns") else None

    def total_outcomes(self):
        return self._base.total_outcomes() \
            if hasattr(self._base, "total_outcomes") else None


def shard_iterator(iterator, process_index: Optional[int] = None,
                   num_processes: Optional[int] = None) -> ShardIterator:
    """Wrap a global DataSetIterator (or any iterable of DataSets) so this
    process sees its own row shard of each global batch."""
    return ShardIterator(iterator, process_index, num_processes)


def shard_files(paths: Sequence[str], process_index: Optional[int] = None,
                num_processes: Optional[int] = None,
                sort: bool = True) -> List[str]:
    """Deterministic round-robin assignment of files to this process
    (reference SparkUtils export/repartition reading pattern). Sorting
    first makes the assignment identical on every host regardless of
    listing order."""
    pi, np_ = _process_defaults(process_index, num_processes)
    items = sorted(paths) if sort else list(paths)
    return items[pi::np_]


def shard_directory(path: str, pattern: str = "*",
                    process_index: Optional[int] = None,
                    num_processes: Optional[int] = None) -> List[str]:
    """``shard_files`` over a directory glob."""
    import glob as _glob
    return shard_files(_glob.glob(os.path.join(path, pattern)),
                       process_index, num_processes)


__all__ = ["shard_dataset_rows", "shard_iterator", "ShardIterator",
           "shard_files", "shard_directory", "UnequalShardError",
           "check_equal_local_shards"]
