"""Compressed gradient collectives: threshold/top-k/quantized encoding with
error feedback.

Parity surface: the reference's distinctive scale story — lossy
threshold-encoded gradient sharing over the Aeron parameter server
(``EncodedGradientsAccumulator``/``EncodingHandler`` over ND4J
``ThresholdCompression``, SURVEY §2.4 DP-2/DP-4) — plus the literature it
descends from: 1-bit SGD with error feedback (Seide et al., 2014) and Deep
Gradient Compression's top-k sparsification with residual accumulation
(Lin et al., 2018).

TPU-native placement. On a single slice the gradient all-reduce rides ICI
and compression is pure overhead — which is why the psum-based
ClusterTrainer deliberately dropped DP-2 (parallel/trainer.py module
docstring). Across slices the same collective crosses DCN, where the
reference's lossy encoding is exactly the right trade again. The schemes
here run INSIDE the compiled train step, on the gradient pytree, with no
host syncs:

- the whole transform is ``decode(encode(g + residual))`` followed by the
  error-feedback residual update ``residual' = (g + residual) - decoded``,
  carried as extra optimizer-adjacent state threaded through the jitted
  step (and through checkpoints — see utils/serialization.py and
  checkpoint/sharded.py);
- for the dense quantized schemes (:class:`Int8Compression`,
  :class:`OneBitCompression`) the quantize→psum→dequantize order is what a
  cross-slice deployment runs (psum of the int representation + scales);
  under GSPMD the psum XLA inserts during backprop is dense, so this
  container validates the MATH (quantize→dequantize around the reduced
  gradient) and accounts the bytes a quantized wire format would move;
- for the sparse schemes (:class:`ThresholdCompression`,
  :class:`TopKCompression`) the ICI-resident form is encode→psum of the
  dense DECODED tensor (sparse representations don't psum), with
  bytes-on-wire accounting — the estimate that makes the DCN win
  measurable — tracked per step in the carried state.

Every scheme accumulates, on device (no host syncs; read at scrape time by
``obs.watch_grad_compression``): cumulative dense vs wire bytes, the last
step's compression ratio, and the residual's global L2 norm.

Enable via ``ParallelWrapper(net, grad_compression=ThresholdCompression())``
/ ``ClusterTrainer(...)``, or directly with
:func:`enable_grad_compression` for single-device training. The scheme
config rides checkpoint metadata, so ``restore_latest`` rebuilds the
compressed step and restores the residuals — kill-and-resume is bitwise
identical to the uninterrupted compressed run, and an elastic N→M
membership change restores residuals like any other replicated state (or
deterministically resets them to zeros when the checkpoint predates
compression).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp

__all__ = [
    "GradientCompression", "ThresholdCompression", "TopKCompression",
    "Int8Compression", "OneBitCompression", "enable_grad_compression",
    "ensure_compress_state", "measure_compression_overhead",
    "compression_stats",
]

_SCHEME_REGISTRY = {}

# fixed per-leaf framing overhead of the accounted wire formats (shape/
# length/scale header — DL4J's threshold encoding carries a 4-int header)
_HEADER_BYTES = 16.0

_ACC_KEYS = ("steps", "dense_bytes", "wire_bytes", "last_wire_bytes",
             "last_ratio", "residual_norm")


def register_scheme(cls):
    _SCHEME_REGISTRY[cls.__name__] = cls
    return cls


@dataclasses.dataclass(frozen=True)
class GradientCompression:
    """Base config: shared error-feedback + accounting machinery; schemes
    implement ``_encode_decode`` (one leaf) and optionally ``_init_ctrl`` /
    ``_update_ctrl`` (controller state, e.g. the adaptive threshold).

    ``error_feedback=True`` (default) carries the per-parameter residual
    ``r' = (g + r) - decode(encode(g + r))`` so the lossy update stays
    unbiased over time — the property that makes compression compose with
    momentum/accumulator updaters at all. Disabling it is only legal with
    stateless updaters (guarded by :func:`enable_grad_compression`)."""

    error_feedback: bool = True

    # ------------------------------------------------------------- config
    def to_config(self) -> dict:
        d = dataclasses.asdict(self)
        d["@scheme"] = type(self).__name__
        return d

    @staticmethod
    def from_config(d: dict) -> "GradientCompression":
        d = dict(d)
        name = d.pop("@scheme")
        cls = _SCHEME_REGISTRY.get(name)
        if cls is None:
            raise ValueError(f"unknown gradient-compression scheme {name!r} "
                             f"(known: {sorted(_SCHEME_REGISTRY)})")
        return cls(**d)

    # -------------------------------------------------------------- state
    def _init_ctrl(self) -> dict:
        return {}

    def _update_ctrl(self, ctrl: dict, nnz_total, n_total: int) -> dict:
        return ctrl

    def init_state(self, params) -> dict:
        """Device-resident compression state: the error-feedback residual
        (zeros, f32, param shapes), the controller state, and the
        bytes-on-wire accumulators. Lives next to ``opt_state`` on the
        model and is donated through the jitted step like it."""
        residual = None
        if self.error_feedback:
            residual = jax.tree_util.tree_map(
                lambda a: jnp.zeros(a.shape, jnp.float32), params)
        return {
            "residual": residual,
            "ctrl": self._init_ctrl(),
            "acc": {k: jnp.zeros((), jnp.float32) for k in _ACC_KEYS},
        }

    # ----------------------------------------------------------- encoding
    def _encode_decode(self, v, ctrl):
        """One f32 leaf -> (decoded leaf, wire_bytes scalar, nnz scalar).
        Pure jnp — this runs inside the traced train step (lint DLT009
        flags host-side work here)."""
        raise NotImplementedError

    def apply(self, grads, state):
        """The in-step transform: error-feedback encode/decode over the
        gradient pytree. Returns ``(decoded_grads, new_state)``; traced
        into the train step, zero host syncs (trace_check-asserted in
        tests/test_compress.py)."""
        ctrl = state["ctrl"]
        acc = state["acc"]
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        if state["residual"] is not None:
            res_leaves = jax.tree_util.tree_flatten(state["residual"])[0]
        else:
            res_leaves = [None] * len(leaves)
        dec_leaves, new_res = [], []
        wire_total = jnp.zeros((), jnp.float32)
        nnz_total = jnp.zeros((), jnp.float32)
        n_total = 0
        dense_total = 0.0  # static: byte count of the uncompressed tree
        for g, r in zip(leaves, res_leaves):
            v = g.astype(jnp.float32)
            if r is not None:
                v = v + r
            dec, wire, nnz = self._encode_decode(v, ctrl)
            wire_total = wire_total + wire
            nnz_total = nnz_total + nnz
            n_total += v.size
            dense_total += float(v.size * 4)  # f32 gradient on the wire
            if r is not None:
                new_res.append(v - dec)
            dec_leaves.append(dec.astype(g.dtype))
        new_ctrl = self._update_ctrl(ctrl, nnz_total, max(n_total, 1))
        residual = None
        rnorm = jnp.zeros((), jnp.float32)
        if state["residual"] is not None:
            residual = jax.tree_util.tree_unflatten(treedef, new_res)
            sq = jnp.zeros((), jnp.float32)
            for r in new_res:
                sq = sq + jnp.sum(r * r)
            rnorm = jnp.sqrt(sq)
        new_acc = {
            "steps": acc["steps"] + 1.0,
            "dense_bytes": acc["dense_bytes"] + dense_total,
            "wire_bytes": acc["wire_bytes"] + wire_total,
            "last_wire_bytes": wire_total,
            "last_ratio": dense_total / jnp.maximum(wire_total, 1.0),
            "residual_norm": rnorm,
        }
        decoded = jax.tree_util.tree_unflatten(treedef, dec_leaves)
        return decoded, {"residual": residual, "ctrl": new_ctrl,
                         "acc": new_acc}


@register_scheme
@dataclasses.dataclass(frozen=True)
class ThresholdCompression(GradientCompression):
    """DL4J's scheme: encode ``|v| >= tau`` as ``sign(v) * tau``, drop the
    rest into the residual. The adaptive controller mirrors DL4J's
    ``AdaptiveThresholdAlgorithm``: after each step the GLOBAL encoded
    fraction is compared to ``target_sparsity`` and ``tau`` is nudged by
    ``adjust_rate`` (within a deadband and hard bounds), carried as
    device-side controller state.

    Wire accounting follows DL4J's dual encoding: 4-byte signed index per
    encoded element (sparse form) OR 2 bits/element (bitmap form),
    whichever is smaller, plus a fixed header per tensor."""

    threshold: float = 1e-3
    adaptive: bool = True
    target_sparsity: float = 1e-3
    adjust_rate: float = 1.2
    deadband: float = 2.0
    min_threshold: float = 1e-6
    max_threshold: float = 1.0

    def _init_ctrl(self) -> dict:
        return {"tau": jnp.full((), float(self.threshold), jnp.float32)}

    def _update_ctrl(self, ctrl, nnz_total, n_total):
        if not self.adaptive:
            return ctrl
        tau = ctrl["tau"]
        ratio = nnz_total / float(n_total)
        hi = self.target_sparsity * self.deadband
        lo = self.target_sparsity / self.deadband
        tau = jnp.where(ratio > hi, tau * self.adjust_rate,
                        jnp.where(ratio < lo, tau / self.adjust_rate, tau))
        return {"tau": jnp.clip(tau, self.min_threshold, self.max_threshold)}

    def _encode_decode(self, v, ctrl):
        tau = ctrl["tau"]
        mask = jnp.abs(v) >= tau
        dec = jnp.where(mask, jnp.sign(v) * tau, 0.0)
        nnz = jnp.sum(mask.astype(jnp.float32))
        sparse_bytes = 4.0 * nnz + _HEADER_BYTES
        bitmap_bytes = math.ceil(v.size / 16) * 4.0 + _HEADER_BYTES
        return dec, jnp.minimum(sparse_bytes, bitmap_bytes), nnz


@register_scheme
@dataclasses.dataclass(frozen=True)
class TopKCompression(GradientCompression):
    """Deep Gradient Compression-style per-tensor top-k by magnitude: the
    ``ratio`` fraction of largest-|v| entries pass through with their
    VALUES (not clamped), the rest accumulate in the residual. Ties at the
    k-th magnitude all pass (deterministic; never fewer than k). Wire
    accounting: 4-byte index + 4-byte value per kept element + header."""

    ratio: float = 0.01
    min_k: int = 1

    def _encode_decode(self, v, ctrl):
        flat = v.reshape(-1)
        n = flat.size
        k = min(n, max(int(self.min_k), int(round(self.ratio * n))))
        a = jnp.abs(flat)
        kth = jax.lax.top_k(a, k)[0][k - 1]
        # a zero k-th magnitude must not pass the whole (zero) tensor
        mask = (a >= kth) & (a > 0)
        dec = jnp.where(mask, flat, 0.0).reshape(v.shape)
        nnz = jnp.sum(mask.astype(jnp.float32))
        return dec, 8.0 * nnz + _HEADER_BYTES, nnz


@register_scheme
@dataclasses.dataclass(frozen=True)
class Int8Compression(GradientCompression):
    """Scaled int8 quantization: symmetric round-to-nearest onto
    [-127, 127] with a max-abs scale per tensor (default) or per
    ``chunk_size`` slice. The int8 lattice is closed under addition up to
    world-size headroom, so a cross-slice deployment psums the int
    representation + scales (dense-quantized psum); here the math is
    validated as quantize→dequantize around the reduced gradient. Wire:
    1 byte/element + 4 bytes/scale + header."""

    chunk_size: Optional[int] = None

    def _encode_decode(self, v, ctrl):
        flat = v.reshape(-1)
        n = flat.size
        if self.chunk_size and n > int(self.chunk_size):
            c = int(self.chunk_size)
            pad = (-n) % c
            m = jnp.pad(flat, (0, pad)).reshape(-1, c)
            scale = jnp.maximum(
                jnp.max(jnp.abs(m), axis=1, keepdims=True) / 127.0, 1e-30)
            q = jnp.clip(jnp.round(m / scale), -127.0, 127.0)
            dec = (q * scale).reshape(-1)[:n].reshape(v.shape)
            nnz = jnp.sum((q != 0).astype(jnp.float32))
            n_scales = m.shape[0]
        else:
            scale = jnp.maximum(jnp.max(jnp.abs(flat)) / 127.0, 1e-30)
            q = jnp.clip(jnp.round(flat / scale), -127.0, 127.0)
            dec = (q * scale).reshape(v.shape)
            nnz = jnp.sum((q != 0).astype(jnp.float32))
            n_scales = 1
        return dec, float(n) + 4.0 * n_scales + _HEADER_BYTES, nnz


@register_scheme
@dataclasses.dataclass(frozen=True)
class OneBitCompression(GradientCompression):
    """1-bit SGD (Seide et al., 2014): per tensor, each element is reduced
    to its sign bit and decoded as the mean of its sign class (two f32
    scales per tensor) — error feedback carries everything the sign bit
    drops. Wire: 1 bit/element + 2 scales + header."""

    def _encode_decode(self, v, ctrl):
        flat = v.reshape(-1)
        n = flat.size
        posf = (flat >= 0).astype(jnp.float32)
        cnt_p = jnp.sum(posf)
        mean_p = jnp.sum(flat * posf) / jnp.maximum(cnt_p, 1.0)
        mean_n = jnp.sum(flat * (1.0 - posf)) / jnp.maximum(n - cnt_p, 1.0)
        dec = jnp.where(flat >= 0, mean_p, mean_n).reshape(v.shape)
        wire = math.ceil(n / 8) + 8.0 + _HEADER_BYTES
        return dec, jnp.full((), wire, jnp.float32), jnp.full((), float(n),
                                                             jnp.float32)


# ------------------------------------------------------------------ wiring
def _model_updaters(model):
    ups = getattr(model, "_updaters", None)
    if ups is None:
        return []
    return list(ups.values()) if isinstance(ups, dict) else list(ups)


def enable_grad_compression(model, scheme: Optional[GradientCompression]):
    """Attach ``scheme`` to ``model`` (MultiLayerNetwork/ComputationGraph):
    the next minted train/tbptt step compresses gradients in-step. Guards:

    - only the jitted SGD-family path compiles compression in — solver
      configs (lbfgs/cg/line descent) raise here, before any trace;
    - ``error_feedback=False`` composes only with stateless updaters: a
      momentum/accumulator updater (Nesterovs/Adam/RmsProp/...) would
      integrate the biased compression error into its state every step and
      drift — raise with the fix spelled out;
    - a model already compressed with a DIFFERENT config raises (the
      carried state belongs to the old scheme).

    Also registers the obs collect-time absorber so ``/metrics`` carries
    the compression ratio / bytes-on-wire / residual-norm instruments."""
    if scheme is None:
        return model
    existing = getattr(model, "grad_compression", None)
    if existing is not None:
        if existing != scheme:
            raise ValueError(
                f"model already has grad_compression={existing!r}; refusing "
                f"to switch to {scheme!r} mid-run — the carried residual/"
                "controller state belongs to the old scheme (reset "
                "model.grad_compression and model.compress_state to None "
                "first if the switch is intentional)")
        return model
    from deeplearning4j_tpu.optimize.updaters import (
        is_sgd_family, updater_has_accumulating_state)
    algo = getattr(model.conf, "optimization_algo",
                   "stochastic_gradient_descent")
    if not is_sgd_family(algo):
        raise ValueError(
            f"grad_compression requires the jitted SGD-family training "
            f"path; this network is configured with optimization_algo="
            f"{algo!r} (solver path) — compression cannot be compiled into "
            "a host-side solver loop")
    if not scheme.error_feedback:
        bad = sorted({type(u).__name__ for u in _model_updaters(model)
                      if updater_has_accumulating_state(u)})
        if bad:
            raise ValueError(
                f"grad_compression(error_feedback=False) does not compose "
                f"with momentum/accumulator updaters ({', '.join(bad)}): "
                "their state would integrate the biased compression error "
                "every step and drift from the dense trajectory. Keep "
                "error_feedback=True (the default) or switch those layers "
                "to plain Sgd")
    model.grad_compression = scheme
    from deeplearning4j_tpu.obs.registry import (get_registry,
                                                 watch_grad_compression)
    model._grad_compress_watch = watch_grad_compression(get_registry(), model)
    return model


def restore_compress_state(model, scheme_config, arrays=None,
                           origin="checkpointed"):
    """The checkpoint ride-along restore policy, shared by the whole-zip
    (utils/serialization.py) and sharded (checkpoint/sharded.py) paths:
    rebuild the scheme from its checkpoint config, enable it on the model,
    and restore ``arrays`` (a flat name->ndarray mapping of the state tree)
    into the zeros template so the next ``fit`` re-mints the compressed
    step and continues the residual chain bitwise. A state that no longer
    fits the template (scheme config drift) — or ``arrays=None`` (a
    checkpoint saved before the first compressed step) — resets
    DETERMINISTICALLY to zeros, the documented fallback policy. Also
    re-baselines the obs bytes-on-wire counter deltas at the restored
    accumulator values so a kill-and-resume never re-counts the pre-crash
    history."""
    import logging
    from deeplearning4j_tpu.utils.serialization import _restore_into
    scheme = GradientCompression.from_config(scheme_config)
    enable_grad_compression(model, scheme)
    template = scheme.init_state(model.params)
    model.compress_state = template
    if arrays:
        try:
            model.compress_state = _restore_into(template, arrays)
        except ValueError as e:
            logging.getLogger(__name__).warning(
                "%s compression state does not fit the scheme's template "
                "(%s) — resetting residuals deterministically to zeros",
                origin, e)
    watch = getattr(model, "_grad_compress_watch", None)
    if watch is not None:
        watch.reseed()
    return scheme


def ensure_compress_state(model):
    """Initialize ``model.compress_state`` (zeros residual + controller)
    when compression is enabled and no state exists yet — a restored model
    arrives with its state already rebuilt by the checkpoint layer."""
    scheme = getattr(model, "grad_compression", None)
    if scheme is None:
        return None
    if model.params is None:
        model.init()
    if getattr(model, "compress_state", None) is None:
        model.compress_state = scheme.init_state(model.params)
    return model.compress_state


def compression_stats(model) -> Optional[dict]:
    """Host-side read of the device-resident accounting accumulators —
    call OFF the step path (this syncs). Returns None when the model has
    no compression state yet."""
    st = getattr(model, "compress_state", None)
    if st is None:
        return None
    out = {k: float(jax.device_get(v)) for k, v in st["acc"].items()}
    ctrl = st["ctrl"]
    if "tau" in ctrl:
        out["tau"] = float(jax.device_get(ctrl["tau"]))
    return out


def measure_compression_overhead(model, repeats: int = 3) -> float:
    """Time the compression program in ISOLATION: the encode+decode+
    error-feedback pass jitted alone over a zeros gradient tree of the
    model's shapes. The in-step cost cannot be isolated host-side (it
    fuses into the compiled step), so this probe is what feeds the
    ``grad_compress_ms`` histogram and ``grad_compress`` tracer spans
    (obs/). Returns best-of-``repeats`` milliseconds. Off the step path —
    syncs freely."""
    from deeplearning4j_tpu.obs import Stopwatch
    from deeplearning4j_tpu.obs.registry import get_registry
    from deeplearning4j_tpu.obs.trace import get_tracer
    scheme = model.grad_compression
    if scheme is None:
        raise ValueError("model has no grad_compression scheme enabled")
    state = ensure_compress_state(model)
    grads = jax.tree_util.tree_map(
        lambda a: jnp.zeros(a.shape, jnp.float32), model.params)
    fn = jax.jit(scheme.apply)
    jax.block_until_ready(fn(grads, state))  # compile outside the clock
    hist = get_registry().histogram(
        "grad_compress_ms", unit="ms",
        help="wall time of one encode+decode+error-feedback pass over the "
             "full gradient pytree (isolated jitted probe — in-step the "
             "pass fuses into the compiled train step)")
    tracer = get_tracer()
    best = float("inf")
    for _ in range(max(1, int(repeats))):
        with tracer.span("grad_compress"):
            sw = Stopwatch().start()
            out = fn(grads, state)
            ms = sw.stop(sync=out) * 1000.0
        hist.observe(ms)
        best = min(best, ms)
    return best
