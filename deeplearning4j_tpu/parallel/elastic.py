"""Elastic cluster training: epoch-boundary membership changes survive
worker death, arrival and whole-job preemption.

The reference's cluster story keeps training through executor loss (Spark
training masters + the Aeron parameter server, SURVEY §2.4); our
ClusterTrainer — like any plain ``jax.distributed`` job — dies (or hangs)
when ANY host dies. This module makes membership a first-class, mutable
property of a training run:

- **Rendezvous over storage, not a new network service.** Workers
  coordinate exclusively through the existing
  :class:`~deeplearning4j_tpu.checkpoint.storage.StorageBackend` byte-store
  (the same medium the checkpoints ride): per-worker **lease** objects
  refreshed by a heartbeat thread, immutable-per-generation **membership
  records** (``gen-N``), and **bump** breadcrumbs requesting a generation's
  supersession. Liveness = lease freshness under a TTL; the membership for
  generation N+1 forms once every live lease has either joined the barrier
  (``barrier >= N+1``) or expired — so a dead worker delays the bump by at
  most one TTL, and a merely-slow worker is waited for.

- **Leader = smallest live worker id.** The leader writes the membership
  record and hosts the generation's ``jax.distributed`` coordinator on a
  fresh port. Two would-be leaders (an expired-but-alive old leader racing
  the new one) converge by read-back: after writing, everyone adopts
  whatever record the store actually holds; a worker the record excludes
  REJOINS at the next generation instead of continuing — that, plus
  generation-fenced checkpoint commits (``CheckpointManager.commit_guard``),
  is the split-brain guard: a stale generation can neither train (its
  collectives have no peers) nor journal checkpoints over the live run.

- **Re-initialize, don't restart (when possible).** At an epoch boundary,
  a membership change tears the collective runtime down IN-PROCESS
  (:class:`ElasticRuntime`), re-initializes ``jax.distributed`` with the
  new world size, rebuilds the mesh, restores the last epoch checkpoint
  (sharded N→M reshard-on-restore, checkpoint/sharded.py) and re-shards
  the data by the new (rank, world). A hung collective MID-epoch — the
  dead-peer signature a CollectiveWatchdog deadline catches — escalates to
  a membership bump the same way: the wedged dispatch is abandoned on its
  daemon thread, the runtime is rebuilt, and training resumes from the
  epoch checkpoint with the survivors. Only when teardown itself fails
  does the worker raise :class:`ElasticRestartRequired`, telling the
  process supervisor (checkpoint/supervisor.py) to respawn it fresh.

- **The XLA coordination service is configured OUT of failure detection.**
  ``jax.distributed.initialize`` installs a client whose reaction to a
  dead peer is to terminate the process (and this jaxlib's Python
  ``missed_heartbeat_callback`` binding aborts on invocation), so
  :class:`ElasticRuntime` builds the service/client directly with an
  effectively-infinite heartbeat budget and ``shutdown_on_destruction=
  False``: the leases + watchdog above own failure detection, and
  torn-down runtimes are leaked into a graveyard (never shut down — the
  shutdown barrier cannot complete with a dead peer) until process exit.

Determinism: membership changes land only at epoch boundaries, every
epoch ends in a sharded checkpoint, and a restore replays the exact
params/opt-state/RNG — so a SAME-world-size restart (e.g. a whole-job
preemption respawned by the supervisor) is bitwise-identical to the
uninterrupted run, and a shrunk/grown world resumes from exactly the last
epoch state (training beyond that point differs only by all-reduce
topology). tests/test_resilience.py asserts both.

Clocks: lease freshness compares store-written wall timestamps against
the OBSERVER's clock, so skew can mis-declare a live worker dead (it
rejoins at the next generation — churn, never split-brain) but cannot
corrupt state; ``clock=`` is injectable for the skew tests.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import socket
import time
from typing import Callable, Dict, List, Optional

from deeplearning4j_tpu.parallel.leases import LEASE_PREFIX, LeaseBoard

log = logging.getLogger(__name__)

GEN_PREFIX = "gen-"
BUMP_PREFIX = "bump-"

# heartbeat budget that neutralizes the XLA coordination service's own
# failure detection (~11 days at 10s beats): the elastic layer's leases +
# the CollectiveWatchdog own dead-peer detection instead
_NEUTRAL_HEARTBEAT_S = 10
_NEUTRAL_MISSING = 100000

__all__ = [
    "ElasticError", "RendezvousTimeout",
    "ElasticRestartRequired", "StaleGenerationError", "Membership",
    "LeaseBoard", "Rendezvous", "ElasticRuntime", "ElasticWorker",
    "GenerationRecord", "ElasticRunSummary",
]


class ElasticError(RuntimeError):
    """Base class for elastic-layer failures."""


class RendezvousTimeout(ElasticError):
    """No membership formed within the join deadline (store outage, no
    leader, or every peer gone)."""


class StaleGenerationError(ElasticError):
    """A checkpoint commit was attempted by a generation the store says is
    superseded — the generation fence that keeps an evicted-but-alive
    leader from journaling over the live run."""


class ElasticRestartRequired(ElasticError):
    """In-process recovery is not possible (runtime teardown failed);
    the process should exit and be respawned by the supervisor
    (checkpoint/supervisor.py maps this to ``ELASTIC_RESTART_EXIT``)."""


class _MembershipChanged(ElasticError):
    """Internal epoch-boundary signal: re-rendezvous."""


# =========================================================== membership data
@dataclasses.dataclass
class Membership:
    """One generation's committed membership (immutable once adopted)."""
    generation: int
    members: List[str]            # sorted worker ids; members[0] leads
    coordinator: str              # "host:port" of the jax.distributed svc
    reason: str = ""
    writer: str = ""

    def rank_of(self, worker_id: str) -> int:
        return self.members.index(worker_id)

    @property
    def world_size(self) -> int:
        return len(self.members)

    def to_json(self) -> bytes:
        return json.dumps(dataclasses.asdict(self)).encode()

    @classmethod
    def from_json(cls, data: bytes) -> "Membership":
        d = json.loads(data.decode())
        return cls(generation=int(d["generation"]),
                   members=list(d["members"]),
                   coordinator=str(d["coordinator"]),
                   reason=d.get("reason", ""), writer=d.get("writer", ""))


def _gen_name(generation: int) -> str:
    return f"{GEN_PREFIX}{generation:06d}"


def _bump_name(generation: int) -> str:
    return f"{BUMP_PREFIX}{generation:06d}"


# ================================================================== leases
# LeaseBoard lives in parallel/leases.py now (re-exported above): the
# serving fleet registers replicas through the same lease protocol, so
# the primitive moved out of the trainer-specific module.


# =============================================================== rendezvous
def _pick_free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


class Rendezvous:
    """The membership protocol over the store (see module docstring)."""

    def __init__(self, store, lease_board: LeaseBoard,
                 join_timeout_s: float = 60.0, poll_s: float = 0.2,
                 scaledown_grace_s: float = 0.0,
                 advertise_host: str = "localhost",
                 pick_port: Callable[[], int] = _pick_free_port,
                 sleep: Callable[[float], None] = time.sleep):
        from deeplearning4j_tpu.checkpoint.storage import as_backend
        self.store = as_backend(store)
        self.leases = lease_board
        self.worker_id = lease_board.worker_id
        self.clock = lease_board.clock
        self.join_timeout_s = float(join_timeout_s)
        self.poll_s = float(poll_s)
        # how long a leader-elect holds a membership SMALLER than the
        # previous generation's before committing it: a whole-fleet
        # preemption respawns workers on a slow path (process start +
        # imports), and without the grace the first one back would form a
        # world of one and train ahead alone. Availability cost: each
        # genuine shrink commits this much later. Keep it under
        # join_timeout_s.
        self.scaledown_grace_s = float(scaledown_grace_s)
        self.advertise_host = advertise_host
        self.pick_port = pick_port
        self.sleep = sleep
        self.evictions = 0
        self.memberships_written = 0

    # -------------------------------------------------------------- records
    def current(self) -> Optional[Membership]:
        """Highest committed membership record, or None before gen 1."""
        from deeplearning4j_tpu.checkpoint.storage import StorageNotFoundError
        names = self.store.list(prefix=GEN_PREFIX)
        for name in reversed(sorted(names)):
            try:
                return Membership.from_json(self.store.get(name))
            except StorageNotFoundError:
                continue  # raced a writer; try the next older record
            except Exception as e:
                log.warning("unreadable membership %s (%s: %s) — skipping",
                            name, type(e).__name__, e)
        return None

    def request_bump(self, generation: int, reason: str):
        """Ask for ``generation`` to be superseded (idempotent, best
        effort: the lease/expiry rules drive the actual bump; this is the
        fast path + the observability breadcrumb)."""
        name = _bump_name(generation)
        try:
            if not self.store.exists(name):
                self.store.put(name, json.dumps({
                    "generation": generation, "reason": reason,
                    "worker": self.worker_id,
                    "time": self.clock()}).encode())
        except Exception as e:
            log.warning("bump request for gen %d failed (%s: %s)",
                        generation, type(e).__name__, e)

    def bump_requested(self, generation: int) -> Optional[str]:
        from deeplearning4j_tpu.checkpoint.storage import StorageError
        try:
            data = self.store.get(_bump_name(generation))
        except (StorageError, OSError):
            return None
        try:
            rec = json.loads(data.decode())
            return f"{rec.get('reason', 'bump')} (by {rec.get('worker')})"
        except ValueError:
            return "bump (unreadable record)"

    # ---------------------------------------------------------------- join
    def propose_or_await(self, want_gen: int,
                         expected: Optional[int] = None,
                         reason: str = "") -> Membership:
        """Join generation >= ``want_gen``; returns the adopted membership
        this worker belongs to. The leader (smallest live id at the
        barrier) writes the record once every live lease has either
        joined or expired; everyone — including a duelling would-be
        leader — adopts the record the store actually holds (read-back
        convergence). A worker excluded by the adopted record retries at
        the NEXT generation (eviction → rejoin, never split-brain).
        ``expected`` (first generation only) additionally waits for that
        many workers so a fast starter cannot form a world of one."""
        deadline = self.clock() + self.join_timeout_s
        want = int(want_gen)
        first_settle: Optional[float] = None
        self.leases.write(barrier=want)
        while True:
            cur = self.current()
            if cur is not None and cur.generation >= want:
                if self.worker_id in cur.members:
                    self.leases.write(barrier=cur.generation)
                    return cur
                # committed without us: our lease looked dead. Rejoin.
                self.evictions += 1
                log.warning("%s evicted from gen %d (%s) — rejoining at "
                            "gen %d", self.worker_id, cur.generation,
                            cur.reason, cur.generation + 1)
                want = cur.generation + 1
                self.leases.write(barrier=want)
            if self.clock() > deadline:
                raise RendezvousTimeout(
                    f"{self.worker_id}: no membership for gen >= "
                    f"{want_gen} within {self.join_timeout_s:.0f}s")
            try:
                self.leases.refresh_if_due()  # stay alive while waiting
            except Exception as e:
                log.warning("lease refresh during rendezvous failed "
                            "(%s: %s)", type(e).__name__, e)
            leases = self.leases.read_all()
            live = self.leases.live(leases)
            cands = sorted(w for w, r in live.items()
                           if int(r.get("barrier", 0)) >= want)
            settled = bool(cands) and all(
                int(r.get("barrier", 0)) >= want for r in live.values())
            if expected is not None and len(cands) < expected:
                settled = False
            if settled and cands[0] == self.worker_id:
                prev = cur  # highest committed record, read this loop
                if (self.scaledown_grace_s > 0 and prev is not None
                        and prev.generation < want
                        and len(cands) < len(prev.members)):
                    if first_settle is None:
                        first_settle = self.clock()
                    if self.clock() - first_settle < self.scaledown_grace_s:
                        self.sleep(self.poll_s)
                        continue  # a respawning member may yet come back
                port = self.pick_port()
                rec = Membership(
                    generation=want, members=cands,
                    coordinator=f"{self.advertise_host}:{port}",
                    reason=reason, writer=self.worker_id)
                try:
                    self.store.put(_gen_name(want), rec.to_json())
                    self.memberships_written += 1
                except Exception as e:
                    log.warning("membership write for gen %d failed "
                                "(%s: %s) — retrying", want,
                                type(e).__name__, e)
                # loop: adopt the read-back record (ours, or a duelling
                # writer's — last put wins and everyone converges on it)
                continue
            self.sleep(self.poll_s)

    # ------------------------------------------------------ change detection
    def membership_changed(self, m: Membership) -> Optional[str]:
        """Epoch-boundary probe: why (if at all) generation ``m`` must
        end. Returns a reason string or None."""
        cur = self.current()
        if cur is not None and cur.generation > m.generation:
            return f"superseded by gen {cur.generation} ({cur.reason})"
        bump = self.bump_requested(m.generation)
        if bump:
            return f"bump requested: {bump}"
        leases = self.leases.read_all()
        live = self.leases.live(leases)
        dead = [w for w in m.members if w not in live]
        if dead:
            return f"peer lease expired: {sorted(dead)}"
        joiners = [w for w in sorted(live) if w not in m.members]
        if joiners:
            return f"new worker(s) waiting: {joiners}"
        ahead = [w for w in m.members
                 if int(live.get(w, {}).get("barrier", 0)) > m.generation]
        if ahead:
            return f"peer(s) moved to a later generation: {sorted(ahead)}"
        return None


# ====================================================== collective runtime
class ElasticRuntime:
    """Join/leave ``jax.distributed`` with a mutable world size.

    Builds the coordination service/client directly (see module
    docstring: neutralized heartbeats, no shutdown-on-destruction) and
    REPLACES the backend view on every transition via
    ``xla_bridge._clear_backends()``. Torn-down clients/services are
    leaked into a graveyard — with a dead peer their shutdown barrier can
    never complete, and with detection neutralized they stay quiet until
    process exit. World size 1 skips ``jax.distributed`` entirely."""

    def __init__(self, init_timeout_s: float = 60.0):
        self.init_timeout_s = float(init_timeout_s)
        self._graveyard: list = []   # deliberate leaks, for the process's
        self._joined_multi = False   # lifetime (a handful per run)
        self.joins = 0

    @staticmethod
    def _set_cpu_collectives(impl: str):
        import jax
        try:
            jax.config.update("jax_cpu_collectives_implementation", impl)
        except Exception as e:
            log.debug("cpu collectives flag unavailable (%s)", e)

    @staticmethod
    def _reset_backend_view():
        """Rebuild jax's device/world view from the CURRENT distributed
        state. ``_clear_backends`` alone is not enough: ``process_count``
        and ``local_devices`` are lru-cached at the API layer and would
        keep reporting the PREVIOUS generation's world."""
        from jax._src import xla_bridge as xb
        xb._clear_backends()
        for fn_name in ("process_count", "local_devices"):
            fn = getattr(xb, fn_name, None)
            if hasattr(fn, "cache_clear"):
                fn.cache_clear()

    def join(self, coordinator: str, num_processes: int, process_id: int):
        import jax
        from jax._src import distributed
        if self._joined_multi:
            self.leave()
        if num_processes <= 1:
            return
        from jax._src.lib import xla_extension
        st = distributed.global_state
        if st.client is not None:
            raise ElasticError(
                "jax.distributed is already initialized outside the "
                "elastic runtime; elastic training owns the collective "
                "runtime lifecycle and cannot take over an existing one")
        # multi-process CPU needs gloo collectives; the flag is only read
        # by the CPU client, so setting it is harmless on TPU. leave()
        # resets it to "none" — a gloo CPU client cannot be built without
        # a distributed client, so the flag must track the join state.
        self._set_cpu_collectives("gloo")
        service = None
        if process_id == 0:
            bind = "[::]:" + coordinator.rsplit(":", 1)[1]
            service = xla_extension.get_distributed_runtime_service(
                bind, num_processes,
                heartbeat_interval=_NEUTRAL_HEARTBEAT_S,
                max_missing_heartbeats=_NEUTRAL_MISSING,
                shutdown_timeout=5)
        try:
            client = xla_extension.get_distributed_runtime_client(
                coordinator, process_id,
                init_timeout=int(self.init_timeout_s),
                shutdown_timeout=5,
                heartbeat_interval=_NEUTRAL_HEARTBEAT_S,
                max_missing_heartbeats=_NEUTRAL_MISSING,
                shutdown_on_destruction=False,
                use_compression=True)
            client.connect()  # bounded by init_timeout; raises on failure
        except Exception:
            if service is not None:
                self._graveyard.append((None, service))
            # the gloo flag must not outlive the join attempt: with no
            # distributed client behind it, the next (world-of-1) backend
            # build would fail outright
            self._set_cpu_collectives("none")
            raise
        st.service = service if service is not None else st.service
        st.client = client
        st.process_id = int(process_id)
        st.num_processes = int(num_processes)
        st.coordinator_address = coordinator
        self._reset_backend_view()
        self._joined_multi = True
        self.joins += 1
        if jax.process_count() != num_processes:
            raise ElasticError(
                f"runtime came up with {jax.process_count()} processes, "
                f"expected {num_processes}")

    def leave(self, graceful: bool = False):
        """Detach from the current collective runtime.

        ``graceful=False`` (crash/hang path): NO shutdown barrier — it
        cannot complete when a peer is dead, the very reason we are
        leaving. The old client/service are leaked into the graveyard;
        their gloo transports keep their sockets, which a later
        generation's connection storm can trip over — the worker's
        XlaRuntimeError→process-restart escalation covers that.

        ``graceful=True`` (healthy boundary: cooperative re-shard or
        completion, every member leaving TOGETHER): run the real
        ``client.shutdown()`` barrier and drop the references, so the
        gloo contexts are destroyed and nothing stale lingers. Falls back
        to the leak path if the barrier fails or wedges (bounded)."""
        if not self._joined_multi:
            return
        import jax
        from jax._src import distributed
        st = distributed.global_state
        client, service = st.client, st.service
        cleaned = False
        if graceful and client is not None:
            from deeplearning4j_tpu.parallel.watchdog import (
                CollectiveWatchdog)

            def _shutdown():
                client.shutdown()  # barrier across all (live) members
                if service is not None:
                    service.shutdown()
            try:
                CollectiveWatchdog(timeout_s=20.0).call(
                    _shutdown, what="graceful collective shutdown")
                cleaned = True
            except Exception as e:
                log.warning("graceful runtime shutdown failed (%s: %s) — "
                            "leaking it instead", type(e).__name__, e)
        if not cleaned:
            self._graveyard.append((client, service))
        st.client = None
        st.service = None
        st.preemption_sync_manager = None
        st.process_id = 0
        st.num_processes = 1
        st.coordinator_address = None
        self._set_cpu_collectives("none")  # no client to back gloo now
        self._reset_backend_view()
        try:
            jax.clear_caches()  # executables over dead backends
        except Exception as e:
            log.debug("clear_caches failed during elastic leave (%s)", e)
        self._joined_multi = False


def _is_xla_runtime_error(e: BaseException) -> bool:
    try:
        from jax._src.lib import xla_extension
        return isinstance(e, xla_extension.XlaRuntimeError)
    except (ImportError, AttributeError):
        return type(e).__name__ == "XlaRuntimeError"


# ============================================================ elastic worker
@dataclasses.dataclass
class GenerationRecord:
    """One generation as this worker experienced it."""
    generation: int
    world_size: int
    rank: int
    epochs: int = 0
    restored_from: Optional[str] = None   # journal entry file, if restored
    ended: str = ""                       # why the generation ended
    wall_s: float = 0.0


@dataclasses.dataclass
class ElasticRunSummary:
    """What happened across the whole elastic run on THIS worker."""
    worker_id: str
    completed: bool
    epochs: int
    generations: List[GenerationRecord]
    evictions: int
    model: object = None

    def __str__(self):
        gens = "; ".join(
            f"g{g.generation}[{g.rank}/{g.world_size}]x{g.epochs}ep"
            + (f" ({g.ended})" if g.ended else "")
            for g in self.generations)
        return (f"elastic[{self.worker_id}]: completed={self.completed} "
                f"epochs={self.epochs} evictions={self.evictions} [{gens}]")


class ElasticWorker:
    """One worker of an elastic training job (run one per process).

    Usage (per worker process)::

        cm = CheckpointManager(storage=backend, sharded=True,
                               async_write=False)
        worker = ElasticWorker(store=backend, worker_id="w0",
                               checkpoint_manager=cm, num_workers=4)
        summary = worker.run(model_factory, data, num_epochs=10)

    ``store`` is the rendezvous medium (any StorageBackend or a
    directory); it may be the checkpoint store itself or a sibling.
    ``num_workers`` is the expected INITIAL quorum — later generations
    form from whoever holds a fresh lease. ``data`` is a re-iterable of
    global DataSet batches (each worker takes its row shard per its rank
    in the current generation — the membership-change re-sharding) or a
    callable ``(rank, world_size) -> iterable`` for custom sharding.
    ``on_generation(model, membership, rank, world)`` runs after every
    (re)build — chaos tests attach fault injectors there; production code
    re-attaches listeners a restored model does not carry.
    """

    def __init__(self, store, worker_id: str, checkpoint_manager,
                 num_workers: Optional[int] = None,
                 lease_ttl_s: float = 10.0,
                 heartbeat_s: Optional[float] = None,
                 join_timeout_s: float = 120.0,
                 poll_s: float = 0.2,
                 scaledown_grace_s: float = 0.0,
                 collective_timeout_s: Optional[float] = 60.0,
                 init_timeout_s: float = 60.0,
                 max_generations: int = 50,
                 max_consecutive_failures: int = 3,
                 advertise_host: str = "localhost",
                 clock: Callable[[], float] = time.time,
                 on_generation: Optional[Callable] = None):
        from deeplearning4j_tpu.checkpoint.storage import as_backend
        self.store = as_backend(store)
        self.worker_id = str(worker_id)
        self.cm = checkpoint_manager
        self.num_workers = num_workers
        self.collective_timeout_s = collective_timeout_s
        self.max_generations = int(max_generations)
        self.max_consecutive_failures = int(max_consecutive_failures)
        self.on_generation = on_generation
        self.leases = LeaseBoard(self.store, worker_id, ttl_s=lease_ttl_s,
                                 heartbeat_s=heartbeat_s, clock=clock)
        self.rendezvous = Rendezvous(self.store, self.leases,
                                     join_timeout_s=join_timeout_s,
                                     poll_s=poll_s,
                                     scaledown_grace_s=scaledown_grace_s,
                                     advertise_host=advertise_host)
        self.runtime = ElasticRuntime(init_timeout_s=init_timeout_s)
        # obs: generation id / world size are THE labels every elastic
        # post-mortem starts from; the transition pause (generation end →
        # training again: re-rendezvous + runtime re-init + restore) is
        # the availability cost of a membership change
        from deeplearning4j_tpu.obs.registry import get_registry
        reg = get_registry()
        self._m_generation = reg.gauge(
            "elastic_generation", unit="generation",
            help="membership generation this worker is training in")
        self._m_world_size = reg.gauge(
            "elastic_world_size", unit="workers",
            help="world size of the current membership generation")
        self._m_generations = reg.counter(
            "elastic_generations_total", unit="generations",
            help="membership generations this worker joined")
        self._m_transition_pause = reg.histogram(
            "elastic_transition_pause_ms", unit="ms",
            help="membership-transition pause: generation end to training "
                 "again (rendezvous + runtime re-init + restore)")
        self._m_evictions = reg.gauge(
            "elastic_evictions", unit="evictions",
            help="times this worker was evicted and had to rejoin")

    # ------------------------------------------------------------ internals
    def _obs_event(self, name: str, **attrs):
        """Lifecycle breadcrumb into the telemetry pipeline: through the
        tracer when tracing is on (reaches every sink, flight ring
        included), straight into the flight ring otherwise — generation
        boundaries must be in the crash ring even with tracing off."""
        from deeplearning4j_tpu.obs.flight import get_flight_recorder
        from deeplearning4j_tpu.obs.trace import get_tracer
        tracer = get_tracer()
        if tracer.enabled:
            tracer.event(name, **attrs)
            return
        fr = get_flight_recorder()
        if fr is not None:
            fr.event(name, **attrs)

    def _assert_current(self, m: Membership):
        """Checkpoint commit fence: refuse to journal from a superseded
        generation (the split-brain guard for an evicted-but-alive
        leader). TOCTOU-approximate like any lease fence — the window is
        one store read at epoch cadence."""
        cur = self.rendezvous.current()
        if cur is not None and cur.generation != m.generation:
            raise StaleGenerationError(
                f"{self.worker_id} (gen {m.generation}) refusing to "
                f"journal a checkpoint: store is at gen {cur.generation}")

    def _boundary_vote(self, local_change: Optional[str],
                       world: int) -> Optional[str]:
        """Epoch-boundary membership decision, made COLLECTIVELY: each
        member contributes its local store observation and everyone
        adopts "change" if anyone saw one — so the whole generation exits
        at the SAME boundary and the graceful runtime shutdown's barrier
        can complete. (One tiny all-gather per epoch; a dead peer makes
        it hang, which the watchdog deadline turns into the usual
        escalation.)"""
        if world <= 1:
            return local_change
        import numpy as np_
        from deeplearning4j_tpu.parallel.watchdog import CollectiveWatchdog

        def vote():
            from jax.experimental import multihost_utils
            flags = multihost_utils.process_allgather(
                np_.array([1 if local_change else 0], np_.int32))
            return int(np_.asarray(flags).sum())
        n = CollectiveWatchdog(
            timeout_s=self.collective_timeout_s or 60.0).call(
                vote, what="membership boundary vote")
        if local_change is not None:
            return local_change
        return "peer detected a membership change" if n > 0 else None

    def _data_for(self, data, rank: int, world: int,
                  membership: Optional[Membership] = None):
        if hasattr(data, "reader") and hasattr(data, "epoch_order"):
            # a datasets/sharded.py ShardedDataset: every generation gets
            # a fresh reader for its (rank, world) slice, claiming
            # record-range leases under THIS worker's id and the current
            # membership generation (the data-plane half of the
            # split-brain fence — a stale generation's reader raises
            # StaleDataLeaseError instead of consuming ranges the live
            # fleet owns)
            return data.reader(
                rank, world, worker_id=self.worker_id,
                generation=membership.generation if membership else 0)
        if callable(data):
            return data(rank, world)
        if world <= 1:
            return data
        from deeplearning4j_tpu.parallel.sharding import shard_iterator
        return shard_iterator(data, rank, world)

    def _build_model(self, model_factory, rec: GenerationRecord):
        restored = self.cm.restore_latest()
        if restored is not None:
            rec.restored_from = (restored._restored_from.path
                                 if restored._restored_from else None)
            return restored
        return model_factory()

    # ----------------------------------------------------------------- run
    def run(self, model_factory: Callable, data, num_epochs: int,
            ) -> ElasticRunSummary:
        """Train to ``num_epochs`` total epochs across however many
        membership generations it takes; returns when this worker has
        seen the final epoch complete. Raises ``RendezvousTimeout`` /
        ``ElasticError`` when no quorum forms, ``ElasticRestartRequired``
        when only a process respawn can recover."""
        from deeplearning4j_tpu.parallel.trainer import ClusterTrainer
        from deeplearning4j_tpu.parallel.watchdog import (
            CollectiveTimeoutError)
        gens: List[GenerationRecord] = []
        self.leases.start()
        model = None
        consecutive = 0
        try:
            cur = self.rendezvous.current()
            want = 1 if cur is None else cur.generation + 1
            first = cur is None
            while True:
                if len(gens) >= self.max_generations:
                    raise ElasticError(
                        f"exceeded max_generations={self.max_generations} "
                        "— the membership is churning faster than "
                        "training progresses")
                t_rdv = time.monotonic()
                m = self.rendezvous.propose_or_await(
                    want, expected=(self.num_workers if first else None),
                    reason="initial quorum" if first else "membership change")
                first = False
                rank, world = m.rank_of(self.worker_id), m.world_size
                rec = GenerationRecord(generation=m.generation,
                                       world_size=world, rank=rank)
                gens.append(rec)
                self._m_generation.set(m.generation)
                self._m_world_size.set(world)
                self._m_generations.inc()
                self._m_evictions.set(self.rendezvous.evictions)
                self._obs_event("elastic.generation_start",
                                generation=m.generation, world=world,
                                rank=rank, reason=m.reason)
                clean_boundary = False
                t0 = time.monotonic()
                try:
                    self.runtime.join(m.coordinator, world, rank)
                except Exception as e:
                    # ANY join failure retries at the next generation —
                    # the common one is client.connect() raising
                    # XlaRuntimeError after the gen's coordinator died
                    # between writing the record and serving it
                    rec.ended = f"join failed: {type(e).__name__}: {e}"
                    log.warning("%s gen %d join failed (%s: %s)",
                                self.worker_id, m.generation,
                                type(e).__name__, e)
                    self.rendezvous.request_bump(
                        m.generation,
                        f"join failed on {self.worker_id}: "
                        f"{type(e).__name__}")
                    self.runtime.leave()  # drop any half-built state
                    consecutive += 1
                    if consecutive >= self.max_consecutive_failures:
                        raise ElasticError(
                            f"{self.worker_id}: {consecutive} consecutive "
                            f"join failures (last: {type(e).__name__}: "
                            f"{e})") from e
                    want = m.generation + 1
                    continue
                local = None
                try:
                    # re-read the journal from storage: in-process
                    # survivors only APPEND entries locally on the host
                    # that journals (the leader) — without the refresh a
                    # non-leader would restore an older checkpoint than
                    # its peers and the generation's collectives would
                    # diverge. Also re-agrees the save sequence counter
                    # fleet-wide after failed/partial save attempts.
                    self.cm.refresh()
                    model = self._build_model(model_factory, rec)
                    self.cm.fence(model)
                    self.cm.commit_guard = lambda m=m: self._assert_current(m)
                    if not self.cm.checkpoints():
                        # epoch-0 set: even a crash in epoch 1 restores
                        # pristine state instead of refitting a maybe-
                        # different fresh model
                        self.cm.save(model)
                    trainer = ClusterTrainer(model)
                    local = self._data_for(data, rank, world, m)
                    if self.on_generation is not None:
                        self.on_generation(model, m, rank, world)
                    if m.generation > 1:
                        # generation 1 is the initial quorum, not a
                        # transition; everything later — in-process
                        # re-shard OR a respawned worker rejoining — pays
                        # this pause before training resumes
                        pause_ms = (time.monotonic() - t_rdv) * 1000.0
                        self._m_transition_pause.observe(pause_ms)
                        self._obs_event("elastic.transition_pause",
                                        generation=m.generation,
                                        world=world,
                                        pause_ms=round(pause_ms, 2))
                    while model.epoch < num_epochs:
                        # exactly ONE epoch per fit call: num_epochs is
                        # the run TOTAL when a restored model carries a
                        # resume marker (first call after restore) and a
                        # relative count otherwise
                        target = (model.epoch + 1
                                  if getattr(model, "_resume_state", None)
                                  is not None else 1)
                        trainer.fit_local_shard(
                            local, num_epochs=target,
                            collective_timeout_s=self.collective_timeout_s,
                            watchdog_every=1,
                            # step-cadence triggers (save_every_n_steps on
                            # the manager) commit MID-epoch sharded
                            # checkpoints — with a seekable sharded reader
                            # a kill-and-resume then replays ZERO consumed
                            # batches even across an N→M reshard
                            checkpoint_manager=self.cm)
                        consecutive = 0
                        self.cm.save(model)
                        rec.epochs += 1
                        if model.epoch >= num_epochs:
                            break  # done: no boundary vote after the end
                        change = self._boundary_vote(
                            self.rendezvous.membership_changed(m), world)
                        if change is not None:
                            raise _MembershipChanged(change)
                    rec.ended = "completed"
                    rec.wall_s = time.monotonic() - t0
                    self._leave_guarded(graceful=True)
                    total = sum(g.epochs for g in gens)
                    summary = ElasticRunSummary(
                        worker_id=self.worker_id, completed=True,
                        epochs=total, generations=gens,
                        evictions=self.rendezvous.evictions, model=model)
                    log.info("%s", summary)
                    return summary
                except _MembershipChanged as e:
                    rec.ended = str(e)
                    clean_boundary = True  # whole generation left together
                    log.info("%s gen %d ends at epoch boundary: %s",
                             self.worker_id, m.generation, e)
                    self.rendezvous.request_bump(m.generation, str(e))
                except CollectiveTimeoutError as e:
                    # THE escalation: a hung mid-epoch collective (dead
                    # peer) becomes a membership bump, not a dead job. The
                    # wedged dispatch thread is already abandoned
                    # (daemon); training resumes from the epoch checkpoint
                    rec.ended = f"collective timeout -> membership bump"
                    log.warning("%s gen %d: hung collective (%s) — "
                                "escalating to membership bump",
                                self.worker_id, m.generation, e)
                    self.rendezvous.request_bump(
                        m.generation, f"collective timeout on "
                        f"{self.worker_id}")
                    consecutive += 1
                except StaleGenerationError as e:
                    rec.ended = f"fenced: {e}"
                    log.warning("%s: %s — rejoining", self.worker_id, e)
                except Exception as e:
                    rec.ended = f"{type(e).__name__}: {e}"
                    self.rendezvous.request_bump(
                        m.generation, f"{type(e).__name__} on "
                        f"{self.worker_id}")
                    if world > 1 and _is_xla_runtime_error(e):
                        # an ERRORED (not merely hung) collective can
                        # poison the process — gloo's transport threads
                        # may std::terminate later no matter what Python
                        # does. In-process recovery is off the table;
                        # exit and let the supervisor respawn us into the
                        # next generation (the SIGKILL-proof path).
                        rec.ended = (f"collective runtime error -> "
                                     f"process restart ({e})")
                        log.warning("%s gen %d: collective runtime error "
                                    "(%s) — escalating to process restart",
                                    self.worker_id, m.generation, e)
                        raise ElasticRestartRequired(
                            f"collective runtime error on "
                            f"{self.worker_id}: {e}") from e
                    log.warning("%s gen %d failed (%s: %s) — requesting "
                                "membership bump", self.worker_id,
                                m.generation, type(e).__name__, e)
                    consecutive += 1
                    if consecutive >= self.max_consecutive_failures:
                        raise
                finally:
                    rec.wall_s = time.monotonic() - t0
                    if local is not None and hasattr(local, "release_all"):
                        # drop this generation's record-range leases so
                        # the next generation's readers don't wait a TTL
                        # on ranges we will never consume
                        try:
                            local.release_all()
                        except Exception as le:
                            log.warning("data-lease release failed "
                                        "(%s: %s)", type(le).__name__, le)
                    self._obs_event("elastic.generation_end",
                                    generation=m.generation,
                                    epochs=rec.epochs, reason=rec.ended)
                # a synchronized boundary exit tears down cooperatively
                # (real shutdown barrier, gloo contexts destroyed);
                # crash/hang exits leak the runtime instead
                self._leave_guarded(graceful=clean_boundary)
                cur = self.rendezvous.current()
                want = max(m.generation,
                           cur.generation if cur else 0) + 1
        except ElasticRestartRequired as e:
            # the process is about to exit ELASTIC_RESTART_EXIT — this is
            # the flight recorder's moment: the ring holds the victim's
            # last seconds and nothing after this write survives
            from deeplearning4j_tpu.obs.flight import flush_flight_recorder
            flush_flight_recorder(f"ELASTIC_RESTART_EXIT: {e}")
            raise
        finally:
            self.cm.commit_guard = None
            self.cm.fence(None)
            self.leases.stop()
            self.leases.withdraw()

    def _leave_guarded(self, graceful: bool = False):
        """Teardown bounded by a deadline; a teardown that itself wedges
        means in-process recovery is off the table — escalate to a
        process restart."""
        from deeplearning4j_tpu.parallel.watchdog import (
            CollectiveTimeoutError, CollectiveWatchdog)
        try:
            CollectiveWatchdog(timeout_s=45.0).call(
                lambda: self.runtime.leave(graceful=graceful),
                what="elastic runtime teardown")
        except CollectiveTimeoutError as e:
            raise ElasticRestartRequired(
                f"collective runtime teardown wedged on {self.worker_id}; "
                "process must be respawned") from e
