"""Device mesh helpers.

This module is the TPU-native replacement for the reference's entire
parallelism plumbing (SURVEY §2.4): thread-per-device workers
(ParallelWrapper.java:124-143), `Nd4j.averageAndPropagate` parameter
averaging (:327-359), threshold-compressed gradient queues
(EncodedGradientsAccumulator.java:33) and the Aeron parameter server
(SharedTrainingMaster.java:451-469) all collapse into ONE abstraction:
a `jax.sharding.Mesh` with named axes

- ``data``  — data parallelism (batch sharding; XLA inserts the gradient
  all-reduce over ICI, exact every step)
- ``model`` — tensor parallelism (param sharding; XLA/GSPMD inserts
  all-gather/reduce-scatter where needed)

plus axis conventions for sequence parallelism (ring attention) layered on
top in ``ring_attention.py``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(dp: Optional[int] = None, tp: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a (data, model) mesh. ``dp`` defaults to n_devices // tp.

    On a v5e slice the mesh axes map onto the physical ICI torus by XLA's
    device ordering; collectives ride ICI, not DCN, within a slice.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if dp is None:
        if n % tp:
            raise ValueError(f"{n} devices not divisible by tp={tp}")
        dp = n // tp
    if dp * tp > n:
        raise ValueError(f"dp*tp = {dp * tp} exceeds {n} devices")
    arr = np.asarray(devices[:dp * tp]).reshape(dp, tp)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Shard the leading (batch) axis over 'data'."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1))))


def shard_batch(mesh: Mesh, arr):
    """Place one host array with its batch axis sharded over the mesh."""
    import jax.numpy as jnp
    a = jnp.asarray(arr)
    return jax.device_put(a, data_sharding(mesh, a.ndim))


def tp_param_spec(key: str, shape) -> P:
    """Tensor-parallel PartitionSpec for one parameter.

    Convention (megatron-style column sharding on the output dimension):
    - matmul weights (n_in, n_out)            -> P(None, 'model')
    - conv kernels HWIO                        -> P(None, None, None, 'model')
    - biases / per-feature vectors (n,)        -> P('model')
    - everything else                          -> replicated
    GSPMD resolves the resulting contractions with all-gathers/reduce-scatters
    over the 'model' axis.
    """
    ndim = len(shape)
    if key in ("W", "U", "W_pw") and ndim == 2:
        return P(None, MODEL_AXIS)
    if key in ("W", "W_dw", "W_pw") and ndim == 4:
        return P(None, None, None, MODEL_AXIS)
    if key == "W" and ndim == 3:  # conv1d WIO
        return P(None, None, MODEL_AXIS)
    if ndim == 1 and key in ("b", "gamma", "beta"):
        return P(MODEL_AXIS)
    return P()


def tp_shardings(mesh: Mesh, params):
    """Build a params-shaped pytree of NamedShardings for tensor parallelism.

    Divisibility-aware: a param whose sharded dim is not divisible by the
    'model' axis size stays replicated (correct, just not partitioned).
    """
    tp = mesh.shape[MODEL_AXIS]

    def leaf(path, a):
        key = None
        for p in reversed(path):
            if hasattr(p, "key"):
                key = str(p.key)
                break
        spec = tp_param_spec(key or "", a.shape)
        # drop the sharding when not divisible
        for axis_idx, axis_name in enumerate(spec):
            if axis_name == MODEL_AXIS and a.shape[axis_idx] % tp:
                return NamedSharding(mesh, P())
        return NamedSharding(mesh, spec)

    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    return jax.tree_util.tree_unflatten(treedef, [leaf(p, a) for p, a in flat])
