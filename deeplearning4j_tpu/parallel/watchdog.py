"""Collective-timeout watchdog for multi-host training.

SURVEY §5 / reference guardrail analog: ``ParallelWrapper.java:105-110``
(worker-thread supervision). On a TPU pod, the failure mode is different: a
peer process dying or a DCN partition leaves a collective (psum/all_gather)
with no matching participant, and the local ``block_until_ready`` blocks
FOREVER with no error. This watchdog bounds that wait: the blocking sync
runs on a worker thread with a deadline; on expiry it emits a diagnostic
(process index/count, device set, elapsed, what was being waited on) and
raises ``CollectiveTimeoutError`` — or hard-aborts the process when
``abort=True`` so the job scheduler can reschedule the worker (a hung XLA
execution cannot be cancelled from Python; only process death frees the
chip).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Optional

log = logging.getLogger(__name__)


class CollectiveTimeoutError(RuntimeError):
    pass


class CollectiveWatchdog:
    """Deadline guard around host-side syncs of device work.

    Usage::

        wd = CollectiveWatchdog(timeout_s=120)
        ...dispatch jitted multi-host step...
        wd.sync(params, what="train step 42")   # bounded wait

    or as a context manager around any blocking call::

        with wd.guard("eval all_gather"):
            value = float(loss)
    """

    def __init__(self, timeout_s: float = 300.0, abort: bool = False,
                 on_timeout: Optional[Callable[[str], None]] = None):
        self.timeout_s = float(timeout_s)
        self.abort = abort
        self.on_timeout = on_timeout

    # ------------------------------------------------------------ diagnostics
    def _diagnose(self, what: str, elapsed: float) -> str:
        import jax
        try:
            pidx, pcnt = jax.process_index(), jax.process_count()
            devs = ",".join(str(d) for d in jax.local_devices())
        except Exception:
            pidx = pcnt = -1
            devs = "?"
        return (f"collective watchdog: '{what}' did not complete within "
                f"{self.timeout_s:.0f}s (elapsed {elapsed:.1f}s) — likely a "
                f"hung DCN/ICI collective (dead peer or partition). "
                f"process {pidx}/{pcnt}, local devices [{devs}]")

    def _expire(self, what: str, elapsed: float):
        msg = self._diagnose(what, elapsed)
        log.error(msg)
        # a hung collective is a post-mortem moment: put the diagnostic in
        # the crash ring and flush it NOW — with abort=True nothing after
        # this line runs, and even the raise path may end in process death
        try:
            from deeplearning4j_tpu.obs.flight import (flush_flight_recorder,
                                                       get_flight_recorder)
            fr = get_flight_recorder()
            if fr is not None:
                fr.event("watchdog.timeout", what=what,
                         elapsed_s=round(elapsed, 2),
                         timeout_s=self.timeout_s)
            flush_flight_recorder(f"watchdog timeout: {what}")
        except Exception:
            log.exception("flight-recorder flush on watchdog timeout "
                          "failed")
        if self.on_timeout is not None:
            try:
                self.on_timeout(msg)
            except Exception:
                log.exception("watchdog on_timeout callback failed")
        if self.abort:
            # a hung XLA execution cannot be cancelled from Python; process
            # death is the only way to free the chip for a restart
            log.error("watchdog aborting process (abort=True)")
            os._exit(42)
        raise CollectiveTimeoutError(msg)

    # ------------------------------------------------------------------ sync
    def sync(self, tree, what: str = "device sync"):
        """Bounded ``jax.block_until_ready`` over a pytree. Returns the tree
        on success; raises CollectiveTimeoutError (or aborts) on deadline."""
        import jax
        self.call(lambda: jax.block_until_ready(tree), what)
        return tree

    # ------------------------------------------------------------------ call
    def call(self, fn: Callable, what: str = "guarded call"):
        """Run a blocking callable under the deadline on a worker thread —
        needed when the HANG can occur inside the dispatch itself (a
        cross-process execute can block synchronously waiting for a dead
        peer's collective rendezvous, so a post-hoc ``sync`` would never be
        reached). Returns fn's result; raises CollectiveTimeoutError (or
        aborts) on deadline. The wedged worker thread cannot be cancelled —
        deployments that must free the chip use ``abort=True``."""
        done = threading.Event()
        out: dict = {}

        def run():
            try:
                out["v"] = fn()
            except BaseException as e:  # surfaced on the caller thread
                out["e"] = e
            finally:
                done.set()

        t0 = time.monotonic()
        t = threading.Thread(target=run, daemon=True)
        t.start()
        if not done.wait(self.timeout_s):
            self._expire(what, time.monotonic() - t0)
        if "e" in out:
            raise out["e"]
        return out.get("v")

    # --------------------------------------------------------------- guard()
    class _Guard:
        def __init__(self, wd: "CollectiveWatchdog", what: str):
            self.wd = wd
            self.what = what
            self._timer: Optional[threading.Timer] = None
            self._t0 = 0.0
            self._fired = threading.Event()

        def __enter__(self):
            self._t0 = time.monotonic()

            def fire():
                self._fired.set()
                # raising in the caller thread is impossible from a timer;
                # log + optional abort here, caller sees the flag on exit
                try:
                    self.wd._expire(self.what, time.monotonic() - self._t0)
                except CollectiveTimeoutError:
                    pass
            self._timer = threading.Timer(self.wd.timeout_s, fire)
            self._timer.daemon = True
            self._timer.start()
            return self

        def __exit__(self, exc_type, exc, tb):
            if self._timer is not None:
                self._timer.cancel()
            if self._fired.is_set() and exc_type is None:
                raise CollectiveTimeoutError(self.wd._diagnose(
                    self.what, time.monotonic() - self._t0))
            return False

    def guard(self, what: str = "guarded section") -> "_Guard":
        """Context manager: if the body outlives the deadline, diagnostics
        fire immediately (and the process aborts when ``abort=True``);
        otherwise exiting in time cancels the timer."""
        return CollectiveWatchdog._Guard(self, what)
