"""Data/tensor-parallel training.

Parity surface: reference ParallelWrapper
(deeplearning4j-scaleout-parallelwrapper/.../ParallelWrapper.java:58 — worker
threads, device affinity :137, averaging/gradient-sharing dispatch loop
:210-265) and the Spark training masters
(ParameterAveragingTrainingMaster.java:308, SharedTrainingMaster.java:302).

TPU-native semantics: the wrapped network's *existing* jit train step is run
with the global batch sharded over the mesh's 'data' axis and params
replicated (or sharded over 'model' for tensor parallelism). XLA/GSPMD
compiles the gradient all-reduce into the step — equivalent to
averaging_frequency=1 EXACT parameter averaging, every step, with no
queues, no compression, no parameter server. DP-2's lossy threshold encoding
(EncodedGradientsAccumulator) is unnecessary on ICI bandwidth and is NOT
applied by default; for cross-slice DCN deployments pass
``grad_compression=`` (parallel/compress.py) to compile threshold/top-k/
quantized encoding with error feedback into the step.
"""

from __future__ import annotations

import logging
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

log = logging.getLogger(__name__)

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.parallel.mesh import (
    DATA_AXIS, make_mesh, replicated, data_sharding, tp_shardings,
)


class _EpochHooksSuppressed:
    """Listener proxy forwarding everything but epoch hooks (used when a
    minibatch is routed through model.fit, which counts a full epoch)."""

    def __init__(self, inner):
        self._inner = inner

    def on_epoch_start(self, model):
        pass

    def on_epoch_end(self, model):
        pass

    def __getattr__(self, name):
        return getattr(self._inner, name)


class ParallelWrapper:
    """Data-parallel (optionally tensor-parallel) training wrapper.

    Example::

        mesh = make_mesh()                      # all chips on 'data'
        pw = ParallelWrapper(net, mesh=mesh)
        pw.fit(iterator, num_epochs=3)

    Unlike the reference there are no replicas: params live once, sharded or
    replicated across the mesh; ``net.params`` stays valid throughout.
    """

    def __init__(self, model, mesh: Optional[Mesh] = None,
                 tensor_parallel: bool = False,
                 prefetch_buffer: int = 2,
                 collect_stats: bool = False,
                 grad_compression=None):
        """``grad_compression`` (a parallel/compress.py
        ``GradientCompression`` scheme, e.g. ``ThresholdCompression()``)
        compiles lossy gradient encoding with error feedback into the
        train step — the TPU-native analogue of the reference's
        threshold-encoded gradient sharing. Worth it when the all-reduce
        crosses DCN (multi-slice); pure overhead on a single ICI slice.
        A model restored from a compressed checkpoint already carries its
        scheme; passing a DIFFERENT one here raises."""
        from deeplearning4j_tpu.parallel.stats import TrainingStats
        self.model = model
        self.mesh = mesh if mesh is not None else make_mesh()
        self.tensor_parallel = tensor_parallel
        self.prefetch_buffer = prefetch_buffer
        self.grad_compression = grad_compression
        self._placed = False
        self._warned_ragged = False
        # phase timing (reference CommonSparkTrainingStats; enable with
        # collect_stats=True, read via .stats)
        self.stats = TrainingStats() if collect_stats else None
        if self.stats is not None:
            # obs: absorbed at scrape time like ParallelInference.stats(),
            # so /metrics carries the phase breakdown with no per-step writes
            from deeplearning4j_tpu.obs.registry import (get_registry,
                                                         watch_training_stats)
            watch_training_stats(get_registry(), self.stats)

    # ---- parameter placement ----
    def _place_params(self):
        if self._placed:
            return
        m = self.model
        if m.params is None:
            m.init()
        if self.tensor_parallel:
            p_sh = tp_shardings(self.mesh, m.params)
        else:
            p_sh = jax.tree_util.tree_map(lambda a: replicated(self.mesh), m.params)
        m.params = jax.device_put(m.params, p_sh)
        m.state = jax.device_put(
            m.state, jax.tree_util.tree_map(lambda a: replicated(self.mesh), m.state))
        # optimizer state mirrors param shardings (moments have param shapes);
        # scalar counters replicate
        def opt_sh(a):
            return replicated(self.mesh)
        if self.tensor_parallel:
            # re-init optimizer state on the sharded params so moment tensors
            # inherit the param shardings
            if hasattr(m, "_txs") and isinstance(m.opt_state, list):
                m.opt_state = [tx.init(p) for tx, p in zip(m._txs, m.params)]
            elif hasattr(m, "_txs") and isinstance(m.opt_state, dict):
                m.opt_state = {n: m._txs[n].init(m.params[n]) for n in m.opt_state}
        else:
            m.opt_state = jax.device_put(
                m.opt_state, jax.tree_util.tree_map(opt_sh, m.opt_state))
        self._place_compress_state()
        self._placed = True

    def _place_compress_state(self):
        """Enable + place the gradient-compression state: the wrapper's
        scheme (or one the model already carries, e.g. restored from a
        compressed checkpoint) is validated by ``enable_grad_compression``,
        the residual/controller state is initialized if absent, and its
        arrays are placed over the mesh — the residual mirrors the param
        placement (tp shardings under tensor parallelism, replicated
        otherwise); controller/accumulator scalars replicate."""
        m = self.model
        scheme = (self.grad_compression if self.grad_compression is not None
                  else getattr(m, "grad_compression", None))
        if scheme is None:
            return
        from deeplearning4j_tpu.parallel.compress import (
            enable_grad_compression, ensure_compress_state)
        enable_grad_compression(m, scheme)
        cs = ensure_compress_state(m)
        residual = cs["residual"]
        if residual is not None:
            if self.tensor_parallel:
                r_sh = tp_shardings(self.mesh, residual)
            else:
                r_sh = jax.tree_util.tree_map(
                    lambda a: replicated(self.mesh), residual)
            residual = jax.device_put(residual, r_sh)
        rest = {k: cs[k] for k in ("ctrl", "acc")}
        rest = jax.device_put(rest, jax.tree_util.tree_map(
            lambda a: replicated(self.mesh), rest))
        m.compress_state = {"residual": residual, **rest}

    def _shard_dataset(self, ds: DataSet) -> DataSet:
        n = ds.features.shape[0]
        dp = self.mesh.shape[DATA_AXIS]
        if n % dp:
            raise ValueError(
                f"Global batch {n} not divisible by data-parallel size {dp}")

        def put(a):
            if a is None:
                return None
            arr = jnp.asarray(a)
            return jax.device_put(arr, data_sharding(self.mesh, arr.ndim))

        return DataSet(put(ds.features), put(ds.labels),
                       put(ds.features_mask), put(ds.labels_mask))

    def _model_fit_batch(self, sharded: DataSet):
        """One training step WITHOUT the model's own epoch-listener side
        effects (model.fit(DataSet) counts a full epoch, so routing batches
        through it would fire epoch hooks once per minibatch). Uses the
        model's internal batch path for the standard SGD case; tbptt/solver
        configs fall back to model.fit."""
        m = self.model
        conf = getattr(m, "conf", None)
        # is_sgd_family is the ONE normalized-name dispatch shared with
        # fit()'s solver dispatch and the compression guards — not another
        # ad-hoc lowercase string tuple
        from deeplearning4j_tpu.optimize.updaters import is_sgd_family
        standard = (conf is not None
                    and getattr(conf, "backprop_type", "standard") == "standard"
                    and is_sgd_family(getattr(conf, "optimization_algo",
                                              "stochastic_gradient_descent")))
        if standard and hasattr(m, "_fit_batch") and hasattr(m, "_get_jitted"):
            from deeplearning4j_tpu.nn.graph import ComputationGraph
            if isinstance(m, ComputationGraph):
                from deeplearning4j_tpu.datasets.dataset import MultiDataSet
                m._fit_batch(m._get_jitted("train"),
                             MultiDataSet.from_dataset(sharded))
            else:
                m._fit_batch(m._get_jitted("train"), sharded)
        else:
            # tbptt/solver configs go through model.fit; suppress its
            # per-call epoch side effects (hooks + epoch counter) so the
            # wrapper's once-per-epoch semantics hold for every config
            saved_listeners = m.listeners
            epoch0 = m.epoch
            m.listeners = [_EpochHooksSuppressed(l) for l in saved_listeners]
            try:
                m.fit(sharded)
            finally:
                m.listeners = saved_listeners
                m.epoch = epoch0

    def _is_ragged(self, ds: DataSet) -> bool:
        """Whether this batch cannot shard evenly. Overridden by
        ClusterTrainer with a PROCESS-LOCAL predicate so every host reaches
        the same drop/train decision without a coordination collective."""
        return bool(ds.num_examples() % self.mesh.shape[DATA_AXIS])

    def fit_batch(self, ds: DataSet, drop_ragged: bool = False) -> bool:
        """Train on ONE global batch (sharded over the mesh); returns whether
        the batch was trained. ``drop_ragged`` drops batches that don't
        divide the data-parallel size instead of raising — static shapes are
        the TPU contract, so a ragged tail is dropped, not recompiled."""
        self._place_params()
        dp = self.mesh.shape[DATA_AXIS]
        if self._is_ragged(ds) and drop_ragged:
            if not self._warned_ragged:
                log.warning(
                    "Dropping ragged batch of %d examples (global batch must "
                    "divide data-parallel size %d)", ds.num_examples(), dp)
                self._warned_ragged = True
            return False
        with self.mesh:
            if self.stats is None:
                self._model_fit_batch(self._shard_dataset(ds))
            else:
                with self.stats.time("data_placement"):
                    sharded = self._shard_dataset(ds)
                with self.stats.time("train_dispatch"):
                    self._model_fit_batch(sharded)
                self.stats.examples += ds.num_examples()
                self.stats.minibatches += 1
        return True

    # ---- training (reference ParallelWrapper.fit dispatch loop :210) ----
    def fit(self, data, num_epochs: int = 1, prefetch: bool = False,
            checkpoint_manager=None):
        """``prefetch=True`` wraps the iterator in a DevicePrefetchIterator
        (perf/prefetch.py): batch N+1's sharded device_put is issued while
        step N runs, so host→device transfer stops serializing the step
        loop. Ragged batches pass through on host and keep the usual
        drop-ragged policy.

        ``checkpoint_manager`` (checkpoint.CheckpointManager) checkpoints
        after trained batches per its triggers and resumes a restored model
        at the exact step — same semantics as MultiLayerNetwork.fit
        (num_epochs is the run's TOTAL target when resuming)."""
        self._place_params()
        explicit_single = isinstance(data, DataSet)
        if explicit_single:
            data = [data]
        prefetch_cls = None
        if prefetch and not explicit_single:
            from deeplearning4j_tpu.perf.prefetch import DevicePrefetchIterator
            prefetch_cls = DevicePrefetchIterator
        from deeplearning4j_tpu.checkpoint.manager import (
            resume_plan, skip_consumed_batches)
        epochs_to_run, skip = resume_plan(self.model, num_epochs)
        if hasattr(data, "bind_epoch"):
            # epoch-aware sharded readers follow the model's epoch
            # counter (see multilayer.py fit)
            data.bind_epoch(lambda: self.model.epoch)
        for _ in range(epochs_to_run):
            for listener in self.model.listeners:
                listener.on_epoch_start(self.model)
            trained = 0
            seen = skip
            resumed_mid_epoch = skip > 0
            # skip UNDER the prefetch wrapper: consumed batches are never
            # sharded/transferred just to be discarded
            stream = skip_consumed_batches(data, skip)
            if prefetch_cls is not None:
                stream = prefetch_cls(stream, mesh=self.mesh)
            for ds in stream:
                seen += 1
                # a single explicit ragged DataSet raises (dropping it would
                # train on nothing); iterator tail batches drop-remainder
                if self.fit_batch(ds, drop_ragged=not explicit_single):
                    trained += 1
                    if checkpoint_manager is not None:
                        checkpoint_manager.step_end(self.model,
                                                    batch_in_epoch=seen)
            skip = 0
            if seen == 0:
                raise ValueError(
                    "No batches this epoch — the data iterable is empty or a "
                    "one-shot generator exhausted by a previous epoch; pass a "
                    "re-iterable DataSetIterator")
            if trained == 0 and not resumed_mid_epoch:
                raise ValueError(
                    "Every batch this epoch was dropped as ragged — the "
                    f"batch size never divides the data-parallel size "
                    f"{self.mesh.shape[DATA_AXIS]}; pick a divisible batch")
            for listener in self.model.listeners:
                listener.on_epoch_end(self.model)
            self.model.epoch += 1
            if checkpoint_manager is not None:
                checkpoint_manager.epoch_end(self.model)
            if self.stats is not None:
                # steps dispatch asynchronously: one sync per epoch shows
                # the true device time under "epoch_sync"
                with self.stats.time("epoch_sync"):
                    jax.block_until_ready(self.model.params)
                self._record_compile_counters()
        return self

    def _record_compile_counters(self):
        """Surface the model's compile/dispatch counts in TrainingStats —
        'N minibatches, 1 compile' becomes assertable next to the phase
        timings (perf/compile_watch.py)."""
        cw = getattr(self.model, "compile_watch", None)
        if self.stats is not None and cw is not None:
            self.stats.set_counter("model_compiles", cw.compiles())
            self.stats.set_counter("model_dispatches", cw.dispatches())

    def output(self, x) -> np.ndarray:
        self._place_params()
        with self.mesh:
            arr = jnp.asarray(x)
            arr = jax.device_put(arr, data_sharding(self.mesh, arr.ndim))
            return self.model.output(arr)


class ClusterTrainer(ParallelWrapper):
    """Multi-host training (reference: the Spark training masters +
    jax.distributed). Each host runs the same program; the mesh spans all
    hosts' devices and each host feeds its local shard of the global batch.

    Replaces: SparkDl4jMultiLayer.fit(RDD) + ParameterAveragingTrainingMaster
    (sync averaging becomes the compiled all-reduce) and SharedTrainingMaster
    (async Aeron gradient sharing is intentionally not reproduced — see module
    docstring).

    Usage (per host)::

        ClusterTrainer.initialize(coordinator_address="host0:1234",
                                  num_processes=4, process_id=rank)
        trainer = ClusterTrainer(net)           # mesh over ALL global devices
        trainer.fit_local_shard(local_iterator) # per-host local data
    """

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        # whether this epoch's first batch passed the equal-shard check
        # (see _verify_equal_local_shards)
        self._epoch_shards_verified = False

    @staticmethod
    def initialize(coordinator_address: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None):
        """jax.distributed.initialize wrapper (DCN bootstrap). No-op when
        single-process."""
        if num_processes is None or num_processes <= 1:
            return
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)

    # ---- multi-host batch assembly ----
    def _verify_equal_local_shards(self, n_local: int, _gather=None):
        """Pre-assembly guard: every host must feed the SAME local batch
        size, or ``make_array_from_process_local_data`` fails (or hangs a
        peer) deep inside assembly. One all-gather of the local count at
        the FIRST batch of each epoch raises a named UnequalShardError on
        every host simultaneously. The check must be an
        unconditionally-aligned collective: every host runs it at the
        same batch index or none does — a value-keyed cache would turn it
        into a conditional collective that deadlocks in exactly the
        unequal case it exists to catch. (Mid-epoch size changes are not
        re-verified for the same reason; mismatched per-host sequences of
        sizes are a systematic sharding bug visible at batch one.)
        ``_gather`` is injectable for tests."""
        if self._epoch_shards_verified:
            return
        import jax as _jax
        if _gather is None:
            if _jax.process_count() == 1:
                self._epoch_shards_verified = True
                return

            def _gather(n):
                from jax.experimental import multihost_utils
                return np.asarray(multihost_utils.process_allgather(
                    np.array([n], np.int64))).ravel()
        from deeplearning4j_tpu.parallel.sharding import (
            check_equal_local_shards)
        check_equal_local_shards(_gather(n_local))
        self._epoch_shards_verified = True

    def _assemble_global(self, ds: DataSet) -> DataSet:
        """Build the global sharded batch from this process's LOCAL rows
        (``jax.make_array_from_process_local_data``); single-process falls
        back to a plain sharded device_put."""
        self._verify_equal_local_shards(ds.num_examples())

        def gput(a):
            if a is None:
                return None
            arr = np.asarray(a)
            sh = data_sharding(self.mesh, arr.ndim)
            if jax.process_count() == 1:
                return jax.device_put(jnp.asarray(arr), sh)
            return jax.make_array_from_process_local_data(sh, arr)
        return DataSet(gput(ds.features), gput(ds.labels),
                       features_mask=gput(ds.features_mask),
                       labels_mask=gput(ds.labels_mask))

    # ParallelWrapper.fit_batch / EarlyStoppingParallelTrainer route here:
    # in cluster mode the incoming DataSet is the process-LOCAL shard
    def _shard_dataset(self, ds: DataSet) -> DataSet:
        if getattr(ds, "_staged_global", False):
            # assembled one batch ahead by the prefetch stage; the marker
            # (not an array-type test) distinguishes this from a USER
            # device-resident local DataSet, which must still assemble
            return ds
        n_global = ds.num_examples() * jax.process_count()
        dp = self.mesh.shape[DATA_AXIS]
        if n_global % dp:
            raise ValueError(
                f"Global batch {n_global} (local {ds.num_examples()} x "
                f"{jax.process_count()} processes) not divisible by "
                f"data-parallel size {dp}")
        return self._assemble_global(ds)

    def _is_ragged(self, ds: DataSet) -> bool:
        """PROCESS-LOCAL ragged predicate: local rows vs this host's share
        of the data axis. Every host must feed the same local batch size
        (shard_iterator guarantees it) — with equal shards this decision is
        identical on all hosts, so no host can drop a batch its peers train
        (which would orphan their collective and hang them). Unequal local
        shards raise a named UnequalShardError BEFORE assembly
        (_verify_equal_local_shards) listing every host's count, instead
        of failing opaquely inside make_array_from_process_local_data."""
        local_share = max(1, self.mesh.shape[DATA_AXIS]
                          // max(1, jax.process_count()))
        return bool(ds.num_examples() % local_share)

    def fit(self, data, num_epochs: int = 1, prefetch: bool = False,
            checkpoint_manager=None):
        """Train from an ORDINARY global iterator: every process walks the
        same iterator and this trainer internally takes the process's row
        shard of each batch (parallel/sharding.py), so user code needs no
        manual pre-sharding (reference SparkDl4jMultiLayer.fit(RDD)
        ergonomics).

        ``prefetch=True`` stages batch N+1's global-batch assembly
        (``make_array_from_process_local_data`` — an async transfer, like
        device_put) while step N runs; see ``fit_local_shard``.
        ``checkpoint_manager`` checkpoints per its triggers — in cluster
        mode only process 0 writes, the others barrier under the watchdog
        deadline (checkpoint/manager.py)."""
        from deeplearning4j_tpu.parallel.sharding import shard_iterator
        if isinstance(data, DataSet):
            data = [data]
        local = shard_iterator(data) if jax.process_count() > 1 else data
        return self.fit_local_shard(local, num_epochs=num_epochs,
                                    prefetch=prefetch,
                                    checkpoint_manager=checkpoint_manager)

    def _stage_local_batch(self, ds: DataSet) -> DataSet:
        """Prefetch hook (perf/prefetch.py place_fn): assemble the global
        sharded batch EARLY so its host→device transfer overlaps the
        in-flight step. Ragged batches return unchanged — host-side — so
        the dispatch-time divisibility error stays loud and clear."""
        if self._is_ragged(ds):
            return ds
        staged = self._shard_dataset(ds)
        staged._staged_global = True  # consumed by _shard_dataset/stats
        return staged

    def score_local_shard(self, ds: DataSet) -> float:
        """Loss over a validation batch given as per-process local rows
        (the multi-host analogue of ``model.score_dataset``). Goes through
        ``_shard_dataset`` so a ragged validation batch raises the same
        clear divisibility error as the training path."""
        self._place_params()
        with self.mesh:
            return float(self.model.score_dataset(self._shard_dataset(ds)))

    def fit_local_shard(self, data, num_epochs: int = 1,
                        collective_timeout_s: Optional[float] = None,
                        watchdog_every: int = 10, prefetch: bool = False,
                        checkpoint_manager=None):
        """Feed per-host local batches; assembles the global sharded array
        from process-local data (multi-host path of ICI+DCN training).

        ``collective_timeout_s`` arms a CollectiveWatchdog (SURVEY §5): every
        ``watchdog_every`` batches the host syncs the dispatched step under a
        deadline, so a hung DCN collective (dead peer / partition) raises a
        diagnostic CollectiveTimeoutError instead of blocking forever.

        ``prefetch=True`` runs the global-batch assembly
        (``_stage_local_batch``) one batch ahead through a
        DevicePrefetchIterator, so batch N+1's host→device transfer
        overlaps step N instead of serializing the loop.
        ``checkpoint_manager`` checkpoints after each step per its triggers
        (process 0 writes, peers barrier) and resumes a restored model at
        the exact step, skipping the batches its checkpoint already
        consumed."""
        wd = None
        if collective_timeout_s is not None:
            from deeplearning4j_tpu.parallel.watchdog import CollectiveWatchdog
            wd = CollectiveWatchdog(timeout_s=collective_timeout_s)
        self._place_params()
        if isinstance(data, DataSet):
            data = [data]
        prefetch_cls = None
        if prefetch:
            from deeplearning4j_tpu.perf.prefetch import DevicePrefetchIterator
            prefetch_cls = DevicePrefetchIterator
        from deeplearning4j_tpu.checkpoint.manager import (
            resume_plan, skip_consumed_batches)
        from deeplearning4j_tpu.obs.trace import get_tracer
        tracer = get_tracer()
        epochs_to_run, skip = resume_plan(self.model, num_epochs)
        if hasattr(data, "bind_epoch"):
            # epoch-aware sharded readers follow the model's epoch
            # counter — fleet-true resume replays the interrupted
            # epoch's shuffle order at ANY world size
            data.bind_epoch(lambda: self.model.epoch)
        step_no = 0
        with self.mesh:
            for _ in range(epochs_to_run):
                # every host re-verifies at its first batch — an ALIGNED
                # once-per-epoch collective (see _verify_equal_local_shards)
                self._epoch_shards_verified = False
                for listener in self.model.listeners:
                    listener.on_epoch_start(self.model)
                seen = skip
                # skip UNDER the prefetch wrapper: consumed batches are
                # never assembled/transferred just to be discarded
                stream = skip_consumed_batches(data, skip)
                if prefetch_cls is not None:
                    stream = prefetch_cls(stream,
                                          place_fn=self._stage_local_batch)
                # same phase spans as MLN/graph fit (obs/trace.py): the
                # elastic worker trains through THIS loop, so its crash
                # ring / event log carry the per-step breakdown too
                stream = tracer.wrap_iter(stream, "train.data_wait")
                for ds in stream:
                    # _model_fit_batch, not model.fit: per-epoch hooks and
                    # the epoch counter must fire once per EPOCH, not once
                    # per minibatch (same contract as ParallelWrapper.fit)
                    def one_step(d=ds):
                        if self.stats is None:
                            self._model_fit_batch(self._shard_dataset(d))
                        else:
                            # a prefetch-staged batch is already the GLOBAL
                            # array: normalize the examples counter back to
                            # process-local rows so the metric doesn't
                            # change meaning with the prefetch flag
                            n_local = d.num_examples()
                            if getattr(d, "_staged_global", False):
                                n_local //= max(1, jax.process_count())
                            with self.stats.time("data_placement"):
                                sharded = self._shard_dataset(d)
                            with self.stats.time("train_dispatch"):
                                self._model_fit_batch(sharded)
                            self.stats.examples += n_local
                            self.stats.minibatches += 1
                    def guarded_step():
                        if wd is None:
                            one_step()
                        else:
                            # the dispatch itself can block synchronously
                            # on a dead peer's collective rendezvous, so
                            # the deadline must wrap the whole call, not
                            # just a later sync
                            wd.call(one_step,
                                    what=f"cluster step {step_no + 1} "
                                         "dispatch")
                    if tracer.enabled:
                        # both spans run inside ONE watchdog call so the
                        # traced path pays the same single worker thread
                        # per step as the untraced one, and the device
                        # sync still sits under the deadline: a hung
                        # collective raises CollectiveTimeoutError (the
                        # elastic membership-bump escalation) instead of
                        # hanging the tracing span forever
                        def traced_step(n=step_no):
                            with tracer.span("train.step_host", step=n):
                                one_step()
                            with tracer.span("train.step_device", step=n):
                                jax.block_until_ready(self.model._score)
                        if wd is None:
                            traced_step()
                        else:
                            wd.call(traced_step,
                                    what=f"cluster step {step_no + 1} "
                                         "dispatch+sync")
                    else:
                        guarded_step()
                    step_no += 1
                    seen += 1
                    if wd is not None and step_no % max(1, watchdog_every) == 0:
                        wd.sync(self.model.params,
                                what=f"cluster step {step_no}")
                    if checkpoint_manager is not None:
                        checkpoint_manager.step_end(self.model,
                                                    batch_in_epoch=seen)
                skip = 0
                if seen == 0:
                    raise ValueError(
                        "No batches this epoch — the data iterable is empty "
                        "or a one-shot generator exhausted by a previous "
                        "epoch; pass a re-iterable DataSetIterator")
                for listener in self.model.listeners:
                    listener.on_epoch_end(self.model)
                self.model.epoch += 1
                if checkpoint_manager is not None:
                    checkpoint_manager.epoch_end(self.model)
                self._record_compile_counters()
            if wd is not None:
                # tail steps after the last every-N sync must not escape the
                # deadline — a hang there would otherwise surface only at
                # the caller's next (unguarded) host sync
                wd.sync(self.model.params, what=f"epoch end (step {step_no})")
        return self


from deeplearning4j_tpu.earlystopping.trainer import EarlyStoppingTrainer


class EarlyStoppingParallelTrainer(EarlyStoppingTrainer):
    """Early stopping composed with data-parallel training (reference
    deeplearning4j-scaleout-parallelwrapper/.../EarlyStoppingParallelTrainer.java:44).

    Each training batch routes through a ParallelWrapper (global batch
    sharded over the mesh); validation scoring runs on the same
    replicated-parameter model, so savers/conditions see identical
    semantics to the single-device EarlyStoppingTrainer.
    """

    def __init__(self, config, model, train_data, validation_data=None,
                 score_calculator=None, mesh: Optional[Mesh] = None,
                 tensor_parallel: bool = False, cluster: bool = False,
                 checkpoint_manager=None):
        """``cluster=True`` routes batches through a ClusterTrainer (multi-
        host assembly of per-process local shards) and, when no explicit
        score_calculator is given, scores validation data through the same
        multi-host path (local rows per process, global loss).
        ``checkpoint_manager`` plugs checkpoint/ in as the saver backend,
        exactly as on the base EarlyStoppingTrainer."""
        trainer_holder = []
        if cluster and score_calculator is None and validation_data is not None:
            def score_calculator(m):
                total, n = 0.0, 0
                for ds in validation_data:
                    total += (trainer_holder[0].score_local_shard(ds)
                              * ds.num_examples())
                    n += ds.num_examples()
                return total / max(n, 1)
        super().__init__(config, model, train_data, validation_data,
                         score_calculator,
                         checkpoint_manager=checkpoint_manager)
        if cluster:
            self.wrapper = ClusterTrainer(model, mesh=mesh,
                                          tensor_parallel=tensor_parallel)
            trainer_holder.append(self.wrapper)
        else:
            self.wrapper = ParallelWrapper(model, mesh=mesh,
                                           tensor_parallel=tensor_parallel)

    def _fit_batch(self, ds) -> bool:
        # per-batch path: no epoch-listener double fire, ragged tails dropped
        # (the base trainer raises if an entire epoch trains nothing)
        return self.wrapper.fit_batch(ds, drop_ragged=True)
