"""Ring attention — sequence/context parallelism over the device mesh.

The reference has NO attention and no sequence parallelism (SURVEY §5
long-context: only tBPTT + masking). This module is the framework's
long-context story, built TPU-first:

- sequences are sharded over a mesh axis (time axis of (b, h, t, d));
- each device holds one Q block and streams K/V blocks around the ring with
  ``lax.ppermute`` (neighbour exchanges ride the ICI torus);
- softmax is accumulated online (flash-attention style log-sum-exp rescaling),
  so the full (t, t) score matrix never materializes — memory is O(t_local^2)
  per device and sequence length scales linearly with the number of devices.

`ring_self_attention` is the public entry: a shard_map'd function usable under
jit and differentiable (autodiff traces through ppermute; the backward pass
performs the reverse ring).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def reference_attention(q, k, v, causal: bool = False, key_mask=None):
    """Plain full-matrix attention (numerical reference / single-device path).
    Shapes: (batch, heads, time, head_dim); optional ``key_mask`` (batch,
    time) zeros out padded keys. The masked fill is a large finite negative
    (dtype-aware), not -inf: a fully-masked row then softmaxes to uniform
    finite weights instead of NaN (fp16's -1e9 would overflow to -inf)."""
    d = q.shape[-1]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(d).astype(q.dtype)
    neg = jnp.asarray(-0.7 * float(jnp.finfo(scores.dtype).max), scores.dtype)
    if causal:
        t_q, t_k = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((t_q, t_k), bool))
        scores = jnp.where(mask, scores, neg)
    if key_mask is not None:
        scores = jnp.where(key_mask[:, None, None, :].astype(bool),
                           scores, neg)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v)


def _ring_block(q, k, v, axis_name: str, causal: bool):
    """Per-device body under shard_map: q/k/v are the LOCAL time blocks."""
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    t_local = q.shape[2]
    d = q.shape[-1]
    scale = 1.0 / jnp.sqrt(d).astype(q.dtype)

    q_pos = my * t_local + jnp.arange(t_local)              # global q positions

    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(i, carry):
        k_cur, v_cur, num, denom, maxv = carry
        src = (my - i) % n                                   # whose K/V block this is
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k_cur) * scale
        if causal:
            k_pos = src * t_local + jnp.arange(t_local)
            mask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(mask[None, None], scores, -jnp.inf)
        block_max = jnp.max(scores, axis=-1)                 # (b,h,tq)
        new_max = jnp.maximum(maxv, block_max)
        # guard -inf rows (fully masked block): exp(-inf - -inf) -> use where
        correction = jnp.exp(jnp.where(jnp.isinf(maxv) & jnp.isinf(new_max),
                                       0.0, maxv - new_max))
        p = jnp.exp(jnp.where(jnp.isinf(scores),
                              -jnp.inf, scores - new_max[..., None]))
        p = jnp.where(jnp.isnan(p), 0.0, p)
        num = num * correction[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v_cur)
        denom = denom * correction + jnp.sum(p, axis=-1)
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (k_next, v_next, num, denom, new_max)

    num0 = jnp.zeros_like(q)
    denom0 = jnp.zeros(q.shape[:-1], q.dtype)
    max0 = jnp.full(q.shape[:-1], -jnp.inf, q.dtype)
    # unrolled python loop: n is static (mesh size), keeps ppermute schedule
    # explicit for XLA overlap
    carry = (k, v, num0, denom0, max0)
    for i in range(n):
        carry = step(i, carry)
    _, _, num, denom, _ = carry
    return num / jnp.maximum(denom[..., None], 1e-30)


def ring_self_attention(q, k, v, mesh: Mesh, axis_name: str = "data",
                        causal: bool = False):
    """Sequence-parallel attention: (b, h, t, d) with t sharded over
    ``axis_name``. Drop-in equal (up to float tolerance) to
    ``reference_attention`` on the gathered sequence."""
    spec = P(None, None, axis_name, None)
    f = jax.shard_map(
        functools.partial(_ring_block, axis_name=axis_name, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return f(q, k, v)


def flash_self_attention(q, k, v, causal: bool = False):
    """Single-device attention through the Pallas TPU flash kernel
    (jax.experimental.pallas.ops.tpu.flash_attention): tiled online-softmax
    in VMEM, never materializing the (t, t) score matrix. Measured 11x over
    the einsum reference at (b4 h8 t4096 d128, causal) on v5e; agrees to
    bf16-matmul tolerance and differentiates. Falls back to
    ``reference_attention`` off-TPU.

    Use for the per-device blocks when sequences fit one chip; shard longer
    sequences with ``ring_self_attention``.
    Shapes: (batch, heads, time, head_dim)."""
    if jax.default_backend() != "tpu":
        return reference_attention(q, k, v, causal=causal)
    from jax.experimental.pallas.ops.tpu.flash_attention import flash_attention
    d = q.shape[-1]
    return flash_attention(q, k, v, causal=causal,
                           sm_scale=float(1.0 / (d ** 0.5)))
