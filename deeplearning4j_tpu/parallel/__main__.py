"""``python -m deeplearning4j_tpu.parallel`` — train a saved model with the
data-parallel wrapper.

Parity surface: reference
``deeplearning4j-scaleout-parallelwrapper/.../main/ParallelWrapperMain.java:29``
(--modelPath/--workers/--prefetchSize/--modelOutputPath CLI driving
ParallelWrapper over a DataSetIterator factory). Workers/averagingFrequency
dissolve into the mesh: the step compiles the all-reduce, every step is an
exact average.
"""

from __future__ import annotations

import argparse
import json


def build_iterator(spec: str, batch: int):
    from deeplearning4j_tpu.datasets import (CifarDataSetIterator,
                                             CSVRecordReader,
                                             IrisDataSetIterator,
                                             MnistDataSetIterator,
                                             RecordReaderDataSetIterator)
    if spec == "iris":
        return IrisDataSetIterator(batch=batch)
    if spec == "mnist":
        return MnistDataSetIterator(batch=batch)
    if spec == "cifar10":
        return CifarDataSetIterator(batch=batch)
    if spec.startswith("csv:"):
        # csv:<path>:<label_index>:<num_classes>
        _, path, label_idx, n_classes = spec.split(":")
        return RecordReaderDataSetIterator(
            CSVRecordReader(path), batch, label_index=int(label_idx),
            num_possible_labels=int(n_classes))
    raise SystemExit(f"Unknown --data spec {spec!r} "
                     "(iris|mnist|cifar10|csv:<path>:<label>:<classes>)")


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Data-parallel training of a saved model (ParallelWrapper)")
    ap.add_argument("--model-path", required=True,
                    help="Model zip (utils.serialization format)")
    ap.add_argument("--data", required=True,
                    help="iris | mnist | cifar10 | csv:<path>:<label>:<classes>")
    ap.add_argument("--batch", type=int, default=128,
                    help="GLOBAL batch size (split over the mesh)")
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--dp", type=int, default=None,
                    help="Data-parallel mesh size (default: all devices)")
    ap.add_argument("--tp", type=int, default=1,
                    help="Tensor-parallel mesh size")
    ap.add_argument("--model-output-path", default=None,
                    help="Where to save the trained model (default: in place)")
    ap.add_argument("--report-stats", action="store_true",
                    help="Print phase-timing stats (CommonSparkTrainingStats)")
    args = ap.parse_args(argv)

    from deeplearning4j_tpu.parallel import ParallelWrapper
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.utils.serialization import restore, write_model

    net = restore(args.model_path)
    mesh = make_mesh(dp=args.dp, tp=args.tp) if (args.dp or args.tp > 1) \
        else make_mesh()
    wrapper = ParallelWrapper(net, mesh=mesh,
                              tensor_parallel=args.tp > 1,
                              collect_stats=args.report_stats)
    iterator = build_iterator(args.data, args.batch)
    wrapper.fit(iterator, num_epochs=args.epochs)
    out = args.model_output_path or args.model_path
    write_model(net, out)
    result = {"saved": out, "epochs": args.epochs,
              "final_score": net.score()}
    if args.report_stats:
        print(wrapper.stats.to_string())
        result["stats"] = wrapper.stats.as_dict()
    print(json.dumps({k: v for k, v in result.items() if k != "stats"}))


if __name__ == "__main__":
    main()
