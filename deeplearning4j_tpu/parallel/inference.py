"""Parallel inference.

Parity surface: reference parallelism/ParallelInference.java:32 (round-robin
device-pinned replicas, :97-134 observables/worker loop) +
BatchedInferenceObservable / BasicInferenceObservable dynamic batching.

TPU-native: one jit-compiled forward with the batch sharded over the mesh
replaces per-device replicas. Dynamic batching keeps the reference's shape:
requests enqueue as observables; a background worker coalesces up to
``batch_limit`` requests (waiting at most ``queue_timeout_ms`` for
stragglers) into ONE device dispatch and distributes the per-request slices.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.parallel.mesh import data_sharding, make_mesh, replicated


class InferenceObservable:
    """Per-request future (reference BasicInferenceObservable /
    BatchedInferenceObservable's per-caller view)."""

    def __init__(self):
        self._done = threading.Event()
        self._out = None
        self._err: Optional[BaseException] = None

    def _resolve(self, out):
        self._out = out
        self._done.set()

    def _fail(self, err: BaseException):
        self._err = err
        self._done.set()

    def is_done(self) -> bool:
        return self._done.is_set()

    def get(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._done.wait(timeout):
            raise TimeoutError("inference result not ready")
        if self._err is not None:
            raise self._err
        return self._out


class ParallelInference:
    """``output()`` for synchronous sharded calls; ``submit()`` /
    ``output_batched()`` for the dynamic-batching path.

    inference_mode: "batched" coalesces concurrent requests on a worker
    thread (reference InferenceMode.BATCHED); "sequential" dispatches each
    request on the caller's thread (InferenceMode.SEQUENTIAL)."""

    def __init__(self, model, mesh=None, batch_limit: int = 32,
                 queue_timeout_ms: int = 5, inference_mode: str = "batched"):
        if inference_mode not in ("batched", "sequential"):
            raise ValueError(f"unknown inference_mode '{inference_mode}'")
        self.model = model
        self.mesh = mesh if mesh is not None else make_mesh()
        self.batch_limit = batch_limit
        self.queue_timeout_ms = queue_timeout_ms
        self.inference_mode = inference_mode
        if model.params is None:
            model.init()
        repl = jax.tree_util.tree_map(lambda a: replicated(self.mesh), model.params)
        model.params = jax.device_put(model.params, repl)
        self._q: "queue.Queue" = queue.Queue()
        self._worker: Optional[threading.Thread] = None
        self._worker_lock = threading.Lock()
        self._stop = threading.Event()
        # observables the worker has dequeued but not yet resolved; shutdown
        # fails these too if the worker never comes back (wedged device call)
        self._inflight: List[InferenceObservable] = []
        self._inflight_lock = threading.Lock()
        # observability (exercised by the latency/throughput tests)
        self.requests_served = 0
        self.batches_dispatched = 0
        self.batch_sizes: List[int] = []

    # ------------------------------------------------------------ sync path
    def output(self, x) -> np.ndarray:
        """Synchronous sharded inference (reference ParallelInference.output)."""
        with self.mesh:
            arr = jnp.asarray(x)
            dp = self.mesh.shape["data"]
            pad = (-arr.shape[0]) % dp
            if pad:
                arr = jnp.concatenate([arr, jnp.zeros((pad,) + arr.shape[1:],
                                                      arr.dtype)])
            arr = jax.device_put(arr, data_sharding(self.mesh, arr.ndim))
            out = self.model.output(arr)
            return out[:out.shape[0] - pad] if pad else out

    # -------------------------------------------------------- batched path
    def submit(self, x) -> InferenceObservable:
        """Enqueue one request; returns its observable (reference
        ParallelInference.java:97 observable provider)."""
        obs = InferenceObservable()
        if self.inference_mode == "sequential":
            try:
                obs._resolve(self.output(np.asarray(x)))
            except BaseException as e:  # surfaced at .get()
                obs._fail(e)
            self.requests_served += 1
            return obs
        # enqueue + worker liveness under one lock: a concurrent shutdown()
        # (same lock) can then never strand this request between the put and
        # the worker start
        with self._worker_lock:
            self._q.put((np.asarray(x), obs))
            self._ensure_worker_locked()
        return obs

    def output_batched(self, x) -> np.ndarray:
        """Blocking convenience over submit() (reference
        BatchedInferenceObservable callers)."""
        return self.submit(x).get()

    _SENTINEL = object()

    def shutdown(self):
        """Stop the worker after draining; pending observables either get
        served by the final drain or failed, never left hanging."""
        with self._worker_lock:
            w = self._worker
            if w is not None and w.is_alive():
                self._stop.set()
                self._q.put(ParallelInference._SENTINEL)
                w.join(timeout=10)
                if w.is_alive():
                    # worker is wedged (e.g. inside a device call): fail the
                    # requests it already dequeued so their get() unblocks
                    with self._inflight_lock:
                        stuck, self._inflight = self._inflight, []
                    for obs in stuck:
                        if not obs.is_done():
                            obs._fail(RuntimeError(
                                "ParallelInference worker did not stop within "
                                "10s at shutdown; in-flight request abandoned"))
            self._worker = None
            # fail anything the worker did not reach (its get() callers
            # would otherwise block forever)
            leftovers = []
            try:
                while True:
                    leftovers.append(self._q.get_nowait())
            except queue.Empty:
                pass
            for item in leftovers:
                if item is not ParallelInference._SENTINEL:
                    item[1]._fail(RuntimeError(
                        "ParallelInference shut down before request served"))

    # ------------------------------------------------------------- worker
    def _ensure_worker_locked(self):
        """Caller holds _worker_lock."""
        if self._worker is None or not self._worker.is_alive():
            self._stop.clear()
            self._worker = threading.Thread(target=self._worker_loop,
                                            daemon=True)
            self._worker.start()

    def _collect(self):
        """Take up to batch_limit requests, waiting queue_timeout_ms for
        stragglers after the first arrives (the reference's batching
        window)."""
        try:
            first = self._q.get(timeout=0.05)
        except queue.Empty:
            return []
        if first is ParallelInference._SENTINEL:
            return []
        items = [first]
        deadline = time.monotonic() + self.queue_timeout_ms / 1000.0
        while len(items) < self.batch_limit:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                nxt = self._q.get(timeout=remaining)
            except queue.Empty:
                break
            if nxt is ParallelInference._SENTINEL:
                break
            items.append(nxt)
        return items

    def _worker_loop(self):
        while not self._stop.is_set():
            items = self._collect()
            if not items:
                continue
            xs = [i[0] for i in items]
            sizes = [len(x) for x in xs]
            with self._inflight_lock:
                self._inflight = [obs for _, obs in items]
            try:
                out = self.output(np.concatenate(xs, axis=0))
                ofs = 0
                for (x, obs), n in zip(items, sizes):
                    obs._resolve(out[ofs:ofs + n])
                    ofs += n
            except BaseException as e:
                for _, obs in items:
                    obs._fail(e)
            finally:
                with self._inflight_lock:
                    self._inflight = []
            self.requests_served += len(items)
            self.batches_dispatched += 1
            self.batch_sizes.append(len(items))
