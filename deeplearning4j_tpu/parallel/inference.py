"""Parallel inference.

Parity surface: reference parallelism/ParallelInference.java:32 (round-robin
device-pinned replicas, :97-134 observables/worker loop) +
BatchedInferenceObservable / BasicInferenceObservable dynamic batching.

TPU-native: one jit-compiled forward with the batch sharded over the mesh
replaces per-device replicas. Dynamic batching keeps the reference's shape:
requests enqueue as observables; a background worker coalesces up to
``batch_limit`` requests (waiting at most ``queue_timeout_ms`` for
stragglers) into ONE device dispatch and distributes the per-request slices.

Shape stability: every dispatch pads to a canonical bucket size
(perf/bucketing.BucketPolicy — on by default), so a serving mix of request
sizes 1..32 compiles a handful of programs instead of one per distinct
coalesced size; ``warmup()`` pre-compiles every bucket before traffic
arrives, and ``stats()`` reports batch-size percentiles, per-bucket dispatch
counts and the model's compile counters.
"""

from __future__ import annotations

import queue
import random
import threading
import time
from collections import Counter, deque
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.parallel.mesh import data_sharding, make_mesh, replicated
from deeplearning4j_tpu.perf.bucketing import BucketPolicy, pad_to_bucket


class QueueFullError(RuntimeError):
    """The bounded request queue stayed full past the admission timeout.

    Raised by :meth:`ParallelInference.submit` instead of blocking forever
    (the pre-bound queue grew without limit under a stalled worker). A
    serving front-end maps this to HTTP 429 — shed load, never queue it
    unboundedly."""


class DeadlineExpiredError(TimeoutError):
    """The request's deadline passed before its batch dispatched.

    Expired requests are evicted at batch formation — they never occupy a
    device-batch slot they cannot use — and their ``get()`` raises this.
    A serving front-end maps it to HTTP 504."""


class InferenceObservable:
    """Per-request future (reference BasicInferenceObservable /
    BatchedInferenceObservable's per-caller view)."""

    def __init__(self):
        self._done = threading.Event()
        self._out = None
        self._err: Optional[BaseException] = None

    def _resolve(self, out):
        self._out = out
        self._done.set()

    def _fail(self, err: BaseException):
        self._err = err
        self._done.set()

    def is_done(self) -> bool:
        return self._done.is_set()

    def get(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._done.wait(timeout):
            raise TimeoutError("inference result not ready")
        if self._err is not None:
            raise self._err
        return self._out


class ParallelInference:
    """``output()`` for synchronous sharded calls; ``submit()`` /
    ``output_batched()`` for the dynamic-batching path.

    inference_mode: "batched" coalesces concurrent requests on a worker
    thread (reference InferenceMode.BATCHED); "sequential" dispatches each
    request on the caller's thread (InferenceMode.SEQUENTIAL).

    queue_depth / queue_put_timeout_ms: the request queue is BOUNDED —
    when no slot frees within the timeout, ``submit`` raises
    :class:`QueueFullError` instead of growing host memory without limit.
    Per-request deadlines (``submit(x, deadline=...)``) are honored at
    batch formation: expired requests are evicted before device dispatch
    (:class:`DeadlineExpiredError`), never wasting a batch slot. The
    ``serving`` subsystem maps these to HTTP 429/504.

    bucket_policy: perf.BucketPolicy controlling the canonical dispatch
    sizes (default: power-of-two buckets with floor 8). Pass ``None`` to
    disable bucketing — every distinct padded batch size then compiles its
    own program, which is almost never what you want in serving.

    fold_bn: serve a BN-folded COPY of the model (perf/fusion.fold_bn) —
    every Conv→BatchNorm pair collapses into the conv's weights/bias, so
    serving dispatches pay no per-request normalize traffic at all. The
    caller's model object is untouched; exact within fp tolerance
    (analysis/lint.py DLT005 flags serving sites that skip this).

    quantize: a ``quant.CalibrationRecord`` — serve an int8-quantized COPY
    of the model (``quant.quantize``, which BN-folds first): per-channel
    int8 weights, calibrated per-tensor activation scales, int32
    accumulation. The quantized graph shares the bucket ladder and
    ``warmup()`` unchanged, and checkpoint hot-swap re-applies the SAME
    record to every newer fp32 checkpoint it swaps in, so a training
    job's commits keep serving quantized (see quant/ docs for the
    accuracy-gate step that should precede this).

    tuning: a ``perf.autotune.TuningRecord`` (or None to inherit the
    model's ``_tuning_record`` restored from a zip/checkpoint): the
    record's serving bucket ladder becomes the bucket policy and is warmed
    at construction, so a tuned endpoint compiles NOTHING at serve time.
    A record searched on a different architecture is refused
    (``StaleTuningRecordError``).

    checkpoint hot-swap: ``start_hot_swap(checkpoint_manager)`` watches the
    manager's journal for a newer step and atomically swaps the new params
    in BETWEEN dispatches — no request is dropped, none observes a
    mid-batch mix of old and new weights, and because only param VALUES
    change (same model object, same bucketed shapes), the warmed compiled
    programs are reused: a swap compiles nothing. ``stats()["hot_swap"]``
    reports swap count and the step currently being served."""

    _DEFAULT_POLICY = object()

    def __init__(self, model, mesh=None, batch_limit: int = 32,
                 queue_timeout_ms: int = 5, inference_mode: str = "batched",
                 bucket_policy=_DEFAULT_POLICY,
                 batch_size_history: int = 1024, fold_bn: bool = False,
                 quantize=None, checkpoint_manager=None,
                 checkpoint_poll_secs: Optional[float] = None,
                 queue_depth: int = 1024,
                 queue_put_timeout_ms: float = 50.0,
                 tuning=None):
        if inference_mode not in ("batched", "sequential"):
            raise ValueError(f"unknown inference_mode '{inference_mode}'")
        if int(queue_depth) < 1:
            raise ValueError(f"queue_depth must be >= 1; got {queue_depth}")
        if queue_put_timeout_ms < 0:
            raise ValueError("queue_put_timeout_ms must be >= 0")
        self._tuning = tuning
        if tuning is None:
            # a model restored from a zip/checkpoint carrying tuning.json
            # brings its record along — inherit it unless overridden
            self._tuning = tuning = getattr(model, "_tuning_record", None)
        if tuning is not None:
            # a tuning is only valid for the architecture it was searched
            # on (StaleTuningRecordError on mismatch — the quant/ stale-
            # record contract); checked BEFORE fold/quantize rebuild the
            # model, against the raw conf the record was searched on
            from deeplearning4j_tpu.perf.autotune import verify_tuning
            verify_tuning(model.conf, tuning)
            if (bucket_policy is ParallelInference._DEFAULT_POLICY
                    and tuning.buckets):
                bucket_policy = BucketPolicy(buckets=tuning.buckets)
            if getattr(tuning, "pallas_kernels", None) is not None:
                # the record's measured kernel-layer winner (perf/pallas):
                # configure BEFORE the warmup below so every warmed ladder
                # program is traced under the inherited selection — steady
                # state then compiles nothing
                from deeplearning4j_tpu.perf import pallas as _pk
                _pk.configure(enabled=tuning.pallas_kernels)
        self._fold_bn = bool(fold_bn)
        self._quantize = quantize
        # read checkpoint provenance BEFORE folding/quantizing: both
        # rebuild the model and do not carry _restored_from over, and
        # losing it here would make the first hot-swap poll re-swap the
        # very checkpoint this server already serves
        restored_from = getattr(model, "_restored_from", None)
        if quantize is not None:
            from deeplearning4j_tpu.quant import quantize as _quantize_net
            model = _quantize_net(model, quantize)  # BN-folds internally
        elif fold_bn:
            from deeplearning4j_tpu.perf.fusion import fold_bn as _fold_bn
            model = _fold_bn(model)
        from deeplearning4j_tpu.quant.lowering import is_quantized
        self.quantized = is_quantized(model)
        self.model = model
        self.mesh = mesh if mesh is not None else make_mesh()
        self.batch_limit = batch_limit
        self.queue_timeout_ms = queue_timeout_ms
        self.inference_mode = inference_mode
        self.bucket_policy = (BucketPolicy()
                              if bucket_policy is ParallelInference._DEFAULT_POLICY
                              else bucket_policy)
        if model.params is None:
            model.init()
        repl = jax.tree_util.tree_map(lambda a: replicated(self.mesh), model.params)
        model.params = jax.device_put(model.params, repl)
        # BOUNDED admission queue: a stalled worker (wedged device call,
        # slow model) must turn into fast typed rejections upstream, not
        # unbounded host-memory growth with every request waiting forever
        self.queue_depth = int(queue_depth)
        self.queue_put_timeout_ms = float(queue_put_timeout_ms)
        self._q: "queue.Queue" = queue.Queue(maxsize=self.queue_depth)
        self.queue_rejections = 0
        self.deadline_evictions = 0
        self._worker: Optional[threading.Thread] = None
        self._worker_lock = threading.Lock()
        self._stop = threading.Event()
        # observables the worker has dequeued but not yet resolved; shutdown
        # fails these too if the worker never comes back (wedged device call)
        self._inflight: List[InferenceObservable] = []
        self._inflight_lock = threading.Lock()
        # observability (exercised by the latency/throughput tests).
        # batch_sizes is BOUNDED: sustained serving must not grow host
        # memory; percentile summaries come from the retained window.
        self.requests_served = 0
        self.batches_dispatched = 0
        self.batch_sizes: "deque" = deque(maxlen=max(1, batch_size_history))
        # pre-pad ROW counts per dispatch (batch_sizes counts coalesced
        # REQUESTS): the histogram a learned bucket ladder trains on
        self.row_sizes: "deque" = deque(maxlen=max(1, batch_size_history))
        self.bucket_dispatches: Counter = Counter()
        self.unwarmed_dispatches = 0
        self._warmed: set = set()
        # sequential mode dispatches on arbitrary caller threads: counter
        # updates are read-modify-write and need the lock
        self._stats_lock = threading.Lock()
        # hot-swap: _model_lock serializes device dispatches against param
        # swaps — a swap waits for the in-flight batch and the next batch
        # sees the new params, so no dispatch ever runs a mid-swap mix
        self._model_lock = threading.Lock()
        self._swap_cm = None
        self._swap_thread: Optional[threading.Thread] = None
        self._swap_stop = threading.Event()
        self.swaps = 0
        self.swap_poll_errors = 0
        # poll backoff under a broken store (utils/backoff.py): seeded per
        # instance so the schedule is reproducible, jittered so a fleet of
        # servers polling one dead store doesn't re-synchronize its retries
        self._swap_backoff_rng = random.Random(0xD14)
        self.swap_consecutive_errors = 0
        self.swap_last_poll_delay: Optional[float] = None
        self.current_checkpoint_step = (None if restored_from is None
                                        else int(restored_from.step))
        # obs: hot-path instruments are shared process-wide (the registry
        # is the source of truth for the Prometheus scrape); stats() is
        # additionally absorbed at collect time so its sections (hot-swap,
        # buckets, attention) need no per-dispatch writes
        from deeplearning4j_tpu.obs.registry import (absorb_inference_stats,
                                                     get_registry)
        from deeplearning4j_tpu.obs.trace import get_tracer
        # configure_tracer mutates the global Tracer in place, so the handle
        # stays valid; caching it keeps the global lookup off the dispatch
        # hot path (the fit loops hoist it the same way)
        self._tracer = get_tracer()
        reg = get_registry()
        self._m_queue_depth = reg.gauge(
            "serving_queue_depth", unit="requests",
            help="requests waiting in the batching queue after a coalesce")
        self._m_occupancy = reg.histogram(
            "serving_batch_occupancy", unit="requests",
            help="coalesced requests per dispatched batch (batch_limit is "
                 "the ceiling)",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256))
        self._m_pad_waste = reg.histogram(
            "serving_pad_waste_rows", unit="rows",
            help="padding rows added per dispatch to reach the bucket "
                 "target (bucket ladder pad waste)",
            buckets=(0, 1, 2, 4, 8, 16, 32, 64, 128, 256, 512))
        absorb_inference_stats(reg, self)
        if tuning is not None and tuning.buckets:
            # warm the RECORDED ladder now, so a tuned endpoint pays zero
            # compiles at serve time (the TuningRecord contract); best-
            # effort — models whose input shape the conf cannot describe
            # (multi-input graphs, index sequences) warm on first traffic
            ex = self._tuning_example()
            if ex is not None:
                try:
                    self.warmup(ex, buckets=tuning.buckets)
                except Exception:
                    import logging
                    logging.getLogger(__name__).warning(
                        "tuning-ladder warmup failed; serving continues "
                        "(first dispatch per bucket will compile)",
                        exc_info=True)
        if checkpoint_manager is not None:
            self.start_hot_swap(checkpoint_manager,
                                poll_secs=checkpoint_poll_secs)

    def _tuning_example(self) -> Optional[np.ndarray]:
        """A zero example with the conf-described feature shape, for
        warming the TuningRecord's bucket ladder; None when the conf does
        not pin a single float input shape."""
        conf = self.model.conf
        it = getattr(conf, "input_type", None)
        if it is None:
            its = getattr(conf, "input_types", None) or ()
            if len(its) != 1:
                return None
            it = its[0]
        if it is None:
            return None
        if it.kind in ("rnn", "cnn1d") and it.timeseries_length is None:
            return None  # no canonical length to warm at
        try:
            shape = it.example_shape(1)
        except ValueError:
            return None
        return np.zeros(shape, np.float32)

    # --------------------------------------------------------- shape policy
    def _pad_target(self, n: int) -> int:
        """Dispatch size for an n-row batch: the policy's bucket, rounded up
        to divide the mesh's data axis (the sequential path used to pad only
        to the axis multiple — one compiled program PER SIZE; now both paths
        share the bucket ladder). Zero-row batches bypass the ladder and
        keep their (valid, if unusual) empty dispatch."""
        dp = self.mesh.shape["data"]
        t = (self.bucket_policy.bucket(n)
             if self.bucket_policy is not None and n >= 1 else n)
        return t + (-t) % dp

    def _record_dispatch_shape(self, target: int, n_rows: int):
        with self._stats_lock:
            self.bucket_dispatches[target] += 1
            self.row_sizes.append(n_rows)
            if target not in self._warmed:
                self.unwarmed_dispatches += 1
        self._m_pad_waste.observe(max(0, target - n_rows))

    # ------------------------------------------------------------ sync path
    def _dispatch(self, arr, target: int, record: bool = True):
        """Pad to EXACTLY ``target`` rows, shard, run the model, slice the
        real rows back out. The single choke point for device dispatches —
        warmup and live traffic go through it with the same shapes, so a
        warmed target is guaranteed to be the compiled one. ``record=False``
        (warmup) keeps the dispatch out of the serving counters PER CALL,
        so concurrent live worker dispatches keep recording correctly."""
        n = arr.shape[0]
        with self.mesh:
            arr = pad_to_bucket(jnp.asarray(arr), target)
            if record:
                self._record_dispatch_shape(target, n)
            arr = jax.device_put(arr, data_sharding(self.mesh, arr.ndim))
            # _model_lock: a checkpoint hot-swap can never land mid-batch —
            # it waits here for the in-flight dispatch, and the very next
            # dispatch serves the new params
            with self._tracer.span("serving.dispatch", rows=n,
                                   target=target):
                with self._model_lock:
                    out = self.model.output(arr)
            return out[:n] if target != n else out

    def output(self, x) -> np.ndarray:
        """Synchronous sharded inference (reference ParallelInference.output),
        padded to the bucket ladder so repeat traffic reuses compiled
        programs."""
        arr = jnp.asarray(x)
        return self._dispatch(arr, self._pad_target(arr.shape[0]))

    def warmup(self, example, buckets=None) -> List[int]:
        """Pre-compile the forward program for every bucket BEFORE traffic
        arrives, so no live request ever pays a multi-second XLA compile.

        ``example``: an array with a leading batch axis — ideally a
        REPRESENTATIVE request, because the default bucket set assumes the
        worst coalesced batch is ``batch_limit`` requests of this size
        (``batch_limit`` caps coalesced REQUESTS, not rows). Pass explicit
        ``buckets`` (batch sizes to warm) when traffic mixes request sizes;
        warm up to your worst-case coalesced row count (see
        bench.py::bench_serving). Returns the warmed dispatch sizes."""
        ex = np.asarray(example)
        if ex.ndim < 1:
            raise ValueError("warmup example needs a leading batch axis")
        feat_shape = ex.shape[1:]
        if buckets is None:
            max_rows = max(1, self.batch_limit) * max(1, ex.shape[0])
            if self.bucket_policy is None:
                buckets = [max_rows]
            else:
                buckets = self.bucket_policy.buckets_up_to(max_rows)
        for b in sorted({int(b) for b in buckets}):
            target = self._pad_target(b)
            if target in self._warmed:
                continue
            # dispatch EXACTLY target rows (not through output(), whose
            # re-bucketing could compile a different shape than live
            # traffic dispatches when target isn't a policy fixed point),
            # unrecorded so warmup doesn't pollute the serving counters
            self._dispatch(np.zeros((target,) + feat_shape, ex.dtype),
                           target, record=False)
            with self._stats_lock:  # stats()/recording iterate this set
                self._warmed.add(target)
        with self._stats_lock:
            return sorted(self._warmed)

    def learned_bucket_policy(self, max_compiles: int = 8) -> BucketPolicy:
        """Latency-aware ladder learned from the recorded pre-pad row-count
        histogram (``BucketPolicy.from_histogram``): at most ``max_compiles``
        buckets placed where this server's traffic actually mass — swap it
        in (new ParallelInference, or warmup a canary) when the static pow2
        ladder over- or under-buckets the observed mix."""
        with self._stats_lock:
            rows = list(self.row_sizes)
        rows = [r for r in rows if r >= 1]
        if not rows:
            raise ValueError(
                "no dispatches recorded yet — serve some traffic (or seed "
                "row_sizes) before learning a bucket ladder")
        return BucketPolicy.from_histogram(rows, max_compiles=max_compiles)

    # ------------------------------------------------- checkpoint hot-swap
    def start_hot_swap(self, checkpoint_manager,
                       poll_secs: Optional[float] = None):
        """Serve newer checkpoints without dropping traffic: watch
        ``checkpoint_manager``'s journal and swap params in atomically
        between dispatches when a newer step commits.

        With ``poll_secs`` a daemon poller calls :meth:`poll_checkpoint`
        on that cadence; leave it ``None`` to poll manually (deterministic
        tests, or an external control plane deciding when to roll). The
        manager may point at the same store a TRAINING process writes to
        (its journal is re-read via ``refresh()`` each poll), which is the
        deployment shape: trainer commits, servers pick it up live."""
        self._swap_cm = checkpoint_manager
        if poll_secs is not None and self._swap_thread is None:
            self._swap_stop.clear()
            self._swap_thread = threading.Thread(
                target=self._hot_swap_loop, args=(float(poll_secs),),
                name="ckpt-hot-swap", daemon=True)
            self._swap_thread.start()
        return self

    def stop_hot_swap(self):
        self._swap_stop.set()
        t, self._swap_thread = self._swap_thread, None
        if t is not None and t.is_alive():
            t.join(timeout=5)

    def _next_poll_delay(self, poll_secs: float, consecutive_errors: int,
                         cap_s: float = 30.0) -> float:
        """Poll cadence given the current error streak: the configured
        ``poll_secs`` while healthy, plus a capped-exponential-jitter
        backoff (utils/backoff.py) once the store starts erroring — a dead
        backend must not be hammered at full poll rate, and recovery resets
        to the configured cadence."""
        if consecutive_errors <= 0:
            return poll_secs
        from deeplearning4j_tpu.utils.backoff import backoff_delay
        return poll_secs + backoff_delay(consecutive_errors - 1,
                                         base_s=max(poll_secs, 0.05),
                                         cap_s=cap_s,
                                         rng=self._swap_backoff_rng)

    def _hot_swap_loop(self, poll_secs: float):
        delay = poll_secs
        while not self._swap_stop.wait(delay):
            try:
                self.poll_checkpoint()
                with self._stats_lock:
                    self.swap_consecutive_errors = 0
            except Exception:
                # the serving path must outlive a broken store; the error
                # count is surfaced in stats() for alerting
                with self._stats_lock:
                    self.swap_poll_errors += 1
                    self.swap_consecutive_errors += 1
                import logging
                logging.getLogger(__name__).exception(
                    "checkpoint hot-swap poll failed; serving continues "
                    "on the current params")
            with self._stats_lock:
                delay = self._next_poll_delay(poll_secs,
                                              self.swap_consecutive_errors)
                self.swap_last_poll_delay = delay

    def poll_checkpoint(self) -> bool:
        """One hot-swap probe: is there a newer committed checkpoint than
        the step being served? If so, restore it OFF the dispatch path,
        then atomically swap params/state in between dispatches. Returns
        whether a swap happened.

        The swap reuses everything already compiled: the model OBJECT (and
        its jit cache, warmed buckets, compile counters) is untouched —
        only param/state VALUES change, at unchanged shapes, so the warmup
        ladder stays valid and the swap compiles nothing new."""
        cm = self._swap_cm
        if cm is None:
            return False
        cm.refresh()
        refresh_err = getattr(cm, "last_refresh_error", None)
        if refresh_err is not None:
            # the journal re-read failed: this probe learned NOTHING (the
            # manager deliberately keeps serving its known journal) —
            # surface the store fault so the poll loop counts it and
            # backs off instead of hammering a dead store at full cadence
            raise refresh_err
        step = cm.latest_step()
        if step is None or (self.current_checkpoint_step is not None
                            and step <= self.current_checkpoint_step):
            return False
        # the expensive part — fetch + deserialize + (maybe) fold + device
        # placement — happens OUTSIDE the model lock: traffic keeps being
        # served on the old params while the new ones are prepared
        restored = cm.restore_latest(load_updater=False)
        if restored is None:
            return False
        # restore_latest may have FALLEN BACK past a torn/corrupt newest
        # entry to a checkpoint at-or-before the one being served — without
        # this guard a rotted newest object would re-swap (or DOWNGRADE to
        # an older surviving checkpoint) on every poll, forever
        restored_step = restored._restored_from.step
        if self.current_checkpoint_step is not None \
                and restored_step <= self.current_checkpoint_step:
            return False
        if self._quantize is not None:
            # the newer (fp32) checkpoint gets the SAME lowering this
            # server was built with: quantize folds + int8-lowers, so the
            # swapped-in tree matches the serving model's structurally
            from deeplearning4j_tpu.quant import quantize as _quantize_net
            restored = _quantize_net(restored, self._quantize)
        elif self._fold_bn:
            from deeplearning4j_tpu.perf.fusion import fold_bn as _fold_bn
            restored = _fold_bn(restored)
        if (jax.tree_util.tree_structure(restored.params)
                != jax.tree_util.tree_structure(self.model.params)):
            raise RuntimeError(
                "hot-swap checkpoint params have a different structure "
                "than the serving model — the store holds a different "
                "architecture; refusing to swap")
        repl = jax.tree_util.tree_map(lambda a: replicated(self.mesh),
                                      restored.params)
        new_params = jax.device_put(restored.params, repl)
        new_state = restored.state
        new_step = restored_step
        with self._model_lock:
            self.model.params = new_params
            self.model.state = new_state
        with self._stats_lock:
            self.swaps += 1
            self.current_checkpoint_step = int(new_step)
        return True

    @staticmethod
    def _size_summary(sizes) -> dict:
        summary = {"count": len(sizes)}
        if sizes:
            summary.update(
                mean=round(float(np.mean(sizes)), 2),
                p50=float(np.percentile(sizes, 50)),
                p95=float(np.percentile(sizes, 95)),
                max=int(max(sizes)))
        return summary

    def stats(self) -> dict:
        """Serving observability: request/dispatch counts, batch-size and
        row-count percentiles over the retained window, per-bucket dispatch
        counts, warmed buckets, and the model's compile/dispatch
        counters."""
        with self._stats_lock:
            # every mutable counter is read under the SAME lock the worker
            # mutates under — dict(bucket_dispatches) racing a new-key
            # insert would raise "dictionary changed size during iteration"
            sizes = list(self.batch_sizes)
            rows = list(self.row_sizes)
            requests_served = self.requests_served
            batches_dispatched = self.batches_dispatched
            warmed = sorted(self._warmed)
            bucket_dispatches = dict(self.bucket_dispatches)
            unwarmed = self.unwarmed_dispatches
            swaps = self.swaps
            current_step = self.current_checkpoint_step
            swap_errors = self.swap_poll_errors
            rejected = self.queue_rejections
            expired = self.deadline_evictions
            swap_consec = self.swap_consecutive_errors
            swap_delay = self.swap_last_poll_delay
        out = {
            "requests_served": requests_served,
            "batches_dispatched": batches_dispatched,
            "quantized": self.quantized,
            "queue": {
                "depth": self.queue_depth,
                "size": self._q.qsize(),
                "rejected": rejected,
                "expired": expired,
            },
            "batch_size": self._size_summary(sizes),
            "row_size": self._size_summary(rows),
            "bucket_policy": (None if self.bucket_policy is None
                              else repr(self.bucket_policy)),
            "tuning": {
                "applied": self._tuning is not None,
                "buckets": (list(self._tuning.buckets)
                            if self._tuning is not None else None),
            },
            "warmed_buckets": warmed,
            "bucket_dispatches": bucket_dispatches,
            "unwarmed_dispatches": unwarmed,
            "hot_swap": {
                "enabled": self._swap_cm is not None,
                "swaps": swaps,
                "current_checkpoint_step": current_step,
                "poll_errors": swap_errors,
                "consecutive_poll_errors": swap_consec,
                "last_poll_delay_s": (None if swap_delay is None
                                      else round(swap_delay, 4)),
            },
        }
        cw = getattr(self.model, "compile_watch", None)
        if cw is not None:
            out["model_compiles"] = cw.compiles()
            out["model_dispatches"] = cw.dispatches()
        # attention kernel-path counters (nn/conf/attention.py _attend): a
        # serving model silently skipping the Pallas flash kernel
        # (attention.flash_fallback > 0) is visible here, not just as a
        # latency regression. Read from THIS model's watch (bump_active
        # routes trace-time events to the tracing model), so two models in
        # one process never misattribute each other's kernel paths.
        if cw is not None:
            att = cw.counters("attention.")
            if att:
                out["attention"] = att
            # fused conv+BN block trace hits (nn/conf/convolutional.py
            # FusedConvBNActivation.apply): a serving model expected to run
            # fused (or folded — folded graphs count ZERO here) is
            # verifiable from stats rather than from step latency
            fus = cw.counters("fusion.")
            if fus:
                out["fusion"] = fus
        # last analysis.trace_check report for this model, if one ran
        report = getattr(self.model, "last_trace_report", None)
        if report is not None:
            out["trace_hazards"] = report.counts()
        return out

    # -------------------------------------------------------- batched path
    def submit(self, x, deadline: Optional[float] = None
               ) -> InferenceObservable:
        """Enqueue one request; returns its observable (reference
        ParallelInference.java:97 observable provider).

        ``deadline``: absolute ``time.monotonic()`` timestamp after which
        the caller no longer wants the answer. Expired requests are
        evicted at batch formation — BEFORE device dispatch, so they never
        occupy a batch slot they cannot use — and their ``get()`` raises
        :class:`DeadlineExpiredError`.

        Full-queue semantics: block up to ``queue_put_timeout_ms`` for a
        slot, then raise :class:`QueueFullError` — load is shed to the
        caller, never queued unboundedly."""
        obs = InferenceObservable()
        if self.inference_mode == "sequential":
            try:
                if deadline is not None and time.monotonic() >= deadline:
                    with self._stats_lock:
                        self.deadline_evictions += 1
                    raise DeadlineExpiredError(
                        "request deadline expired before dispatch")
                obs._resolve(self.output(np.asarray(x)))
            except BaseException as e:  # surfaced at .get()
                obs._fail(e)
            with self._stats_lock:
                self.requests_served += 1
            return obs
        item = (np.asarray(x), obs, deadline)
        give_up = time.monotonic() + self.queue_put_timeout_ms / 1000.0
        while True:
            # enqueue + worker liveness under ONE lock: a concurrent
            # shutdown() (same lock) can then never strand this request
            # between the put and the worker start. The put itself is
            # non-blocking — a submitter waiting for a slot must never
            # hold the lock shutdown() needs.
            with self._worker_lock:
                try:
                    self._q.put_nowait(item)
                except queue.Full:
                    pass
                else:
                    self._ensure_worker_locked()
                    return obs
            remaining = give_up - time.monotonic()
            if remaining <= 0:
                with self._stats_lock:
                    self.queue_rejections += 1
                raise QueueFullError(
                    f"request queue full (queue_depth={self.queue_depth})"
                    f" after {self.queue_put_timeout_ms:g}ms — the worker "
                    "is not draining fast enough; shed load upstream")
            time.sleep(min(0.001, remaining))

    def output_batched(self, x) -> np.ndarray:
        """Blocking convenience over submit() (reference
        BatchedInferenceObservable callers)."""
        return self.submit(x).get()

    _SENTINEL = object()

    def shutdown(self):
        """Stop the worker after draining; pending observables either get
        served by the final drain or failed, never left hanging."""
        self.stop_hot_swap()
        with self._worker_lock:
            w = self._worker
            if w is not None and w.is_alive():
                self._stop.set()
                try:  # wake the worker promptly; a FULL queue already
                    self._q.put_nowait(ParallelInference._SENTINEL)
                except queue.Full:  # keeps it busy and re-checking _stop
                    pass
                w.join(timeout=10)
                if w.is_alive():
                    # worker is wedged (e.g. inside a device call): fail the
                    # requests it already dequeued so their get() unblocks
                    with self._inflight_lock:
                        stuck, self._inflight = self._inflight, []
                    for obs in stuck:
                        if not obs.is_done():
                            obs._fail(RuntimeError(
                                "ParallelInference worker did not stop within "
                                "10s at shutdown; in-flight request abandoned"))
            self._worker = None
            # fail anything the worker did not reach (its get() callers
            # would otherwise block forever)
            leftovers = []
            try:
                while True:
                    leftovers.append(self._q.get_nowait())
            except queue.Empty:
                pass
            for item in leftovers:
                if item is not ParallelInference._SENTINEL:
                    item[1]._fail(RuntimeError(
                        "ParallelInference shut down before request served"))

    # ------------------------------------------------------------- worker
    def _ensure_worker_locked(self):
        """Caller holds _worker_lock."""
        if self._worker is None or not self._worker.is_alive():
            self._stop.clear()
            self._worker = threading.Thread(target=self._worker_loop,
                                            daemon=True)
            self._worker.start()

    def _collect(self):
        """Take up to batch_limit requests, waiting queue_timeout_ms for
        stragglers after the first arrives (the reference's batching
        window)."""
        try:
            first = self._q.get(timeout=0.05)
        except queue.Empty:
            return []
        if first is ParallelInference._SENTINEL:
            return []
        items = [first]
        deadline = time.monotonic() + self.queue_timeout_ms / 1000.0
        while len(items) < self.batch_limit:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                nxt = self._q.get(timeout=remaining)
            except queue.Empty:
                break
            if nxt is ParallelInference._SENTINEL:
                break
            items.append(nxt)
        return items

    def _worker_loop(self):
        while not self._stop.is_set():
            items = self._collect()
            if not items:
                continue
            # deadline eviction at BATCH FORMATION: an expired request is
            # answered (DeadlineExpiredError) before device dispatch and
            # never occupies a batch slot it cannot use — the batch that
            # does dispatch carries only requests whose callers still want
            # the answer
            now = time.monotonic()
            expired = [it for it in items
                       if it[2] is not None and now >= it[2]]
            items = [it for it in items
                     if it[2] is None or now < it[2]]
            if expired:
                # count BEFORE failing: a caller woken by get() must see
                # the eviction already reflected in stats()
                with self._stats_lock:
                    self.deadline_evictions += len(expired)
            for _, obs, dl in expired:
                obs._fail(DeadlineExpiredError(
                    f"request deadline expired {now - dl:.3f}s before "
                    "batch dispatch"))
            if not items:
                continue
            # what's STILL queued after this coalesce = the backlog a new
            # request joins; occupancy tells whether batching is working
            self._m_queue_depth.set(self._q.qsize())
            self._m_occupancy.observe(len(items))
            xs = [i[0] for i in items]
            sizes = [len(x) for x in xs]
            with self._inflight_lock:
                self._inflight = [obs for _, obs, _ in items]
            try:
                out = self.output(np.concatenate(xs, axis=0))
                ofs = 0
                for (x, obs, _), n in zip(items, sizes):
                    obs._resolve(out[ofs:ofs + n])
                    ofs += n
            except BaseException as e:
                for _, obs, _ in items:
                    obs._fail(e)
            finally:
                with self._inflight_lock:
                    self._inflight = []
            with self._stats_lock:  # stats() iterates these concurrently
                self.requests_served += len(items)
                self.batches_dispatched += 1
                self.batch_sizes.append(len(items))
