"""Parallel inference.

Parity surface: reference parallelism/ParallelInference.java:32 (round-robin
device-pinned replicas, :97-134) + BatchedInferenceObservable dynamic
batching.

TPU-native: one jit-compiled forward with the batch sharded over the mesh
replaces per-device replicas; a simple request-batching queue provides the
dynamic-batching behaviour of BatchedInferenceObservable.
"""

from __future__ import annotations

import queue
import threading
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.parallel.mesh import data_sharding, make_mesh, replicated


class ParallelInference:
    def __init__(self, model, mesh=None, batch_limit: int = 32,
                 queue_timeout_ms: int = 5):
        self.model = model
        self.mesh = mesh if mesh is not None else make_mesh()
        self.batch_limit = batch_limit
        self.queue_timeout_ms = queue_timeout_ms
        if model.params is None:
            model.init()
        repl = jax.tree_util.tree_map(lambda a: replicated(self.mesh), model.params)
        model.params = jax.device_put(model.params, repl)
        self._q: "queue.Queue" = queue.Queue()
        self._worker: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def output(self, x) -> np.ndarray:
        """Synchronous sharded inference (reference ParallelInference.output)."""
        with self.mesh:
            arr = jnp.asarray(x)
            dp = self.mesh.shape["data"]
            pad = (-arr.shape[0]) % dp
            if pad:
                arr = jnp.concatenate([arr, jnp.zeros((pad,) + arr.shape[1:],
                                                      arr.dtype)])
            arr = jax.device_put(arr, data_sharding(self.mesh, arr.ndim))
            out = self.model.output(arr)
            return out[:out.shape[0] - pad] if pad else out

    def output_batched(self, x) -> np.ndarray:
        """Queue + dynamic batching entry point (reference
        BatchedInferenceObservable): collects concurrent requests into one
        device batch."""
        done = threading.Event()
        slot = {}
        self._q.put((np.asarray(x), slot, done))
        self._drain()
        done.wait()
        return slot["out"]

    def _drain(self):
        with self._lock:
            items = []
            try:
                while len(items) < self.batch_limit:
                    items.append(self._q.get_nowait())
            except queue.Empty:
                pass
            if not items:
                return
            xs = [i[0] for i in items]
            sizes = [len(x) for x in xs]
            big = np.concatenate(xs, axis=0)
            out = self.output(big)
            ofs = 0
            for (x, slot, done), n in zip(items, sizes):
                slot["out"] = out[ofs:ofs + n]
                ofs += n
                done.set()
