"""Storage-backed TTL leases — the repo's one membership primitive.

Factored out of :mod:`deeplearning4j_tpu.parallel.elastic` so that the
serving fleet (:mod:`deeplearning4j_tpu.fleet`) registers replicas through
the SAME lease/read-back protocol the elastic trainer uses, instead of
growing a second discovery service:

- A participant owns one store object ``<prefix><id>`` holding a JSON
  record ``{worker_id, incarnation, seq, time, barrier, ...payload}``,
  refreshed by a daemon heartbeat thread every ``heartbeat_s`` (default
  ttl/3). Liveness = the record's wall timestamp is within ``ttl_s`` of
  the OBSERVER's clock (``clock=`` injectable for skew tests).
- ``payload`` extends the protocol for the fleet: static fields set via
  :meth:`LeaseBoard.set_payload` (a replica's address, placement) plus a
  live ``payload_fn`` sampled at every write (load, warmup state). A
  payload sampler that raises is counted and logged, never fatal — the
  core liveness beat must not die because a stats hook did.
- Store faults during a heartbeat are likewise survivable until the TTL
  (chaos tests inject FlakyBackend faults here on purpose).

Readers use :meth:`read_all`/:meth:`live`; clean exits :meth:`withdraw`
so peers need not wait out a TTL. The elastic trainer's rendezvous
(generation barriers via the ``barrier`` field) and the fleet's
membership view (``fleet/membership.py``) are both thin layers over this
class.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import uuid
from typing import Callable, Dict, Optional

log = logging.getLogger(__name__)

LEASE_PREFIX = "lease-"

__all__ = ["LEASE_PREFIX", "LeaseBoard"]


class LeaseBoard:
    """Per-participant heartbeat leases in the store.

    A lease is ``<prefix><worker_id>`` holding ``{worker_id, incarnation,
    seq, time, barrier}`` plus any payload fields; a background thread
    refreshes it every ``heartbeat_s`` (default ttl/3). ``barrier`` is the
    generation an elastic worker is ready to join — the rendezvous settles
    when every LIVE lease has either reached the barrier or expired. The
    fleet ignores ``barrier`` and rides the payload instead."""

    def __init__(self, store, worker_id: str, ttl_s: float = 10.0,
                 heartbeat_s: Optional[float] = None,
                 clock: Callable[[], float] = time.time,
                 prefix: str = LEASE_PREFIX,
                 payload_fn: Optional[Callable[[], dict]] = None):
        from deeplearning4j_tpu.checkpoint.storage import as_backend
        self.store = as_backend(store)
        self.worker_id = str(worker_id)
        self.ttl_s = float(ttl_s)
        self.heartbeat_s = (float(heartbeat_s) if heartbeat_s is not None
                            else self.ttl_s / 3.0)
        self.clock = clock
        self.prefix = str(prefix)
        self.payload_fn = payload_fn
        self.incarnation = uuid.uuid4().hex[:12]
        self._lock = threading.Lock()
        self._payload: dict = {}
        self._barrier_gen = 0
        self._seq = 0
        self._last_write = float("-inf")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.heartbeat_errors = 0
        self.payload_errors = 0
        self.read_errors = 0

    # ------------------------------------------------------------- writing
    def set_payload(self, **fields):
        """Merge static fields into every subsequent lease write (e.g. a
        replica's address and placement). Does not write by itself — call
        :meth:`write` to publish immediately."""
        with self._lock:
            self._payload.update(fields)

    def write(self, barrier: Optional[int] = None):
        """Write this worker's lease now (also what the heartbeat thread
        calls). ``barrier`` updates the joined-generation marker."""
        extra = {}
        if self.payload_fn is not None:
            try:
                extra = dict(self.payload_fn())
            except Exception as e:
                self.payload_errors += 1
                log.warning("lease payload sampler for %s failed (%s: %s)",
                            self.worker_id, type(e).__name__, e)
        with self._lock:
            if barrier is not None:
                self._barrier_gen = int(barrier)
            self._seq += 1
            rec = dict(self._payload)
            rec.update(extra)
            rec.update({"worker_id": self.worker_id,
                        "incarnation": self.incarnation,
                        "seq": self._seq,
                        "time": self.clock(),
                        "barrier": self._barrier_gen})
        self.store.put(self.prefix + self.worker_id,
                       json.dumps(rec).encode())
        self._last_write = self.clock()

    def refresh_if_due(self):
        """Heartbeat inline when no beat landed for a heartbeat interval
        — keeps a worker alive through long WAITS (the rendezvous poll
        loop) even when the background thread isn't running."""
        if self.clock() - self._last_write >= self.heartbeat_s:
            self.write()

    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()

        def beat():
            while not self._stop.wait(self.heartbeat_s):
                try:
                    self.write()
                except Exception as e:
                    # a missed beat is survivable until the TTL; chaos
                    # tests inject faults here deliberately
                    self.heartbeat_errors += 1
                    log.warning("lease heartbeat for %s failed (%s: %s)",
                                self.worker_id, type(e).__name__, e)
        self._thread = threading.Thread(
            target=beat, name=f"lease-{self.worker_id}", daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.heartbeat_s * 2 + 1)
            self._thread = None

    # ------------------------------------------------------------- reading
    def read_all(self) -> Dict[str, dict]:
        """Every parseable lease in the store, by worker id.

        A lease that cannot be fetched or parsed counts as absent (=
        expired) for THIS scan rather than failing the whole membership
        view — over a cloud backend one transient fault on one key must
        not make every peer look dead. ``read_errors`` counts the skips
        so persistent corruption stays visible."""
        out = {}
        for name in self.store.list(prefix=self.prefix):
            try:
                rec = json.loads(self.store.get(name).decode())
                out[str(rec["worker_id"])] = rec
            except Exception as e:
                self.read_errors += 1
                log.warning("unreadable lease %s (%s: %s)", name,
                            type(e).__name__, e)
        return out

    def is_fresh(self, rec: dict, now: Optional[float] = None) -> bool:
        now = self.clock() if now is None else now
        return (now - float(rec.get("time", 0))) <= self.ttl_s

    def live(self, leases: Optional[Dict[str, dict]] = None) -> Dict[str, dict]:
        leases = self.read_all() if leases is None else leases
        now = self.clock()
        return {w: r for w, r in leases.items() if self.is_fresh(r, now)}

    def withdraw(self):
        """Delete this worker's lease (clean exit — peers need not wait a
        TTL to notice)."""
        try:
            self.store.delete(self.prefix + self.worker_id)
        except Exception as e:
            log.warning("lease withdraw for %s failed (%s: %s)",
                        self.worker_id, type(e).__name__, e)
