"""Pipeline parallelism (GPipe-style microbatch pipelining).

Beyond-reference capability (the reference has no pipeline parallelism;
SURVEY §2.4 covers only data-parallel wrappers): stacks of identical blocks
are sharded layer-wise over a mesh axis ``stage`` and microbatches stream
through the stages with ``lax.ppermute`` forwarding activations — the
standard TPU pipelining recipe (GPipe, Huang et al. 2019; the
jax-ml scaling-book "pipelining" chapter's shard_map formulation).

Design:

* Block params are STACKED on a leading (S, ...) axis and sharded
  ``P('stage')`` — each device holds one stage's weights. SPMD requires the
  per-stage computation to be the same program, so pipelining applies to
  homogeneous block stacks (the practical case: repeated transformer/dense/
  recurrent blocks). Heterogeneous first/last layers (embedding, head) run
  outside the pipelined region.
* A global batch is split into M microbatches. The wrapped step runs
  M + S - 1 ticks of ``lax.scan``; at tick t, stage s processes microbatch
  t - s (bubble fraction = (S-1)/(M+S-1)).
* The whole schedule lives inside ONE shard_map-ed jit program;
  ``jax.grad`` differentiates straight through the ppermute ring (its
  transpose is the reverse permute), so backward is pipelined too and the
  optimizer update is a per-stage-local optax step on the stacked params.
  Microbatch gradients accumulate exactly (GPipe semantics: one optimizer
  step per global batch).

``pipeline_apply`` is the schedule; ``GPipeTrainer`` wires it to a loss and
an optax transformation. Parity contract (tests/test_pipeline.py): outputs
and gradients equal the plain sequential stack to float tolerance.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

STAGE_AXIS = "stage"


def make_pipeline_mesh(n_stages: Optional[int] = None, devices=None) -> Mesh:
    """1-D mesh over the ``stage`` axis."""
    devices = list(devices if devices is not None else jax.devices())
    n = n_stages or len(devices)
    if n > len(devices):
        raise ValueError(
            f"Requested {n} pipeline stages but only {len(devices)} devices "
            "are available")
    return Mesh(np.asarray(devices[:n]), (STAGE_AXIS,))


def stage_shardings(mesh: Mesh, stacked_params):
    """NamedShardings placing each stage's slice of the stacked params on
    its device (leading axis over 'stage')."""
    return jax.tree_util.tree_map(
        lambda a: NamedSharding(mesh, P(STAGE_AXIS)), stacked_params)


def pipeline_apply(block_fn: Callable, stacked_params, x_microbatches,
                   mesh: Mesh):
    """Run M microbatches through S pipelined stages.

    ``block_fn(params_slice, x) -> y`` is one stage's computation (same
    shapes in and out). ``stacked_params`` leaves are (S, ...) and sharded
    over 'stage'; ``x_microbatches`` is (M, mb, ...) (replicated input).
    Returns (M, mb, ...) outputs of the LAST stage (replicated).
    """
    S = mesh.shape[STAGE_AXIS]
    M = x_microbatches.shape[0]

    def per_stage(params_slice, xs):
        # params_slice leaves arrive as (1, ...): this stage's weights
        p_local = jax.tree_util.tree_map(lambda a: a[0], params_slice)
        s = jax.lax.axis_index(STAGE_AXIS)
        T = M + S - 1
        # the carry becomes stage-varying after the first tick; mark the
        # initial zeros accordingly (shard_map varying-axes typing)
        zero = jax.lax.pvary(jnp.zeros_like(xs[0]), (STAGE_AXIS,))
        fwd = [(i, i + 1) for i in range(S - 1)]

        def tick(carry, t):
            send = carry
            # activations from the previous stage (stage 0 receives junk)
            recv = jax.lax.ppermute(send, STAGE_AXIS, fwd) if S > 1 else send
            # stage 0 consumes microbatch t (while t < M); others consume recv
            mb = jnp.take(xs, jnp.clip(t, 0, M - 1), axis=0)
            x_in = jnp.where(s == 0, mb, recv)
            out = block_fn(p_local, x_in)
            # collect: the LAST stage finished microbatch t-(S-1) this tick
            ready = (s == S - 1) & (t >= S - 1)
            return out, jnp.where(ready, out, jnp.zeros_like(out))

        _, collected = jax.lax.scan(tick, zero, jnp.arange(T))
        # collected[t] holds microbatch t-(S-1): shift into order; only the
        # last stage contributed non-zeros, so a psum broadcasts the result
        outs = collected[S - 1:]
        return jax.lax.psum(outs, STAGE_AXIS)

    from jax.experimental.shard_map import shard_map
    fn = shard_map(per_stage, mesh=mesh,
                   in_specs=(P(STAGE_AXIS), P()),
                   out_specs=P())
    return fn(stacked_params, x_microbatches)


class GPipeTrainer:
    """Train a homogeneous block stack with pipelined fwd+bwd.

    Example::

        mesh = make_pipeline_mesh(4)
        tr = GPipeTrainer(block_fn, loss_fn, updater, mesh)
        params = tr.place(stacked_params)         # shard stages
        params, opt, loss = tr.step(params, opt, x_microbatches, y_microbatches)

    ``loss_fn(y_pred, y) -> scalar`` is applied per microbatch and averaged
    (exact GPipe gradient accumulation).
    """

    def __init__(self, block_fn: Callable, loss_fn: Callable, updater,
                 mesh: Optional[Mesh] = None):
        self.block_fn = block_fn
        self.loss_fn = loss_fn
        self.mesh = mesh if mesh is not None else make_pipeline_mesh()
        self.tx = updater.to_optax() if hasattr(updater, "to_optax") \
            else updater
        self._step = None

    def place(self, stacked_params):
        return jax.device_put(stacked_params,
                              stage_shardings(self.mesh, stacked_params))

    def init_opt(self, stacked_params):
        return self.tx.init(stacked_params)

    def _build(self):
        def loss_over_pipeline(params, xs, ys):
            preds = pipeline_apply(self.block_fn, params, xs, self.mesh)
            losses = jax.vmap(self.loss_fn)(preds, ys)
            return jnp.mean(losses)

        grad_fn = jax.value_and_grad(loss_over_pipeline)

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def step(params, opt_state, xs, ys):
            import optax
            loss, grads = grad_fn(params, xs, ys)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        return step

    def step(self, params, opt_state, x_microbatches, y_microbatches):
        if self._step is None:
            self._step = self._build()
        with self.mesh:
            return self._step(params, opt_state,
                              jnp.asarray(x_microbatches),
                              jnp.asarray(y_microbatches))


__all__ = ["GPipeTrainer", "make_pipeline_mesh", "pipeline_apply",
           "stage_shardings", "STAGE_AXIS"]
