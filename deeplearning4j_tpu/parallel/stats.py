"""Distributed-training phase timing stats.

Parity surface: reference
``dl4j-spark/.../api/stats/CommonSparkTrainingStats.java:18`` (per-phase
timing: getInitialModelAfter/fit/split times, exported key set) and
``SparkTrainingStats`` aggregation.

TPU-native phases: ``data_placement`` (host->device sharded transfer),
``train_dispatch`` (async step dispatch), ``epoch_sync`` (the single
block-until-ready per epoch — on TPU the real step time shows up here, since
dispatch is asynchronous).

Besides phase timings, integer ``counters`` carry point-in-time gauges —
notably ``model_compiles``/``model_dispatches`` from perf/compile_watch.py,
so a recompile storm (the silent TPU performance killer) shows up right next
to the timings it inflates.
"""

from __future__ import annotations

import time
from typing import Dict, List


class TrainingStats:
    """Accumulates (phase -> durations); mirrors the reference's
    getValue(key)/getKeySet surface with host wall-clock measurements."""

    def __init__(self):
        self._durations: Dict[str, List[float]] = {}
        self.examples = 0
        self.minibatches = 0
        self.counters: Dict[str, int] = {}  # lint: disable=DLT007 (pre-obs surface; absorbed into the registry by obs.absorb_training_stats)

    # -------------------------------------------------------------- record
    class _Timer:
        def __init__(self, stats: "TrainingStats", phase: str):
            self.stats = stats
            self.phase = phase

        def __enter__(self):
            self.t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self.stats.record(self.phase, time.perf_counter() - self.t0)

    def time(self, phase: str) -> "_Timer":
        return self._Timer(self, phase)

    def record(self, phase: str, seconds: float):
        self._durations.setdefault(phase, []).append(seconds)

    def set_counter(self, name: str, value: int):
        """Set a point-in-time gauge (e.g. cumulative compile count)."""
        self.counters[name] = int(value)

    def inc_counter(self, name: str, by: int = 1):
        self.counters[name] = self.counters.get(name, 0) + int(by)

    # --------------------------------------------------------------- query
    def key_set(self):
        return sorted(self._durations)

    def get_value(self, phase: str) -> List[float]:
        return list(self._durations.get(phase, []))

    def total_seconds(self, phase: str) -> float:
        return sum(self._durations.get(phase, []))

    def count(self, phase: str) -> int:
        return len(self._durations.get(phase, []))

    def as_dict(self) -> dict:
        out = {"examples": self.examples, "minibatches": self.minibatches}
        if self.counters:
            out["counters"] = dict(self.counters)
        for phase, ds in self._durations.items():
            out[phase] = {"count": len(ds), "total_ms": sum(ds) * 1000.0,
                          "mean_ms": sum(ds) / len(ds) * 1000.0}
        return out

    def to_string(self) -> str:
        lines = [f"TrainingStats: {self.examples} examples, "
                 f"{self.minibatches} minibatches"]
        for phase in self.key_set():
            ds = self._durations[phase]
            lines.append(f"  {phase:<16} n={len(ds):<6} "
                         f"total={sum(ds) * 1000:9.1f} ms  "
                         f"mean={sum(ds) / len(ds) * 1000:7.2f} ms")
        for name in sorted(self.counters):
            lines.append(f"  {name:<16} {self.counters[name]}")
        return "\n".join(lines)
