"""Pallas TPU scatter-add kernel for embedding-table updates.

The embedding-update segment-sum has three implementations in this
framework, chosen by regime (all exact up to dtype):

| path | where | measured (V=10k, N=49k, D=128, v5e) |
|---|---|---|
| XLA ``.at[].add`` scatter | any | 253 ms |
| one-hot bf16 matmul (kernels.py) | TPU, ``N*V*2B`` under gate | 19 ms |
| this Pallas kernel | TPU, table scratch fits VMEM | 158 ms |

The Pallas kernel streams (idx, grads) blocks through VMEM while the whole
table rides a persistent VMEM scratch accumulator (the input buffer itself
is donated to the output), applying rows serially — the dependency chain
of duplicate indices is respected EXACTLY, not just in expectation like
the count-normalized scatter. ``kernels._scatter_mean_update`` dispatches
here automatically in the regime where the one-hot path is memory-gated
out but the table still fits VMEM; call it directly when exact sequential
accumulation matters.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# table scratch must fit VMEM alongside the streamed blocks
VMEM_TABLE_BYTES = 12 * 1024 * 1024


def fits_vmem(table) -> bool:
    return table.size * table.dtype.itemsize <= VMEM_TABLE_BYTES


def scatter_add_pallas(table, idx, grads, block: int = 1024):
    """table[idx[n]] += grads[n] for n in order; exact duplicate handling.

    table (V, D) float32, idx (N,) int32 (any N — ragged tails pad
    internally with zero-gradient rows), grads (N, D) float32. Off TPU, or
    when the table exceeds the VMEM budget, falls back to ``.at[].add``."""
    # the whole table lives in a VMEM scratch accumulator; past the budget
    # the kernel cannot compile, so large tables take the XLA scatter
    if jax.default_backend() != "tpu" or not fits_vmem(table):
        return table.at[idx].add(grads)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = idx.shape[0]
    if n % block:
        pad = block - n % block
        idx = jnp.pad(idx, (0, pad))
        # padded rows add zeros to row idx=0: harmless
        grads = jnp.pad(grads, ((0, pad), (0, 0)))
        n = idx.shape[0]
    V, D = table.shape

    def kernel(idx_ref, grads_ref, table_ref, out_ref, acc_ref):
        # VMEM scratch persists across grid iterations: init from the table
        # on the first step, accumulate, write out on the last. (Accumulating
        # directly into a revisited aliased output block races with its
        # block-fetch pipelining.)
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _():
            acc_ref[:] = table_ref[:]

        def body(i, _):
            acc_ref[idx_ref[i], :] += grads_ref[i, :]
            return 0
        jax.lax.fori_loop(0, block, body, 0)

        @pl.when(step == pl.num_programs(0) - 1)
        def _():
            out_ref[:] = acc_ref[:]

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(table.shape, table.dtype),
        grid=(n // block,),
        in_specs=[
            pl.BlockSpec((block,), lambda i: (i,),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((block, D), lambda i: (i, 0)),
            pl.BlockSpec((V, D), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((V, D), lambda i: (0, 0)),
        scratch_shapes=[pltpu.VMEM((V, D), table.dtype)],
        input_output_aliases={2: 0},  # donate the table buffer
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
    )(idx, grads, table)
