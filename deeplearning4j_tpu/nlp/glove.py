"""GloVe embeddings.

Parity surface: reference ``models/glove/Glove.java:43`` (429 LoC; Builder,
co-occurrence learning via AdaGrad) with the co-occurrence counting pass of
``models/glove/count/`` (CountMap/RoundCount).

TPU redesign: the host builds the sparse co-occurrence table in one
vectorized pass (symmetric window, 1/distance weighting — the standard GloVe
recipe the reference's AbstractCoOccurrences implements), then training is a
shuffled stream of (row, col, log x, f(x)) batches through the jitted AdaGrad
kernel ``kernels.glove_step`` — one XLA program per batch instead of the
reference's per-pair host loop."""

from __future__ import annotations

from typing import Iterable, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.nlp import kernels
from deeplearning4j_tpu.nlp.word2vec import Corpus, Word2Vec


class Glove(Word2Vec):
    """GloVe trainer.

    Builder-parity knobs (reference Glove.Builder): ``x_max`` + ``alpha``
    (weighting function), ``learning_rate`` (AdaGrad base), ``epochs``,
    ``layer_size``, ``window_size``, ``min_word_frequency``, ``symmetric``."""

    def __init__(self, x_max: float = 100.0, alpha: float = 0.75,
                 symmetric: bool = True, shuffle: bool = True, **kwargs):
        kwargs.setdefault("learning_rate", 0.05)
        super().__init__(**kwargs)
        self.x_max = x_max
        self.alpha = alpha
        self.symmetric = symmetric
        self.shuffle = shuffle
        # context-side table + biases + AdaGrad state
        self.syn0c = self.bias = self.bias_c = None
        self._gw = self._gwc = self._gb = self._gbc = None
        self.loss_history: List[float] = []

    # -------------------------------------------------------- co-occurrence
    def _cooccurrences(self, sequences) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Sparse symmetric co-occurrence counts with 1/distance weighting
        (reference AbstractCoOccurrences' windowed pass), vectorized: per
        window offset d, aligned slices of the flattened corpus give every
        co-occurring pair at distance d at once; pairs are keyed i*V+j and
        aggregated with one bincount."""
        seqs = list(self._index_sequences(sequences))
        empty = (np.zeros(0, np.int32),) * 2 + (np.zeros(0, np.float32),)
        if not seqs:
            return empty
        flat = np.concatenate(seqs)
        sid = np.repeat(np.arange(len(seqs)), [len(s) for s in seqs])
        V = self.vocab.num_words()
        keys_all: List[np.ndarray] = []
        wts_all: List[np.ndarray] = []
        for d in range(1, self.window_size + 1):
            if len(flat) <= d:
                break
            same = sid[:-d] == sid[d:]
            i, j = flat[:-d][same], flat[d:][same]
            wt = np.full(len(i), 1.0 / d, np.float64)
            keys_all.append(i * V + j)
            wts_all.append(wt)
            if self.symmetric:
                keys_all.append(j * V + i)
                wts_all.append(wt)
        if not keys_all:
            return empty
        keys = np.concatenate(keys_all)
        wts = np.concatenate(wts_all)
        uniq, inv = np.unique(keys, return_inverse=True)
        sums = np.bincount(inv, weights=wts).astype(np.float32)
        return ((uniq // V).astype(np.int32), (uniq % V).astype(np.int32), sums)

    # -------------------------------------------------------------- training
    def fit(self, sentences: Optional[Corpus] = None, **_):
        it = self._as_iterator(sentences)

        def tokenized():
            it.reset()
            return self._tokenized(it)

        if self.vocab is None:
            self.build_vocab(tokenized())
        V, D = self.vocab.num_words(), self.layer_size
        rows, cols, x = self._cooccurrences(tokenized())
        if len(rows) == 0:
            raise ValueError("empty co-occurrence table — corpus too small")
        logx = np.log(x)
        weight = np.minimum(1.0, (x / self.x_max) ** self.alpha).astype(np.float32)
        rng = self._rng
        scale = 0.5 / D
        self.syn0 = (rng.random((V, D), np.float32) - 0.5) * 2 * scale
        self.syn0c = (rng.random((V, D), np.float32) - 0.5) * 2 * scale
        self.bias = np.zeros(V, np.float32)
        self.bias_c = np.zeros(V, np.float32)
        self._gw = np.zeros((V, D), np.float32)
        self._gwc = np.zeros((V, D), np.float32)
        self._gb = np.zeros(V, np.float32)
        self._gbc = np.zeros(V, np.float32)
        b = self.batch_size
        for _ in range(self.epochs):
            order = rng.permutation(len(rows)) if self.shuffle \
                else np.arange(len(rows))
            losses = []
            for s in range(0, len(order), b):
                sel = order[s:s + b]
                r, _ = self._pad(rows[sel], b)
                c, _ = self._pad(cols[sel], b)
                lx, _ = self._pad(logx[sel], b)
                # padded entries carry weight 0 => zero gradient and loss
                wt, _ = self._pad(weight[sel], b)
                (self.syn0, self.syn0c, self.bias, self.bias_c,
                 self._gw, self._gwc, self._gb, self._gbc, l) = \
                    kernels.glove_step(
                        self.syn0, self.syn0c, self.bias, self.bias_c,
                        self._gw, self._gwc, self._gb, self._gbc,
                        r.astype(np.int32), c.astype(np.int32),
                        lx.astype(np.float32), wt.astype(np.float32),
                        np.float32(self.learning_rate))
                losses.append(l)
            # one host sync per epoch, after all batches are queued
            self.loss_history.append(
                float(np.mean([float(x) for x in losses])) if losses else 0.0)
        return self

    # ------------------------------------------------------------- accessors
    def word_vector(self, word: str) -> Optional[np.ndarray]:
        """GloVe's final vectors are main + context (the standard W + W~)."""
        i = self.vocab.index_of(word) if self.vocab is not None else -1
        if i < 0 or self.syn0 is None:
            return None
        return np.asarray(self.syn0[i]) + np.asarray(self.syn0c[i])

    def get_word_vector_matrix(self) -> np.ndarray:
        return np.asarray(self.syn0) + np.asarray(self.syn0c)
