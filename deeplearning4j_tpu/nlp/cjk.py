"""Lightweight CJK tokenizers behind the TokenizerFactory SPI.

Parity surface: the reference bundles full tokenizer stacks for Chinese
(deeplearning4j-nlp-chinese ChineseTokenizer.java:1 /
ChineseTokenizerFactory.java, wrapping ansj), Japanese
(deeplearning4j-nlp-japanese, a kuromoji fork, ~55 files) and Korean
(deeplearning4j-nlp-korean, open-korean-text) — all exposed through the same
TokenizerFactory SPI as the default whitespace tokenizer.

These are deliberately lightweight, dependency-free equivalents that make
zh/ja/ko corpora *trainable* end-to-end (Word2Vec/ParagraphVectors/BoW):

* ``ChineseTokenizerFactory`` — forward-maximum-match over a bundled lexicon
  of frequent words (user-extensible), single-character fallback. FMM is the
  classic dictionary segmentation baseline (what ansj's core does before its
  statistical re-ranking).
* ``JapaneseTokenizerFactory`` — script-class segmentation (kanji/hiragana/
  katakana/latin/digit runs) with greedy particle splitting inside hiragana
  runs; the standard dictionary-free baseline for kana/kanji text.
* ``KoreanTokenizerFactory`` — whitespace eojeol splitting plus josa
  (particle) stripping, emitting stem and particle as separate tokens the
  way open-korean-text's stemmed tokens do.

All three accept the SPI's TokenPreProcess; Latin/digit runs embedded in CJK
text fall back to whitespace/word tokenization so mixed corpora work.
"""

from __future__ import annotations

import re
from typing import Iterable, List, Optional, Set

from deeplearning4j_tpu.nlp.tokenization import Tokenizer, TokenizerFactory

# ----------------------------------------------------------------- Chinese

# Frequent multi-character words (subset of any standard frequency list —
# the bundled seed keeps common NLP/news vocabulary segmentable; extend per
# corpus via the constructor).
_ZH_LEXICON = """
我们 你们 他们 她们 自己 什么 没有 可以 知道 现在 时候 这个 那个 这些 那些
因为 所以 但是 如果 虽然 还是 就是 不是 一个 很多 非常 已经 开始 进行 工作
学习 生活 问题 中国 北京 上海 世界 国家 政府 经济 发展 社会 文化 历史 科学
技术 计算 计算机 电脑 网络 互联网 数据 人工 智能 人工智能 机器 学习 机器学习
深度 深度学习 神经 网络 神经网络 模型 训练 语言 自然 处理 自然语言 研究 大学
老师 学生 朋友 家庭 父母 孩子 今天 明天 昨天 时间 地方 东西 事情 方法 方面
重要 主要 需要 应该 能够 希望 觉得 认为 表示 通过 对于 关于 根据 由于 为了
以及 或者 并且 而且 然后 于是 公司 企业 市场 产品 服务 用户 系统 信息 软件
硬件 程序 代码 算法 分析 设计 开发 测试 应用 平台 环境 资源 管理 项目 团队
喜欢 快乐 高兴 美丽 漂亮 好吃 天气 音乐 电影 图书 读书 旅游 运动 健康 医生
医院 城市 农村 交通 汽车 飞机 火车 地铁 食物 水果 蔬菜 米饭 面条 咖啡 牛奶
""".split()

_CJK_RUN_RE = re.compile(r"[一-鿿㐀-䶿]+")
_WORD_RE = re.compile(r"[A-Za-z0-9_]+|[^\sA-Za-z0-9_]")


class ChineseTokenizerFactory(TokenizerFactory):
    """Forward-maximum-match segmentation (reference
    ChineseTokenizerFactory.java surface). ``lexicon`` adds words to the
    bundled list (``extend=False`` replaces it); the FMM window adapts to
    the longest lexicon entry."""

    def __init__(self, lexicon: Optional[Iterable[str]] = None,
                 extend: bool = True):
        super().__init__()
        words: Set[str] = set(_ZH_LEXICON) if (lexicon is None or extend) \
            else set()
        if lexicon is not None:
            words.update(lexicon)
        self._lex = words
        self._max_len = max((len(w) for w in words), default=1)

    def _segment_cjk(self, run: str) -> List[str]:
        out, i, n = [], 0, len(run)
        while i < n:
            for ln in range(min(self._max_len, n - i), 1, -1):
                if run[i:i + ln] in self._lex:
                    out.append(run[i:i + ln])
                    i += ln
                    break
            else:
                out.append(run[i])  # single-char fallback
                i += 1
        return out

    def create(self, text: str) -> Tokenizer:
        tokens: List[str] = []
        for chunk in text.split():
            i = 0
            for m in _CJK_RUN_RE.finditer(chunk):
                if m.start() > i:
                    tokens.extend(_WORD_RE.findall(chunk[i:m.start()]))
                tokens.extend(self._segment_cjk(m.group()))
                i = m.end()
            if i < len(chunk):
                tokens.extend(_WORD_RE.findall(chunk[i:]))
        return Tokenizer(tokens, self._pre)


# ---------------------------------------------------------------- Japanese

_JA_PARTICLES = sorted(
    ["から", "まで", "より", "ので", "のに", "けど", "でも", "だけ", "ほど",
     "など", "は", "が", "を", "に", "で", "と", "も", "の", "へ", "や",
     "ね", "よ", "か", "な"], key=len, reverse=True)


def _script(ch: str) -> str:
    o = ord(ch)
    if 0x4E00 <= o <= 0x9FFF or 0x3400 <= o <= 0x4DBF or ch in "々〆ヶ":
        return "kanji"
    if 0x3040 <= o <= 0x309F:
        return "hira"
    if 0x30A0 <= o <= 0x30FF or o == 0xFF70 or 0xFF66 <= o <= 0xFF9D:
        return "kata"
    if ch.isdigit():
        return "digit"
    if ch.isalpha():
        return "latin"
    return "other"


class JapaneseTokenizerFactory(TokenizerFactory):
    """Script-transition segmentation with greedy particle splitting
    (reference deeplearning4j-nlp-japanese kuromoji-fork surface). The
    long-vowel mark and small kana stay attached to katakana runs; hiragana
    runs are split on the particle list so content words separate from
    function words."""

    def _split_hira(self, run: str) -> List[str]:
        out, i, n = [], 0, len(run)
        while i < n:
            for p in _JA_PARTICLES:
                if run.startswith(p, i):
                    out.append(p)
                    i += len(p)
                    break
            else:
                # consume up to the next particle start as one token
                j = i + 1
                while j < n and not any(run.startswith(p, j)
                                        for p in _JA_PARTICLES):
                    j += 1
                out.append(run[i:j])
                i = j
        return out

    def create(self, text: str) -> Tokenizer:
        tokens: List[str] = []
        run, cls = "", None
        def flush():
            if not run:
                return
            if cls == "hira":
                tokens.extend(self._split_hira(run))
            elif cls != "other":
                tokens.append(run)
            else:
                tokens.extend(t for t in _WORD_RE.findall(run)
                              if not t.isspace())
        for ch in text:
            c = _script(ch)
            # long-vowel mark / iteration marks extend the current run
            if ch in "ーゝゞヽヾ" and run:
                run += ch
                continue
            if c == cls:
                run += ch
            else:
                flush()
                run, cls = ch, c
        flush()
        return Tokenizer([t for t in tokens if t.strip()], self._pre)


# ------------------------------------------------------------------ Korean

_KO_JOSA = sorted(
    ["에서는", "에서도", "으로는", "으로도", "부터", "까지", "에서", "에게",
     "으로", "라는", "이라는", "은", "는", "이", "가", "을", "를", "의",
     "에", "로", "와", "과", "도", "만", "께", "야"], key=len, reverse=True)


def _is_hangul(ch: str) -> bool:
    return 0xAC00 <= ord(ch) <= 0xD7A3 or 0x1100 <= ord(ch) <= 0x11FF


class KoreanTokenizerFactory(TokenizerFactory):
    """Whitespace eojeol splitting + josa stripping (reference
    deeplearning4j-nlp-korean open-korean-text surface): '학교에서' ->
    ['학교', '에서']. Particles only split when a Hangul stem of 2+
    syllables remains, which avoids mangling short words."""

    def __init__(self, emit_josa: bool = True):
        super().__init__()
        self.emit_josa = emit_josa

    def _split_eojeol(self, w: str) -> List[str]:
        if not all(_is_hangul(c) for c in w):
            return [t for t in _WORD_RE.findall(w)]
        for josa in _KO_JOSA:
            if w.endswith(josa) and len(w) - len(josa) >= 2:
                stem = w[:-len(josa)]
                return [stem, josa] if self.emit_josa else [stem]
        return [w]

    def create(self, text: str) -> Tokenizer:
        tokens: List[str] = []
        for w in text.split():
            tokens.extend(self._split_eojeol(w))
        return Tokenizer(tokens, self._pre)


__all__ = ["ChineseTokenizerFactory", "JapaneseTokenizerFactory",
           "KoreanTokenizerFactory"]
