"""Word-vector persistence.

Parity surface: reference ``models/embeddings/loader/WordVectorSerializer.java``
— the word2vec *text* format (``V D`` header then ``word v1 … vD`` lines,
readable by gensim/fastText) and the *Google binary* format
(``V D\\n`` ASCII header then ``word`` + space + D little-endian float32 per
word), plus full-model save/restore.

Host-side IO only; matrices are plain numpy."""

from __future__ import annotations

import io
import json
import zipfile
from typing import List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.nlp.vocab import AbstractCache, VocabWord


class StaticWordVectors:
    """Lookup-only word vectors as returned by the readers (reference
    WordVectors interface: getWordVectorMatrix/similarity/wordsNearest)."""

    def __init__(self, vocab: AbstractCache, matrix: np.ndarray):
        self.vocab = vocab
        self.syn0 = np.asarray(matrix, np.float32)

    def has_word(self, word: str) -> bool:
        return self.vocab.contains_word(word)

    def word_vector(self, word: str) -> Optional[np.ndarray]:
        i = self.vocab.index_of(word)
        return None if i < 0 else self.syn0[i]

    def get_word_vector_matrix(self) -> np.ndarray:
        return self.syn0

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.word_vector(a), self.word_vector(b)
        if va is None or vb is None:
            return float("nan")
        denom = (np.linalg.norm(va) * np.linalg.norm(vb)) or 1e-12
        return float(va @ vb / denom)

    def words_nearest(self, word_or_vec, top_n: int = 10) -> List[str]:
        if isinstance(word_or_vec, str):
            v = self.word_vector(word_or_vec)
            exclude = {word_or_vec}
        else:
            v = np.asarray(word_or_vec, np.float32)
            exclude = set()
        if v is None:
            return []
        norms = np.linalg.norm(self.syn0, axis=1) * (np.linalg.norm(v) or 1e-12)
        sims = (self.syn0 @ v) / np.maximum(norms, 1e-12)
        out = []
        for i in np.argsort(-sims):
            w = self.vocab.word_at_index(int(i))
            if w not in exclude:
                out.append(w)
            if len(out) >= top_n:
                break
        return out


def _model_vocab_matrix(model_or_pair) -> Tuple[AbstractCache, np.ndarray]:
    if isinstance(model_or_pair, tuple):
        vocab, matrix = model_or_pair
    else:
        vocab = model_or_pair.vocab
        matrix = model_or_pair.get_word_vector_matrix()
    return vocab, np.asarray(matrix, np.float32)


def _vocab_from_words(words: List[str], counts: Optional[List[int]] = None
                      ) -> AbstractCache:
    """Rebuild a cache preserving the on-disk word order (readers must not
    re-sort — the matrix rows are positional)."""
    cache = AbstractCache()
    for i, w in enumerate(words):
        vw = VocabWord(w, counts[i] if counts else 1)
        vw.index = i
        cache._words[w] = vw
        cache._by_index.append(vw)
    cache.total_word_occurrences = sum(v.count for v in cache._by_index)
    return cache


class WordVectorSerializer:
    """Static façade mirroring the reference's WordVectorSerializer."""

    # ----------------------------------------------------------- text format
    @staticmethod
    def write_word_vectors(model, path: str):
        """word2vec text format (reference writeWordVectors)."""
        vocab, matrix = _model_vocab_matrix(model)
        with open(path, "w", encoding="utf-8") as f:
            f.write(f"{len(matrix)} {matrix.shape[1]}\n")
            for i in range(len(matrix)):
                word = vocab.word_at_index(i)
                vec = " ".join(f"{x:.6g}" for x in matrix[i])
                f.write(f"{word} {vec}\n")

    @staticmethod
    def read_word_vectors(path: str) -> StaticWordVectors:
        """Read the text format; tolerates a missing header line (reference
        loadTxtVectors sniffs for it)."""
        words: List[str] = []
        rows: List[np.ndarray] = []
        with open(path, "r", encoding="utf-8") as f:
            first = f.readline().rstrip("\n")
            parts = first.split()
            if len(parts) != 2 or not all(p.isdigit() for p in parts):
                words.append(parts[0])
                rows.append(np.asarray([float(x) for x in parts[1:]], np.float32))
            for line in f:
                parts = line.rstrip("\n").split()
                if not parts:
                    continue
                words.append(parts[0])
                rows.append(np.asarray([float(x) for x in parts[1:]], np.float32))
        return StaticWordVectors(_vocab_from_words(words), np.stack(rows))

    # --------------------------------------------------------- binary format
    @staticmethod
    def write_word2vec_binary(model, path: str):
        """Google word2vec binary format (reference writeWordVectors binary
        branch / loadGoogleModel's inverse)."""
        vocab, matrix = _model_vocab_matrix(model)
        with open(path, "wb") as f:
            f.write(f"{len(matrix)} {matrix.shape[1]}\n".encode())
            for i in range(len(matrix)):
                f.write(vocab.word_at_index(i).encode("utf-8") + b" ")
                f.write(matrix[i].astype("<f4").tobytes())
                f.write(b"\n")

    @staticmethod
    def read_word2vec_binary(path: str) -> StaticWordVectors:
        with open(path, "rb") as f:
            header = f.readline().decode("utf-8").split()
            v, d = int(header[0]), int(header[1])
            words, rows = [], []
            for _ in range(v):
                chars = bytearray()
                while True:
                    ch = f.read(1)
                    if not ch or ch == b" ":
                        break
                    if ch != b"\n":       # leading newline from previous row
                        chars.extend(ch)
                words.append(chars.decode("utf-8"))
                rows.append(np.frombuffer(f.read(4 * d), "<f4").copy())
        return StaticWordVectors(_vocab_from_words(words), np.stack(rows))

    # ------------------------------------------------------------ full model
    @staticmethod
    def write_word2vec_model(model, path: str):
        """Full-model zip (reference writeWord2VecModel: config + syn0 + syn1
        + vocab frequencies), restorable for continued training."""
        vocab, _ = _model_vocab_matrix(model)
        config = {
            "layer_size": model.layer_size, "window_size": model.window_size,
            "negative": model.negative, "learning_rate": model.learning_rate,
            "min_learning_rate": model.min_learning_rate,
            "sampling": model.sampling, "epochs": model.epochs,
            "min_word_frequency": model.min_word_frequency,
            "use_cbow": model.use_cbow, "seed": model.seed,
        }
        vocab_rows = [{"word": vw.word, "count": vw.count}
                      for vw in vocab.vocab_words()]
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
            z.writestr("config.json", json.dumps(config))
            z.writestr("vocab.json", json.dumps(vocab_rows))
            for name, arr in (("syn0", model.syn0), ("syn1", model.syn1)):
                buf = io.BytesIO()
                np.save(buf, np.asarray(arr))
                z.writestr(name + ".npy", buf.getvalue())

    @staticmethod
    def read_word2vec_model(path: str):
        from deeplearning4j_tpu.nlp.word2vec import Word2Vec
        with zipfile.ZipFile(path, "r") as z:
            config = json.loads(z.read("config.json"))
            vocab_rows = json.loads(z.read("vocab.json"))
            syn0 = np.load(io.BytesIO(z.read("syn0.npy")))
            syn1 = np.load(io.BytesIO(z.read("syn1.npy")))
        model = Word2Vec(**config)
        model.vocab = _vocab_from_words([r["word"] for r in vocab_rows],
                                        [r["count"] for r in vocab_rows])
        model.syn0, model.syn1 = syn0, syn1
        # rebuild the derived tables the kernels need
        from deeplearning4j_tpu.nlp.vocab import build_huffman, unigram_table
        if model.use_hs:
            model._codes, model._points, model._lengths = build_huffman(model.vocab)
        if model.negative > 0:
            model._neg_table = unigram_table(model.vocab)
        return model
