"""Sentence -> padded word-vector tensors for CNN text classification.

Parity surface: reference
``deeplearning4j-nlp/.../iterator/CnnSentenceDataSetIterator.java:47``
(builder: sentenceProvider, wordVectors, minibatchSize, maxSentenceLength,
unknownWordHandling REMOVE_WORD|USE_UNKNOWN, sentencesAlongHeight) and
``LabeledSentenceProvider``/``CollectionLabeledSentenceProvider``.

TPU-native layout: the reference emits NCHW (b, 1, maxLen, vecSize); this
framework is NHWC, so batches are (b, maxLen, vecSize, 1) — time along
height, embedding along width, one channel — ready for ``ConvolutionLayer``
with ``InputType.convolutional(maxLen, vec_size, 1)``. Variable-length
sentences zero-pad on the right with a per-step feature mask (b, maxLen).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet


class CollectionLabeledSentenceProvider:
    """(sentence, label) pairs from lists (reference
    CollectionLabeledSentenceProvider.java)."""

    def __init__(self, sentences: Sequence[str], labels: Sequence[str],
                 seed: Optional[int] = None):
        if len(sentences) != len(labels):
            raise ValueError("sentences and labels must align")
        self._data = list(zip(sentences, labels))
        self._labels = sorted(set(labels))
        self._rng = None if seed is None else np.random.default_rng(seed)
        self.reset()

    def reset(self):
        self._order = list(range(len(self._data)))
        if self._rng is not None:
            self._rng.shuffle(self._order)
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < len(self._order)

    def next_sentence(self) -> Tuple[str, str]:
        s, l = self._data[self._order[self._pos]]
        self._pos += 1
        return s, l

    def all_labels(self) -> List[str]:
        return self._labels

    def num_labels(self) -> int:
        return len(self._labels)


class CnnSentenceDataSetIterator:
    """Batches of (b, maxLen, vec_size, 1) word-vector tensors + one-hot
    labels + right-pad feature masks (reference
    CnnSentenceDataSetIterator.java:47).

    ``word_vectors`` is anything with ``word_vector(word) -> np.ndarray |
    None`` (Word2Vec, SequenceVectors, loaded serializer models).
    ``unknown_word_handling``: "remove_word" drops OOV tokens (reference
    REMOVE_WORD); "use_unknown" substitutes ``unknown_word``'s vector."""

    def __init__(self, sentence_provider, word_vectors,
                 batch_size: int = 32, max_sentence_length: int = 64,
                 unknown_word_handling: str = "remove_word",
                 unknown_word: str = "UNK",
                 tokenizer_factory=None):
        from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory
        self.provider = sentence_provider
        self.word_vectors = word_vectors
        self.batch_size = batch_size
        self.max_sentence_length = max_sentence_length
        self.unknown_word_handling = unknown_word_handling
        self.unknown_word = unknown_word
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        probe = None
        for cand in ("the", "a"):
            probe = word_vectors.word_vector(cand)
            if probe is not None:
                break
        if probe is None:
            # fall back to any vector the model can produce
            mat = getattr(word_vectors, "get_word_vector_matrix", None)
            if mat is not None:
                probe = np.asarray(mat())[0]
        if probe is None:
            raise ValueError("word_vectors yields no vectors to size from")
        self.vec_size = int(np.asarray(probe).shape[-1])
        self._labels = list(self.provider.all_labels())
        self._lab_idx = {l: i for i, l in enumerate(self._labels)}

    # -------------------------------------------------------------- iterate
    def reset(self):
        self.provider.reset()

    def has_next(self) -> bool:
        return self.provider.has_next()

    def _vectors_for(self, sentence: str) -> np.ndarray:
        toks = self.tokenizer_factory.create(sentence).get_tokens()
        vecs = []
        for t in toks:
            v = self.word_vectors.word_vector(t)
            if v is None:
                if self.unknown_word_handling == "use_unknown":
                    v = self.word_vectors.word_vector(self.unknown_word)
                    if v is None:
                        v = np.zeros(self.vec_size, np.float32)
                else:           # remove_word
                    continue
            vecs.append(np.asarray(v, np.float32))
            if len(vecs) >= self.max_sentence_length:
                break
        if not vecs:
            vecs = [np.zeros(self.vec_size, np.float32)]
        return np.stack(vecs)

    def next(self, num: Optional[int] = None) -> DataSet:
        num = num or self.batch_size
        sents, labs = [], []
        while self.provider.has_next() and len(sents) < num:
            s, l = self.provider.next_sentence()
            sents.append(self._vectors_for(s))
            labs.append(self._lab_idx[l])
        if not sents:
            # NOT StopIteration: PEP 479 turns that into RuntimeError when
            # this is called inside a generator frame
            raise ValueError("sentence provider exhausted; reset() first")
        b = len(sents)
        T = max(v.shape[0] for v in sents)
        feats = np.zeros((b, T, self.vec_size, 1), np.float32)
        fmask = np.zeros((b, T), np.float32)
        for i, v in enumerate(sents):
            feats[i, : v.shape[0], :, 0] = v
            fmask[i, : v.shape[0]] = 1.0
        labels = np.eye(len(self._labels), dtype=np.float32)[labs]
        return DataSet(feats, labels, features_mask=fmask)

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next()

    # ------------------------------------------------------------- metadata
    def get_labels(self) -> List[str]:
        return self._labels

    def input_columns(self) -> int:
        return self.vec_size

    def total_outcomes(self) -> int:
        return len(self._labels)

    def load_single_sentence(self, sentence: str) -> np.ndarray:
        """One sentence -> (1, len, vec_size, 1) tensor (reference
        loadSingleSentence)."""
        v = self._vectors_for(sentence)
        return v[None, :, :, None]
