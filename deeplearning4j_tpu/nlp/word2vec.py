"""Word2Vec façade.

Parity surface: reference ``models/word2vec/Word2Vec.java:45`` (extends
SequenceVectors; Builder wires a SentenceIterator + TokenizerFactory into
sequence production) with learning impls ``SkipGram.java:156`` /
``CBOW.java``.

The TPU redesign keeps the reference's shape — Word2Vec IS a SequenceVectors
whose sequences come from tokenized sentences — but the training math runs as
jitted XLA scatter programs (see kernels.py) instead of libnd4j's native
sg/cbow kernels."""

from __future__ import annotations

from typing import Iterable, List, Optional, Union

from deeplearning4j_tpu.nlp.sentenceiterator import (
    CollectionSentenceIterator, SentenceIterator,
)
from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors
from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory, TokenizerFactory,
)

Corpus = Union[SentenceIterator, Iterable[str]]


class Word2Vec(SequenceVectors):
    """SkipGram/CBOW word embeddings over sentences.

    Mirrors the reference Builder surface: ``min_word_frequency``,
    ``iterations``, ``epochs``, ``layer_size``, ``window_size``, ``negative``
    (0 selects hierarchical softmax, as the reference's
    ``useHierarchicSoftmax(true).negativeSample(0)`` combo), ``sampling``,
    ``learning_rate``/``min_learning_rate``, ``use_cbow`` (reference
    ``elementsLearningAlgorithm(new CBOW<>())``), ``seed``, plus
    ``tokenizer_factory`` and ``sentence_iterator`` (reference
    ``.iterate(iter).tokenizerFactory(t)``)."""

    def __init__(self, sentence_iterator: Optional[Corpus] = None,
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 **kwargs):
        super().__init__(**kwargs)
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self.sentence_iterator = sentence_iterator

    # ------------------------------------------------------------ sequences
    def _as_iterator(self, corpus: Optional[Corpus]) -> SentenceIterator:
        corpus = corpus if corpus is not None else self.sentence_iterator
        if corpus is None:
            raise ValueError(
                "no corpus: pass sentences to fit() or set sentence_iterator")
        if isinstance(corpus, SentenceIterator):
            return corpus
        return CollectionSentenceIterator(list(corpus))

    def _tokenized(self, it: SentenceIterator):
        tf = self.tokenizer_factory
        if type(tf) is DefaultTokenizerFactory and tf._pre is None:
            # plain whitespace split: skip the per-sentence Tokenizer object
            # churn (measured ~40% of the word2vec host budget)
            for sentence in it:
                tokens = sentence.split()
                if tokens:
                    yield tokens
            return
        for sentence in it:
            tokens = tf.create(sentence).get_tokens()
            if tokens:
                yield tokens

    # -------------------------------------------------------------- training
    def fit(self, sentences: Optional[Corpus] = None, **kwargs):
        """Build vocab (if needed) and train. ``sentences`` may be raw
        strings, a SentenceIterator, or omitted to use the constructor's
        iterator (reference Word2Vec.fit())."""
        it = self._as_iterator(sentences)

        def factory():
            it.reset()
            return self._tokenized(it)

        return super().fit(factory, **kwargs)

    # ------------------------------------------------------------- accessors
    def vocab_size(self) -> int:
        return 0 if self.vocab is None else self.vocab.num_words()
