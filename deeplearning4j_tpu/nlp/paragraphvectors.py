"""ParagraphVectors (doc2vec).

Parity surface: reference ``models/paragraphvectors/ParagraphVectors.java:60``
(1,461 LoC: Builder wiring LabelAwareIterator + LabelsSource, fit, and
inferVector) with sequence learning algorithms
``models/embeddings/learning/impl/sequence/DBOW.java`` (the doc vector
predicts each word, PV-DBOW) and ``DM.java`` (the doc vector joins every
context bag, PV-DM).

TPU redesign: document vectors live as extra rows appended after the V word
rows of the shared ``syn0`` table, so the existing jitted SGNS/CBOW/HS scatter
kernels train words and documents in the same XLA program — DBOW is
``sgns_step`` with the document row as the input-side index, DM is
``cbow_step`` with the document row appended to each context bag.
``infer_vector`` runs the frozen-tables kernels (kernels.sgns_infer_step /
cbow_infer_step) so inference never mutates the model, matching the
reference's locked-learning inferVector semantics."""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np

from deeplearning4j_tpu.nlp import kernels
from deeplearning4j_tpu.nlp.sentenceiterator import (
    LabelAwareIterator, LabelAwareListSentenceIterator, LabelledDocument,
    SimpleLabelAwareIterator,
)
from deeplearning4j_tpu.nlp.word2vec import Word2Vec

Docs = Union[LabelAwareIterator, Sequence[LabelledDocument], Sequence[str]]


class ParagraphVectors(Word2Vec):
    """PV-DM / PV-DBOW document embeddings.

    ``dm=True`` selects PV-DM (reference ``new DM<>()``), ``dm=False``
    PV-DBOW (``new DBOW<>()``). ``train_words`` additionally runs plain
    skip-gram over the words (reference ``trainWordVectors(true)``)."""

    def __init__(self, dm: bool = True, train_words: bool = False, **kwargs):
        super().__init__(**kwargs)
        self.dm = dm
        self.train_words = train_words
        self.label_index: Dict[str, int] = {}

    # ------------------------------------------------------------ documents
    def _as_docs(self, documents: Docs) -> LabelAwareIterator:
        if isinstance(documents, LabelAwareIterator):
            return documents
        documents = list(documents)
        if documents and isinstance(documents[0], LabelledDocument):
            return SimpleLabelAwareIterator(documents)
        return LabelAwareListSentenceIterator(list(documents))

    def _doc_tokens(self, doc: LabelledDocument) -> List[str]:
        return self.tokenizer_factory.create(doc.content).get_tokens()

    # -------------------------------------------------------------- training
    def fit(self, documents: Docs, chunk_docs: int = 256):
        it = self._as_docs(documents)
        if self.vocab is None:
            it.reset()
            self.build_vocab(self._doc_tokens(d) for d in it)
        # collect labels in first-seen order (reference LabelsSource)
        it.reset()
        for d in it:
            for lbl in d.labels:
                self.label_index.setdefault(lbl, len(self.label_index))
        if self.syn0 is None:
            self._init_tables()
        # append one doc row per label after the V word rows; refits with
        # fresh labels grow the table so new rows are trained, not silently
        # scatter-dropped out of bounds
        want = self.vocab.num_words() + len(self.label_index)
        have = self.syn0.shape[0]
        if have < want:
            D = self.syn0.shape[1]
            doc_rows = ((self._rng.random((want - have, D), np.float32) - 0.5) / D)
            self.syn0 = np.concatenate([np.asarray(self.syn0), doc_rows])
        widx = {vw.word: vw.index for vw in self.vocab.vocab_words()}
        V = self.vocab.num_words()
        total = self.vocab.total_word_occurrences * self.epochs * self.iterations
        for _ in range(self.epochs):
            chunk: List[Tuple[np.ndarray, int]] = []
            it.reset()
            for d in it:
                idx = [widx[t] for t in self._doc_tokens(d) if t in widx]
                if not idx or not d.labels:
                    continue
                for lbl in d.labels:
                    chunk.append((np.asarray(idx, np.int64),
                                  V + self.label_index[lbl]))
                if len(chunk) >= chunk_docs:
                    self._fit_doc_chunk(chunk, total)
                    chunk = []
            if chunk:
                self._fit_doc_chunk(chunk, total)
        return self

    def _fit_doc_chunk(self, chunk, total_expected):
        seqs = [c[0] for c in chunk]
        doc_rows = np.asarray([c[1] for c in chunk], np.int64)
        for _ in range(self.iterations):
            lr = self._lr(total_expected)
            if self.dm:
                centers, bags, bmask, rows = self._bags_with_docs(seqs, doc_rows)
                if len(centers):
                    # doc row joins each context bag in an extra column
                    bags = np.concatenate([bags, rows[:, None]], axis=1)
                    bmask = np.concatenate(
                        [bmask, np.ones((len(bmask), 1), np.float32)], axis=1)
                    self._train_bags(centers, bags, bmask, lr)
            else:
                # DBOW: the doc row is the input-side index for every word
                flat = np.concatenate(seqs)
                rows = np.repeat(doc_rows, [len(s) for s in seqs])
                self._train_pairs(flat, rows, lr)
            if self.train_words:
                centers, contexts = self._pairs_for_chunk(seqs)
                if len(centers):
                    self._train_pairs(centers, contexts, lr)
            self.words_processed += sum(len(s) for s in seqs)

    def _bags_with_docs(self, seqs, doc_rows, rng=None):
        """_bags_for_chunk plus the originating doc row per surviving center.
        ``rng`` defaults to the model RNG; inference passes a seed-local one
        so infer_vector never advances (or depends on) model state."""
        rng = rng if rng is not None else self._rng
        flat = np.concatenate(seqs)
        sid = np.repeat(np.arange(len(seqs)), [len(s) for s in seqs])
        flat, sid = self._subsample(flat, sid)
        n = len(flat)
        w = self.window_size
        if n < 1:
            return (np.zeros(0, np.int64), np.zeros((0, 2 * w), np.int64),
                    np.zeros((0, 2 * w), np.float32), np.zeros(0, np.int64))
        r = rng.integers(1, w + 1, n)
        bags = np.zeros((n, 2 * w), np.int64)
        mask = np.zeros((n, 2 * w), np.float32)
        col = 0
        for d in range(1, w + 1):
            for sign in (-1, 1):
                src = np.arange(n) + sign * d
                ok = (src >= 0) & (src < n)
                ok[ok] &= sid[src[ok]] == sid[ok.nonzero()[0]]
                ok &= d <= r
                bags[ok, col] = flat[src[ok]]
                mask[ok, col] = 1.0
                col += 1
        # unlike plain CBOW, a bag may be empty: the doc row still predicts
        return flat, bags, mask, doc_rows[sid]

    # ------------------------------------------------------------- inference
    def infer_vector(self, text: str, learning_rate: Optional[float] = None,
                     iterations: int = 30, seed: int = 0) -> np.ndarray:
        """Train a fresh doc vector against the frozen model (reference
        ParagraphVectors.inferVector). Negative-sampling models only — the
        reference's HS path would need a dedicated frozen-HS kernel."""
        if self.syn0 is None:
            raise ValueError("model is not trained")
        if self.negative <= 0:
            raise NotImplementedError(
                "infer_vector requires negative sampling (negative > 0)")
        widx = {vw.word: vw.index for vw in self.vocab.vocab_words()}
        tokens = self.tokenizer_factory.create(text).get_tokens()
        idx = np.asarray([widx[t] for t in tokens if t in widx], np.int64)
        D = self.syn0.shape[1]
        rng = np.random.default_rng(seed)
        docvec = ((rng.random(D, np.float32) - 0.5) / D)
        if len(idx) == 0:
            return docvec
        lr = np.float32(learning_rate if learning_rate is not None
                        else self.learning_rate)
        b = self.batch_size
        syn0 = np.asarray(self.syn0)
        syn1 = np.asarray(self.syn1)
        if self.dm:
            # build bags once without subsampling (inference is deterministic
            # modulo the seed; subsampling is a training-time regularizer)
            sampling, self.sampling = self.sampling, 0.0
            try:
                centers, bags, bmask, _ = self._bags_with_docs(
                    [idx], np.zeros(1, np.int64), rng=rng)
            finally:
                self.sampling = sampling
        else:
            centers = idx
        for _ in range(iterations):
            for s in range(0, len(centers), b):
                ce, wmask = self._pad(centers[s:s + b], b)
                if wmask is None:
                    wmask = np.ones(b, np.float32)
                negs = self._neg_table[rng.integers(
                    0, len(self._neg_table), (b, self.negative))].astype(np.int32)
                if self.dm:
                    bg, _ = self._pad(bags[s:s + b], b)
                    bm, _ = self._pad(bmask[s:s + b], b)
                    docvec, _ = kernels.cbow_infer_step(
                        docvec, syn0, syn1, ce.astype(np.int32),
                        bg.astype(np.int32), bm.astype(np.float32),
                        negs, wmask, lr)
                else:
                    docvec, _ = kernels.sgns_infer_step(
                        docvec, syn1, ce.astype(np.int32), negs, wmask, lr)
        return np.asarray(docvec)

    # ------------------------------------------------------------- accessors
    def doc_vector(self, label: str) -> Optional[np.ndarray]:
        i = self.label_index.get(label)
        if i is None or self.syn0 is None:
            return None
        return np.asarray(self.syn0[self.vocab.num_words() + i])

    def labels(self) -> List[str]:
        return sorted(self.label_index, key=self.label_index.get)

    def get_word_vector_matrix(self) -> np.ndarray:
        return np.asarray(self.syn0[: self.vocab.num_words()])

    def similarity_to_label(self, text: str, label: str) -> float:
        """Cosine between an inferred vector for ``text`` and a trained doc
        vector (reference predict/similarityToLabel)."""
        v = self.infer_vector(text)
        d = self.doc_vector(label)
        if d is None:
            return float("nan")
        denom = (np.linalg.norm(v) * np.linalg.norm(d)) or 1e-12
        return float(v @ d / denom)

    def predict(self, text: str) -> Optional[str]:
        """Most similar label for a text (reference predict)."""
        if not self.label_index:
            return None
        v = self.infer_vector(text)
        V = self.vocab.num_words()
        docs = np.asarray(self.syn0[V:])
        norms = np.linalg.norm(docs, axis=1) * (np.linalg.norm(v) or 1e-12)
        sims = (docs @ v) / np.maximum(norms, 1e-12)
        return self.labels()[int(np.argmax(sims))]
