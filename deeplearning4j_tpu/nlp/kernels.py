"""Jitted embedding-training kernels.

Parity surface: reference ``models/embeddings/learning/impl/elements/
SkipGram.java:156-283`` (learnSequence -> batched native sg op) and
``CBOW.java`` — there the math lives in libnd4j's custom sg/cbow CUDA/C++
kernels; here each step is ONE XLA program: gathers, closed-form SGNS/HS
gradients, and scatter-adds (``.at[].add``) that XLA lowers to efficient TPU
scatters. Duplicate indices within a batch accumulate, matching the
sequential semantics of the reference's hogwild updates in expectation.

All steps donate the embedding tables: no copies in the hot loop, HBM-bandwidth
friendly.

Stability note: the reference applies pair updates *sequentially* (hogwild
host threads), so each touch of a row moves it by at most ~lr. A naive
batched scatter-ADD instead sums the gradients of every duplicate index in
the batch — with a small vocab (or very frequent words) that multiplies the
effective step by the duplicate count and diverges. The TPU-native answer
here is a count-normalized scatter (scatter-mean per destination row): each
row moves by lr times the *average* gradient of the pairs touching it, which
matches the sequential semantics in expectation and is unconditionally
stable."""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

_EPS = 1e-7

# TPU scatters serialize row-by-row (profiled ~13x slower than expressing
# the same segment-sum as a one-hot matmul on the MXU). The matmul path
# materializes a transient (N, V) bf16 one-hot, so it is gated on memory;
# above the budget (huge vocab x batch) the scatter path remains.
_ONEHOT_BYTES_LIMIT = int(os.environ.get("DL4J_TPU_ONEHOT_SCATTER_BYTES",
                                         2 * 1024**3))


def _scatter_mean_update(table, idx, grads, weights, lr):
    """table += lr * segment_mean(grads over idx).

    idx (N,) int32 destination rows, grads (N, D), weights (N,) 0/1 validity.
    Rows untouched in this batch keep count 0 and receive no update. The
    count vector is a cheap scalar scatter; the (V, D) accumulation uses the
    one-hot-matmul MXU path when the transient one-hot fits the budget."""
    V = table.shape[0]
    n = idx.shape[0]
    # the matmul rewrite only pays where scatters are slow (TPU); CPU keeps
    # the exact fp32 scatter (cheap there, and no bf16 rounding)
    if jax.default_backend() == "tpu":
        if n * V * 2 <= _ONEHOT_BYTES_LIMIT:
            oh = jax.nn.one_hot(idx, V, dtype=jnp.bfloat16)
            # counts ride the SAME matmul as a trailing all-ones column
            # (a scalar .at[].add count scatter serializes row-by-row on
            # TPU and dominated this step's profile); f32 accumulator
            # output is free on the MXU and avoids rounding the (V, D)
            # update to bf16 before it lands in the f32 table
            rhs = jnp.concatenate(
                [(grads * weights[:, None]).astype(jnp.bfloat16),
                 weights[:, None].astype(jnp.bfloat16)], axis=1)
            acc = jnp.matmul(oh.T, rhs, preferred_element_type=jnp.float32)
            upd = acc[:, :-1] / jnp.maximum(acc[:, -1:], 1.0)
            return table + lr * upd.astype(table.dtype)
    cnt = jnp.zeros((V,), table.dtype).at[idx].add(weights)
    scale = (weights / jnp.maximum(cnt, 1.0)[idx])[:, None]
    if jax.default_backend() == "tpu":
        from deeplearning4j_tpu.nlp import pallas_scatter
        if pallas_scatter.fits_vmem(table):
            # above the one-hot gate but table fits VMEM: the Pallas kernel
            # (~1.6x XLA scatter), exact fp32
            return pallas_scatter.scatter_add_pallas(table, idx,
                                                     lr * grads * scale)
    return table.at[idx].add(lr * grads * scale)


@functools.partial(jax.jit, donate_argnums=(0, 1))
def sgns_step(syn0, syn1neg, centers, contexts, negs, wmask, lr):
    """Skip-gram negative sampling.

    syn0 (V, D) input vectors; syn1neg (V, D) output vectors;
    centers/contexts (B,) int32; negs (B, K) int32; wmask (B,) 1/0 padding
    mask (ragged final batches pad to the compiled batch size); lr scalar.

    word2vec convention (and the reference's SkipGram op): the *context*
    word's input vector is trained against the *center* word's output path.
    Callers pass (centers, contexts) as generated; the symmetric pairing
    means either orientation converges identically.
    """
    v = syn0[contexts]                                   # (B, D)
    u_pos = syn1neg[centers]                             # (B, D)
    u_neg = syn1neg[negs]                                # (B, K, D)
    s_pos = jax.nn.sigmoid(jnp.sum(v * u_pos, axis=-1))  # (B,)
    s_neg = jax.nn.sigmoid(jnp.einsum("bd,bkd->bk", v, u_neg))
    g_pos = (1.0 - s_pos) * wmask                        # label 1
    g_neg = -s_neg * wmask[:, None]                      # label 0
    dv = g_pos[:, None] * u_pos + jnp.einsum("bk,bkd->bd", g_neg, u_neg)
    du_pos = g_pos[:, None] * v
    du_neg = g_neg[..., None] * v[:, None, :]
    B, K = negs.shape
    D = v.shape[-1]
    syn0 = _scatter_mean_update(syn0, contexts, dv, wmask, lr)
    # centers and negatives both land in syn1neg: one joint normalized scatter
    out_idx = jnp.concatenate([centers, negs.reshape(-1)])
    out_grads = jnp.concatenate([du_pos, du_neg.reshape(B * K, D)])
    out_w = jnp.concatenate([wmask, jnp.repeat(wmask, K)])
    syn1neg = _scatter_mean_update(syn1neg, out_idx, out_grads, out_w, lr)
    nll = -(jnp.log(s_pos + _EPS) + jnp.sum(jnp.log(1.0 - s_neg + _EPS), axis=-1))
    loss = jnp.sum(nll * wmask) / jnp.maximum(jnp.sum(wmask), 1.0)
    return syn0, syn1neg, loss


@functools.partial(jax.jit, donate_argnums=(0, 1))
def hs_step(syn0, syn1, contexts, codes, points, lengths, lr):
    """Skip-gram hierarchical softmax.

    codes/points (B, L) per-pair Huffman path of the center word, lengths (B,)
    valid path length. The ragged walk of the reference
    (SkipGram.java inner loop over vocabWord.getPoints()) becomes a masked
    dense (B, L, D) computation."""
    v = syn0[contexts]                                   # (B, D)
    u = syn1[points]                                     # (B, L, D)
    B, L = codes.shape
    # padding rows carry lengths=0, so the path mask doubles as batch mask
    mask = (jnp.arange(L)[None, :] < lengths[:, None]).astype(v.dtype)
    s = jax.nn.sigmoid(jnp.einsum("bd,bld->bl", v, u))   # (B, L)
    g = (1.0 - codes.astype(v.dtype) - s) * mask         # word2vec: 1 - code - sigma
    dv = jnp.einsum("bl,bld->bd", g, u)
    du = g[..., None] * v[:, None, :]
    D = v.shape[-1]
    valid = (lengths > 0).astype(v.dtype)
    syn0 = _scatter_mean_update(syn0, contexts, dv, valid, lr)
    syn1 = _scatter_mean_update(syn1, points.reshape(-1),
                                du.reshape(B * L, D), mask.reshape(-1), lr)
    # masked binary cross-entropy along the path
    target = 1.0 - codes.astype(v.dtype)
    bce = -(target * jnp.log(s + _EPS) + (1.0 - target) * jnp.log(1.0 - s + _EPS))
    loss = jnp.sum(bce * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return syn0, syn1, loss


@functools.partial(jax.jit, donate_argnums=(0, 1))
def cbow_step(syn0, syn1neg, centers, context_bags, bag_mask, negs, wmask, lr):
    """CBOW with negative sampling (reference CBOW.java).

    context_bags (B, W) int32 context indices (padded), bag_mask (B, W) 1/0,
    centers (B,), negs (B, K), wmask (B,) batch padding mask. The bag mean
    predicts the center."""
    bags = syn0[context_bags]                             # (B, W, D)
    m = bag_mask[..., None]
    denom = jnp.maximum(jnp.sum(bag_mask, axis=-1, keepdims=True), 1.0)
    h = jnp.sum(bags * m, axis=1) / denom                 # (B, D) bag mean
    u_pos = syn1neg[centers]
    u_neg = syn1neg[negs]
    s_pos = jax.nn.sigmoid(jnp.sum(h * u_pos, axis=-1))
    s_neg = jax.nn.sigmoid(jnp.einsum("bd,bkd->bk", h, u_neg))
    g_pos = (1.0 - s_pos) * wmask
    g_neg = -s_neg * wmask[:, None]
    dh = g_pos[:, None] * u_pos + jnp.einsum("bk,bkd->bd", g_neg, u_neg)
    du_pos = g_pos[:, None] * h
    du_neg = g_neg[..., None] * h[:, None, :]
    B, K = negs.shape
    D = h.shape[-1]
    W = context_bags.shape[1]
    # distribute the bag gradient equally to members (mean => /count)
    dbag = (dh[:, None, :] * m) / denom[..., None]        # (B, W, D)
    bag_w = (bag_mask * wmask[:, None]).reshape(-1)
    syn0 = _scatter_mean_update(syn0, context_bags.reshape(-1),
                                dbag.reshape(B * W, D), bag_w, lr)
    out_idx = jnp.concatenate([centers, negs.reshape(-1)])
    out_grads = jnp.concatenate([du_pos, du_neg.reshape(B * K, D)])
    out_w = jnp.concatenate([wmask, jnp.repeat(wmask, K)])
    syn1neg = _scatter_mean_update(syn1neg, out_idx, out_grads, out_w, lr)
    nll = -(jnp.log(s_pos + _EPS) + jnp.sum(jnp.log(1.0 - s_neg + _EPS), axis=-1))
    loss = jnp.sum(nll * wmask) / jnp.maximum(jnp.sum(wmask), 1.0)
    return syn0, syn1neg, loss


@functools.partial(jax.jit, donate_argnums=(0, 1))
def cbow_hs_step(syn0, syn1, centers_codes, centers_points, centers_lengths,
                 context_bags, bag_mask, lr):
    """CBOW with hierarchical softmax (reference CBOW.java's HS branch):
    the context-bag mean walks the *center* word's Huffman path.

    centers_codes/points (B, L), centers_lengths (B,) — padded batch rows
    carry lengths=0 so the path mask doubles as the batch mask (as in
    hs_step). context_bags (B, W) int32, bag_mask (B, W)."""
    bags = syn0[context_bags]                             # (B, W, D)
    m = bag_mask[..., None]
    denom = jnp.maximum(jnp.sum(bag_mask, axis=-1, keepdims=True), 1.0)
    h = jnp.sum(bags * m, axis=1) / denom                 # (B, D)
    u = syn1[centers_points]                              # (B, L, D)
    B, L = centers_codes.shape
    mask = (jnp.arange(L)[None, :] < centers_lengths[:, None]).astype(h.dtype)
    s = jax.nn.sigmoid(jnp.einsum("bd,bld->bl", h, u))
    g = (1.0 - centers_codes.astype(h.dtype) - s) * mask
    dh = jnp.einsum("bl,bld->bd", g, u)
    du = g[..., None] * h[:, None, :]
    D = h.shape[-1]
    W = context_bags.shape[1]
    dbag = (dh[:, None, :] * m) / denom[..., None]
    valid = (centers_lengths > 0).astype(h.dtype)
    bag_w = (bag_mask * valid[:, None]).reshape(-1)
    syn0 = _scatter_mean_update(syn0, context_bags.reshape(-1),
                                dbag.reshape(B * W, D), bag_w, lr)
    syn1 = _scatter_mean_update(syn1, centers_points.reshape(-1),
                                du.reshape(B * L, D), mask.reshape(-1), lr)
    target = 1.0 - centers_codes.astype(h.dtype)
    bce = -(target * jnp.log(s + _EPS) + (1.0 - target) * jnp.log(1.0 - s + _EPS))
    loss = jnp.sum(bce * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return syn0, syn1, loss


@functools.partial(jax.jit, donate_argnums=(0,))
def sgns_infer_step(docvec, syn1neg, centers, negs, wmask, lr):
    """DBOW inference step (reference ParagraphVectors.inferVector): a single
    frozen-everything-else SGNS pass where only the document vector trains.

    docvec (D,); centers (B,) words of the document; negs (B, K)."""
    u_pos = syn1neg[centers]                              # (B, D)
    u_neg = syn1neg[negs]                                 # (B, K, D)
    s_pos = jax.nn.sigmoid(u_pos @ docvec)                # (B,)
    s_neg = jax.nn.sigmoid(jnp.einsum("bkd,d->bk", u_neg, docvec))
    g_pos = (1.0 - s_pos) * wmask
    g_neg = -s_neg * wmask[:, None]
    dv = jnp.einsum("b,bd->d", g_pos, u_pos) + \
        jnp.einsum("bk,bkd->d", g_neg, u_neg)
    docvec = docvec + lr * dv / jnp.maximum(jnp.sum(wmask), 1.0)
    nll = -(jnp.log(s_pos + _EPS) + jnp.sum(jnp.log(1.0 - s_neg + _EPS), axis=-1))
    loss = jnp.sum(nll * wmask) / jnp.maximum(jnp.sum(wmask), 1.0)
    return docvec, loss


@functools.partial(jax.jit, donate_argnums=(0,))
def cbow_infer_step(docvec, syn0, syn1neg, centers, context_bags, bag_mask,
                    negs, wmask, lr):
    """DM inference step: the doc vector joins each context bag (frozen word
    vectors), gradient flows to the doc vector only."""
    bags = syn0[context_bags]                             # (B, W, D)
    m = bag_mask[..., None]
    count = jnp.sum(bag_mask, axis=-1, keepdims=True) + 1.0   # + doc vector
    h = (jnp.sum(bags * m, axis=1) + docvec[None, :]) / count
    u_pos = syn1neg[centers]
    u_neg = syn1neg[negs]
    s_pos = jax.nn.sigmoid(jnp.sum(h * u_pos, axis=-1))
    s_neg = jax.nn.sigmoid(jnp.einsum("bd,bkd->bk", h, u_neg))
    g_pos = (1.0 - s_pos) * wmask
    g_neg = -s_neg * wmask[:, None]
    dh = g_pos[:, None] * u_pos + jnp.einsum("bk,bkd->bd", g_neg, u_neg)
    dv = jnp.sum(dh / count, axis=0)                      # doc's share of each bag
    docvec = docvec + lr * dv / jnp.maximum(jnp.sum(wmask), 1.0)
    nll = -(jnp.log(s_pos + _EPS) + jnp.sum(jnp.log(1.0 - s_neg + _EPS), axis=-1))
    loss = jnp.sum(nll * wmask) / jnp.maximum(jnp.sum(wmask), 1.0)
    return docvec, loss


@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5, 6, 7))
def glove_step(w, wc, b, bc, gw, gwc, gb, gbc, rows, cols, logx, weight, lr):
    """AdaGrad step on the GloVe objective (reference glove/Glove.java +
    legacy GloVe.java AdaGrad math): f(x) * (w_i·wc_j + b_i + bc_j - log x)^2.

    w/wc (V, D) main/context vectors, b/bc (V,) biases, g* AdaGrad
    accumulators, rows/cols (B,) co-occurrence pair indices, logx (B,)
    log co-occurrence, weight (B,) f(x)."""
    wi = w[rows]
    wj = wc[cols]
    diff = jnp.sum(wi * wj, axis=-1) + b[rows] + bc[cols] - logx   # (B,)
    fdiff = weight * diff
    loss = 0.5 * jnp.mean(fdiff * diff)
    dwi = fdiff[:, None] * wj
    dwj = fdiff[:, None] * wi
    # AdaGrad: accumulate squared grads, scale updates
    gw = gw.at[rows].add(dwi * dwi)
    gwc = gwc.at[cols].add(dwj * dwj)
    gb = gb.at[rows].add(fdiff * fdiff)
    gbc = gbc.at[cols].add(fdiff * fdiff)
    w = w.at[rows].add(-lr * dwi / jnp.sqrt(gw[rows] + _EPS))
    wc = wc.at[cols].add(-lr * dwj / jnp.sqrt(gwc[cols] + _EPS))
    b = b.at[rows].add(-lr * fdiff / jnp.sqrt(gb[rows] + _EPS))
    bc = bc.at[cols].add(-lr * fdiff / jnp.sqrt(gbc[cols] + _EPS))
    return w, wc, b, bc, gw, gwc, gb, gbc, loss


# ---------------------------------------------------------------------------
# Whole-chunk scanned steps: ONE dispatch for a stack of (num_batches, B)
# slices. NOT used by the SequenceVectors training loops — measured on the
# v5e tunnel, per-batch dispatch wins because it overlaps host pair/negative
# prep with device compute, while the scan serializes them. Kept as a
# parity-tested alternative for environments where dispatch latency
# dominates (e.g. extreme RPC latency and precomputed batches). The
# underlying (unjitted) step bodies are reused via .__wrapped__ so the math
# stays defined once.

def _scanned(step_fn, num_tables=2):
    def scan_fn(*args):
        tables = args[:num_tables]
        batches = args[num_tables:-1]
        lr = args[-1]

        def body(carry, inp):
            out = step_fn(*carry, *inp, lr)
            return out[:num_tables], out[num_tables]

        tables, losses = jax.lax.scan(body, tables, batches)
        return (*tables, losses)

    return functools.partial(jax.jit, donate_argnums=tuple(range(num_tables)))(scan_fn)


sgns_scan = _scanned(sgns_step.__wrapped__)
hs_scan = _scanned(hs_step.__wrapped__)
cbow_scan = _scanned(cbow_step.__wrapped__)
cbow_hs_scan = _scanned(cbow_hs_step.__wrapped__)


# ---------------------------------------------------------------------------
# Macro-dispatch SGNS: one XLA program trains a whole (NB, B) stack of pair
# batches with negatives drawn ON DEVICE from the unigram table. Motivation
# (measured on the v5e tunnel): host->device bandwidth is ~16-38 MB/s and
# per-dispatch overhead ~2.5 ms, so shipping (B, K) negatives per batch and
# dispatching per batch made the r3 word2vec bench transfer-bound. Here the
# host ships only the packed pair indices (int16 when the vocab allows) and
# the device does the rest: ~7x less H2D traffic and NB fewer dispatches.

_sgns_macro_cache = {}


def sgns_macro_step(K: int):
    """Returns the jitted macro step for K negatives (cached per K)."""
    fn = _sgns_macro_cache.get(K)
    if fn is not None:
        return fn

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def run(syn0, syn1neg, neg_table, centers, contexts, key, lr):
        centers = centers.astype(jnp.int32)
        contexts = contexts.astype(jnp.int32)
        B = centers.shape[1]
        wm = jnp.ones((B,), syn0.dtype)
        T = neg_table.shape[0]

        def body(carry, inp):
            s0, s1, k = carry
            ce, ct = inp
            k, k2 = jax.random.split(k)
            negs = neg_table[jax.random.randint(k2, (B, K), 0, T)]
            s0, s1, loss = sgns_step.__wrapped__(s0, s1, ce, ct, negs, wm, lr)
            return (s0, s1, k), loss

        (syn0, syn1neg, _), losses = jax.lax.scan(
            body, (syn0, syn1neg, key), (centers, contexts))
        return syn0, syn1neg, losses

    _sgns_macro_cache[K] = run
    return run


# ---------------------------------------------------------------------------
# Corpus-resident SGNS: the encoded corpus lives in HBM and the device
# generates (center, context) pairs AND negatives itself — per macro-step the
# host ships only a PRNG key and the lr scalar, so throughput is completely
# independent of host->device bandwidth (the r4 path still shipped int16
# pair batches through a ~16-38 MB/s tunnel).
#
# Pair distribution matches the host enumeration exactly: the reference
# (SkipGram.java:156) visits every position with a dynamic radius
# r ~ U[1, w] and trains all offsets d <= r on both sides, so offset d
# occurs with probability (w - d + 1)/w per side per position. Here each
# sampled pair draws (position ~ U[corpus], side ~ ±1, d ~ P(d) ∝ w-d+1)
# — the same joint distribution, sampled i.i.d. instead of enumerated; an
# epoch processes T*(w+1) pairs, the enumeration's expected pair count.
#
# Negatives are SHARED per micro-batch (K rows serve all B pairs): their
# accumulation then becomes a dense (K, B) x (B, D) matmul instead of a
# B*K-row scatter, which removes ~85% of the scatter-matmul FLOPs. Sharing
# negatives across a minibatch is the standard batched-word2vec design
# (Ji et al. 2016, "Parallelizing Word2Vec in Shared and Distributed
# Memory"); with count-normalized updates it matches the per-pair-negative
# path on every embedding-quality test in tests/test_nlp.py.

_sgns_corpus_cache = {}


def sgns_corpus_macro_step(K: int, W: int, B: int, NB: int):
    """Jitted macro step: NB on-device-generated batches of B pairs, K
    shared negatives per batch, window w=W. Cached per static config.
    The corpus operand may be sentinel-padded (sid=-1) to a canonical
    length; the true token count and the active-batch quota arrive as
    device scalars (``true_t``, ``n_active``), so one compiled program
    serves every segment length up to the padding budget."""
    key_ = (K, W, B, NB)
    fn = _sgns_corpus_cache.get(key_)
    if fn is not None:
        return fn

    import numpy as np
    # inverse-CDF table for P(d) ∝ (W - d + 1), d in 1..W
    wts = np.arange(W, 0, -1, dtype=np.int64)
    cum = np.cumsum(wts)
    total = int(cum[-1])
    dist_cdf = jnp.asarray(cum, jnp.int32)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def run(syn0, syn1neg, corpus, sid, neg_table, keep, key, lr, true_t,
            n_active):
        # corpus/sid may be PADDED to the segment budget so every segment
        # length compiles the same program; ``true_t`` (device scalar) is
        # the real token count — position sampling and validity use it, so
        # the sentinel padding (sid = -1) is never sampled or paired.
        # ``n_active`` (device scalar) masks trailing batches beyond the
        # segment's pair quota: NB stays static (one compiled scan) while
        # the trained pair count still tracks the true T.
        Tpad = corpus.shape[0]
        TT = neg_table.shape[0]
        true_t = jnp.asarray(true_t, jnp.int32)
        n_active = jnp.asarray(n_active, jnp.int32)

        def body(carry, inp):
            s0, s1 = carry
            k, bi = inp
            kp, kd, kside, kneg, kkeep = jax.random.split(k, 5)
            pos = jax.random.randint(kp, (B,), 0, true_t)
            d = 1 + jnp.searchsorted(
                dist_cdf, jax.random.randint(kd, (B,), 0, total),
                side="right").astype(jnp.int32)
            side = jnp.where(jax.random.bernoulli(kside, 0.5, (B,)), 1, -1)
            cpos = pos + side * d
            valid = (cpos >= 0) & (cpos < true_t) & (bi < n_active)
            cposc = jnp.clip(cpos, 0, Tpad - 1)
            valid &= sid[pos] == sid[cposc]
            # corpus/sid may ship int16 (halved tunnel upload); index math
            # in int32
            centers = corpus[pos].astype(jnp.int32)
            contexts = corpus[cposc].astype(jnp.int32)
            if keep is not None:
                # APPROXIMATE subsampling: drops pairs whose endpoints fail
                # the keep draw. The host path removes words from the
                # stream BEFORE pairing (windows then reach across dropped
                # words) — reference semantics. Close in expectation, not
                # identical; the auto gate in SequenceVectors.fit therefore
                # keeps sampling>0 configs on the host path unless
                # device_corpus=True is explicit.
                k1, k2 = jax.random.split(kkeep)
                valid &= jax.random.bernoulli(k1, keep[centers])
                valid &= jax.random.bernoulli(k2, keep[contexts])
            wmask = valid.astype(s0.dtype)
            negs = neg_table[jax.random.randint(kneg, (K,), 0, TT)]

            # SGNS with shared negatives (same convention as sgns_step:
            # context word's input vector vs center word's output path)
            v = s0[contexts]                                  # (B, D)
            u_pos = s1[centers]                               # (B, D)
            u_neg = s1[negs]                                  # (K, D)
            s_pos = jax.nn.sigmoid(jnp.sum(v * u_pos, -1))    # (B,)
            s_neg = jax.nn.sigmoid(v @ u_neg.T)               # (B, K)
            g_pos = (1.0 - s_pos) * wmask
            g_neg = -s_neg * wmask[:, None]
            dv = g_pos[:, None] * u_pos + g_neg @ u_neg
            du_pos = g_pos[:, None] * v
            s0 = _scatter_mean_update(s0, contexts, dv, wmask, lr)
            s1 = _scatter_mean_update(s1, centers, du_pos, wmask, lr)
            # shared negatives: dense accumulation, count = #valid pairs
            npairs = jnp.maximum(jnp.sum(wmask), 1.0)
            s1 = s1.at[negs].add(lr * (g_neg.T @ v) / npairs)
            nll = -(jnp.log(s_pos + _EPS)
                    + jnp.sum(jnp.log(1.0 - s_neg + _EPS), -1))
            loss = jnp.sum(nll * wmask) / npairs
            return (s0, s1), loss

        keys = jax.random.split(key, NB)
        (syn0, syn1neg), losses = jax.lax.scan(
            body, (syn0, syn1neg), (keys, jnp.arange(NB, dtype=jnp.int32)))
        return syn0, syn1neg, losses

    _sgns_corpus_cache[key_] = run
    return run
