"""NLP embeddings stack (reference deeplearning4j-nlp-parent, 56.4k LoC).

TPU-native redesign of the SequenceVectors/Word2Vec family: the reference's
lock-free multithreaded host SGD (SkipGram.java:156 batching into native sg/
cbow kernels) becomes batched device steps — windows are vectorized host-side
into (center, context) index batches, and one jitted XLA program does the
negative-sampling/hierarchical-softmax math with scatter-add updates
(SURVEY §7 step 8's segment-sum design).

CJK tokenization: ``nlp/cjk.py`` ships working Chinese (dictionary FMM),
Japanese (script-class + particle segmentation) and Korean (eojeol + josa
stripping) tokenizer factories behind the same SPI, so zh/ja/ko corpora
train end-to-end out of the box. They are lightweight equivalents of the
reference's bundled stacks (deeplearning4j-nlp-chinese ansj wrapper,
deeplearning4j-nlp-japanese kuromoji fork, deeplearning4j-nlp-korean);
a user who wants full morphological analysis can still register a factory
wrapping any Python analyzer (e.g. fugashi/konlpy) — the downstream
trainers (SequenceVectors/Word2Vec/ParagraphVectors/TF-IDF) are
tokenizer-agnostic. The UIMA adapter stack (deeplearning4j-nlp-uima ~14k
LoC, Apache-UIMA JVM SPI binding) remains scoped out as a JVM-ecosystem
integration with no Python-side equivalent surface.
"""

from deeplearning4j_tpu.nlp.tokenization import (
    CommonPreprocessor,
    DefaultTokenizerFactory,
    NGramTokenizerFactory,
    TokenizerFactory,
)
from deeplearning4j_tpu.nlp.cjk import (
    ChineseTokenizerFactory,
    JapaneseTokenizerFactory,
    KoreanTokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import AbstractCache, VocabConstructor, VocabWord
from deeplearning4j_tpu.nlp.sentenceiterator import (
    BasicLineIterator,
    CollectionSentenceIterator,
    LabelAwareIterator,
    LabelledDocument,
    SentenceIterator,
    SimpleLabelAwareIterator,
)
from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors
from deeplearning4j_tpu.nlp.word2vec import Word2Vec
from deeplearning4j_tpu.nlp.cnn_sentence import (
    CnnSentenceDataSetIterator, CollectionLabeledSentenceProvider,
)
from deeplearning4j_tpu.nlp.paragraphvectors import ParagraphVectors
from deeplearning4j_tpu.nlp.glove import Glove
from deeplearning4j_tpu.nlp.serializer import StaticWordVectors, WordVectorSerializer
from deeplearning4j_tpu.nlp.vectorizers import (
    BagOfWordsVectorizer,
    BaseTextVectorizer,
    TfidfVectorizer,
)

__all__ = [
    "AbstractCache",
    "BagOfWordsVectorizer",
    "BaseTextVectorizer",
    "BasicLineIterator",
    "ChineseTokenizerFactory",
    "JapaneseTokenizerFactory",
    "KoreanTokenizerFactory",
    "CollectionLabeledSentenceProvider",
    "CollectionSentenceIterator",
    "CnnSentenceDataSetIterator",
    "CommonPreprocessor",
    "DefaultTokenizerFactory",
    "Glove",
    "LabelAwareIterator",
    "LabelledDocument",
    "NGramTokenizerFactory",
    "ParagraphVectors",
    "SentenceIterator",
    "SequenceVectors",
    "TfidfVectorizer",
    "SimpleLabelAwareIterator",
    "StaticWordVectors",
    "TokenizerFactory",
    "VocabConstructor",
    "VocabWord",
    "Word2Vec",
    "WordVectorSerializer",
]
