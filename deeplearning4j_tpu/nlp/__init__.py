"""NLP embeddings stack (reference deeplearning4j-nlp-parent, 56.4k LoC).

TPU-native redesign of the SequenceVectors/Word2Vec family: the reference's
lock-free multithreaded host SGD (SkipGram.java:156 batching into native sg/
cbow kernels) becomes batched device steps — windows are vectorized host-side
into (center, context) index batches, and one jitted XLA program does the
negative-sampling/hierarchical-softmax math with scatter-add updates
(SURVEY §7 step 8's segment-sum design).

Scope decision — UIMA + CJK tokenizer stacks
(deeplearning4j-nlp-uima ~14k LoC, deeplearning4j-nlp-japanese/korean ~9k):
NOT replicated. Those modules are thin adapters binding Apache UIMA's
analysis-engine SPI and the Kuromoji/Arirang analyzers — JVM-ecosystem
integrations, not model capability. The ``TokenizerFactory`` SPI here
(nlp/tokenization.py) is the extension point they would plug into: a user
needing CJK segmentation registers a factory wrapping any Python tokenizer
(e.g. fugashi/konlpy) with identical downstream behavior. Everything the
reference *trains* with those tokens (SequenceVectors/Word2Vec/
ParagraphVectors/TF-IDF) is implemented and tokenizer-agnostic.
"""

from deeplearning4j_tpu.nlp.tokenization import (
    CommonPreprocessor,
    DefaultTokenizerFactory,
    NGramTokenizerFactory,
    TokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import AbstractCache, VocabConstructor, VocabWord
from deeplearning4j_tpu.nlp.sentenceiterator import (
    BasicLineIterator,
    CollectionSentenceIterator,
    LabelAwareIterator,
    LabelledDocument,
    SentenceIterator,
    SimpleLabelAwareIterator,
)
from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors
from deeplearning4j_tpu.nlp.word2vec import Word2Vec
from deeplearning4j_tpu.nlp.cnn_sentence import (
    CnnSentenceDataSetIterator, CollectionLabeledSentenceProvider,
)
from deeplearning4j_tpu.nlp.paragraphvectors import ParagraphVectors
from deeplearning4j_tpu.nlp.glove import Glove
from deeplearning4j_tpu.nlp.serializer import StaticWordVectors, WordVectorSerializer
from deeplearning4j_tpu.nlp.vectorizers import (
    BagOfWordsVectorizer,
    BaseTextVectorizer,
    TfidfVectorizer,
)

__all__ = [
    "AbstractCache",
    "BagOfWordsVectorizer",
    "BaseTextVectorizer",
    "BasicLineIterator",
    "CollectionLabeledSentenceProvider",
    "CollectionSentenceIterator",
    "CnnSentenceDataSetIterator",
    "CommonPreprocessor",
    "DefaultTokenizerFactory",
    "Glove",
    "LabelAwareIterator",
    "LabelledDocument",
    "NGramTokenizerFactory",
    "ParagraphVectors",
    "SentenceIterator",
    "SequenceVectors",
    "TfidfVectorizer",
    "SimpleLabelAwareIterator",
    "StaticWordVectors",
    "TokenizerFactory",
    "VocabConstructor",
    "VocabWord",
    "Word2Vec",
    "WordVectorSerializer",
]
