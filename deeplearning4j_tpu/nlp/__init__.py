"""NLP embeddings stack (reference deeplearning4j-nlp-parent, 56.4k LoC).

TPU-native redesign of the SequenceVectors/Word2Vec family: the reference's
lock-free multithreaded host SGD (SkipGram.java:156 batching into native sg/
cbow kernels) becomes batched device steps — windows are vectorized host-side
into (center, context) index batches, and one jitted XLA program does the
negative-sampling/hierarchical-softmax math with scatter-add updates
(SURVEY §7 step 8's segment-sum design).
"""

from deeplearning4j_tpu.nlp.tokenization import (
    CommonPreprocessor,
    DefaultTokenizerFactory,
    NGramTokenizerFactory,
    TokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import AbstractCache, VocabConstructor, VocabWord
from deeplearning4j_tpu.nlp.sentenceiterator import (
    BasicLineIterator,
    CollectionSentenceIterator,
    LabelAwareIterator,
    LabelledDocument,
    SentenceIterator,
    SimpleLabelAwareIterator,
)
from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors
from deeplearning4j_tpu.nlp.word2vec import Word2Vec
from deeplearning4j_tpu.nlp.paragraphvectors import ParagraphVectors
from deeplearning4j_tpu.nlp.glove import Glove
from deeplearning4j_tpu.nlp.serializer import StaticWordVectors, WordVectorSerializer

__all__ = [
    "AbstractCache",
    "BasicLineIterator",
    "CollectionSentenceIterator",
    "CommonPreprocessor",
    "DefaultTokenizerFactory",
    "Glove",
    "LabelAwareIterator",
    "LabelledDocument",
    "NGramTokenizerFactory",
    "ParagraphVectors",
    "SentenceIterator",
    "SequenceVectors",
    "SimpleLabelAwareIterator",
    "StaticWordVectors",
    "TokenizerFactory",
    "VocabConstructor",
    "VocabWord",
    "Word2Vec",
    "WordVectorSerializer",
]
