"""Sentence / document iterators.

Parity surface: reference ``text/sentenceiterator/`` (SentenceIterator SPI,
BasicLineIterator, CollectionSentenceIterator, SentencePreProcessor) and
``text/documentiterator/`` (LabelledDocument, LabelAwareIterator,
LabelsSource) used by ParagraphVectors.

Pure host-side code. Iterators are restartable via ``reset()`` — the trainers
make multiple epochs over the corpus, mirroring the reference's
``iterator.reset()`` calls in SequenceVectors.fit."""

from __future__ import annotations

from typing import Callable, Iterable, List, Optional


class SentencePreProcessor:
    """reference sentenceiterator/SentencePreProcessor.java."""

    def pre_process(self, sentence: str) -> str:
        raise NotImplementedError


class SentenceIterator:
    """SPI (reference SentenceIterator.java): nextSentence/hasNext/reset."""

    def __init__(self, pre: Optional[SentencePreProcessor] = None):
        self._pre = pre

    def set_pre_processor(self, pre: SentencePreProcessor):
        self._pre = pre
        return self

    def _apply(self, s: str) -> str:
        return self._pre.pre_process(s) if self._pre is not None else s

    def reset(self):
        raise NotImplementedError

    def __iter__(self) -> Iterable[str]:
        raise NotImplementedError


class CollectionSentenceIterator(SentenceIterator):
    """In-memory list of sentences (reference CollectionSentenceIterator.java)."""

    def __init__(self, sentences: List[str],
                 pre: Optional[SentencePreProcessor] = None):
        super().__init__(pre)
        self._sentences = list(sentences)

    def reset(self):
        pass

    def __iter__(self):
        for s in self._sentences:
            yield self._apply(s)


class BasicLineIterator(SentenceIterator):
    """One sentence per line from a file (reference BasicLineIterator.java)."""

    def __init__(self, path: str, pre: Optional[SentencePreProcessor] = None,
                 encoding: str = "utf-8"):
        super().__init__(pre)
        self.path = path
        self.encoding = encoding

    def reset(self):
        pass

    def __iter__(self):
        with open(self.path, "r", encoding=self.encoding) as f:
            for line in f:
                line = line.rstrip("\n")
                if line:
                    yield self._apply(line)


class LabelledDocument:
    """reference documentiterator/LabelledDocument.java — content + labels."""

    def __init__(self, content: str, labels: Optional[List[str]] = None):
        self.content = content
        self.labels = list(labels or [])

    def __repr__(self):
        return f"LabelledDocument(labels={self.labels!r})"


class LabelAwareIterator:
    """SPI (reference documentiterator/LabelAwareIterator.java)."""

    def reset(self):
        raise NotImplementedError

    def __iter__(self) -> Iterable[LabelledDocument]:
        raise NotImplementedError


class SimpleLabelAwareIterator(LabelAwareIterator):
    """Wraps a list of LabelledDocuments (reference
    SimpleLabelAwareIterator.java)."""

    def __init__(self, documents: List[LabelledDocument]):
        self._docs = list(documents)

    def reset(self):
        pass

    def __iter__(self):
        return iter(self._docs)


class LabelAwareListSentenceIterator(LabelAwareIterator):
    """Sentences auto-labelled DOC_0, DOC_1, … (reference LabelsSource's
    generated labels + LabelAwareListSentenceIterator)."""

    def __init__(self, sentences: List[str], template: str = "DOC_%d"):
        self._docs = [LabelledDocument(s, [template % i])
                      for i, s in enumerate(sentences)]

    def reset(self):
        pass

    def __iter__(self):
        return iter(self._docs)
