"""Bag-of-words / TF-IDF text vectorizers.

Parity surface: reference
``deeplearning4j-nlp/.../bagofwords/vectorizer/BaseTextVectorizer.java``
(fit over an iterator: vocab + document frequencies),
``BagOfWordsVectorizer.java`` (transform -> raw count vector, vectorize ->
DataSet with one-hot label) and ``TfidfVectorizer.java:127``
(tfidfWord = (count/docLen) * log10(totalDocs/docFreq) — MathUtils.idf/tf).
"""

from __future__ import annotations

import math
from typing import Iterable, List, Optional, Sequence, Union

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nlp.sentenceiterator import LabelledDocument
from deeplearning4j_tpu.nlp.tokenization import (DefaultTokenizerFactory,
                                                 TokenizerFactory)
from deeplearning4j_tpu.nlp.vocab import AbstractCache, VocabWord

Corpus = Iterable[Union[str, LabelledDocument]]


class BaseTextVectorizer:
    """Shared fit machinery: vocabulary, document frequencies, labels."""

    def __init__(self, min_word_frequency: int = 1,
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 stop_words: Sequence[str] = ()):
        self.min_word_frequency = min_word_frequency
        self.tokenizer = tokenizer_factory or DefaultTokenizerFactory()
        self.stop_words = set(stop_words)
        self.vocab: Optional[AbstractCache] = None
        self.labels: List[str] = []
        self._doc_freq: dict = {}
        self.total_docs = 0

    def _tokens(self, text: str) -> List[str]:
        toks = self.tokenizer.create(text).get_tokens()
        return [t for t in toks if t and t not in self.stop_words]

    @staticmethod
    def _doc(doc) -> tuple:
        if isinstance(doc, LabelledDocument):
            return doc.content, list(doc.labels)
        return doc, []

    def fit(self, corpus: Corpus) -> "BaseTextVectorizer":
        """Build vocabulary + per-word document frequencies (reference
        BaseTextVectorizer.buildVocab)."""
        from collections import Counter
        self._doc_freq = {}  # re-fit replaces, never mixes, corpora stats
        self.total_docs = 0
        counts: Counter = Counter()
        labels = []
        for doc in corpus:
            text, doc_labels = self._doc(doc)
            for lab in doc_labels:
                if lab not in labels:
                    labels.append(lab)
            toks = self._tokens(text)
            counts.update(toks)
            for t in set(toks):
                self._doc_freq[t] = self._doc_freq.get(t, 0) + 1
            self.total_docs += 1
        cache = AbstractCache()
        for word, n in counts.items():
            if n >= self.min_word_frequency:
                cache.add_token(VocabWord(word, n))
        cache.finalize_vocab()
        self.vocab = cache
        self.labels = labels
        return self

    def vocab_size(self) -> int:
        return 0 if self.vocab is None else self.vocab.num_words()

    def index_of(self, word: str) -> int:
        return self.vocab.index_of(word)

    def _counts(self, text: str):
        counts = {}
        n_tokens = 0
        for t in self._tokens(text):
            n_tokens += 1
            if self.vocab.contains_word(t):
                counts[t] = counts.get(t, 0) + 1
        return counts, n_tokens

    def transform(self, text: str) -> np.ndarray:
        raise NotImplementedError

    def vectorize(self, text: str, label: str) -> DataSet:
        """(features, one-hot label) pair (reference vectorize(String,String))."""
        x = self.transform(text).reshape(1, -1)
        y = np.zeros((1, max(len(self.labels), 1)), np.float32)
        if label in self.labels:
            y[0, self.labels.index(label)] = 1.0
        return DataSet(x, y)

    def fit_transform(self, corpus: Sequence) -> np.ndarray:
        self.fit(corpus)
        return np.stack([self.transform(self._doc(d)[0]) for d in corpus])


class BagOfWordsVectorizer(BaseTextVectorizer):
    """Raw term-count vectors (reference BagOfWordsVectorizer.java:76)."""

    def transform(self, text: str) -> np.ndarray:
        out = np.zeros(self.vocab_size(), np.float32)
        counts, _ = self._counts(text)
        for word, c in counts.items():
            out[self.vocab.index_of(word)] = c
        return out


class TfidfVectorizer(BaseTextVectorizer):
    """TF-IDF vectors (reference TfidfVectorizer.java:127):
    tf = count/docLength, idf = log10(totalDocs/docFreq)."""

    def idf(self, word: str) -> float:
        df = self._doc_freq.get(word, 0)
        if self.total_docs == 0 or df == 0:
            return 0.0
        return math.log10(self.total_docs / df)

    def tfidf(self, word: str, count: int, doc_length: int) -> float:
        tf = count / max(doc_length, 1)
        return tf * self.idf(word)

    def transform(self, text: str) -> np.ndarray:
        out = np.zeros(self.vocab_size(), np.float32)
        counts, n_tokens = self._counts(text)
        for word, c in counts.items():
            out[self.vocab.index_of(word)] = self.tfidf(word, c, n_tokens)
        return out
