"""SequenceVectors — the generic embedding trainer.

Parity surface: reference ``models/sequencevectors/SequenceVectors.java:49``
(:136 vocab build, :192 fit spawning VectorCalculationsThreads) with learning
algorithms ``SkipGram.java:156`` / ``CBOW.java``.

TPU-native redesign: the reference's producer/consumer threads + native sg
kernel become (a) a vectorized numpy pass that turns a chunk of index
sequences into dense (center, context) pair batches — subsampling, dynamic
window shrink, negative sampling all vectorized — and (b) one jitted scatter
step per batch (kernels.py). Sequences are anything that yields token lists,
so DeepWalk graph walks and ParagraphVectors documents reuse this class
unchanged (mirroring the reference's SequenceVectors genericity)."""

from __future__ import annotations

import logging
from typing import Iterable, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.nlp import kernels
from deeplearning4j_tpu.nlp.vocab import (
    AbstractCache, VocabConstructor, build_huffman, unigram_table,
)
from deeplearning4j_tpu.perf.compile_watch import CompileWatch

log = logging.getLogger(__name__)


class SequenceVectors:
    """Train element embeddings over sequences (see module docstring).

    Builder-style keyword config mirrors the reference's
    SequenceVectors.Builder: layer_size, window_size, negative (0 => use
    hierarchical softmax), learning_rate/min_learning_rate (linear decay),
    sampling (subsampling threshold), epochs, batch_size, min_word_frequency,
    use_cbow."""

    def __init__(self, layer_size: int = 100, window_size: int = 5,
                 negative: int = 5, use_hierarchic_softmax: Optional[bool] = None,
                 learning_rate: float = 0.025, min_learning_rate: float = 1e-4,
                 sampling: float = 0.0, epochs: int = 1, iterations: int = 1,
                 batch_size: int = 2048, min_word_frequency: int = 1,
                 use_cbow: bool = False, seed: int = 12345,
                 device_corpus: Optional[bool] = None):
        self.layer_size = layer_size
        self.window_size = window_size
        self.negative = negative
        self.use_hs = (negative == 0 if use_hierarchic_softmax is None
                       else use_hierarchic_softmax)
        self.learning_rate = learning_rate
        self.min_learning_rate = min_learning_rate
        self.sampling = sampling
        self.epochs = epochs
        self.iterations = iterations
        self.batch_size = batch_size
        self.min_word_frequency = min_word_frequency
        self.use_cbow = use_cbow
        self.seed = seed
        # None = auto: corpus-resident device training for plain SGNS
        # skip-gram when the corpus is big enough to matter (see fit())
        self.device_corpus = device_corpus

        self.vocab: Optional[AbstractCache] = None
        self.syn0: Optional[np.ndarray] = None
        self.syn1: Optional[np.ndarray] = None
        self._codes = self._points = self._lengths = None
        self._neg_table: Optional[np.ndarray] = None
        self._neg_table_dev = None   # device copy, shipped once per fit
        self._jax_key = None
        self._rng = np.random.default_rng(seed)
        self.words_processed = 0
        self.loss_history: List[float] = []
        # compile/dispatch counters for the device-corpus macro step: the
        # padded-segment scheme promises ONE compiled program for all
        # full-budget segments (tests assert it here)
        self.compile_watch = CompileWatch("SequenceVectors")

    # ------------------------------------------------------------ vocab/init
    def build_vocab(self, sequences: Iterable[List[str]]):
        self.vocab = VocabConstructor(self.min_word_frequency) \
            .build_joint_vocabulary([sequences])
        return self

    def _init_tables(self):
        v, d = self.vocab.num_words(), self.layer_size
        self.syn0 = ((self._rng.random((v, d), np.float32) - 0.5) / d)
        self.syn1 = np.zeros((v, d), np.float32)
        if self.use_hs:
            self._codes, self._points, self._lengths = build_huffman(self.vocab)
        if self.negative > 0:
            self._neg_table = unigram_table(self.vocab)
            self._neg_table_dev = None

    # --------------------------------------------------------- vectorization
    def _index_sequences(self, sequences: Iterable[List[str]]):
        """tokens -> index arrays, dropping OOV words (reference: vocab-filtered
        sequences in SequenceVectors' AsyncSequencer)."""
        widx = {vw.word: vw.index for vw in self.vocab.vocab_words()}
        for tokens in sequences:
            idx = [widx[t] for t in tokens if t in widx]
            if len(idx) >= 2:
                yield np.asarray(idx, np.int64)

    def _index_flat(self, sequences: Iterable[List[str]], widx=None):
        """Vectorized (flat, sid) indexing for the device-corpus path: one
        C-level pass instead of a per-sentence python list build (the
        per-token loop was ~40% of the device path's host budget)."""
        import itertools
        if widx is None:
            widx = {vw.word: vw.index for vw in self.vocab.vocab_words()}
        seqs = [s if isinstance(s, list) else list(s) for s in sequences]
        lens = np.fromiter((len(s) for s in seqs), np.int64, count=len(seqs))
        flat = np.fromiter(
            map(widx.get, itertools.chain.from_iterable(seqs),
                itertools.repeat(-1)),
            np.int64, count=int(lens.sum()))
        sid = np.repeat(np.arange(len(seqs), dtype=np.int64), lens)
        ok = flat >= 0  # drop OOV
        flat, sid = flat[ok], sid[ok]
        # drop sentences left with < 2 tokens (matches _index_sequences)
        counts = np.bincount(sid, minlength=len(seqs))
        good = counts[sid] >= 2
        flat, sid = flat[good], sid[good]
        return flat, sid

    def _subsample(self, flat, sid):
        """Frequent-word subsampling (word2vec formula; reference
        SkipGram's sequence pre-filter with ``sampling > 0``)."""
        if not self.sampling:
            return flat, sid
        counts = np.array([vw.count for vw in self.vocab.vocab_words()], np.float64)
        total = counts.sum()
        freq = counts / total
        t = self.sampling
        keep_prob = np.minimum(1.0, np.sqrt(t / freq) + t / freq)
        keep = self._rng.random(len(flat)) < keep_prob[flat]
        return flat[keep], sid[keep]

    def _pairs_for_chunk(self, seqs: List[np.ndarray]):
        """Vectorized window pair generation over a chunk of sequences.
        Returns (centers, contexts) with the reference's dynamic window:
        per-center radius uniform in [1, window]."""
        flat = np.concatenate(seqs)
        sid = np.repeat(np.arange(len(seqs)), [len(s) for s in seqs])
        flat, sid = self._subsample(flat, sid)
        n = len(flat)
        if n < 2:
            return (np.zeros(0, np.int64),) * 2
        r = self._rng.integers(1, self.window_size + 1, n)
        centers, contexts = [], []
        for d in range(1, self.window_size + 1):
            same = sid[:-d] == sid[d:]
            left = same & (d <= r[:-d])    # center i, context i+d
            right = same & (d <= r[d:])    # center i+d, context i
            centers.append(flat[:-d][left])
            contexts.append(flat[d:][left])
            centers.append(flat[d:][right])
            contexts.append(flat[:-d][right])
        return np.concatenate(centers), np.concatenate(contexts)

    def _bags_for_chunk(self, seqs: List[np.ndarray]):
        """CBOW bags: for each center, its (2*window) padded context bag."""
        flat = np.concatenate(seqs)
        sid = np.repeat(np.arange(len(seqs)), [len(s) for s in seqs])
        flat, sid = self._subsample(flat, sid)
        n = len(flat)
        w = self.window_size
        if n < 2:
            return (np.zeros(0, np.int64), np.zeros((0, 2 * w), np.int64),
                    np.zeros((0, 2 * w), np.float32))
        r = self._rng.integers(1, w + 1, n)
        bags = np.zeros((n, 2 * w), np.int64)
        mask = np.zeros((n, 2 * w), np.float32)
        col = 0
        for d in range(1, w + 1):
            for sign in (-1, 1):
                src = np.arange(n) + sign * d
                ok = (src >= 0) & (src < n)
                ok[ok] &= sid[src[ok]] == sid[ok.nonzero()[0]]
                ok &= d <= r
                bags[ok, col] = flat[src[ok]]
                mask[ok, col] = 1.0
                col += 1
        has_ctx = mask.sum(-1) > 0
        return flat[has_ctx], bags[has_ctx], mask[has_ctx]

    # -------------------------------------------------------------- training
    def _lr(self, total_expected: int) -> float:
        frac = min(1.0, self.words_processed / max(1, total_expected))
        return max(self.min_learning_rate, self.learning_rate * (1.0 - frac))

    def _pad(self, arr, b, fill=0):
        if len(arr) == b:
            return arr, None
        pad = b - len(arr)
        wmask = np.ones(b, np.float32)
        wmask[len(arr):] = 0.0
        widths = ((0, pad),) + ((0, 0),) * (arr.ndim - 1)
        return np.pad(arr, widths, constant_values=fill), wmask

    # full macros of NB x batch_size pairs go through ONE scanned dispatch
    # with device-side negative sampling (kernels.sgns_macro_step); the
    # ragged tail falls through to the per-batch path below. NB=8 keeps the
    # compile cache to one program while amortizing the tunnel's ~2.5 ms
    # per-dispatch overhead.
    _MACRO_NB = 8

    def _train_pairs_macro(self, centers, contexts, lr):
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.nlp import kernels as _k
        b = self.batch_size
        macro = b * self._MACRO_NB
        n_macros = len(centers) // macro
        if self._neg_table_dev is None:
            self._neg_table_dev = jnp.asarray(self._neg_table)
        if self._jax_key is None:
            self._jax_key = jax.random.key(self.seed)
        # int16 halves H2D traffic through the tunnel when the tables allow.
        # Gate on the actual table height, NOT vocab.num_words():
        # ParagraphVectors appends doc rows beyond the word vocab, and an
        # int16 cast would silently wrap those indices negative.
        dt = np.int16 if self.syn0.shape[0] < 2 ** 15 else np.int32
        step = _k.sgns_macro_step(self.negative)
        losses = []
        for m in range(n_macros):
            sl = slice(m * macro, (m + 1) * macro)
            ce = np.ascontiguousarray(
                centers[sl].astype(dt).reshape(self._MACRO_NB, b))
            ct = np.ascontiguousarray(
                contexts[sl].astype(dt).reshape(self._MACRO_NB, b))
            self._jax_key, k = jax.random.split(self._jax_key)
            self.syn0, self.syn1, l = step(
                self.syn0, self.syn1, self._neg_table_dev, ce, ct, k,
                np.float32(lr))
            losses.append(l)
        return n_macros * macro, losses

    def _train_pairs(self, centers, contexts, lr):
        """Feed (center, context) pairs through the jitted steps in
        batch_size slices; the final ragged slice pads with a zero mask.
        Losses are returned as DEVICE scalars — any ``float()`` here would be
        a host-sync serialization barrier per batch (profiled at ~80 ms each
        over a TPU tunnel vs 19 ms of actual compute); callers aggregate once
        per epoch."""
        b = self.batch_size
        losses = []
        start = 0
        if self.negative > 0 and len(centers) >= b * self._MACRO_NB:
            start, macro_losses = self._train_pairs_macro(centers, contexts, lr)
            losses.extend(macro_losses)
        for s in range(start, len(centers), b):
            ce, ct = centers[s:s + b], contexts[s:s + b]
            ce, wmask = self._pad(ce, b)
            ct, _ = self._pad(ct, b)
            if wmask is None:
                wmask = np.ones(b, np.float32)
            if self.negative > 0:
                negs = self._neg_table[
                    self._rng.integers(0, len(self._neg_table),
                                       (b, self.negative))].astype(np.int32)
                self.syn0, self.syn1, l = kernels.sgns_step(
                    self.syn0, self.syn1, ce.astype(np.int32),
                    ct.astype(np.int32), negs, wmask, np.float32(lr))
            else:
                codes = self._codes[ce]
                points = self._points[ce]
                lengths = (self._lengths[ce] * wmask).astype(np.int32)
                self.syn0, self.syn1, l = kernels.hs_step(
                    self.syn0, self.syn1, ct.astype(np.int32), codes, points,
                    lengths, np.float32(lr))
            losses.append(l)
        return losses

    def _train_bags(self, centers, bags, bmask, lr):
        b = self.batch_size
        losses = []
        for s in range(0, len(centers), b):
            ce, wmask = self._pad(centers[s:s + b], b)
            bg, _ = self._pad(bags[s:s + b], b)
            bm, _ = self._pad(bmask[s:s + b], b)
            if wmask is None:
                wmask = np.ones(b, np.float32)
            if self.negative > 0:
                negs = self._neg_table[
                    self._rng.integers(0, len(self._neg_table),
                                       (b, self.negative))].astype(np.int32)
                self.syn0, self.syn1, l = kernels.cbow_step(
                    self.syn0, self.syn1, ce.astype(np.int32),
                    bg.astype(np.int32), bm.astype(np.float32), negs, wmask,
                    np.float32(lr))
            else:
                # hierarchical softmax: walk the center word's Huffman path
                # (padded rows carry lengths=0, masking loss and updates)
                codes = self._codes[ce]
                points = self._points[ce]
                lengths = (self._lengths[ce] * wmask).astype(np.int32)
                self.syn0, self.syn1, l = kernels.cbow_hs_step(
                    self.syn0, self.syn1, codes, points, lengths,
                    bg.astype(np.int32), bm.astype(np.float32), np.float32(lr))
            losses.append(l)
        return losses

    # below this corpus size the host enumeration path wins (device pair
    # sampling needs enough batches to cover the corpus; tiny test corpora
    # also keep the exact reference enumeration semantics)
    _DEVICE_CORPUS_MIN_TOKENS = 50_000

    def fit(self, sequences, chunk_sentences: int = 512):
        """Train (reference SequenceVectors.fit :192). ``sequences`` is a
        factory (callable or re-iterable) of token-list iterables.

        Plain SGNS skip-gram on a large corpus takes the corpus-resident
        device path (kernels.sgns_corpus_macro_step): the encoded corpus
        ships to HBM once and pair/negative generation happens on-device,
        so throughput no longer scales with host->device bandwidth.
        ``device_corpus=True/False`` forces/disables it."""
        seq_factory = sequences if callable(sequences) else (lambda: sequences)
        if self.vocab is None:
            self.build_vocab(seq_factory())
        if self.syn0 is None:
            self._init_tables()
        dev_capable = (self.negative > 0 and not self.use_cbow
                       and not self.use_hs)
        if self.device_corpus and not dev_capable:
            raise ValueError(
                "device_corpus=True supports plain SGNS skip-gram only "
                "(negative > 0, no CBOW, no hierarchical softmax); this "
                f"config has negative={self.negative}, "
                f"use_cbow={self.use_cbow}, use_hs={self.use_hs}")
        # auto mode additionally requires sampling == 0: the device kernel
        # approximates subsampling by dropping pairs per-endpoint rather
        # than removing words from the stream (windows do not reach across
        # dropped words) — close in expectation but not the reference
        # semantics, so it must be opted into explicitly
        use_dev = (self.device_corpus if self.device_corpus is not None
                   else (dev_capable and self.sampling == 0))
        if use_dev:
            # decide the gate WITHOUT materializing the corpus: the vocab
            # pass already counted every in-vocab token, so the device-path
            # decision is free and the sequence factory streams segment by
            # segment inside _fit_device_corpus (host RAM stays bounded by
            # one segment, not the corpus)
            if (self.device_corpus
                    or (self.vocab.total_word_occurrences
                        >= self._DEVICE_CORPUS_MIN_TOKENS)):
                return self._fit_device_corpus(seq_factory)
            # below the gate the corpus is small by definition: tokenize
            # once and reuse on the host path instead of re-running the
            # factory per epoch
            token_lists = [t for t in seq_factory()]
            seq_factory = (lambda lists=token_lists: lists)
        total = self.vocab.total_word_occurrences * self.epochs * self.iterations
        for epoch in range(self.epochs):
            epoch_losses: List = []
            chunk: List[np.ndarray] = []
            for idx in self._index_sequences(seq_factory()):
                chunk.append(idx)
                if len(chunk) >= chunk_sentences:
                    self._fit_chunk(chunk, total, epoch_losses)
                    chunk = []
            if chunk:
                self._fit_chunk(chunk, total, epoch_losses)
            # single host sync per epoch: stack the device scalars and pull
            # one value (per-batch float() would serialize the dispatch queue)
            if epoch_losses:
                import jax.numpy as jnp
                # one host sync per epoch; atleast_1d also admits the vector
                # losses of the kernels.*_scan API
                flat_losses = jnp.concatenate(
                    [jnp.atleast_1d(l) for l in epoch_losses])
                self.loss_history.append(float(jnp.mean(flat_losses)))
        return self

    # segment size (tokens) for the device-corpus path: one segment = ONE
    # async macro dispatch, so host indexing of segment i+1 overlaps device
    # training of segment i; whole sentences per segment keep window
    # semantics exact (windows never cross sentence boundaries anyway)
    _DEVICE_CORPUS_SEG_TOKENS = 98_304

    def _segment_token_lists(self, token_lists):
        """Greedy whole-sentence packing, never exceeding the budget (so
        every full segment compiles the SAME macro program; only the
        leftover tail adds one more variant)."""
        budget = self._DEVICE_CORPUS_SEG_TOKENS
        seg, n = [], 0
        for t in token_lists:
            if seg and n + len(t) > budget:
                yield seg
                seg, n = [], 0
            seg.append(t)
            n += len(t)
        if seg:
            yield seg

    def _fit_device_corpus(self, seq_factory):
        """Corpus-resident training (see fit()): per segment of whole
        sentences, upload the encoded indices once (content-hash cached
        across epochs AND across fits on the same corpus) and run ONE
        jitted macro dispatch that generates pairs and negatives on device.

        ``seq_factory`` is consumed LAZILY, one segment at a time — the
        host never holds more than one segment of token lists, so RAM is
        bounded by the segment budget regardless of corpus size. Segments
        are PADDED up to ``_DEVICE_CORPUS_SEG_TOKENS`` with an inert
        sentinel (sid=-1; the true token count rides along as a device
        scalar for position sampling/validity), so every segment shares ONE
        compiled macro program instead of one per distinct length
        (``self.compile_watch`` counts the compiles).

        Pair quota per segment: T*(window+1) sampled pairs — the exact
        expected pair count of the reference's dynamic-window enumeration
        (per position 2*E[r] = window+1 pairs), drawn from the same joint
        (position, side, offset) distribution by the kernel; the static
        scan length is sized for the budget and trailing batches beyond
        the quota are masked on device. Dispatches are async; the only
        host sync is the per-epoch loss aggregation, so host-side indexing
        of the next segment overlaps device training of the current one."""
        import hashlib

        import jax
        import jax.numpy as jnp

        if self._neg_table_dev is None:
            self._neg_table_dev = jnp.asarray(
                self._neg_table.astype(np.int32))
        if self._jax_key is None:
            self._jax_key = jax.random.key(self.seed)
        # device-resident tables from the FIRST dispatch: a numpy first
        # step would compile its own donation-less specialization of the
        # macro program (breaking the one-compile contract) and copy the
        # tables every step
        self.syn0 = jnp.asarray(self.syn0)
        self.syn1 = jnp.asarray(self.syn1)
        keep = None
        if self.sampling:
            counts = np.array([vw.count for vw in self.vocab.vocab_words()],
                              np.float64)
            freq = counts / counts.sum()
            t = self.sampling
            keep = jnp.asarray(np.minimum(
                1.0, np.sqrt(t / freq) + t / freq).astype(np.float32))
        # int16 halves tunnel upload when the index ranges allow
        cdt = np.int16 if self.syn0.shape[0] < 2 ** 15 else np.int32
        B = self.batch_size
        W = self.window_size
        total_expected = (self.vocab.total_word_occurrences * self.epochs
                          * self.iterations)
        cache = getattr(self, "_corpus_dev_cache", None)
        if cache is None:
            # insertion-ordered, FIFO-bounded: long-lived processes fitting
            # many distinct corpora must not pin HBM forever
            cache = self._corpus_dev_cache = {}
        widx = {vw.word: vw.index for vw in self.vocab.vocab_words()}
        if not callable(seq_factory):
            seq_factory = (lambda lists=seq_factory: lists)

        def first_pass_plan():
            """Index + upload segments lazily, so the caller's dispatch of
            segment i overlaps (async) with indexing of segment i+1 — and
            the factory is only ever consumed one segment ahead.
            Boundaries (sid) are part of the cache identity."""
            budget = self._DEVICE_CORPUS_SEG_TOKENS
            for seg in self._segment_token_lists(seq_factory()):
                flat, sid = self._index_flat(seg, widx)
                if len(flat) < 2:
                    continue
                flat = flat.astype(cdt)
                sdt = (np.int16 if sid[-1] < 2 ** 15 else np.int32)
                sid = sid.astype(sdt)
                T = len(flat)
                if T < budget:
                    # pad to the budget with an inert sentinel: sid=-1
                    # never matches a real sentence id, and the kernel
                    # samples positions from the TRUE length (shipped as a
                    # device scalar) — so every <=budget segment compiles
                    # the SAME macro program regardless of its length
                    flat = np.concatenate(
                        [flat, np.zeros(budget - T, flat.dtype)])
                    sid = np.concatenate(
                        [sid, np.full(budget - T, -1, sid.dtype)])
                h = hashlib.sha1(flat.tobytes())
                h.update(sid.tobytes())
                hit = cache.get(h.digest())
                if hit is None:
                    hit = (jnp.asarray(flat), jnp.asarray(sid))
                    while len(cache) >= 1024:  # FIFO bound on pinned HBM
                        cache.pop(next(iter(cache)))
                    cache[h.digest()] = hit
                # static scan length from the padded shape (one program);
                # the segment's true quota T*(W+1) rides along as n_active
                # — trailing batches are masked on device. A segment can
                # only EXCEED the budget via one oversized sentence; it
                # keeps its own (rare) program
                nb = max(1, -(-(max(T, budget) * (W + 1)) // B))
                nvb = min(nb, max(1, -(-(T * (W + 1)) // B)))
                yield hit[0], hit[1], T, nb, nvb

        plan = None  # filled on the first pass; later passes reuse it
        for _epoch in range(self.epochs):
            epoch_losses = []
            for _ in range(self.iterations):
                entries = first_pass_plan() if plan is None else plan
                built = [] if plan is None else None
                for corpus_dev, sid_dev, T, nb, nvb in entries:
                    lr = self._lr(total_expected)
                    step = self.compile_watch.wrap(
                        kernels.sgns_corpus_macro_step(
                            self.negative, W, B, nb), "sgns_corpus_macro")
                    self._jax_key, k = jax.random.split(self._jax_key)
                    self.syn0, self.syn1, losses = step(
                        self.syn0, self.syn1, corpus_dev, sid_dev,
                        self._neg_table_dev, keep, k, np.float32(lr),
                        np.int32(T), np.int32(nvb))
                    # quota-masked trailing batches carry no pairs: keep
                    # them out of the loss history
                    epoch_losses.append(losses[:nvb])
                    self.words_processed += T
                    if built is not None:
                        built.append((corpus_dev, sid_dev, T, nb, nvb))
                if built is not None:
                    plan = built
            if epoch_losses:
                self.loss_history.append(float(jnp.mean(
                    jnp.concatenate([jnp.atleast_1d(l)
                                     for l in epoch_losses]))))
        return self

    def _fit_chunk(self, chunk, total_expected, epoch_losses):
        for _ in range(self.iterations):
            lr = self._lr(total_expected)
            if self.use_cbow:
                centers, bags, bmask = self._bags_for_chunk(chunk)
                if len(centers):
                    epoch_losses.extend(self._train_bags(centers, bags, bmask, lr))
            else:
                centers, contexts = self._pairs_for_chunk(chunk)
                if len(centers):
                    epoch_losses.extend(self._train_pairs(centers, contexts, lr))
            self.words_processed += sum(len(s) for s in chunk)

    # -------------------------------------------------------------- lookups
    def word_vector(self, word: str) -> Optional[np.ndarray]:
        i = self.vocab.index_of(word)
        return None if i < 0 else np.asarray(self.syn0[i])

    def get_word_vector_matrix(self) -> np.ndarray:
        return np.asarray(self.syn0)

    def has_word(self, word: str) -> bool:
        return self.vocab is not None and self.vocab.contains_word(word)

    def similarity(self, a: str, b: str) -> float:
        """Cosine similarity (reference WordVectors.similarity)."""
        va, vb = self.word_vector(a), self.word_vector(b)
        if va is None or vb is None:
            return float("nan")
        denom = (np.linalg.norm(va) * np.linalg.norm(vb)) or 1e-12
        return float(va @ vb / denom)

    def words_nearest(self, word_or_vec, top_n: int = 10) -> List[str]:
        """Nearest words by cosine (reference wordsNearest)."""
        if isinstance(word_or_vec, str):
            v = self.word_vector(word_or_vec)
            exclude = {word_or_vec}
        else:
            v = np.asarray(word_or_vec, np.float32)
            exclude = set()
        if v is None:
            return []
        # get_word_vector_matrix, not raw syn0: subclasses append non-word
        # rows (ParagraphVectors doc vectors) or combine tables (GloVe W+W~)
        m = self.get_word_vector_matrix()
        norms = np.linalg.norm(m, axis=1) * (np.linalg.norm(v) or 1e-12)
        sims = (m @ v) / np.maximum(norms, 1e-12)
        order = np.argsort(-sims)
        out = []
        for i in order:
            w = self.vocab.word_at_index(int(i))
            if w not in exclude:
                out.append(w)
            if len(out) >= top_n:
                break
        return out
