"""Vocabulary construction + Huffman coding.

Parity surface: reference ``models/word2vec/wordstore/VocabConstructor.java:31``
(parallel corpus scan -> joint vocabulary with min-frequency pruning),
``models/word2vec/wordstore/inmemory/AbstractCache.java`` (the VocabCache),
and ``models/sequencevectors/graph/huffman/`` + ``models/word2vec/Huffman.java``
(binary Huffman tree assigning codes/points for hierarchical softmax).

Host-side; the outputs consumed on-device are dense numpy tables
(codes/points padded to max code length, unigram negative-sampling table)."""

from __future__ import annotations

import heapq
from collections import Counter
from typing import Dict, Iterable, List, Optional

import numpy as np


class VocabWord:
    """reference models/word2vec/VocabWord.java — a vocab element with
    frequency and Huffman code/point arrays."""

    __slots__ = ("word", "count", "index", "codes", "points")

    def __init__(self, word: str, count: int = 1):
        self.word = word
        self.count = count
        self.index = -1
        self.codes: List[int] = []
        self.points: List[int] = []

    def __repr__(self):
        return f"VocabWord({self.word!r}, n={self.count}, i={self.index})"


class AbstractCache:
    """In-memory vocab cache (reference inmemory/AbstractCache.java):
    word <-> index <-> VocabWord lookups plus total counts."""

    def __init__(self):
        self._words: Dict[str, VocabWord] = {}
        self._by_index: List[VocabWord] = []
        self.total_word_occurrences = 0

    # --- construction ---
    def add_token(self, vw: VocabWord):
        if vw.word in self._words:
            self._words[vw.word].count += vw.count
        else:
            self._words[vw.word] = vw

    def finalize_vocab(self):
        """Assign indices by descending frequency (the reference sorts the
        vocab for the unigram table and Huffman build)."""
        self._by_index = sorted(self._words.values(),
                                key=lambda w: (-w.count, w.word))
        for i, vw in enumerate(self._by_index):
            vw.index = i
        self.total_word_occurrences = sum(w.count for w in self._by_index)

    # --- lookups (reference VocabCache API) ---
    def num_words(self) -> int:
        return len(self._by_index)

    def contains_word(self, word: str) -> bool:
        return word in self._words

    def word_frequency(self, word: str) -> int:
        vw = self._words.get(word)
        return 0 if vw is None else vw.count

    def index_of(self, word: str) -> int:
        vw = self._words.get(word)
        return -1 if vw is None else vw.index

    def word_at_index(self, index: int) -> Optional[str]:
        if 0 <= index < len(self._by_index):
            return self._by_index[index].word
        return None

    def word_for(self, word: str) -> Optional[VocabWord]:
        return self._words.get(word)

    def vocab_words(self) -> List[VocabWord]:
        return list(self._by_index)

    def words(self) -> List[str]:
        return [w.word for w in self._by_index]


class VocabConstructor:
    """Corpus scan -> vocabulary (reference VocabConstructor.java:31).

    The reference runs parallel VocabRunnables per source; here one vectorized
    Counter pass per source achieves the same joint vocabulary."""

    def __init__(self, min_word_frequency: int = 1):
        self.min_word_frequency = min_word_frequency

    def build_joint_vocabulary(self, sources: Iterable[Iterable[List[str]]]) -> AbstractCache:
        counts: Counter = Counter()
        for source in sources:
            for tokens in source:
                counts.update(tokens)
        cache = AbstractCache()
        for word, n in counts.items():
            if n >= self.min_word_frequency:
                cache.add_token(VocabWord(word, n))
        cache.finalize_vocab()
        return cache


def build_huffman(cache: AbstractCache, max_code_length: int = 40):
    """Binary Huffman tree over word frequencies (reference Huffman.java /
    GraphHuffman.java): fills each VocabWord's codes (0/1 branch decisions)
    and points (inner-node indices root->leaf).

    Returns dense (codes, points, lengths) numpy arrays padded to the max
    actual code length — the device-side hierarchical softmax consumes these
    with a validity mask instead of per-word ragged loops."""
    n = cache.num_words()
    if n == 0:
        return (np.zeros((0, 1), np.int32), np.zeros((0, 1), np.int32),
                np.zeros((0,), np.int32))
    heap = [(vw.count, i, None, None) for i, vw in enumerate(cache.vocab_words())]
    heapq.heapify(heap)
    next_id = n
    parent: Dict[int, tuple] = {}  # node id -> (parent inner id, branch bit)
    while len(heap) > 1:
        c1, id1, _, _ = heapq.heappop(heap)
        c2, id2, _, _ = heapq.heappop(heap)
        inner = next_id
        next_id += 1
        parent[id1] = (inner, 0)
        parent[id2] = (inner, 1)
        heapq.heappush(heap, (c1 + c2, inner, None, None))
    root = heap[0][1] if heap else None
    for i, vw in enumerate(cache.vocab_words()):
        codes, points = [], []
        node = i
        while node != root and node in parent:
            inner, bit = parent[node]
            codes.append(bit)
            # inner-node row in syn1: inner ids start at n
            points.append(inner - n)
            node = inner
        codes.reverse()
        points.reverse()
        if len(codes) > max_code_length:
            raise ValueError(f"Huffman code longer than {max_code_length}")
        vw.codes = codes
        vw.points = points
    max_len = max((len(vw.codes) for vw in cache.vocab_words()), default=1) or 1
    codes_arr = np.zeros((n, max_len), np.int32)
    points_arr = np.zeros((n, max_len), np.int32)
    lengths = np.zeros((n,), np.int32)
    for i, vw in enumerate(cache.vocab_words()):
        L = len(vw.codes)
        lengths[i] = L
        codes_arr[i, :L] = vw.codes
        points_arr[i, :L] = vw.points
    return codes_arr, points_arr, lengths


def unigram_table(cache: AbstractCache, table_size: int = 100_000,
                  power: float = 0.75) -> np.ndarray:
    """Negative-sampling table: word index repeated proportional to
    count^0.75 (reference InMemoryLookupTable.resetWeights / makeTable)."""
    counts = np.array([vw.count for vw in cache.vocab_words()], np.float64)
    if counts.size == 0:
        return np.zeros((table_size,), np.int32)
    probs = counts ** power
    probs /= probs.sum()
    reps = np.maximum(1, np.round(probs * table_size)).astype(np.int64)
    table = np.repeat(np.arange(len(counts), dtype=np.int32), reps)
    if len(table) < table_size:
        table = np.concatenate([table, np.full(table_size - len(table),
                                               len(counts) - 1, np.int32)])
    return table[:table_size]
