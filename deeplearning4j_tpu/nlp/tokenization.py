"""Tokenizer SPI.

Parity surface: reference ``text/tokenization/tokenizerfactory/
TokenizerFactory.java:31`` (SPI: create(String) -> Tokenizer with an optional
TokenPreProcess), DefaultTokenizerFactory, NGramTokenizerFactory, and
``text/tokenization/tokenizer/preprocessor/CommonPreprocessor.java``.

Pure host-side code (tokenization never touches the device)."""

from __future__ import annotations

import re
from typing import Callable, Iterable, List, Optional


class TokenPreProcess:
    """reference tokenizer/TokenPreProcess.java."""

    def pre_process(self, token: str) -> str:
        raise NotImplementedError


class CommonPreprocessor(TokenPreProcess):
    """Lowercase + strip punctuation/digits-adjacent junk (reference
    CommonPreprocessor.java: replaceAll punctuation, toLowerCase)."""

    _PUNCT = re.compile(r"[\d.:,\"'\(\)\[\]|/?!;]+")

    def pre_process(self, token: str) -> str:
        return self._PUNCT.sub("", token).lower()


class LowCasePreprocessor(TokenPreProcess):
    def pre_process(self, token: str) -> str:
        return token.lower()


class Tokenizer:
    """reference tokenizer/Tokenizer.java — iterator over tokens."""

    def __init__(self, tokens: List[str], pre: Optional[TokenPreProcess] = None):
        if pre is not None:
            tokens = [pre.pre_process(t) for t in tokens]
        self._tokens = [t for t in tokens if t]

    def count_tokens(self) -> int:
        return len(self._tokens)

    def get_tokens(self) -> List[str]:
        return list(self._tokens)

    def __iter__(self):
        return iter(self._tokens)


class TokenizerFactory:
    """SPI base (reference TokenizerFactory.java:31)."""

    def __init__(self):
        self._pre: Optional[TokenPreProcess] = None

    def set_token_pre_processor(self, pre: TokenPreProcess):
        self._pre = pre
        return self

    def get_token_pre_processor(self) -> Optional[TokenPreProcess]:
        return self._pre

    def create(self, text: str) -> Tokenizer:
        raise NotImplementedError


class DefaultTokenizerFactory(TokenizerFactory):
    """Whitespace tokenizer (reference DefaultTokenizerFactory.java wraps a
    StringTokenizer on whitespace)."""

    def create(self, text: str) -> Tokenizer:
        return Tokenizer(text.split(), self._pre)


class NGramTokenizerFactory(TokenizerFactory):
    """Word n-grams over a base tokenizer (reference
    NGramTokenizerFactory.java)."""

    def __init__(self, base: Optional[TokenizerFactory] = None,
                 min_n: int = 1, max_n: int = 2):
        super().__init__()
        self._base = base or DefaultTokenizerFactory()
        self.min_n = min_n
        self.max_n = max_n

    def create(self, text: str) -> Tokenizer:
        words = self._base.create(text).get_tokens()
        out = []
        for n in range(self.min_n, self.max_n + 1):
            for i in range(len(words) - n + 1):
                out.append(" ".join(words[i:i + n]))
        return Tokenizer(out, self._pre)
