"""CachedBackend — a local-disk LRU tier in front of any StorageBackend.

A replica fleet cold-starting from the lake, or a streaming index build
making a second pass over a corpus, should pay the wire cost once:

- **byte-budgeted LRU**: entries live as files under ``cache_dir``;
  filling past ``max_bytes`` evicts least-recently-used entries first.
  Objects larger than the whole budget bypass the cache entirely;
- **verify-on-read**: every hit is checked against the sha256 recorded at
  fill time — a rotted or truncated cache file is evicted and silently
  refetched from the inner backend (cache corruption must never be
  weaker than no cache);
- **single-flight**: concurrent ``get`` of the same missing key fetches
  once; the other callers wait and hit — a 16-replica fleet restoring the
  same checkpoint costs one wire transfer, not sixteen
  (``single_flight_waits`` counts the saved fetches);
- **write-through put**: the inner put commits first (the durability
  contract lives THERE), then the cache is refreshed, so read-your-writes
  holds through the cache.

``list``/``exists`` always delegate — the cache is never authoritative
about what exists, only about bytes already fetched. Stack order matters:
``CachedBackend(RetryingBackend(CloudObjectBackend(...)))`` gives hits
that never touch the retry layer and fills that get its full fault
handling (what :func:`~deeplearning4j_tpu.checkpoint.cloud.backend_from_url`
builds).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from deeplearning4j_tpu.checkpoint.storage import StorageBackend

log = logging.getLogger(__name__)

__all__ = ["CachedBackend"]

_TMP_SUFFIX = ".tmp"
_META_SUFFIX = ".meta"
_DATA_SUFFIX = ".bin"


class CachedBackend(StorageBackend):
    """See module docstring. ``cache_dir`` is created on demand and may be
    shared across process restarts — surviving entries are re-indexed (and
    still verified on every read). ``verify=False`` trades the per-hit
    sha256 for speed; the chaos tests keep it on."""

    def __init__(self, inner: StorageBackend, cache_dir: str,
                 max_bytes: int = 256 << 20, *, verify: bool = True):
        if max_bytes <= 0:
            raise ValueError("max_bytes must be > 0")
        self.inner = inner
        self.cache_dir = str(cache_dir)
        self.max_bytes = int(max_bytes)
        self.verify = bool(verify)
        self._lock = threading.Lock()           # index + counters
        self._key_locks: Dict[str, threading.Lock] = {}  # single-flight
        # name -> (entry_stem, size, sha256); insertion order = LRU order
        self._index: "OrderedDict[str, Tuple[str, int, str]]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.corrupt_evictions = 0
        self.single_flight_waits = 0
        self._reindex()

    # ------------------------------------------------------------ indexing
    def _reindex(self):
        """Adopt entries left by a previous process: each ``.meta`` sidecar
        names its object and records the fill-time sha; LRU order is
        file mtime. Verification still happens per-read, so a stale or
        rotted adopted entry self-heals."""
        if not os.path.isdir(self.cache_dir):
            return
        found = []
        for fn in os.listdir(self.cache_dir):
            if not fn.endswith(_META_SUFFIX):
                continue
            stem = fn[:-len(_META_SUFFIX)]
            meta_path = os.path.join(self.cache_dir, fn)
            data_path = os.path.join(self.cache_dir, stem + _DATA_SUFFIX)
            try:
                with open(meta_path, "r", encoding="utf-8") as f:
                    meta = json.load(f)
                size = os.path.getsize(data_path)
                found.append((os.path.getmtime(data_path),
                              str(meta["name"]), stem, size,
                              str(meta["sha256"])))
            except (OSError, ValueError, KeyError):
                for p in (meta_path, data_path):
                    try:
                        os.remove(p)
                    except OSError:
                        pass
        for _, name, stem, size, sha in sorted(found):
            self._index[name] = (stem, size, sha)
            self._bytes += size
        self._evict_over_budget()

    @staticmethod
    def _stem(name: str) -> str:
        return hashlib.sha256(name.encode()).hexdigest()[:40]

    def _paths(self, stem: str) -> Tuple[str, str]:
        return (os.path.join(self.cache_dir, stem + _DATA_SUFFIX),
                os.path.join(self.cache_dir, stem + _META_SUFFIX))

    def _key_lock(self, name: str) -> threading.Lock:
        with self._lock:
            lock = self._key_locks.get(name)
            if lock is None:
                lock = self._key_locks[name] = threading.Lock()
            return lock

    # ------------------------------------------------------------ eviction
    def _evict_entry_locked(self, name: str, *, corrupt: bool = False):
        entry = self._index.pop(name, None)
        if entry is None:
            return
        stem, size, _ = entry
        self._bytes -= size
        if corrupt:
            self.corrupt_evictions += 1
        else:
            self.evictions += 1
        for p in self._paths(stem):
            try:
                os.remove(p)
            except OSError:
                pass

    def _evict_over_budget(self):
        while self._bytes > self.max_bytes and self._index:
            oldest = next(iter(self._index))
            self._evict_entry_locked(oldest)

    # ---------------------------------------------------------------- fill
    def _fill(self, name: str, data: bytes):
        if len(data) > self.max_bytes:
            return  # would evict the whole cache for one object
        os.makedirs(self.cache_dir, exist_ok=True)
        stem = self._stem(name)
        data_path, meta_path = self._paths(stem)
        sha = hashlib.sha256(data).hexdigest()
        tmp = data_path + _TMP_SUFFIX
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, data_path)  # atomic: readers see whole entries
        tmp = meta_path + _TMP_SUFFIX
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"name": name, "sha256": sha, "size": len(data)}, f)
        os.replace(tmp, meta_path)
        with self._lock:
            if name in self._index:
                _, old_size, _ = self._index.pop(name)
                self._bytes -= old_size
            self._index[name] = (stem, len(data), sha)
            self._bytes += len(data)
            self._evict_over_budget()

    def _read_entry(self, name: str) -> Optional[bytes]:
        """A verified cache hit, or None (absent OR corrupt — the corrupt
        entry is already evicted so the caller just refetches)."""
        with self._lock:
            entry = self._index.get(name)
        if entry is None:
            return None
        stem, size, sha = entry
        data_path, _ = self._paths(stem)
        try:
            with open(data_path, "rb") as f:
                data = f.read(size + 1)
        except OSError:
            data = None
        ok = (data is not None and len(data) == size
              and (not self.verify
                   or hashlib.sha256(data).hexdigest() == sha))
        if not ok:
            log.warning("cache entry for %s is corrupt or unreadable — "
                        "evicting and refetching from %s", name,
                        self.inner.describe())
            with self._lock:
                self._evict_entry_locked(name, corrupt=True)
            return None
        with self._lock:
            if name in self._index:
                self._index.move_to_end(name)
        return data

    # ----------------------------------------------------------- interface
    def get(self, name: str) -> bytes:
        data = self._read_entry(name)
        if data is not None:
            with self._lock:
                self.hits += 1
            return data
        klock = self._key_lock(name)
        waited = not klock.acquire(blocking=False)
        if waited:
            klock.acquire()
        try:
            if waited:
                # someone fetched while we queued — their fill is our hit
                data = self._read_entry(name)
                if data is not None:
                    with self._lock:
                        self.hits += 1
                        self.single_flight_waits += 1
                    return data
            data = self.inner.get(name)
            with self._lock:
                self.misses += 1
            self._fill(name, data)
            return data
        finally:
            klock.release()

    def put(self, name: str, data: bytes, fsync_directory: bool = True):
        data = bytes(data)
        self.inner.put(name, data, fsync_directory=fsync_directory)
        self._fill(name, data)  # write-through AFTER the durable commit

    def list(self, prefix: str = "") -> List[str]:
        return self.inner.list(prefix)

    def delete(self, name: str):
        self.inner.delete(name)
        with self._lock:
            self._evict_entry_locked(name)

    def exists(self, name: str) -> bool:
        return self.inner.exists(name)

    def clean_orphans(self):
        swept = self.inner.clean_orphans()
        if os.path.isdir(self.cache_dir):
            for fn in os.listdir(self.cache_dir):
                if fn.endswith(_TMP_SUFFIX):
                    try:
                        os.remove(os.path.join(self.cache_dir, fn))
                    except OSError:
                        pass
        return swept

    # ------------------------------------------------------------- insight
    def stats(self) -> Dict[str, float]:
        with self._lock:
            total = self.hits + self.misses
            return {"hits": self.hits, "misses": self.misses,
                    "hit_rate": (self.hits / total) if total else 0.0,
                    "evictions": self.evictions,
                    "corrupt_evictions": self.corrupt_evictions,
                    "single_flight_waits": self.single_flight_waits,
                    "entries": len(self._index),
                    "bytes_cached": self._bytes,
                    "max_bytes": self.max_bytes}

    def describe(self) -> str:
        return f"CachedBackend({self.inner.describe()}, {self.cache_dir})"
