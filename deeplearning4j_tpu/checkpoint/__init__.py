"""checkpoint/ — asynchronous, crash-consistent checkpointing with
exact-step resume, pluggable storage and automatic recovery.

Five cooperating pieces (see each module's docstring):

- ``manager``  — CheckpointManager: host snapshot on the training thread,
                 async atomic journaled commits, retention, triggers,
                 multi-host barrier, ``restore_latest``/``restore_best``
                 with fall-back past torn files, early-stopping saver
                 protocol;
- ``manifest`` — the checksummed journal + atomic commit primitives that
                 make a torn write detectable through any backend;
- ``storage``  — the StorageBackend interface: LocalFSBackend (default),
                 ObjectStoreBackend (GCS-style put/get/list/delete) and
                 RetryingBackend (bounded exponential-backoff-with-jitter
                 retries + per-op timeouts for transient faults);
- ``resume``   — ``train_until``: the auto-resume driver looping
                 restore_latest + fit under a restart budget, turning
                 preemption into a no-op for callers;
- ``faults``   — the chaos harness: FaultInjector (step / epoch-boundary /
                 probabilistic kills), FlakyBackend (seeded storage
                 faults + latency), tear/flip corruption simulators.

Wired end-to-end as ``fit(..., checkpoint_manager=cm)`` on
MultiLayerNetwork, ComputationGraph, ParallelWrapper and ClusterTrainer;
serving picks new checkpoints up live via
``ParallelInference.start_hot_swap``.
"""

from deeplearning4j_tpu.checkpoint.manager import (  # noqa: F401
    CheckpointError,
    CheckpointManager,
    ResumeState,
    consume_resume_state,
)
from deeplearning4j_tpu.checkpoint.faults import (  # noqa: F401
    FaultInjector,
    FlakyBackend,
    SimulatedCrash,
    flip_byte,
    flip_object_byte,
    tear_file,
    tear_object,
)
from deeplearning4j_tpu.checkpoint.manifest import (  # noqa: F401
    ManifestError,
    file_sha256,
    load_manifest,
    scan_checkpoint_files,
)
from deeplearning4j_tpu.checkpoint.storage import (  # noqa: F401
    LocalFSBackend,
    ObjectStoreBackend,
    PermanentStorageError,
    RetryingBackend,
    StorageBackend,
    StorageError,
    StorageNotFoundError,
    TransientStorageError,
)
from deeplearning4j_tpu.checkpoint.resume import (  # noqa: F401
    CrashRecord,
    RestartBudgetExceeded,
    RestartPolicy,
    RunSummary,
    train_until,
)
