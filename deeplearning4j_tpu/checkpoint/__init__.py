"""checkpoint/ — asynchronous, crash-consistent checkpointing with
exact-step resume, pluggable storage and automatic recovery.

Five cooperating pieces (see each module's docstring):

- ``manager``  — CheckpointManager: host snapshot on the training thread,
                 async atomic journaled commits, retention, triggers,
                 multi-host barrier, ``restore_latest``/``restore_best``
                 with fall-back past torn files, early-stopping saver
                 protocol;
- ``manifest`` — the checksummed journal + atomic commit primitives that
                 make a torn write detectable through any backend;
- ``storage``  — the StorageBackend interface: LocalFSBackend (default),
                 ObjectStoreBackend (GCS-style put/get/list/delete) and
                 RetryingBackend (bounded exponential-backoff-with-jitter
                 retries + per-op timeouts for transient faults,
                 Retry-After hints honored);
- ``cloud``    — CloudObjectBackend: the real wire-protocol client
                 (S3-dialect REST, signed requests, paged listing,
                 multipart puts with abort-on-failure) + backend_from_url;
- ``cache``    — CachedBackend: local-disk LRU tier (byte-budgeted,
                 sha256 verify-on-read, single-flight fetches);
- ``emulator`` — ObjectStoreEmulator: hermetic fault-injecting HTTP
                 object store for chaos tests (FlakyBackend's successor
                 at the wire level);
- ``resume``   — ``train_until``: the auto-resume driver looping
                 restore_latest + fit under a restart budget, turning
                 preemption into a no-op for callers;
- ``faults``   — the chaos harness: FaultInjector (step / epoch-boundary /
                 probabilistic kills, as exceptions or REAL SIGKILL),
                 FlakyBackend (seeded storage faults + latency, aimable
                 at name prefixes), tear/flip corruption simulators;
- ``sharded``  — per-host shard files journaled as one set entry with
                 per-shard sha256 and N→M reshard-on-restore
                 (``CheckpointManager(sharded=True)``);
- ``supervisor`` — ``train_until_process``: restart crashed/preempted
                 training as NEW OS processes under the same
                 RestartPolicy/CrashRecord semantics as ``train_until``.

Wired end-to-end as ``fit(..., checkpoint_manager=cm)`` on
MultiLayerNetwork, ComputationGraph, ParallelWrapper and ClusterTrainer;
serving picks new checkpoints up live via
``ParallelInference.start_hot_swap``.
"""

from deeplearning4j_tpu.checkpoint.manager import (  # noqa: F401
    CheckpointError,
    CheckpointManager,
    ResumeState,
    consume_resume_state,
)
from deeplearning4j_tpu.checkpoint.faults import (  # noqa: F401
    FaultInjector,
    FlakyBackend,
    SimulatedCrash,
    flip_byte,
    flip_object_byte,
    tear_file,
    tear_object,
)
from deeplearning4j_tpu.checkpoint.manifest import (  # noqa: F401
    ManifestError,
    file_sha256,
    load_manifest,
    scan_checkpoint_files,
)
from deeplearning4j_tpu.checkpoint.storage import (  # noqa: F401
    LocalFSBackend,
    ObjectStoreBackend,
    PermanentStorageError,
    RetryingBackend,
    StorageBackend,
    StorageError,
    StorageNotFoundError,
    TransientStorageError,
    sweep_orphan_keys,
)
from deeplearning4j_tpu.checkpoint.cloud import (  # noqa: F401
    CloudCredentials,
    CloudObjectBackend,
    backend_from_url,
)
from deeplearning4j_tpu.checkpoint.cache import (  # noqa: F401
    CachedBackend,
)
from deeplearning4j_tpu.checkpoint.emulator import (  # noqa: F401
    ObjectStoreEmulator,
)
from deeplearning4j_tpu.checkpoint.resume import (  # noqa: F401
    CrashRecord,
    RestartBudgetExceeded,
    RestartPolicy,
    RunSummary,
    train_until,
)
from deeplearning4j_tpu.checkpoint.sharded import (  # noqa: F401
    ShardedCheckpointError,
    restore_sharded,
    scan_shard_sets,
    shard_snapshot,
    simulated_shard_snapshots,
    state_sha,
)
from deeplearning4j_tpu.checkpoint.supervisor import (  # noqa: F401
    ELASTIC_RESTART_EXIT,
    ProcessCrashRecord,
    ProcessRunSummary,
    train_until_process,
)
