"""checkpoint/ — asynchronous, crash-consistent checkpointing with
exact-step resume.

Three cooperating pieces (see each module's docstring):

- ``manager``  — CheckpointManager: host snapshot on the training thread,
                 async atomic journaled commits, retention, triggers,
                 multi-host barrier, ``restore_latest``/``restore_best``
                 with fall-back past torn files, early-stopping saver
                 protocol;
- ``manifest`` — the checksummed journal + tmp/fsync/rename commit
                 primitives that make a torn write detectable;
- ``faults``   — FaultInjector / tear_file / flip_byte: the crash and
                 corruption simulators the resume-bitwise tests drive.

Wired end-to-end as ``fit(..., checkpoint_manager=cm)`` on
MultiLayerNetwork, ComputationGraph, ParallelWrapper and ClusterTrainer.
"""

from deeplearning4j_tpu.checkpoint.manager import (  # noqa: F401
    CheckpointError,
    CheckpointManager,
    ResumeState,
    consume_resume_state,
)
from deeplearning4j_tpu.checkpoint.faults import (  # noqa: F401
    FaultInjector,
    SimulatedCrash,
    flip_byte,
    tear_file,
)
from deeplearning4j_tpu.checkpoint.manifest import (  # noqa: F401
    ManifestError,
    file_sha256,
    load_manifest,
    scan_checkpoint_files,
)
