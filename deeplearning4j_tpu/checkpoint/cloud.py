"""CloudObjectBackend — a real wire-protocol object-store client behind
the five-method :class:`~deeplearning4j_tpu.checkpoint.storage.StorageBackend`
surface.

Speaks the S3-style REST dialect over stdlib ``http.client`` (no SDK
dependency): GET/PUT/HEAD/DELETE on ``/{bucket}/{key}``, ``list-type=2``
paged listing with continuation tokens, and the multipart-upload protocol
(initiate → per-part PUT with sha256 → complete/abort) for objects above a
size threshold. Everything durable in the repo — manifests, sharded
checkpoints, leases, ledgers, the flight recorder — already speaks
StorageBackend, so pointing any of it at a bucket is a constructor swap.

Design rules (each is load-bearing):

- **Taxonomy mapping.** HTTP status → the existing error taxonomy so
  :class:`RetryingBackend` and the manager's fallback logic work unchanged
  over the wire: 404 → :class:`StorageNotFoundError`; 400/403 (and other
  4xx) → :class:`PermanentStorageError` — retrying a bad request or bad
  credentials only delays the real error; 408/429/5xx and every
  connection-level fault (refused, reset, timeout, short body) →
  :class:`TransientStorageError`. A 429/503 ``Retry-After`` header is
  parsed onto the error's ``retry_after_s`` so RetryingBackend can honor
  the server's own schedule (capped at its backoff ceiling).
- **Bounded I/O.** Every socket operation carries ``timeout=`` and every
  response read is byte-bounded (lint DLT021 enforces both for this
  module): a hostile or wedged server costs one deadline, not a hung
  training run or unbounded memory.
- **Atomic puts.** A single-shot put is one request; a multipart put is
  invisible until the final ``complete`` — parts live outside the object
  namespace and any failure triggers an abort, so readers NEVER observe a
  torn upload. Each part carries its sha256 so a corrupted part is
  rejected at upload time (400), not discovered at restore.
- **Signing stub point.** Requests are signed with a V4-shaped
  HMAC-SHA256 scheme (``DLT4-HMAC-SHA256``) over a canonical
  method/path/query/date/payload-sha string. :meth:`_signature` is the
  single seam where a production AWS SigV4 implementation slots in; the
  emulator verifies this scheme end to end. Credentials resolve
  explicit args → environment → credentials file → anonymous.

Integrity stays where it already lives: the manifest layer's
sha256-per-entry detects bit-rot through this backend exactly as it does
locally, and restore falls back past it (tests/test_zz_lake.py proves the
full path against the fault-scripted emulator).
"""

from __future__ import annotations

import hashlib
import hmac
import http.client
import logging
import os
import time
import urllib.parse
import xml.etree.ElementTree as ET
from typing import Dict, List, Optional, Tuple

from deeplearning4j_tpu.checkpoint.storage import (
    LocalFSBackend,
    ObjectStoreBackend,
    PermanentStorageError,
    RetryingBackend,
    StorageBackend,
    StorageNotFoundError,
    TransientStorageError,
    sweep_orphan_keys,
)

log = logging.getLogger(__name__)

__all__ = ["CloudObjectBackend", "CloudCredentials", "backend_from_url",
           "SIGNING_SCHEME"]

SIGNING_SCHEME = "DLT4-HMAC-SHA256"

# Environment variables consulted for credentials, in order; the AWS pair
# is accepted so an existing environment works unmodified.
_ENV_KEYS = (("DLT_LAKE_ACCESS_KEY_ID", "DLT_LAKE_SECRET_ACCESS_KEY"),
             ("AWS_ACCESS_KEY_ID", "AWS_SECRET_ACCESS_KEY"))
_ENV_CREDENTIALS_FILE = "DLT_LAKE_SHARED_CREDENTIALS_FILE"

_CHUNK = 1 << 20  # socket read granularity; bounds below cap totals


class CloudCredentials:
    """A resolved (access_key, secret_key) pair, or anonymous.

    Resolution order — first hit wins:

    1. explicit ``access_key``/``secret_key`` arguments;
    2. environment: ``DLT_LAKE_ACCESS_KEY_ID``/``DLT_LAKE_SECRET_ACCESS_KEY``
       then ``AWS_ACCESS_KEY_ID``/``AWS_SECRET_ACCESS_KEY``;
    3. a credentials file (``credentials_file`` argument or
       ``$DLT_LAKE_SHARED_CREDENTIALS_FILE``): ``key = value`` lines,
       ``#`` comments and ``[section]`` headers ignored, keys
       ``access_key_id``/``secret_access_key`` (an AWS-style shared
       credentials file parses as-is);
    4. anonymous (requests go unsigned).
    """

    def __init__(self, access_key: Optional[str] = None,
                 secret_key: Optional[str] = None):
        self.access_key = access_key
        self.secret_key = secret_key

    @property
    def anonymous(self) -> bool:
        return self.access_key is None or self.secret_key is None

    @classmethod
    def resolve(cls, access_key: Optional[str] = None,
                secret_key: Optional[str] = None,
                credentials_file: Optional[str] = None,
                env: Optional[Dict[str, str]] = None) -> "CloudCredentials":
        env = os.environ if env is None else env
        if access_key and secret_key:
            return cls(access_key, secret_key)
        for ak_var, sk_var in _ENV_KEYS:
            ak, sk = env.get(ak_var), env.get(sk_var)
            if ak and sk:
                return cls(ak, sk)
        path = credentials_file or env.get(_ENV_CREDENTIALS_FILE)
        if path and os.path.isfile(path):
            fields: Dict[str, str] = {}
            with open(path, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line or line.startswith(("#", ";", "[")):
                        continue
                    if "=" in line:
                        k, _, v = line.partition("=")
                        fields[k.strip().lower()] = v.strip()
            ak = fields.get("access_key_id") or fields.get(
                "aws_access_key_id")
            sk = fields.get("secret_access_key") or fields.get(
                "aws_secret_access_key")
            if ak and sk:
                return cls(ak, sk)
        return cls()


def sign_request(secret_key: str, method: str, path: str, query: str,
                 date: str, payload_sha: str) -> str:
    """The DLT4 signature over the canonical request string. Module-level
    so the emulator verifies with the exact same code the client signs
    with — the two cannot drift."""
    canonical = "\n".join((method.upper(), path, query, date, payload_sha))
    return hmac.new(secret_key.encode(), canonical.encode(),
                    hashlib.sha256).hexdigest()


class CloudObjectBackend(StorageBackend):
    """S3-dialect HTTP object-store client (see module docstring).

    ``endpoint`` is ``http://host:port`` (https accepted); ``bucket`` is
    the flat namespace all five methods operate in. One fresh connection
    per request — simple, stateless, and immune to a poisoned keep-alive
    socket after a mid-body disconnect.

    Knobs: ``timeout_s`` bounds EVERY socket operation (connect, send,
    recv); ``multipart_threshold`` and ``part_size`` shape large puts;
    ``max_object_bytes`` caps any single response body;
    ``list_page_size`` is the server-side page size (``max-keys``).
    """

    def __init__(self, endpoint: str, bucket: str = "checkpoints", *,
                 access_key: Optional[str] = None,
                 secret_key: Optional[str] = None,
                 credentials_file: Optional[str] = None,
                 timeout_s: float = 10.0,
                 multipart_threshold: int = 8 << 20,
                 part_size: int = 5 << 20,
                 max_object_bytes: int = 1 << 31,
                 list_page_size: int = 1000):
        parsed = urllib.parse.urlsplit(endpoint)
        if parsed.scheme not in ("http", "https"):
            raise ValueError(f"endpoint must be http(s)://host:port, "
                             f"got {endpoint!r}")
        if not parsed.hostname:
            raise ValueError(f"endpoint has no host: {endpoint!r}")
        if part_size <= 0 or multipart_threshold <= 0:
            raise ValueError("part_size and multipart_threshold must be > 0")
        self.scheme = parsed.scheme
        self.host = parsed.hostname
        self.port = parsed.port or (443 if parsed.scheme == "https" else 80)
        self.bucket = bucket
        self.credentials = CloudCredentials.resolve(
            access_key, secret_key, credentials_file)
        self.timeout_s = float(timeout_s)
        self.multipart_threshold = int(multipart_threshold)
        self.part_size = int(part_size)
        self.max_object_bytes = int(max_object_bytes)
        self.list_page_size = int(list_page_size)
        self.op_counts: Dict[str, int] = {}
        self.requests_sent = 0
        self.multipart_puts = 0
        self.multipart_aborts = 0
        self.uploads_aborted = 0  # clean_orphans: abandoned uploads reaped

    # ------------------------------------------------------------ plumbing
    def _count(self, op: str):
        self.op_counts[op] = self.op_counts.get(op, 0) + 1

    def _path(self, key: Optional[str] = None) -> str:
        base = "/" + urllib.parse.quote(self.bucket, safe="")
        if key is None:
            return base
        return base + "/" + urllib.parse.quote(key, safe="/-_.~")

    def _headers(self, method: str, path: str, query: str,
                 body: bytes) -> Dict[str, str]:
        payload_sha = hashlib.sha256(body).hexdigest()
        date = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        headers = {"Host": f"{self.host}:{self.port}",
                   "x-dlt-date": date,
                   "x-dlt-content-sha256": payload_sha,
                   "Content-Length": str(len(body))}
        if not self.credentials.anonymous:
            sig = self._signature(method, path, query, date, payload_sha)
            headers["Authorization"] = (
                f"{SIGNING_SCHEME} "
                f"Credential={self.credentials.access_key}/{date[:8]}, "
                f"SignedHeaders=host;x-dlt-date;x-dlt-content-sha256, "
                f"Signature={sig}")
        return headers

    def _signature(self, method: str, path: str, query: str, date: str,
                   payload_sha: str) -> str:
        """THE signing stub point: a production SigV4 (credential scoping,
        canonical header folding, signing-key derivation chain) replaces
        this one method; everything above and below is unchanged."""
        return sign_request(self.credentials.secret_key, method, path,
                            query, date, payload_sha)

    def _request(self, op: str, method: str, path: str, query: str = "",
                 body: bytes = b"", body_limit: Optional[int] = None
                 ) -> Tuple[int, Dict[str, str], bytes]:
        """One signed HTTP round trip → (status, headers, bounded body).

        Connection-level faults (refused/reset/timeout/short body) raise
        :class:`TransientStorageError`; HTTP statuses are returned to the
        caller, which maps them per-op (a 404 means different things to
        ``get`` and ``exists``)."""
        url = path + ("?" + query if query else "")
        headers = self._headers(method, path, query, body)
        limit = self.max_object_bytes if body_limit is None else body_limit
        self.requests_sent += 1
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout_s) \
            if self.scheme == "http" else \
            http.client.HTTPSConnection(self.host, self.port,
                                        timeout=self.timeout_s)
        try:
            conn.request(method, url, body=body, headers=headers)
            resp = conn.getresponse()
            status = resp.status
            resp_headers = dict(resp.getheaders())
            # a HEAD reply declares the object's length but carries no
            # body — reading against the header would misfire as a
            # mid-transfer disconnect
            data = b"" if method == "HEAD" else \
                self._read_bounded(resp, resp_headers, limit, op)
            return status, resp_headers, data
        except (http.client.HTTPException, OSError) as e:
            if isinstance(e, StorageNotFoundError):
                raise
            raise TransientStorageError(
                f"{op} {self.describe()}: connection fault "
                f"({type(e).__name__}: {e})") from e
        finally:
            conn.close()

    @staticmethod
    def _read_bounded(resp, headers: Dict[str, str], limit: int,
                      op: str) -> bytes:
        """Read a response body under an explicit byte bound. A declared
        length over the bound is a permanent fault (the object is simply
        too big for this client's budget); a body shorter than declared is
        a mid-transfer disconnect → transient."""
        declared: Optional[int] = None
        try:
            declared = int(headers.get("Content-Length", ""))
        except ValueError:
            pass
        if declared is not None and declared > limit:
            raise PermanentStorageError(
                f"{op}: response body {declared}B exceeds the "
                f"{limit}B bound")
        budget = declared if declared is not None else limit
        chunks = []
        got = 0
        while got < budget:
            chunk = resp.read(min(_CHUNK, budget - got))
            if not chunk:
                break
            chunks.append(chunk)
            got += len(chunk)
        if declared is None and resp.read(1):
            raise PermanentStorageError(
                f"{op}: unbounded response body exceeds the {limit}B bound")
        if declared is not None and got != declared:
            raise TransientStorageError(
                f"{op}: short body — got {got} of {declared} bytes "
                f"(mid-transfer disconnect)")
        return b"".join(chunks)

    @staticmethod
    def _retry_after(headers: Dict[str, str]) -> Optional[float]:
        raw = headers.get("Retry-After")
        if raw is None:
            return None
        try:
            return max(0.0, float(raw))
        except ValueError:
            return None  # HTTP-date form — fall back to our own schedule

    def _raise_for_status(self, op: str, name: str, status: int,
                          headers: Dict[str, str], body: bytes):
        where = f"{self.bucket}/{name}" if name else self.bucket
        detail = body[:200].decode("utf-8", "replace")
        if status == 404:
            raise StorageNotFoundError(f"no such object: {where}")
        if status in (408, 429) or status >= 500:
            raise TransientStorageError(
                f"{op} {where}: HTTP {status} ({detail})",
                retry_after_s=self._retry_after(headers))
        raise PermanentStorageError(f"{op} {where}: HTTP {status} ({detail})")

    # ----------------------------------------------------------- interface
    def put(self, name: str, data: bytes, fsync_directory: bool = True):
        self._count("put")
        data = bytes(data)
        if len(data) >= self.multipart_threshold:
            return self._put_multipart(name, data)
        status, headers, body = self._request(
            "put", "PUT", self._path(name), body=data, body_limit=1 << 20)
        if status not in (200, 201, 204):
            self._raise_for_status("put", name, status, headers, body)

    def _put_multipart(self, name: str, data: bytes):
        """Initiate → PUT parts (each with its sha256) → complete; ANY
        failure aborts the upload so a torn put is never visible. The
        complete is the single atomic commit point."""
        self.multipart_puts += 1
        path = self._path(name)
        status, headers, body = self._request(
            "mpu-initiate", "POST", path, query="uploads",
            body_limit=1 << 20)
        if status != 200:
            self._raise_for_status("mpu-initiate", name, status, headers,
                                   body)
        upload_id = _xml_text(body, "UploadId")
        if not upload_id:
            raise PermanentStorageError(
                f"mpu-initiate {self.bucket}/{name}: no UploadId in reply")
        try:
            etags = []
            for number, off in enumerate(range(0, len(data),
                                               self.part_size), start=1):
                part = data[off:off + self.part_size]
                q = (f"partNumber={number}&uploadId="
                     f"{urllib.parse.quote(upload_id, safe='')}")
                status, headers, body = self._request(
                    "mpu-part", "PUT", path, query=q, body=part,
                    body_limit=1 << 20)
                if status != 200:
                    self._raise_for_status("mpu-part", name, status,
                                           headers, body)
                etags.append((number,
                              headers.get("ETag",
                                          hashlib.sha256(part).hexdigest())))
            parts_xml = "".join(
                f"<Part><PartNumber>{n}</PartNumber><ETag>{e}</ETag></Part>"
                for n, e in etags)
            complete = (f"<CompleteMultipartUpload>{parts_xml}"
                        f"</CompleteMultipartUpload>").encode()
            q = f"uploadId={urllib.parse.quote(upload_id, safe='')}"
            status, headers, body = self._request(
                "mpu-complete", "POST", path, query=q, body=complete,
                body_limit=1 << 20)
            if status != 200:
                self._raise_for_status("mpu-complete", name, status,
                                       headers, body)
        except BaseException:
            self._abort_upload(name, upload_id)
            raise

    def _abort_upload(self, name: str, upload_id: str) -> bool:
        """Best-effort multipart abort; a failed abort leaves the upload
        for :meth:`clean_orphans` to reap later."""
        self.multipart_aborts += 1
        q = f"uploadId={urllib.parse.quote(upload_id, safe='')}"
        try:
            status, _, _ = self._request(
                "mpu-abort", "DELETE", self._path(name), query=q,
                body_limit=1 << 20)
            return status in (200, 204, 404)
        except Exception as e:
            log.warning("multipart abort of %s/%s upload %s failed "
                        "(%s: %s) — clean_orphans will reap it",
                        self.bucket, name, upload_id, type(e).__name__, e)
            return False

    def get(self, name: str) -> bytes:
        self._count("get")
        status, headers, body = self._request("get", "GET",
                                              self._path(name))
        if status != 200:
            self._raise_for_status("get", name, status, headers, body)
        return body

    def list(self, prefix: str = "") -> List[str]:
        self._count("list")
        names: List[str] = []
        token: Optional[str] = None
        while True:
            q = (f"list-type=2&max-keys={self.list_page_size}"
                 f"&prefix={urllib.parse.quote(prefix, safe='')}")
            if token:
                q += f"&continuation-token={urllib.parse.quote(token, safe='')}"
            status, headers, body = self._request(
                "list", "GET", self._path(), query=q, body_limit=16 << 20)
            if status != 200:
                self._raise_for_status("list", "", status, headers, body)
            page, truncated, token = _parse_list_page(body)
            names.extend(page)
            if not truncated:
                break
            if not token:
                raise PermanentStorageError(
                    "list: truncated page without a continuation token")
        return sorted(names)

    def delete(self, name: str):
        self._count("delete")
        status, headers, body = self._request(
            "delete", "DELETE", self._path(name), body_limit=1 << 20)
        if status not in (200, 204, 404):  # deleting a missing key is a no-op
            self._raise_for_status("delete", name, status, headers, body)

    def exists(self, name: str) -> bool:
        self._count("exists")
        status, headers, body = self._request(
            "exists", "HEAD", self._path(name), body_limit=1 << 20)
        if status == 200:
            return True
        if status == 404:
            return False
        self._raise_for_status("exists", name, status, headers, body)
        return False  # unreachable

    def clean_orphans(self):
        """Reap BOTH orphan classes a crash can leave in a bucket: staging
        keys under the shared ``tmp-``/``.part`` convention (same sweep as
        ObjectStoreBackend) and abandoned multipart uploads — parts from a
        writer that died between initiate and complete/abort hold storage
        but are invisible to every reader."""
        swept = sweep_orphan_keys(self)
        status, headers, body = self._request(
            "mpu-list", "GET", self._path(), query="uploads",
            body_limit=16 << 20)
        if status != 200:
            self._raise_for_status("mpu-list", "", status, headers, body)
        uploads = _parse_uploads_page(body)
        for key, upload_id in uploads:
            if self._abort_upload(key, upload_id):
                self.uploads_aborted += 1
        if uploads:
            log.info("aborted %d abandoned multipart upload(s) in %s",
                     len(uploads), self.bucket)
        return swept

    def describe(self) -> str:
        return (f"CloudObjectBackend({self.scheme}://{self.host}:"
                f"{self.port}/{self.bucket})")


# ------------------------------------------------------------ XML parsing
def _xml_text(body: bytes, tag: str) -> Optional[str]:
    try:
        root = ET.fromstring(body.decode("utf-8", "replace"))
    except ET.ParseError:
        return None
    if root.tag == tag:
        return root.text
    el = root.find(f".//{tag}")
    return el.text if el is not None else None


def _parse_list_page(body: bytes) -> Tuple[List[str], bool, Optional[str]]:
    """One ListBucketResult page → (keys, is_truncated, next_token)."""
    try:
        root = ET.fromstring(body.decode("utf-8", "replace"))
    except ET.ParseError as e:
        raise TransientStorageError(f"list: unparseable page ({e})") from e
    keys = [el.text or "" for el in root.findall(".//Contents/Key")]
    truncated = (root.findtext("IsTruncated", "false").strip().lower()
                 == "true")
    token = root.findtext("NextContinuationToken") or None
    return keys, truncated, token


def _parse_uploads_page(body: bytes) -> List[Tuple[str, str]]:
    """ListMultipartUploadsResult → [(key, upload_id), ...]."""
    try:
        root = ET.fromstring(body.decode("utf-8", "replace"))
    except ET.ParseError as e:
        raise TransientStorageError(
            f"mpu-list: unparseable reply ({e})") from e
    out = []
    for up in root.findall(".//Upload"):
        key, uid = up.findtext("Key"), up.findtext("UploadId")
        if key and uid:
            out.append((key, uid))
    return out


# ------------------------------------------------------------ URL factory
def backend_from_url(url: str, *, cache_dir: Optional[str] = None,
                     cache_bytes: int = 256 << 20,
                     retries: int = 5,
                     timeout_s: float = 10.0,
                     access_key: Optional[str] = None,
                     secret_key: Optional[str] = None) -> StorageBackend:
    """One string → a ready-to-use backend stack. The shared address
    syntax for ``tools/lake.py``, ``restore_and_serve`` and tests:

    - ``http://host:port/bucket`` (or https) →
      RetryingBackend(CloudObjectBackend), Retry-After honored;
    - ``mem:`` → a fresh in-process ObjectStoreBackend (test double);
    - ``file:/path`` or a bare path → LocalFSBackend.

    ``cache_dir`` additionally wraps the stack in a CachedBackend disk LRU
    (``cache_bytes`` budget) — cache hits never touch the wire or the
    retry layer; fills and write-throughs go through both.
    """
    inner: StorageBackend
    if url.startswith(("http://", "https://")):
        parsed = urllib.parse.urlsplit(url)
        bucket = parsed.path.strip("/")
        if not bucket or "/" in bucket:
            raise ValueError(
                f"cloud URL must be http(s)://host:port/bucket, got {url!r}")
        endpoint = f"{parsed.scheme}://{parsed.netloc}"
        cloud = CloudObjectBackend(endpoint, bucket, timeout_s=timeout_s,
                                   access_key=access_key,
                                   secret_key=secret_key)
        inner = RetryingBackend(cloud, max_retries=retries) \
            if retries > 0 else cloud
    elif url.startswith("mem:"):
        inner = ObjectStoreBackend(bucket=url[4:] or "checkpoints")
    else:
        path = url[5:] if url.startswith("file:") else url
        inner = LocalFSBackend(path)
    if cache_dir:
        from deeplearning4j_tpu.checkpoint.cache import CachedBackend
        inner = CachedBackend(inner, cache_dir, max_bytes=cache_bytes)
    return inner
