"""Checksummed checkpoint journal + atomic commit primitives.

Crash consistency contract (checkpoint/manager.py is the only writer):

1. checkpoint bytes are committed atomically through a
   :class:`~deeplearning4j_tpu.checkpoint.storage.StorageBackend` — on the
   local filesystem that is ``<dir>/tmp/`` + fsync + ``os.replace`` (atomic
   on POSIX) + directory fsync, so a crash mid-write leaves only a ``tmp/``
   orphan, never a half-written ``ckpt-*.zip``; on an object store a put is
   whole-object atomic by construction;
2. only AFTER the payload is durable is its entry (with the payload's
   sha256) journaled into ``manifest.json``, itself rewritten atomically
   with an embedded checksum over the entries payload.

So at every instant the manifest describes only fully-committed objects,
and a torn manifest or a bit-rotted checkpoint is DETECTED (self-checksum /
per-entry sha256) instead of restored: ``restore_latest`` falls back entry
by entry, and a missing or corrupt manifest degrades to scanning the
backend, where the zip layer's CRC checks still reject torn payloads. The
journal/fallback logic is identical through every backend — only the five
byte-store ops differ.

``load_manifest`` / ``write_manifest`` / ``scan_checkpoint_files`` accept a
directory path (wrapped in a LocalFSBackend, the historical signature) or
any ``StorageBackend``.

Reference analogue: none — DL4J's CheckpointListener writes in place with
no journal; a crash mid-save loses the run. This is part of the durability
substrate a preemptible-TPU deployment must supply itself.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import List, Optional

MANIFEST_NAME = "manifest.json"
TMP_DIR = "tmp"
MANIFEST_VERSION = 1


class ManifestError(RuntimeError):
    """The manifest exists but is torn/corrupt (invalid JSON, bad
    self-checksum, or wrong shape)."""


def file_sha256(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def _entries_checksum(entries: List[dict]) -> str:
    payload = json.dumps(entries, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(payload.encode()).hexdigest()


def _fsync_dir(directory: str):
    # directory fsync makes the rename itself durable; some filesystems
    # (or platforms) don't support opening a directory — best effort there
    try:
        fd = os.open(directory, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_write_bytes(directory: str, name: str, data: bytes,
                       fsync_directory: bool = True) -> str:
    """Write ``data`` as ``<directory>/<name>`` via tmp/ + fsync + rename.
    Returns the final path. Callers see either the complete new file or no
    file — never a prefix.

    ``fsync_directory=False`` skips making the RENAME itself durable —
    valid only when the caller immediately follows with another
    atomic write in the SAME directory whose dir-fsync covers this one
    (the manager's payload-then-manifest commit: the entry only becomes
    durable together with, never before, the payload's directory entry)."""
    tmp_dir = os.path.join(directory, TMP_DIR)
    os.makedirs(tmp_dir, exist_ok=True)
    # nested names ("shards/x.npz", the data-lake key shape) stage FLAT in
    # tmp/ and land under their subdirectory on the rename
    fd, tmp_path = tempfile.mkstemp(dir=tmp_dir,
                                    prefix=name.replace(os.sep, "_")
                                               .replace("/", "_") + ".",
                                    suffix=".part")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        final = os.path.join(directory, name)
        parent = os.path.dirname(final)
        if parent and not os.path.isdir(parent):
            os.makedirs(parent, exist_ok=True)
        os.replace(tmp_path, final)
    except BaseException:
        try:
            os.remove(tmp_path)
        except OSError:
            pass
        raise
    if fsync_directory:
        _fsync_dir(directory)
    return final


def clean_tmp(directory: str):
    """Remove orphaned partial writes left by a crash mid-checkpoint."""
    tmp_dir = os.path.join(directory, TMP_DIR)
    if not os.path.isdir(tmp_dir):
        return
    for name in os.listdir(tmp_dir):
        try:
            os.remove(os.path.join(tmp_dir, name))
        except OSError:
            pass


def _as_backend(target):
    # deferred import: storage.py imports atomic_write_bytes from here
    from deeplearning4j_tpu.checkpoint.storage import as_backend
    return as_backend(target)


def write_manifest(target, entries: List[dict]):
    """Atomically rewrite the journal with a self-checksum over its entries.
    ``target`` is a directory path or a StorageBackend."""
    body = {"version": MANIFEST_VERSION, "entries": entries,
            "checksum": _entries_checksum(entries)}
    _as_backend(target).put(MANIFEST_NAME,
                            json.dumps(body, indent=1).encode())


def load_manifest(target) -> Optional[List[dict]]:
    """Entries from the journal; ``None`` when no manifest exists yet.
    Raises :class:`ManifestError` on a torn/corrupt manifest — callers fall
    back to :func:`scan_checkpoint_files`. ``target`` is a directory path
    or a StorageBackend."""
    from deeplearning4j_tpu.checkpoint.storage import (StorageError,
                                                       StorageNotFoundError)
    backend = _as_backend(target)
    try:
        raw = backend.get(MANIFEST_NAME)
    except StorageNotFoundError:
        return None
    except (OSError, StorageError) as e:
        # present-but-unreadable (EACCES/EIO on a flaky mount, a store
        # outage): surface as a torn manifest so the manager falls back to
        # its rebuild-from-scan path instead of failing construction
        raise ManifestError(
            f"unreadable manifest at {backend.describe()}/{MANIFEST_NAME}: "
            f"{type(e).__name__}: {e}") from e
    try:
        body = json.loads(raw.decode("utf-8"))
        entries = body["entries"]
        if not isinstance(entries, list):
            raise TypeError("entries is not a list")
        if body.get("checksum") != _entries_checksum(entries):
            raise ValueError("manifest self-checksum mismatch")
    except (ValueError, KeyError, TypeError, UnicodeDecodeError) as e:
        raise ManifestError(
            f"corrupt manifest at {backend.describe()}/{MANIFEST_NAME}: "
            f"{e}") from e
    return entries


def scan_checkpoint_files(target) -> List[dict]:
    """Degraded-mode recovery: entries (without sha256) for every
    ``ckpt-*.zip`` present, in name (= commit) order. Used when the
    manifest itself was lost or torn; the zip CRC layer still guards each
    payload's integrity during restore. ``target`` is a directory path or
    a StorageBackend."""
    names = _as_backend(target).list(prefix="ckpt-")
    return [{"file": n, "sha256": None} for n in names
            if n.endswith(".zip")]
