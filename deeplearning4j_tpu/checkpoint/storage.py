"""Pluggable checkpoint storage backends.

``CheckpointManager`` + the manifest journal speak one small byte-store
interface — :class:`StorageBackend` — instead of the filesystem directly,
so checkpoints can land anywhere that offers atomic single-object commits:

- :class:`LocalFSBackend` — the original behavior (tmp + fsync + rename in
  one directory, checkpoint/manifest.py's commit primitives) and the
  default when a manager is built from a ``directory``;
- :class:`ObjectStoreBackend` — GCS/S3-style put/get/list/delete
  semantics: whole-object atomic puts (an object is either absent or the
  complete last-put bytes — exactly the property the torn-write fallback
  relies on locally), no partial reads, list-by-prefix. The in-process
  dict implementation here is the test double; a real GCS client maps 1:1
  onto the five methods;
- :class:`RetryingBackend` — a wrapper adding bounded
  exponential-backoff-with-jitter retries (utils/backoff.py, shared with
  storage/remote.py) and optional per-op timeouts, so TRANSIENT storage
  faults (throttling, flaky DCN, a 9p hiccup) never kill a training run.
  Permanent faults (:class:`PermanentStorageError`) are surfaced
  immediately — retrying a 403 only delays the real error.

Durability contract every backend must keep (what the manager's
payload-then-manifest commit depends on):

1. ``put`` is atomic: readers see the old object or the complete new one,
   never a prefix;
2. after ``put(name, data)`` returns, ``get(name)`` observes ``data``
   (read-your-writes within the writer process suffices);
3. ``get`` of a missing object raises :class:`StorageNotFoundError`.

Integrity does NOT move into the backend: the manifest layer keeps its
sha256-per-entry + self-checksummed journal through ANY backend, so a
bit-rotted object is detected and restore falls back identically whether
the bytes came from a local disk or an object store
(tests/test_resilience.py proves both).
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from typing import Callable, Dict, List, Optional

from deeplearning4j_tpu.utils.backoff import backoff_delay

log = logging.getLogger(__name__)

__all__ = [
    "StorageBackend", "LocalFSBackend", "ObjectStoreBackend",
    "RetryingBackend", "StorageError", "TransientStorageError",
    "PermanentStorageError", "StorageNotFoundError", "as_backend",
    "ORPHAN_KEY_PREFIXES", "ORPHAN_KEY_SUFFIXES", "is_orphan_key",
    "sweep_orphan_keys",
]


class StorageError(RuntimeError):
    """Base class for backend failures."""


class TransientStorageError(StorageError):
    """A fault worth retrying: throttling, timeouts, flaky transport.

    ``retry_after_s`` carries a server-issued ``Retry-After`` hint when the
    fault came off the wire (a 429/503 from an object store);
    :class:`RetryingBackend` honors it in place of its own backoff delay,
    capped at the configured ceiling. ``None`` means "no hint — use the
    schedule"."""

    def __init__(self, *args, retry_after_s: Optional[float] = None):
        super().__init__(*args)
        self.retry_after_s = retry_after_s


class PermanentStorageError(StorageError):
    """A fault retries cannot fix: auth, missing bucket, bad request."""


class StorageNotFoundError(PermanentStorageError, FileNotFoundError):
    """The named object does not exist (also a FileNotFoundError so
    path-era callers' ``except FileNotFoundError`` keeps working)."""


class StorageBackend:
    """Abstract byte store for checkpoint payloads + the manifest journal.

    Implementations provide the five operations below; see the module
    docstring for the atomicity/visibility contract. ``describe()`` feeds
    log lines and ``ResumeState.path`` provenance strings."""

    def put(self, name: str, data: bytes, fsync_directory: bool = True):
        """Atomically commit ``data`` as the object ``name``.

        ``fsync_directory`` is a LOCAL-FS durability hint (make the rename
        itself durable); object stores, where a put is durable on return,
        ignore it. The manager passes ``False`` for the checkpoint payload
        because the manifest put that immediately follows in the same
        directory covers it."""
        raise NotImplementedError

    def get(self, name: str) -> bytes:
        """The complete committed bytes of ``name``;
        :class:`StorageNotFoundError` when absent."""
        raise NotImplementedError

    def list(self, prefix: str = "") -> List[str]:
        """Committed object names starting with ``prefix``, sorted."""
        raise NotImplementedError

    def delete(self, name: str):
        """Remove ``name``; deleting a missing object is a no-op (retention
        is best-effort and a retried delete must be idempotent)."""
        raise NotImplementedError

    def exists(self, name: str) -> bool:
        raise NotImplementedError

    # ------------------------------------------------------------- optional
    def clean_orphans(self):
        """Remove partial-write leftovers from a crash: local tmp files
        (:class:`LocalFSBackend`), orphaned ``tmp-``/``.part`` keys
        (:class:`ObjectStoreBackend`, :func:`sweep_orphan_keys`), abandoned
        multipart uploads (``CloudObjectBackend``). Base class is a no-op
        for backends with nothing to clean."""

    def describe(self) -> str:
        return type(self).__name__


# Naming convention for keys that are, by construction, never a committed
# object: staging keys a writer parks bytes under before its final atomic
# commit. A crash between staging and commit leaves them behind;
# ``clean_orphans`` sweeps them because no reader ever looks for them.
ORPHAN_KEY_PREFIXES = ("tmp-",)
ORPHAN_KEY_SUFFIXES = (".tmp", ".part")


def is_orphan_key(name: str) -> bool:
    """True when ``name`` is a staging key under the orphan convention
    (a ``tmp-`` basename segment or a ``.tmp``/``.part`` suffix)."""
    base = name.rsplit("/", 1)[-1]
    return (base.startswith(ORPHAN_KEY_PREFIXES)
            or name.endswith(ORPHAN_KEY_SUFFIXES))


def sweep_orphan_keys(backend: "StorageBackend") -> List[str]:
    """Delete every orphan-convention key visible in ``backend`` and return
    the deleted names. Shared by :class:`ObjectStoreBackend` and
    ``CloudObjectBackend`` (which additionally aborts in-flight multipart
    uploads over the wire). Deletes are idempotent, so racing a concurrent
    sweep is harmless."""
    swept = [n for n in backend.list() if is_orphan_key(n)]
    for name in swept:
        backend.delete(name)
    if swept:
        log.info("swept %d orphan key(s) from %s: %s", len(swept),
                 backend.describe(), ", ".join(swept[:8]))
    return swept


class LocalFSBackend(StorageBackend):
    """One directory on a local filesystem — the manager's historical
    behavior, via the same tmp + fsync + rename commit primitive
    (manifest.atomic_write_bytes). Names may nest ("shards/x.npz", the
    data-lake key shape): they land as subdirectories and ``list``
    walks them back out with "/"-joined names."""

    def __init__(self, directory: str):
        self.directory = str(directory)

    def _ensure_dir(self):
        os.makedirs(self.directory, exist_ok=True)

    def put(self, name: str, data: bytes, fsync_directory: bool = True):
        from deeplearning4j_tpu.checkpoint.manifest import atomic_write_bytes
        self._ensure_dir()
        atomic_write_bytes(self.directory, name, data,
                           fsync_directory=fsync_directory)

    def get(self, name: str) -> bytes:
        path = os.path.join(self.directory, name)
        try:
            with open(path, "rb") as f:
                return f.read()
        except FileNotFoundError as e:
            raise StorageNotFoundError(f"no such object: {path}") from e

    def list(self, prefix: str = "") -> List[str]:
        if not os.path.isdir(self.directory):
            return []
        from deeplearning4j_tpu.checkpoint.manifest import TMP_DIR
        names = []
        for root, dirs, files in os.walk(self.directory):
            if root == self.directory and TMP_DIR in dirs:
                dirs.remove(TMP_DIR)  # staging area, never an object
            rel = os.path.relpath(root, self.directory)
            for n in files:
                name = n if rel == "." else \
                    os.path.join(rel, n).replace(os.sep, "/")
                if name.startswith(prefix):
                    names.append(name)
        return sorted(names)

    def delete(self, name: str):
        try:
            os.remove(os.path.join(self.directory, name))
        except FileNotFoundError:
            pass

    def exists(self, name: str) -> bool:
        return os.path.isfile(os.path.join(self.directory, name))

    def clean_orphans(self):
        from deeplearning4j_tpu.checkpoint.manifest import clean_tmp
        if os.path.isdir(self.directory):
            clean_tmp(self.directory)
        return sweep_orphan_keys(self)

    def describe(self) -> str:
        return f"LocalFSBackend({self.directory})"


class ObjectStoreBackend(StorageBackend):
    """GCS-style flat-namespace object store, modeled in process.

    ``store`` is the bucket: a plain dict shared between backend instances
    the way a real bucket is shared between processes — a serving process's
    manager and a training process's manager pointing at the same dict see
    each other's commits, which is what the hot-swap tests exercise.
    Objects are immutable snapshots (puts copy), so a caller mutating its
    buffer after ``put`` cannot corrupt the committed version."""

    # one lock per shared bucket dict, NOT per backend instance: two
    # instances over the same store (the trainer/serving shape above) must
    # exclude each other, or a reader's list() races a writer's put()
    # ("dictionary changed size during iteration"). Plain dicts can't be
    # weakly referenced, so the registry refcounts backends per store and
    # a weakref.finalize on each backend drops the entry when its last
    # user is collected — the store (and every checkpoint in it) is not
    # pinned for the life of the process.
    _STORE_LOCKS: Dict[int, list] = {}  # id(store) -> [store, lock, refs]
    _REGISTRY_LOCK = threading.Lock()

    @classmethod
    def _lock_for(cls, store: Dict[str, bytes], owner) -> threading.Lock:
        import weakref
        with cls._REGISTRY_LOCK:
            key = id(store)
            entry = cls._STORE_LOCKS.get(key)
            if entry is None:
                entry = [store, threading.Lock(), 0]
                cls._STORE_LOCKS[key] = entry
            entry[2] += 1

        def _release(key=key, entry=entry):
            with cls._REGISTRY_LOCK:
                entry[2] -= 1
                if entry[2] <= 0 and cls._STORE_LOCKS.get(key) is entry:
                    del cls._STORE_LOCKS[key]

        weakref.finalize(owner, _release)
        return entry[1]

    def __init__(self, store: Optional[Dict[str, bytes]] = None,
                 bucket: str = "checkpoints"):
        self._store: Dict[str, bytes] = store if store is not None else {}
        self.bucket = bucket
        self._lock = self._lock_for(self._store, self)
        self.op_counts: Dict[str, int] = {}

    def _count(self, op: str):
        self.op_counts[op] = self.op_counts.get(op, 0) + 1

    def put(self, name: str, data: bytes, fsync_directory: bool = True):
        b = bytes(data)
        with self._lock:
            self._count("put")
            self._store[name] = b

    def get(self, name: str) -> bytes:
        with self._lock:
            self._count("get")
            try:
                return self._store[name]
            except KeyError as e:
                raise StorageNotFoundError(
                    f"no such object: {self.bucket}/{name}") from e

    def list(self, prefix: str = "") -> List[str]:
        with self._lock:
            self._count("list")
            return sorted(n for n in self._store if n.startswith(prefix))

    def delete(self, name: str):
        with self._lock:
            self._count("delete")
            self._store.pop(name, None)

    def exists(self, name: str) -> bool:
        with self._lock:
            return name in self._store

    def clean_orphans(self):
        """Sweep orphaned staging keys (``tmp-``/``.part`` convention).

        Committed puts here are all-or-nothing, but clients that STAGE
        through the store (a resumable uploader parking parts, a copier
        writing ``<name>.tmp`` before a final put+delete) leave orphan keys
        behind on a crash — the object-store analogue of LocalFSBackend's
        tmp files."""
        sweep_orphan_keys(self)

    def describe(self) -> str:
        return f"ObjectStoreBackend({self.bucket})"


class RetryingBackend(StorageBackend):
    """Bounded exponential-backoff-with-jitter retries + per-op timeouts
    around any inner backend.

    Retries :class:`TransientStorageError`, ``OSError`` and ``TimeoutError``
    (``retry_on`` overrides); :class:`PermanentStorageError` and everything
    else propagate immediately. After ``max_retries`` failed retries the
    LAST transient error is re-raised — the caller (the manager's writer
    thread) then surfaces it as a CheckpointError instead of hanging.

    When a caught :class:`TransientStorageError` carries a server-issued
    ``retry_after_s`` hint (CloudObjectBackend parses it off 429/503
    ``Retry-After`` headers), the hint replaces that attempt's backoff
    delay, capped at ``max_backoff_s``; hint-less faults use the jittered
    schedule unchanged. ``retry_after_honored`` counts the substitutions.

    ``op_timeout_s`` bounds each attempt: the inner op runs on a worker
    thread (the watchdog's deadline pattern — a hung 9p fsync or stalled
    store RPC cannot be cancelled in-place) and an overrun counts as a
    transient fault. A timed-out attempt's thread is abandoned, daemon, and
    its late result discarded; leave ``op_timeout_s=None`` (default) to run
    ops inline with zero threading overhead.

    ``rng`` seeds the jitter for deterministic tests; ``sleep`` is
    injectable for the same reason."""

    _RETRYABLE = (TransientStorageError, OSError, TimeoutError)

    def __init__(self, inner: StorageBackend, max_retries: int = 5,
                 base_backoff_s: float = 0.05, max_backoff_s: float = 2.0,
                 op_timeout_s: Optional[float] = None,
                 retry_on: Optional[tuple] = None,
                 rng: Optional[random.Random] = None,
                 sleep: Callable[[float], None] = time.sleep):
        if max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        self.inner = inner
        self.max_retries = int(max_retries)
        self.base_backoff_s = float(base_backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self.op_timeout_s = op_timeout_s
        self.retry_on = tuple(retry_on) if retry_on is not None \
            else RetryingBackend._RETRYABLE
        self._rng = rng if rng is not None else random.Random()
        self._sleep = sleep
        self.attempts = 0
        self.retries = 0
        self.gave_up = 0
        self.retry_after_honored = 0

    # ---------------------------------------------------------- core loop
    def _attempt_once(self, op: str, fn: Callable):
        if self.op_timeout_s is None:
            return fn()
        done = threading.Event()
        out: dict = {}

        def run():
            try:
                out["v"] = fn()
            except BaseException as e:
                out["e"] = e
            finally:
                done.set()

        t = threading.Thread(target=run, daemon=True,
                             name=f"storage-{op}-timeout")
        t.start()
        if not done.wait(self.op_timeout_s):
            raise TransientStorageError(
                f"storage op '{op}' on {self.inner.describe()} exceeded "
                f"its {self.op_timeout_s:.3g}s deadline")
        if "e" in out:
            raise out["e"]
        return out.get("v")

    def _with_retries(self, op: str, fn: Callable):
        # StorageNotFoundError subclasses FileNotFoundError (an OSError) —
        # but a missing object is a definitive answer, not a fault, and
        # retrying it would turn every restore fallback probe into a
        # multi-second backoff stall
        last: Optional[BaseException] = None
        for attempt in range(self.max_retries + 1):
            self.attempts += 1
            try:
                return self._attempt_once(op, fn)
            except PermanentStorageError:
                raise
            except self.retry_on as e:
                last = e
                if attempt >= self.max_retries:
                    break
                hint = getattr(e, "retry_after_s", None)
                if hint is not None:
                    # the server said when to come back — believe it, but
                    # never wait longer than our own backoff ceiling (a
                    # hostile/buggy Retry-After must not stall the writer)
                    delay = min(max(float(hint), 0.0), self.max_backoff_s)
                    self.retry_after_honored += 1
                else:
                    delay = backoff_delay(attempt,
                                          base_s=self.base_backoff_s,
                                          cap_s=self.max_backoff_s,
                                          rng=self._rng)
                log.warning(
                    "storage op '%s' on %s failed (%s: %s) — retry %d/%d "
                    "in %.3fs", op, self.inner.describe(),
                    type(e).__name__, e, attempt + 1, self.max_retries,
                    delay)
                self.retries += 1
                self._sleep(delay)
        self.gave_up += 1
        log.error("storage op '%s' on %s failed after %d attempts — giving "
                  "up", op, self.inner.describe(), self.max_retries + 1)
        raise last

    # ----------------------------------------------------------- interface
    def put(self, name: str, data: bytes, fsync_directory: bool = True):
        return self._with_retries(
            "put", lambda: self.inner.put(name, data,
                                          fsync_directory=fsync_directory))

    def get(self, name: str) -> bytes:
        return self._with_retries("get", lambda: self.inner.get(name))

    def list(self, prefix: str = "") -> List[str]:
        return self._with_retries("list", lambda: self.inner.list(prefix))

    def delete(self, name: str):
        return self._with_retries("delete", lambda: self.inner.delete(name))

    def exists(self, name: str) -> bool:
        return self._with_retries("exists", lambda: self.inner.exists(name))

    def clean_orphans(self):
        return self._with_retries("clean_orphans", self.inner.clean_orphans)

    def describe(self) -> str:
        return f"RetryingBackend({self.inner.describe()})"


def as_backend(target) -> StorageBackend:
    """Normalize a ``StorageBackend`` | directory path into a backend —
    the shim that lets the manifest functions keep their path-based
    signatures for existing callers."""
    if isinstance(target, StorageBackend):
        return target
    return LocalFSBackend(str(target))
