"""A hermetic, fault-injecting object-store emulator — the HTTP-level
successor to :class:`~deeplearning4j_tpu.checkpoint.faults.FlakyBackend`.

One stdlib :class:`ThreadingHTTPServer` (the serving house style) speaking
the same S3-dialect REST that :class:`CloudObjectBackend` emits: object
GET/PUT/HEAD/DELETE, ``list-type=2`` paging with continuation tokens, the
full multipart protocol (initiate/part/complete/abort + in-flight upload
listing), and DLT4 signature verification when credentials are configured.
Objects live in an in-process dict, so every chaos test runs with zero
external services — but the failure surface is the REAL one: sockets,
status codes, headers, half-sent bodies.

Faults are scripted exactly like FlakyBackend's, aimable at a logical op
and a key prefix, consumed one request each:

- ``script("latency", seconds=0.2)``        — stall, then answer normally;
- ``script("status", code=429, retry_after=0.05)`` — error burst with an
  optional ``Retry-After`` header (503s the same way);
- ``script("disconnect")``                  — declare the full
  Content-Length, send half the body, close the socket (mid-transfer
  disconnect → the client's short-body transient);
- ``script("bitrot")``                      — serve the body with one byte
  flipped (transport-level rot; :meth:`flip_byte` rots at REST instead).

A torn multipart upload is composed from primitives: script a ``status``
fault on op ``"complete"`` (and optionally on ``"abort"``) — the client
must abort, and a reader must never observe the partial object;
``clean_orphans`` reaps whatever an aborted abort leaves behind.

Ops for targeting: ``get put list exists delete initiate part complete
abort mpu-list``. ``transient_rate`` adds FlakyBackend-style seeded
probabilistic 503s on top of scripted faults.
"""

from __future__ import annotations

import hashlib
import logging
import random
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from deeplearning4j_tpu.checkpoint.cloud import SIGNING_SCHEME, sign_request
from deeplearning4j_tpu.utils.http import parse_content_length

log = logging.getLogger(__name__)

__all__ = ["ObjectStoreEmulator"]

_FAULT_KINDS = ("latency", "status", "disconnect", "bitrot")


class _Handler(BaseHTTPRequestHandler):
    server_ref: "ObjectStoreEmulator" = None  # bound per-emulator below
    protocol_version = "HTTP/1.1"
    timeout = 30  # a wedged client costs one handler thread for 30s, max

    def log_message(self, fmt, *args):  # route through logging, not stderr
        log.debug("emulator: " + fmt, *args)

    # --------------------------------------------------------- dispatch
    def do_GET(self):
        self.server_ref.handle(self, "GET")

    def do_PUT(self):
        self.server_ref.handle(self, "PUT")

    def do_POST(self):
        self.server_ref.handle(self, "POST")

    def do_DELETE(self):
        self.server_ref.handle(self, "DELETE")

    def do_HEAD(self):
        self.server_ref.handle(self, "HEAD")


class ObjectStoreEmulator:
    """See module docstring. ``start()`` binds (port 0 = auto), ``.url``
    is the endpoint for :class:`CloudObjectBackend`; use as a context
    manager in tests. ``require_auth`` defaults on when both keys are
    given."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 access_key: Optional[str] = None,
                 secret_key: Optional[str] = None,
                 require_auth: Optional[bool] = None,
                 max_body_bytes: int = 256 << 20,
                 transient_rate: float = 0.0,
                 seed: Optional[int] = None):
        self.host = host
        self.port = int(port)
        self.access_key = access_key
        self.secret_key = secret_key
        self.require_auth = (bool(access_key and secret_key)
                             if require_auth is None else bool(require_auth))
        self.max_body_bytes = int(max_body_bytes)
        self.transient_rate = float(transient_rate)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.objects: Dict[str, Dict[str, bytes]] = {}   # bucket -> key -> b
        self._uploads: Dict[Tuple[str, str], Dict[int, bytes]] = {}
        self._upload_keys: Dict[str, str] = {}           # upload_id -> key
        self._upload_seq = 0
        self._scripts: List[dict] = []
        self.calls: Dict[str, int] = {}
        self.faults_injected = 0
        self.auth_rejections = 0
        self.pages_served = 0
        self.parts_received = 0
        self.completes = 0
        self.aborts = 0
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "ObjectStoreEmulator":
        handler = type("BoundEmulatorHandler", (_Handler,),
                       {"server_ref": self})
        self._httpd = ThreadingHTTPServer((self.host, self.port), handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="object-store-emulator",
                                        daemon=True)
        self._thread.start()
        log.info("object-store emulator listening on %s", self.url)
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "ObjectStoreEmulator":
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def bucket_url(self, bucket: str) -> str:
        return f"{self.url}/{bucket}"

    # ------------------------------------------------------ fault scripting
    def script(self, kind: str, n: int = 1, *, op: Optional[str] = None,
               match: Optional[str] = None, code: int = 503,
               retry_after: Optional[float] = None, seconds: float = 0.1):
        """Queue ``n`` one-shot faults of ``kind`` (see module docstring),
        optionally aimed at a logical ``op`` and/or a key prefix
        ``match`` — FlakyBackend's ``script_failures`` at the HTTP level."""
        if kind not in _FAULT_KINDS:
            raise ValueError(f"unknown fault kind {kind!r}; "
                             f"one of {_FAULT_KINDS}")
        with self._lock:
            for _ in range(max(0, int(n))):
                self._scripts.append({"kind": kind, "op": op,
                                      "match": match, "code": int(code),
                                      "retry_after": retry_after,
                                      "seconds": float(seconds)})

    def clear_scripts(self):
        with self._lock:
            self._scripts.clear()

    def _take_fault(self, op: str, key: str) -> Optional[dict]:
        with self._lock:
            for i, f in enumerate(self._scripts):
                if f["op"] is not None and f["op"] != op:
                    continue
                if f["match"] is not None and not key.startswith(f["match"]):
                    continue
                self.faults_injected += 1
                return self._scripts.pop(i)
            if self.transient_rate > 0 and \
                    self._rng.random() < self.transient_rate:
                self.faults_injected += 1
                return {"kind": "status", "code": 503, "retry_after": None}
        return None

    # ------------------------------------------------------ chaos utilities
    def flip_byte(self, bucket: str, key: str, offset: int = 0):
        """Bit-rot AT REST: flip one byte of the committed object — every
        subsequent read serves the rotted bytes (vs the one-shot transport
        rot of ``script("bitrot")``)."""
        with self._lock:
            data = bytearray(self.objects[bucket][key])
            data[offset % max(1, len(data))] ^= 0xFF
            self.objects[bucket][key] = bytes(data)

    def in_flight_uploads(self) -> List[Tuple[str, str]]:
        """[(bucket/key, upload_id)] of sessions not yet completed or
        aborted — what clean_orphans should reap."""
        with self._lock:
            return [(f"{b}/{k}", uid)
                    for (b, uid), _ in self._uploads.items()
                    for k in [self._upload_keys[uid]]]

    # ------------------------------------------------------------- request
    def handle(self, h: _Handler, method: str):
        try:
            self._handle(h, method)
        except (BrokenPipeError, ConnectionResetError):
            pass  # client hung up mid-reply — its problem, not ours
        except Exception as e:  # a handler crash must not kill the server
            log.warning("emulator handler error (%s: %s)",
                        type(e).__name__, e)
            try:
                self._error(h, 500, f"InternalError: {e}")
            except OSError:
                pass  # socket already gone; the warning above recorded it

    def _handle(self, h: _Handler, method: str):
        raw_path, _, raw_query = h.path.partition("?")
        query = urllib.parse.parse_qs(raw_query, keep_blank_values=True)
        segments = [s for s in raw_path.split("/") if s]
        bucket = urllib.parse.unquote(segments[0]) if segments else ""
        key = urllib.parse.unquote("/".join(segments[1:])) \
            if len(segments) > 1 else ""

        body = b""
        if method in ("PUT", "POST"):
            length, err = parse_content_length(h.headers,
                                               self.max_body_bytes)
            if err is not None:
                return self._error(h, err[0], err[1])
            body = h.rfile.read(length)
            if len(body) != length:
                return  # client died mid-send; nothing to answer
        op = self._logical_op(method, key, query)
        with self._lock:
            self.calls[op] = self.calls.get(op, 0) + 1

        declared_sha = h.headers.get("x-dlt-content-sha256")
        if declared_sha is not None and method in ("PUT", "POST") and \
                hashlib.sha256(body).hexdigest() != declared_sha:
            # per-part / per-object integrity: a payload corrupted in
            # flight is rejected at upload time, not found at restore
            return self._error(h, 400, "BadDigest")
        if not self._check_auth(h, method, raw_path, raw_query,
                                declared_sha):
            return self._error(h, 403, "SignatureDoesNotMatch")

        fault = self._take_fault(op, key)
        if fault is not None:
            if fault["kind"] == "latency":
                time.sleep(fault["seconds"])
            elif fault["kind"] == "status":
                extra = {}
                if fault.get("retry_after") is not None:
                    extra["Retry-After"] = f"{fault['retry_after']:g}"
                return self._error(h, fault["code"],
                                   "scripted fault", extra)
            # disconnect/bitrot apply at send time, below
        tear = fault is not None and fault["kind"] == "disconnect"
        rot = fault is not None and fault["kind"] == "bitrot"

        if op == "list":
            return self._do_list(h, bucket, query)
        if op == "mpu-list":
            return self._do_mpu_list(h, bucket)
        if op == "initiate":
            return self._do_initiate(h, bucket, key)
        if op == "part":
            return self._do_part(h, bucket, key, query, body)
        if op == "complete":
            return self._do_complete(h, bucket, key, query, body)
        if op == "abort":
            return self._do_abort(h, query)
        if op == "put":
            return self._do_put(h, bucket, key, body)
        if op == "get":
            return self._do_get(h, bucket, key, tear=tear, rot=rot)
        if op == "exists":
            return self._do_head(h, bucket, key)
        if op == "delete":
            return self._do_delete(h, bucket, key)
        return self._error(h, 400, f"unsupported request {method} {h.path}")

    @staticmethod
    def _logical_op(method: str, key: str, query: Dict[str, list]) -> str:
        if method == "GET":
            if not key:
                return "mpu-list" if "uploads" in query else "list"
            return "get"
        if method == "PUT":
            return "part" if "uploadId" in query else "put"
        if method == "POST":
            if "uploads" in query:
                return "initiate"
            if "uploadId" in query:
                return "complete"
            return "post"
        if method == "DELETE":
            return "abort" if "uploadId" in query else "delete"
        if method == "HEAD":
            return "exists"
        return method.lower()

    def _check_auth(self, h: _Handler, method: str, path: str, query: str,
                    declared_sha: Optional[str]) -> bool:
        """Verify the DLT4 signature with the SAME code the client signs
        with (cloud.sign_request) — drift between signer and verifier is
        structurally impossible."""
        if not self.require_auth:
            return True
        auth = h.headers.get("Authorization", "")
        date = h.headers.get("x-dlt-date", "")
        ok = False
        if auth.startswith(SIGNING_SCHEME + " ") and declared_sha and date:
            fields = dict(
                part.strip().split("=", 1)
                for part in auth[len(SIGNING_SCHEME):].split(",")
                if "=" in part)
            cred = fields.get("Credential", "")
            sig = fields.get("Signature", "")
            expect = sign_request(self.secret_key, method, path, query,
                                  date, declared_sha)
            ok = (cred.split("/")[0] == self.access_key
                  and hmac_compare(sig, expect))
        if not ok:
            with self._lock:
                self.auth_rejections += 1
        return ok

    # -------------------------------------------------------------- ops
    def _do_put(self, h: _Handler, bucket: str, key: str, body: bytes):
        with self._lock:
            self.objects.setdefault(bucket, {})[key] = body
        self._reply(h, 200, b"")

    def _do_get(self, h: _Handler, bucket: str, key: str, *,
                tear: bool = False, rot: bool = False):
        with self._lock:
            data = self.objects.get(bucket, {}).get(key)
        if data is None:
            return self._error(h, 404, f"NoSuchKey: {bucket}/{key}")
        if rot and data:
            rotten = bytearray(data)
            rotten[len(rotten) // 2] ^= 0xFF
            data = bytes(rotten)
        if tear:
            # declare everything, deliver half, hang up: the mid-transfer
            # disconnect CloudObjectBackend must classify as transient
            h.send_response(200)
            h.send_header("Content-Length", str(len(data)))
            h.end_headers()
            h.wfile.write(data[:len(data) // 2])
            h.wfile.flush()
            h.close_connection = True
            try:
                h.connection.close()
            except OSError:
                pass
            return
        self._reply(h, 200, data,
                    {"x-dlt-content-sha256":
                     hashlib.sha256(data).hexdigest()})

    def _do_head(self, h: _Handler, bucket: str, key: str):
        with self._lock:
            data = self.objects.get(bucket, {}).get(key)
        if data is None:
            return self._error(h, 404, "NoSuchKey", head=True)
        h.send_response(200)
        h.send_header("Content-Length", str(len(data)))
        h.end_headers()

    def _do_delete(self, h: _Handler, bucket: str, key: str):
        with self._lock:
            self.objects.get(bucket, {}).pop(key, None)
        self._reply(h, 204, b"")

    def _do_list(self, h: _Handler, bucket: str, query: Dict[str, list]):
        prefix = query.get("prefix", [""])[0]
        max_keys = max(1, int(query.get("max-keys", ["1000"])[0]))
        token = query.get("continuation-token", [None])[0]
        with self._lock:
            keys = sorted(k for k in self.objects.get(bucket, {})
                          if k.startswith(prefix))
            self.pages_served += 1
        if token:
            keys = [k for k in keys if k > token]
        page, rest = keys[:max_keys], keys[max_keys:]
        truncated = bool(rest)
        parts = ["<?xml version='1.0'?><ListBucketResult>",
                 f"<IsTruncated>{'true' if truncated else 'false'}"
                 f"</IsTruncated>"]
        if truncated:
            parts.append(f"<NextContinuationToken>{_xml_escape(page[-1])}"
                         f"</NextContinuationToken>")
        parts.extend(f"<Contents><Key>{_xml_escape(k)}</Key></Contents>"
                     for k in page)
        parts.append("</ListBucketResult>")
        self._reply(h, 200, "".join(parts).encode(),
                    {"Content-Type": "application/xml"})

    def _do_initiate(self, h: _Handler, bucket: str, key: str):
        with self._lock:
            self._upload_seq += 1
            upload_id = f"mpu-{self._upload_seq:08d}"
            self._uploads[(bucket, upload_id)] = {}
            self._upload_keys[upload_id] = key
        body = (f"<?xml version='1.0'?><InitiateMultipartUploadResult>"
                f"<Bucket>{_xml_escape(bucket)}</Bucket>"
                f"<Key>{_xml_escape(key)}</Key>"
                f"<UploadId>{upload_id}</UploadId>"
                f"</InitiateMultipartUploadResult>").encode()
        self._reply(h, 200, body, {"Content-Type": "application/xml"})

    def _do_part(self, h: _Handler, bucket: str, key: str,
                 query: Dict[str, list], body: bytes):
        upload_id = query.get("uploadId", [""])[0]
        try:
            number = int(query.get("partNumber", [""])[0])
        except ValueError:
            return self._error(h, 400, "InvalidPartNumber")
        with self._lock:
            session = self._uploads.get((bucket, upload_id))
            if session is None:
                return self._error(h, 404, f"NoSuchUpload: {upload_id}")
            session[number] = body
            self.parts_received += 1
        self._reply(h, 200, b"",
                    {"ETag": hashlib.sha256(body).hexdigest()})

    def _do_complete(self, h: _Handler, bucket: str, key: str,
                     query: Dict[str, list], body: bytes):
        upload_id = query.get("uploadId", [""])[0]
        with self._lock:
            session = self._uploads.get((bucket, upload_id))
            if session is None:
                return self._error(h, 404, f"NoSuchUpload: {upload_id}")
            numbers = sorted(session)
            if not numbers or numbers != list(range(1, numbers[-1] + 1)):
                return self._error(h, 400, "InvalidPart: gap in parts")
            # the atomic commit point: assembled object appears all at
            # once; the session disappears with it
            assembled = b"".join(session[n] for n in numbers)
            self.objects.setdefault(bucket, {})[key] = assembled
            del self._uploads[(bucket, upload_id)]
            del self._upload_keys[upload_id]
            self.completes += 1
        reply = (f"<?xml version='1.0'?><CompleteMultipartUploadResult>"
                 f"<Key>{_xml_escape(key)}</Key>"
                 f"</CompleteMultipartUploadResult>").encode()
        self._reply(h, 200, reply, {"Content-Type": "application/xml"})

    def _do_abort(self, h: _Handler, query: Dict[str, list]):
        upload_id = query.get("uploadId", [""])[0]
        with self._lock:
            key = self._upload_keys.pop(upload_id, None)
            removed = False
            for (b, uid) in list(self._uploads):
                if uid == upload_id:
                    del self._uploads[(b, uid)]
                    removed = True
            if removed:
                self.aborts += 1
        self._reply(h, 204 if removed or key else 404, b"")

    def _do_mpu_list(self, h: _Handler, bucket: str):
        with self._lock:
            ups = [(self._upload_keys[uid], uid)
                   for (b, uid) in self._uploads if b == bucket]
        parts = ["<?xml version='1.0'?><ListMultipartUploadsResult>"]
        parts.extend(f"<Upload><Key>{_xml_escape(k)}</Key>"
                     f"<UploadId>{uid}</UploadId></Upload>"
                     for k, uid in sorted(ups))
        parts.append("</ListMultipartUploadsResult>")
        self._reply(h, 200, "".join(parts).encode(),
                    {"Content-Type": "application/xml"})

    # ------------------------------------------------------------- replies
    def _reply(self, h: _Handler, code: int, body: bytes,
               headers: Optional[Dict[str, str]] = None):
        h.send_response(code)
        for k, v in (headers or {}).items():
            h.send_header(k, v)
        h.send_header("Content-Length", str(len(body)))
        h.end_headers()
        if body:
            h.wfile.write(body)

    def _error(self, h: _Handler, code: int, message: str,
               headers: Optional[Dict[str, str]] = None,
               head: bool = False):
        body = b"" if head else (f"<?xml version='1.0'?><Error>"
                                 f"<Message>{_xml_escape(message)}"
                                 f"</Message></Error>").encode()
        self._reply(h, code, body, headers)


def hmac_compare(a: str, b: str) -> bool:
    import hmac as _hmac
    return _hmac.compare_digest(a.encode(), b.encode())


def _xml_escape(s: str) -> str:
    return (s.replace("&", "&amp;").replace("<", "&lt;")
            .replace(">", "&gt;"))
