"""Multi-host sharded checkpoints with N→M reshard-on-restore.

The PR-2/PR-5 checkpoint format requires params to be process-0
addressable: ``snapshot_training_state`` runs ``jax.device_get`` over the
whole tree, which on a multi-host tensor-parallel (or optimizer-sharded)
job would try to fetch remote shards and fail. This module removes that
restriction: every host snapshots only the blocks it OWNS, writes them as
its own shard object, and the manifest journals the shard *set* as one
first-class entry (per-shard sha256, committed only after every shard is
durable — see ``CheckpointManager._save_sharded``).

Ownership is derived from the array's real sharding: for each distinct
index block of ``sharding.devices_indices_map``, the device with the
smallest id is the owner, and a host writes the block iff that owner is
local. Replicated arrays therefore land in host 0's shard once; sharded
arrays land as exactly one copy of each block, wherever it lives. Plain
host arrays (numpy) belong to host 0.

Restore is the reverse: fetch every shard named by the manifest entry,
verify each against its journaled sha256, reassemble full host arrays
from the blocks, and build the model exactly like
``utils.serialization.restore_checkpoint``. Because assembly produces the
FULL global state on the host, the restoring world does not need to match
the writing world: a checkpoint written by 4 workers restores into 3 (or
1) — params/opt-state are reassembled identically and the new world's
trainer re-places them over its own mesh. That is the N→M
reshard-on-restore the elastic layer (parallel/elastic.py) leans on when
membership changes. (The cost: full state must fit host RAM during
restore; a streaming reshard is future work.)

Shard objects are named ``shard-<base>.d<k>of<M>.zip`` — a prefix the
manifest's ``scan_checkpoint_files`` (``ckpt-*``) never matches, so torn
manifest recovery cannot mistake a shard for a whole checkpoint;
:func:`scan_shard_sets` rebuilds sharded entries from *complete* sets
only (an incomplete set — a crash between shard puts and the journal
write — is ignored, exactly like a tmp/ orphan).
"""

from __future__ import annotations

import hashlib
import io
import json
import logging
import re
import zipfile
from typing import Dict, List, Optional, Tuple

import numpy as np

log = logging.getLogger(__name__)

SHARD_PREFIX = "shard-"
SHARD_FORMAT_VERSION = 1
_SHARD_RE = re.compile(
    r"^shard-(ckpt-(\d{10})-(\d{5}))\.d(\d{3})of(\d{3})\.zip$")

__all__ = [
    "ShardedCheckpointError", "shard_snapshot", "simulated_shard_snapshots",
    "shard_zip_bytes", "shard_object_name", "restore_from_payloads",
    "restore_sharded", "scan_shard_sets", "state_sha", "SHARD_PREFIX",
    "shard_block_summary", "fetch_blocks",
]


class ShardedCheckpointError(RuntimeError):
    """A shard set is unusable: missing/corrupt shard, incomplete block
    coverage, or shards from mismatched checkpoints. The manager's restore
    walk treats it like any torn checkpoint — fall back a generation,
    never assemble a mixed or partial state."""


# ------------------------------------------------------------- block slicing
def _norm_index(index, shape) -> Tuple[Tuple[int, int], ...]:
    """Normalize a devices_indices_map index (tuple of slices) to
    ((start, stop), ...) pairs; scalars normalize to ()."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        if sl.step not in (None, 1):
            raise ShardedCheckpointError(
                f"non-unit-stride shard index {index} is not supported")
        out.append((start, stop))
    return tuple(out)


def _leaf_blocks(arr) -> List[dict]:
    """The blocks of ``arr`` THIS host owns. Owner of an index block = the
    participating device with the smallest id; a replicated array is owned
    entirely by the sharding's first device's host."""
    import jax
    if not isinstance(arr, jax.Array):
        # plain host array (or python scalar): host 0 owns it whole
        a = np.asarray(arr)
        if jax.process_index() != 0:
            return []
        return [{"index": tuple((0, d) for d in a.shape), "data": a}]
    shape = arr.shape
    owner: Dict[tuple, int] = {}
    for dev, idx in arr.sharding.devices_indices_map(shape).items():
        key = _norm_index(idx, shape)
        if key not in owner or dev.id < owner[key]:
            owner[key] = dev.id
    blocks = []
    for shard in arr.addressable_shards:
        key = _norm_index(shard.index, shape)
        if owner.get(key) == shard.device.id:
            blocks.append({"index": key, "data": np.asarray(shard.data)})
    return blocks


def _tree_blocks(tree) -> List[dict]:
    """Owned blocks for every leaf of ``tree``, keyed like the
    ``coefficients.npz`` layout (utils.serialization path keys)."""
    import jax
    from deeplearning4j_tpu.utils.serialization import _path_key
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        leaf_key = _path_key(path)
        gshape = tuple(np.shape(leaf))
        dtype = str(np.asarray(leaf).dtype) if not hasattr(leaf, "dtype") \
            else str(leaf.dtype)
        for b in _leaf_blocks(leaf):
            out.append({"leaf": leaf_key, "shape": list(gshape),
                        "dtype": dtype, "index": b["index"],
                        "data": b["data"]})
    return out


# ---------------------------------------------------------------- snapshots
def shard_snapshot(model) -> dict:
    """This host's shard of everything exact-step resume needs. Block data
    is copied to host memory on the calling thread (same donation-safety
    discipline as ``snapshot_training_state``); the RNG key and counters —
    replicated by construction — ride in host 0's shard only."""
    import jax
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    if model.params is None:
        model.init()
    if isinstance(model, MultiLayerNetwork):
        model_type = "MultiLayerNetwork"
    elif isinstance(model, ComputationGraph):
        model_type = "ComputationGraph"
    else:
        raise TypeError(f"Cannot checkpoint {type(model)}")
    host = jax.process_index()
    rng = model._rng
    comp = getattr(model, "grad_compression", None)
    cs = getattr(model, "compress_state", None)
    return {
        "model_type": model_type,
        "conf_json": model.conf.to_json(),
        "iteration": int(model.iteration),
        "epoch": int(model.epoch),
        "host": host,
        "num_hosts": jax.process_count(),
        "coefficients": _tree_blocks([model.params, model.state]),
        "updaterState": (None if model.opt_state is None
                         else _tree_blocks(model.opt_state)),
        "rng": (None if (rng is None or host != 0)
                else np.asarray(jax.random.key_data(rng))),
        # gradient-compression ride-along: residual/controller blocks shard
        # exactly like opt_state (replicated residuals land in host 0's
        # shard once), the scheme config rides the shard metadata
        "grad_compression": None if comp is None else comp.to_config(),
        "compressState": None if cs is None else _tree_blocks(cs),
        # augmentation + tuning ride-alongs (pure-config metadata): the
        # SAME rng-exact resume contract as the whole-zip path — an
        # elastic replica restoring these shards must train the identical
        # (augmented, tuned) step or it silently diverges
        "augmentation": (None if getattr(model, "augmentation", None)
                         is None else model.augmentation.to_dict()),
        "tuning_record": (None
                          if getattr(model, "_tuning_record", None) is None
                          else model._tuning_record.to_dict()),
    }


def simulated_shard_snapshots(model, num_hosts: int) -> List[dict]:
    """``num_hosts`` synthetic host shards of a single-process model —
    each leaf row-partitioned into contiguous chunks (leaves too small to
    split belong to host 0). Lets single-process tests and benches
    exercise the exact multi-shard assemble/restore path a real N-host
    job produces."""
    import jax
    from deeplearning4j_tpu.utils.serialization import _path_key

    def split(tree, host):
        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        out = []
        for path, leaf in flat:
            a = np.asarray(jax.device_get(leaf))
            gshape = tuple(a.shape)
            if a.ndim >= 1 and a.shape[0] >= num_hosts:
                bounds = np.linspace(0, a.shape[0], num_hosts + 1).astype(int)
                lo, hi = int(bounds[host]), int(bounds[host + 1])
                if lo == hi:
                    continue
                index = ((lo, hi),) + tuple((0, d) for d in a.shape[1:])
                data = a[lo:hi]
            elif host == 0:
                index = tuple((0, d) for d in gshape)
                data = a
            else:
                continue
            out.append({"leaf": _path_key(path), "shape": list(gshape),
                        "dtype": str(a.dtype), "index": index, "data": data})
        return out

    base = shard_snapshot(model)
    cs = getattr(model, "compress_state", None)
    snaps = []
    for host in range(num_hosts):
        snaps.append({
            **{k: base[k] for k in ("model_type", "conf_json", "iteration",
                                    "epoch", "grad_compression",
                                    "augmentation", "tuning_record")},
            "host": host,
            "num_hosts": num_hosts,
            "coefficients": split([model.params, model.state], host),
            "updaterState": (None if model.opt_state is None
                             else split(model.opt_state, host)),
            "compressState": None if cs is None else split(cs, host),
            "rng": base["rng"] if host == 0 else None,
        })
    return snaps


# ------------------------------------------------------------------- format
def shard_object_name(base: str, host: int, num_hosts: int) -> str:
    return f"{SHARD_PREFIX}{base}.d{host:03d}of{num_hosts:03d}.zip"


def shard_zip_bytes(snap: dict, extra_meta: Optional[dict] = None) -> bytes:
    """One host shard as zip bytes (ZIP_STORED, same rationale as
    ``checkpoint_zip_bytes``): metadata + config + a block index + the
    block arrays, plus the RNG key on host 0."""
    meta = {
        "format_version": SHARD_FORMAT_VERSION,
        "shard": True,  # manifest rebuild must never mistake this for a
        "model_type": snap["model_type"],  # whole checkpoint
        "iteration": snap["iteration"],
        "epoch": snap["epoch"],
        "host": snap["host"],
        "num_hosts": snap["num_hosts"],
        "has_updater": snap["updaterState"] is not None,
        "has_rng": snap["rng"] is not None,
        "grad_compression": snap.get("grad_compression"),
        "has_compress": snap.get("compressState") is not None,
        "augmentation": snap.get("augmentation"),
        "tuning_record": snap.get("tuning_record"),
    }
    meta.update(extra_meta or {})
    index, arrays = [], {}
    # distinct per-tree key prefixes (compressState cannot share
    # coefficients' "c"); readers resolve keys through blockindex.json, so
    # old shards stay readable
    for tree, prefix in (("coefficients", "c"), ("updaterState", "u"),
                         ("compressState", "x")):
        for i, b in enumerate(snap.get(tree) or []):
            key = f"{prefix}{i}"
            index.append({"key": key, "tree": tree, "leaf": b["leaf"],
                          "shape": b["shape"], "dtype": b["dtype"],
                          "index": [list(p) for p in b["index"]]})
            arrays[key] = b["data"]
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_STORED) as z:
        z.writestr("metadata.json", json.dumps(meta))
        z.writestr("configuration.json", snap["conf_json"])
        z.writestr("blockindex.json", json.dumps(index))
        bbuf = io.BytesIO()
        np.savez(bbuf, **arrays)
        z.writestr("blocks.npz", bbuf.getvalue())
        if snap["rng"] is not None:
            rbuf = io.BytesIO()
            np.savez(rbuf, key_data=snap["rng"])
            z.writestr("rngState.npz", rbuf.getvalue())
    return buf.getvalue()


def _parse_shard(data: bytes) -> dict:
    try:
        with zipfile.ZipFile(io.BytesIO(data), "r") as z:
            meta = json.loads(z.read("metadata.json"))
            conf_json = z.read("configuration.json").decode()
            index = json.loads(z.read("blockindex.json"))
            blocks = dict(np.load(io.BytesIO(z.read("blocks.npz"))))
            rng = None
            if meta.get("has_rng") and "rngState.npz" in z.namelist():
                rng = dict(np.load(io.BytesIO(
                    z.read("rngState.npz"))))["key_data"]
    except (zipfile.BadZipFile, KeyError, ValueError, OSError) as e:
        raise ShardedCheckpointError(
            f"unreadable shard ({type(e).__name__}: {e})") from e
    return {"meta": meta, "conf_json": conf_json, "index": index,
            "blocks": blocks, "rng": rng}


# ----------------------------------------------------------------- assembly
def _assemble(parsed: List[dict], tree: str) -> Dict[str, np.ndarray]:
    """Full host arrays from every shard's blocks of ``tree``. Coverage is
    enforced: duplicated (leaf, index) blocks and block element counts
    that do not sum to the leaf's size both raise — a partial or doubled
    assembly must never restore silently."""
    leaves: Dict[str, dict] = {}
    seen = set()
    for p in parsed:
        for ent in p["index"]:
            if ent["tree"] != tree:
                continue
            key = (ent["leaf"], tuple(tuple(x) for x in ent["index"]))
            if key in seen:
                raise ShardedCheckpointError(
                    f"duplicate block for leaf '{ent['leaf']}' at "
                    f"{ent['index']} across shards")
            seen.add(key)
            data = p["blocks"][ent["key"]]
            info = leaves.setdefault(ent["leaf"], {
                "shape": tuple(ent["shape"]),
                "array": np.empty(tuple(ent["shape"]),
                                  dtype=np.dtype(ent["dtype"])),
                "filled": 0,
            })
            sl = tuple(slice(a, b) for a, b in ent["index"])
            info["array"][sl] = data
            info["filled"] += int(np.prod(data.shape, dtype=np.int64))
    out = {}
    for leaf, info in leaves.items():
        want = int(np.prod(info["shape"], dtype=np.int64))
        if info["filled"] != want:
            raise ShardedCheckpointError(
                f"incomplete coverage for leaf '{leaf}': {info['filled']} "
                f"of {want} elements present — missing or torn shard")
        out[leaf] = info["array"]
    return out


def restore_from_payloads(payloads: List[bytes], load_updater: bool = True):
    """(model, meta) from a complete list of shard payload bytes. Every
    shard must agree on (model_type, iteration, epoch, num_hosts) and the
    list must hold exactly ``num_hosts`` shards — shards from different
    checkpoint generations can never silently mix."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn.conf.graph import ComputationGraphConfiguration
    from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.utils.serialization import _restore_into
    parsed = [_parse_shard(d) for d in payloads]
    idents = {(p["meta"]["model_type"], p["meta"]["iteration"],
               p["meta"]["epoch"], p["meta"]["num_hosts"]) for p in parsed}
    if len(idents) != 1:
        raise ShardedCheckpointError(
            f"shards disagree on checkpoint identity: {sorted(idents)} — "
            "refusing to mix generations")
    num_hosts = int(parsed[0]["meta"]["num_hosts"])
    hosts = sorted(int(p["meta"].get("host", -1)) for p in parsed)
    if hosts != list(range(num_hosts)):
        raise ShardedCheckpointError(
            f"shard set holds hosts {hosts} but the checkpoint was "
            f"written by hosts 0..{num_hosts - 1} — missing or "
            "duplicated shard")
    meta_p = next(p for p in parsed if p["meta"].get("host") == 0)
    meta = meta_p["meta"]
    conf_json = meta_p["conf_json"]
    if meta["model_type"] == "MultiLayerNetwork":
        model = MultiLayerNetwork(MultiLayerConfiguration.from_json(conf_json))
    else:
        model = ComputationGraph(
            ComputationGraphConfiguration.from_json(conf_json))
    model.init()
    coeff = _assemble(parsed, "coefficients")
    model.params, model.state = _restore_into(
        [model.params, model.state], coeff)
    if load_updater and meta.get("has_updater"):
        upd = _assemble(parsed, "updaterState")
        model.opt_state = _restore_into(model.opt_state, upd)
    if meta.get("grad_compression"):
        # same ride-along policy as the whole-zip restore (shared helper) —
        # residuals reassembled like opt_state: replicated residuals
        # restore onto ANY world size
        from deeplearning4j_tpu.parallel.compress import (
            restore_compress_state)
        cs = _assemble(parsed, "compressState") \
            if meta.get("has_compress") else None
        restore_compress_state(model, meta["grad_compression"], cs,
                               origin="sharded")
    if meta.get("augmentation"):
        from deeplearning4j_tpu.datasets.augment import ImageAugmentation
        model.augmentation = ImageAugmentation.from_dict(
            meta["augmentation"])
    if meta.get("tuning_record"):
        from deeplearning4j_tpu.perf.autotune import TuningRecord
        model._tuning_record = TuningRecord.from_dict(meta["tuning_record"])
    if meta_p["rng"] is not None:
        model._rng = jax.random.wrap_key_data(jnp.asarray(meta_p["rng"]))
    model.iteration = int(meta.get("iteration", 0))
    model.epoch = int(meta.get("epoch", 0))
    return model, meta


def shard_block_summary(payload: bytes) -> List[dict]:
    """The (tree, leaf, index) coverage of one shard payload — journaled
    per shard at save time so a selective restore can decide which shard
    OBJECTS it needs without fetching any of them."""
    return [{"tree": e["tree"], "leaf": e["leaf"], "index": e["index"]}
            for e in _parse_shard(payload)["index"]]


def fetch_blocks(storage, entry: dict, want,
                 trees: Tuple[str, ...] = ("coefficients", "updaterState"),
                 ) -> Dict[str, Dict[str, List[tuple]]]:
    """Streaming reshard-on-restore: fetch ONLY the shard objects holding
    blocks ``want`` selects, instead of reassembling the full state.

    ``want(tree, leaf, index)`` (index = ((start, stop), ...) over the
    leaf's global shape) returns whether the restoring host needs that
    block — e.g. the row-range its NEW sharding assigns it. Shards whose
    journaled block summary (written by ``CheckpointManager._save_sharded``)
    contains no wanted block are never fetched, so per-host bytes read
    shrink with the host's share of the state. Entries journaled before
    block summaries existed fall back to fetching every shard (correct,
    just not selective). Fetched shards are sha-verified like a full
    restore.

    Returns ``{tree: {leaf: [(index, array), ...]}}`` holding exactly the
    wanted blocks. This is the block-level half of a streaming reshard:
    full-model restores (DP-replicated params need every block anyway)
    keep using :func:`restore_sharded`; tensor-parallel or
    optimizer-sharded hosts pull their slice here and ``device_put`` it
    straight into their new placement."""
    fetched: Dict[str, Dict[str, List[tuple]]] = {t: {} for t in trees}
    for s in entry.get("shards", []):
        summary = s.get("blocks")
        if summary is not None:
            wanted = any(
                b["tree"] in trees
                and want(b["tree"], b["leaf"],
                         tuple(tuple(p) for p in b["index"]))
                for b in summary)
            if not wanted:
                continue
        data = storage.get(s["file"])
        if s.get("sha256") is not None and \
                hashlib.sha256(data).hexdigest() != s["sha256"]:
            raise ShardedCheckpointError(
                f"checksum mismatch for shard {s['file']} (torn/corrupt)")
        parsed = _parse_shard(data)
        for ent in parsed["index"]:
            if ent["tree"] not in trees:
                continue
            index = tuple(tuple(p) for p in ent["index"])
            if not want(ent["tree"], ent["leaf"], index):
                continue
            fetched[ent["tree"]].setdefault(ent["leaf"], []).append(
                (index, parsed["blocks"][ent["key"]]))
    return fetched


def restore_sharded(storage, entry: dict, load_updater: bool = True):
    """(model, meta) for a manifest shard-set entry: fetch every shard,
    verify each against its journaled sha256 (when present), reassemble.
    Any failure raises — the manager's restore walk falls back one whole
    generation rather than ever mixing shard sets."""
    payloads = []
    for s in entry.get("shards", []):
        data = storage.get(s["file"])  # StorageNotFoundError if gone
        if s.get("sha256") is not None and \
                hashlib.sha256(data).hexdigest() != s["sha256"]:
            raise ShardedCheckpointError(
                f"checksum mismatch for shard {s['file']} (torn/corrupt)")
        payloads.append(data)
    return restore_from_payloads(payloads, load_updater=load_updater)


def scan_shard_sets(storage) -> List[dict]:
    """Degraded-mode recovery (manifest lost/torn): rebuild shard-set
    entries from COMPLETE sets present in storage, in (step, seq) order.
    Incomplete sets — a crash landed between shard puts and the journal
    write — are skipped, like tmp/ orphans; per-shard zip metadata still
    gates restore via :func:`restore_from_payloads`'s identity checks."""
    groups: Dict[str, dict] = {}
    for name in storage.list(prefix=SHARD_PREFIX):
        m = _SHARD_RE.match(name)
        if not m:
            continue
        base, step, seq, host, num = (m.group(1), int(m.group(2)),
                                      int(m.group(3)), int(m.group(4)),
                                      int(m.group(5)))
        g = groups.setdefault(base, {"step": step, "seq": seq,
                                     "num_hosts": num, "files": {}})
        g["files"][host] = name
    entries = []
    for base, g in groups.items():
        if set(g["files"]) != set(range(g["num_hosts"])):
            log.warning("ignoring incomplete shard set %s (%d of %d shards "
                        "present)", base, len(g["files"]), g["num_hosts"])
            continue
        entries.append({
            "file": f"{base}.sharded",
            "sharded": True,
            "num_hosts": g["num_hosts"],
            "shards": [{"file": g["files"][h], "sha256": None}
                       for h in range(g["num_hosts"])],
            "step": g["step"],
            "seq": g["seq"],
            "sha256": None,
        })
    entries.sort(key=lambda e: (e["step"], e["seq"]))
    return entries


# ---------------------------------------------------------------- utilities
def state_sha(model) -> str:
    """Deterministic digest over params + layer state + opt-state (+ the
    gradient-compression residual/controller state when present) — the
    cross-world equality probe the elastic tests use: a checkpoint
    restored into ANY world size must produce the same digest."""
    import jax
    h = hashlib.sha256()
    for tree in (model.params, model.state, model.opt_state,
                 getattr(model, "compress_state", None)):
        for leaf in jax.tree_util.tree_leaves(tree):
            a = np.ascontiguousarray(np.asarray(jax.device_get(leaf)))
            h.update(str((a.shape, str(a.dtype))).encode())
            h.update(a.tobytes())
    return h.hexdigest()
