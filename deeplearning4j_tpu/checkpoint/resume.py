"""Auto-resume training driver: preemption becomes a no-op for callers.

``CheckpointManager`` (PR 2) made crash-resume *possible* — kill training
at an arbitrary step, ``restore_latest()``, refit, and the result is
bitwise-identical to the uninterrupted run. This module makes it
*automatic*: :func:`train_until` owns the crash → backoff → restore →
refit loop, so the caller writes one line and preemptions, transient
storage outages (surfaced as ``CheckpointError``) and hung collectives
(surfaced by a ``CollectiveWatchdog`` deadline) all collapse into restart
cycles recorded in a :class:`RunSummary` instead of a dead job. This is
the recovery half CheckFreq (FAST'21) and Check-N-Run (NSDI'22) identify
as the actual fault-tolerance gap in production training — checkpointing
without automated recovery just produces well-preserved corpses.

Mechanics that keep the bitwise guarantee intact:

- a step-0 checkpoint is committed up front (``save_initial``), so even a
  crash before the first periodic save restores to the pristine
  params/RNG state rather than needing a fresh model whose training would
  then silently differ from "the run that was promised";
- every restart restores via ``restore_latest()`` — the torn/bit-rot
  fallback applies, so flaky storage under the checkpoints degrades to an
  older restore point, never to garbage;
- the restart budget (:class:`RestartPolicy`) bounds the loop: crash
  storms escalate to :class:`RestartBudgetExceeded` carrying the full
  crash history, instead of looping forever on a permanently-broken job.
"""

from __future__ import annotations

import dataclasses
import logging
import random
import time
from typing import Callable, List, Optional

from deeplearning4j_tpu.utils.backoff import backoff_delay

log = logging.getLogger(__name__)

__all__ = ["RestartPolicy", "CrashRecord", "RunSummary",
           "RestartBudgetExceeded", "train_until"]


@dataclasses.dataclass
class RestartPolicy:
    """How :func:`train_until` reacts to a crash.

    ``max_restarts`` bounds recovery attempts; ``backoff_s`` is the base of
    a capped exponential backoff between them (with jitter via
    utils/backoff.py — restarting a preempted fleet in lockstep recreates
    the stampede that got it preempted); ``restart_on`` is the exception
    allowlist (default: any ``Exception`` — ``KeyboardInterrupt`` /
    ``SystemExit`` always propagate)."""
    max_restarts: int = 5
    backoff_s: float = 1.0
    max_backoff_s: float = 60.0
    restart_on: tuple = (Exception,)
    seed: int = 0

    def __post_init__(self):
        if self.max_restarts < 0:
            raise ValueError("max_restarts must be >= 0")
        if self.backoff_s < 0 or self.max_backoff_s < 0:
            raise ValueError("backoff must be >= 0")


@dataclasses.dataclass
class CrashRecord:
    """One crash/restore cycle in a run's history."""
    attempt: int            # 1-based restart number this crash triggered
    error_type: str
    error: str
    crashed_at_step: Optional[int]   # model.iteration when the crash hit
    restored_step: Optional[int]     # checkpoint step recovery resumed from
    restored_epoch: Optional[int]
    backoff_s: float
    # the victim's last seconds: one-liner summaries of the newest crash
    # flight-recorder ring entries (obs/flight.py) — in-process tail for
    # train_until, the flushed storage dump for train_until_process
    flight_tail: Optional[List[str]] = None


@dataclasses.dataclass
class RunSummary:
    """What happened across the whole ``train_until`` run — the record an
    operator reads after the fact to see how rough the ride was."""
    model: object
    completed: bool
    restarts: int
    crashes: List[CrashRecord]
    wall_time_s: float

    def __str__(self):
        tail = "; ".join(
            f"#{c.attempt} {c.error_type}@step{c.crashed_at_step}"
            f"->resume@{c.restored_step}" for c in self.crashes)
        return (f"train_until: completed={self.completed} "
                f"restarts={self.restarts} wall={self.wall_time_s:.1f}s"
                + (f" [{tail}]" if tail else ""))


class RestartBudgetExceeded(RuntimeError):
    """The restart budget ran out (or recovery itself is impossible);
    ``summary`` carries the full crash history for escalation."""

    def __init__(self, message: str, summary: RunSummary):
        super().__init__(message)
        self.summary = summary


def train_until(model, data, num_epochs: int, checkpoint_manager,
                restart_policy: Optional[RestartPolicy] = None,
                watchdog=None,
                on_restart: Optional[Callable] = None,
                save_initial: bool = True,
                fit_kwargs: Optional[dict] = None) -> RunSummary:
    """Train ``model`` to ``num_epochs`` TOTAL epochs, surviving crashes by
    restoring from ``checkpoint_manager`` and refitting — the caller sees a
    completed run (with its crash history in the returned
    :class:`RunSummary`) or a loud :class:`RestartBudgetExceeded`.

    ``model`` may already be restored/part-trained: ``fit``'s resume
    semantics apply (``num_epochs`` is the run's total target). ``data``
    must replay deterministically (the bitwise-resume precondition every
    ``fit`` wire-in documents).

    ``watchdog`` (a parallel/watchdog.py ``CollectiveWatchdog``) runs each
    fit attempt under its deadline: a hung multi-host collective — the
    crash mode that otherwise blocks FOREVER with no error — becomes a
    ``CollectiveTimeoutError``, which is just another restartable crash
    here. Pass a generous deadline (a whole fit attempt, not one step).

    ``on_restart(model, attempt)`` is called after each restore, before
    the refit — chaos tests use it to re-arm fault injectors on the fresh
    model object; production code can re-attach listeners the restored
    model does not carry.

    ``save_initial`` commits a step-0 checkpoint before the first attempt
    when the store has none, so a crash before the first periodic save
    still restores to the pristine state (otherwise recovery would need a
    fresh model whose run could differ from the promised one). The initial
    save is synchronous (``wait=True``) — it doubles as a fail-fast probe
    that storage is writable at all.
    """
    policy = restart_policy if restart_policy is not None else RestartPolicy()
    fit_kwargs = dict(fit_kwargs or {})
    rng = random.Random(policy.seed)
    cm = checkpoint_manager
    crashes: List[CrashRecord] = []
    t0 = time.monotonic()

    def summary(completed: bool) -> RunSummary:
        return RunSummary(model=model, completed=completed,
                          restarts=len(crashes), crashes=crashes,
                          wall_time_s=time.monotonic() - t0)

    if save_initial and not cm.checkpoints():
        if getattr(model, "params", None) is None:
            model.init()
        cm.save(model, wait=True)

    attempt = 0
    try:
        while True:
            # fence the manager to THIS attempt's model: a watchdog-timed-
            # out fit thread cannot be cancelled, only outlived — if it
            # wakes later, its step_end/save calls are dropped instead of
            # committing a stale-lineage checkpoint the next restore would
            # pick up behind the recovered run's back
            cm.fence(model)
            try:
                def _fit():
                    return model.fit(data, num_epochs=num_epochs,
                                     checkpoint_manager=cm, **fit_kwargs)
                if watchdog is not None:
                    watchdog.call(_fit, what=f"train_until fit attempt "
                                             f"{attempt + 1}")
                else:
                    _fit()
                s = summary(True)
                log.info("%s", s)
                return s
            except policy.restart_on as e:
                attempt += 1
                crashed_at = getattr(model, "iteration", None)
                if attempt > policy.max_restarts:
                    crashes.append(CrashRecord(
                        attempt=attempt, error_type=type(e).__name__,
                        error=str(e), crashed_at_step=crashed_at,
                        restored_step=None, restored_epoch=None,
                        backoff_s=0.0))
                    s = summary(False)
                    log.error("train_until giving up: %s", s)
                    raise RestartBudgetExceeded(
                        f"restart budget exhausted after "
                        f"{policy.max_restarts} restarts (last crash: "
                        f"{type(e).__name__}: {e})", s) from e
                delay = (backoff_delay(attempt - 1, base_s=policy.backoff_s,
                                       cap_s=policy.max_backoff_s, rng=rng)
                         if policy.backoff_s > 0 else 0.0)
                log.warning(
                    "train_until crash %d/%d (%s: %s) at step %s — "
                    "restoring latest checkpoint after %.2fs backoff",
                    attempt, policy.max_restarts, type(e).__name__, e,
                    crashed_at, delay)
                if delay:
                    time.sleep(delay)
                # the crash's own record goes in FIRST (causal order) with
                # its own attempt number; restore retries below append
                # RestoreFailed records after it, each consuming a further
                # attempt. restored_step is filled in once restore lands.
                crash_rec = CrashRecord(
                    attempt=attempt, error_type=type(e).__name__,
                    error=str(e), crashed_at_step=crashed_at,
                    restored_step=None, restored_epoch=None,
                    backoff_s=delay)
                # same process, so the flight ring is directly readable:
                # attach what the victim was doing when it crashed
                try:
                    from deeplearning4j_tpu.obs.flight import (
                        get_flight_recorder)
                    fr = get_flight_recorder()
                    if fr is not None and fr.recorded:
                        crash_rec.flight_tail = fr.tail_summary(8)
                except Exception as fe:
                    log.debug("could not attach flight tail (%s: %s)",
                              type(fe).__name__, fe)
                crashes.append(crash_rec)
                # a failed RESTORE is itself recoverable (a transient
                # storage outage makes restore_latest raise or fall all
                # the way through to None) — it consumes restart budget
                # with backoff, like any other crash, rather than
                # bypassing the budget with an instant give-up
                restored = None
                while restored is None:
                    restore_err_type = "RestoreFailed"
                    restore_err = "restore_latest returned no checkpoint"
                    try:
                        restored = cm.restore_latest()
                    except policy.restart_on as re_err:
                        # keep the REAL error in the crash history — the
                        # operator must be able to tell a storage outage
                        # from an empty store
                        restore_err_type = type(re_err).__name__
                        restore_err = f"restore_latest failed: {re_err}"
                        log.warning("restore_latest failed (%s: %s)",
                                    type(re_err).__name__, re_err)
                        restored = None
                    if restored is not None:
                        break
                    attempt += 1
                    if attempt > policy.max_restarts:
                        s = summary(False)
                        raise RestartBudgetExceeded(
                            "no restorable checkpoint within the restart "
                            "budget (transient storage outage outlasting "
                            "the budget, storage lost every committed "
                            "checkpoint, or save_initial=False before the "
                            "first periodic save) — cannot recover "
                            "without silently restarting a different run",
                            s) from e
                    retry_delay = (backoff_delay(
                        attempt - 1, base_s=policy.backoff_s,
                        cap_s=policy.max_backoff_s, rng=rng)
                        if policy.backoff_s > 0 else 0.0)
                    log.warning(
                        "no restorable checkpoint yet — retrying restore "
                        "(%d/%d) after %.2fs backoff", attempt,
                        policy.max_restarts, retry_delay)
                    crashes.append(CrashRecord(
                        attempt=attempt, error_type=restore_err_type,
                        error=restore_err,
                        crashed_at_step=crashed_at, restored_step=None,
                        restored_epoch=None, backoff_s=retry_delay))
                    if retry_delay:
                        time.sleep(retry_delay)
                rs = restored._restored_from
                if rs is not None:
                    crash_rec.restored_step = rs.step
                    crash_rec.restored_epoch = rs.epoch
                model = restored
                if on_restart is not None:
                    on_restart(model, attempt)
    finally:
        # lift the fence on every exit: the manager goes back to the
        # caller, who may legitimately save other models through it
        cm.fence(None)
