"""Chaos harness for the fault-tolerance stack.

Three kinds of injected failure, all deterministic (seeded) so chaos tests
replay exactly:

- **Process death** — :class:`FaultInjector`, a training listener that
  raises :class:`SimulatedCrash` where a preemption lands from the
  training loop's point of view: after a step's parameter update and
  before the next batch (``kill_at_step``), at an epoch boundary before
  the boundary checkpoint (``kill_at_epoch``), or at a random step drawn
  from a seeded RNG (``kill_probability``). Tests drive it to prove the
  subsystem's core claim: crash at an ARBITRARY point + ``train_until``'s
  restore/refit loop produces final params bitwise-identical to the
  uninterrupted run.

- **Storage faults** — :class:`FlakyBackend`, a
  checkpoint/storage.py wrapper injecting seeded transient errors,
  scripted error bursts, scripted permanent errors and write latency into
  any backend. Put under a ``RetryingBackend`` it proves transient faults
  never kill a run; put bare it proves they surface as loud
  ``CheckpointError``s instead of corrupt state.

- **Data corruption** — ``tear_file`` / ``flip_byte`` (local paths) and
  ``tear_object`` / ``flip_object_byte`` (any backend) simulate the
  disk-level failure modes the manifest layer must detect: a write torn
  by a crash (truncation) and silent bit rot (flip) — both must make
  ``restore_latest`` fall back, never restore garbage, identically
  through every backend.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from typing import List, Optional

from deeplearning4j_tpu.checkpoint.storage import (
    StorageBackend, TransientStorageError)

log = logging.getLogger(__name__)


class SimulatedCrash(RuntimeError):
    """Raised by FaultInjector to simulate a preemption/crash mid-training."""


class FaultInjector:
    """Listener that raises :class:`SimulatedCrash` at a chosen point.
    Attach with ``model.set_listeners`` (or alongside real listeners via
    ``add_listener``)::

        net.set_listeners(FaultInjector(kill_at_step=7))
        with pytest.raises(SimulatedCrash):
            net.fit(data, num_epochs=3, checkpoint_manager=cm)

    Modes (at least one; they compose — first to trigger fires):

    - ``kill_at_step=k``: crash once ``k`` optimizer steps have fully
      applied their updates (before step ``k``'s checkpoint trigger, so
      the newest durable checkpoint is an EARLIER step);
    - ``kill_at_epoch=e``: crash at the boundary where the ``e``-th epoch
      (1-based) has just completed — after its last step's checkpoint,
      BEFORE the epoch counter increments or an epoch-boundary save runs,
      the exact window a preemption likes to find;
    - ``kill_probability=p``: after every step, crash with probability
      ``p`` from a seeded RNG — randomized preemption points that replay
      identically for a given ``seed``.

    ``max_kills`` bounds the total crashes one injector fires (default 1:
    a listener that keeps killing a resumed run would turn ``train_until``
    into a restart-budget test); raise it to simulate repeated preemption.

    ``kill_mode`` selects HOW the injector kills:

    - ``"exception"`` (default): raise :class:`SimulatedCrash` — the
      in-process crash ``train_until``'s restore/refit loop recovers;
    - ``"process"``: ``SIGKILL`` the current process — REAL process death
      (no cleanup, no atexit, no flushing), the preemption shape the
      process supervisor (checkpoint/supervisor.py) and the elastic layer
      (parallel/elastic.py) must survive. Only meaningful in a worker
      subprocess a supervisor watches.
    """

    def __init__(self, kill_at_step: Optional[int] = None,
                 kill_at_epoch: Optional[int] = None,
                 kill_probability: Optional[float] = None,
                 seed: int = 0, max_kills: int = 1,
                 kill_mode: str = "exception"):
        if kill_at_step is None and kill_at_epoch is None \
                and kill_probability is None:
            raise ValueError("need kill_at_step, kill_at_epoch or "
                             "kill_probability")
        if kill_at_step is not None and kill_at_step < 1:
            raise ValueError("kill_at_step must be >= 1")
        if kill_at_epoch is not None and kill_at_epoch < 1:
            raise ValueError("kill_at_epoch must be >= 1")
        if kill_probability is not None \
                and not 0.0 < kill_probability <= 1.0:
            raise ValueError("kill_probability must be in (0, 1]")
        if kill_mode not in ("exception", "process"):
            raise ValueError("kill_mode must be 'exception' or 'process'")
        self.kill_mode = kill_mode
        self.kill_at_step = None if kill_at_step is None else int(kill_at_step)
        self.kill_at_epoch = (None if kill_at_epoch is None
                              else int(kill_at_epoch))
        self.kill_probability = kill_probability
        self.max_kills = int(max_kills)
        self._rng = random.Random(seed)
        self.fired = False
        self.kills = 0

    def _kill(self, why: str):
        self.fired = True
        self.kills += 1
        # flush the crash flight recorder BEFORE dying — for
        # kill_mode="process" the SIGKILL leaves no other chance, and the
        # dump in storage is what the supervisor's post-mortem reads
        try:
            from deeplearning4j_tpu.obs.flight import flush_flight_recorder
            flush_flight_recorder(f"fault injection: {why}")
        except Exception:
            log.exception("flight-recorder flush before injected kill "
                          "failed")
        if self.kill_mode == "process":
            # REAL death: no exception anyone could catch, no cleanup —
            # exactly what a preemption does to a worker
            import signal
            os.kill(os.getpid(), signal.SIGKILL)
        raise SimulatedCrash(f"fault injection: {why}")

    def _armed(self) -> bool:
        return self.kills < self.max_kills

    def iteration_done(self, model, iteration, epoch):
        if not self._armed():
            return
        # ``iteration`` is the model's pre-increment counter: after the k-th
        # optimizer step it reads k-1, so the crash lands exactly when
        # kill_at_step steps have fully applied their updates
        if self.kill_at_step is not None \
                and iteration + 1 >= self.kill_at_step:
            self._kill(f"killed training after step {iteration + 1}")
        if self.kill_probability is not None \
                and self._rng.random() < self.kill_probability:
            self._kill(f"randomly killed training after step "
                       f"{iteration + 1} (p={self.kill_probability})")

    def on_epoch_start(self, model):
        pass

    def on_epoch_end(self, model):
        # fires with model.epoch still at the just-completed epoch's index
        # (fit increments afterwards), so completing epoch index e means
        # e+1 epochs are done
        if self._armed() and self.kill_at_epoch is not None \
                and model.epoch + 1 >= self.kill_at_epoch:
            self._kill(f"killed training at the end of epoch "
                       f"{model.epoch + 1}")


class FlakyBackend(StorageBackend):
    """Storage-fault injection wrapper (chaos testing's storage half).

    Deterministic (seeded) TRANSIENT faults: each intercepted op fails
    with :class:`TransientStorageError` with probability
    ``transient_rate`` — drawn from ``random.Random(seed)``, so a given
    seed yields the same fault schedule every run. On top of that:

    - ``script_failures(n, error=...)`` queues ``n`` guaranteed failures
      for the next matching ops (deterministic "store is down for exactly
      two puts" scenarios, or a scripted *permanent* error);
    - ``put_latency_s`` sleeps before every put — the slow-object-store
      write the per-op timeout in ``RetryingBackend`` must bound.

    ``ops`` restricts which operations can fault (default: all mutating +
    reading ops). ``match`` restricts faults to object NAMES with that
    prefix (for ``list``, the listing prefix) — how chaos is aimed at the
    elastic membership path specifically: ``match="lease-"`` faults only
    the lease heartbeats, ``match="gen-"`` only the membership records,
    while checkpoints riding the same backend stay healthy. Counters
    (``calls``, ``faults_injected``) let tests assert the chaos actually
    happened — a chaos test whose injector never fired proves nothing.
    """

    _ALL_OPS = ("put", "get", "list", "delete", "exists")

    def __init__(self, inner: StorageBackend, seed: int = 0,
                 transient_rate: float = 0.0, put_latency_s: float = 0.0,
                 ops=("put", "get", "list", "delete"),
                 match: Optional[str] = None):
        if not 0.0 <= transient_rate < 1.0:
            raise ValueError("transient_rate must be in [0, 1)")
        unknown = set(ops) - set(FlakyBackend._ALL_OPS)
        if unknown:
            raise ValueError(f"unknown ops: {sorted(unknown)}")
        self.inner = inner
        self.transient_rate = float(transient_rate)
        self.put_latency_s = float(put_latency_s)
        self.ops = tuple(ops)
        self.match = match
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._scripted: List[BaseException] = []
        self.calls = 0
        self.faults_injected = 0

    def script_failures(self, n: int, error: Optional[BaseException] = None):
        """Queue ``n`` guaranteed failures for the next matching ops.
        ``error`` defaults to a TransientStorageError; pass a
        PermanentStorageError instance to script a non-retryable fault."""
        with self._lock:
            for _ in range(n):
                self._scripted.append(
                    error if error is not None else TransientStorageError(
                        "scripted transient storage fault"))

    def _maybe_fail(self, op: str, name: Optional[str] = None):
        if op not in self.ops:
            return
        if self.match is not None and \
                (name is None or not name.startswith(self.match)):
            return
        with self._lock:
            self.calls += 1
            if self._scripted:
                self.faults_injected += 1
                raise self._scripted.pop(0)
            if self.transient_rate and \
                    self._rng.random() < self.transient_rate:
                self.faults_injected += 1
                raise TransientStorageError(
                    f"injected transient fault on '{op}' "
                    f"(rate={self.transient_rate})")

    def put(self, name: str, data: bytes, fsync_directory: bool = True):
        self._maybe_fail("put", name)
        if self.put_latency_s:
            time.sleep(self.put_latency_s)
        return self.inner.put(name, data, fsync_directory=fsync_directory)

    def get(self, name: str) -> bytes:
        self._maybe_fail("get", name)
        return self.inner.get(name)

    def list(self, prefix: str = "") -> List[str]:
        self._maybe_fail("list", prefix)
        return self.inner.list(prefix)

    def delete(self, name: str):
        self._maybe_fail("delete", name)
        return self.inner.delete(name)

    def exists(self, name: str) -> bool:
        self._maybe_fail("exists", name)
        return self.inner.exists(name)

    def clean_orphans(self):
        return self.inner.clean_orphans()

    def describe(self) -> str:
        return f"FlakyBackend({self.inner.describe()})"


# --------------------------------------------------------- data corruption
def tear_file(path: str, keep_fraction: float = 0.5) -> int:
    """Truncate ``path`` to ``keep_fraction`` of its bytes — a torn write.
    Returns the new size."""
    size = os.path.getsize(path)
    keep = max(0, int(size * keep_fraction))
    with open(path, "r+b") as f:
        f.truncate(keep)
    return keep


def flip_byte(path: str, offset: int = -1):
    """XOR one byte (default: the last) — silent corruption that leaves the
    file size intact, so only a checksum can catch it."""
    size = os.path.getsize(path)
    pos = offset % size
    with open(path, "r+b") as f:
        f.seek(pos)
        b = f.read(1)
        f.seek(pos)
        f.write(bytes([b[0] ^ 0xFF]))


def tear_object(backend: StorageBackend, name: str,
                keep_fraction: float = 0.5) -> int:
    """Backend-generic ``tear_file``: replace the object with a truncated
    prefix of itself. (An object-store put is atomic, so a REAL torn write
    cannot happen there — but replication glitches and buggy middleboxes
    produce exactly this shape, and the sha256 fallback must catch it the
    same way.) Returns the new size."""
    data = backend.get(name)
    keep = max(0, int(len(data) * keep_fraction))
    backend.put(name, data[:keep])
    return keep


def flip_object_byte(backend: StorageBackend, name: str, offset: int = -1):
    """Backend-generic ``flip_byte``: XOR one byte of the object in place
    (size unchanged — only a checksum can catch it)."""
    data = bytearray(backend.get(name))
    pos = offset % len(data)
    data[pos] ^= 0xFF
    backend.put(name, bytes(data))
