"""Fault injection for checkpoint/resume testing.

``FaultInjector`` is a training listener that kills the run at a chosen
optimizer step — after the step's parameter update, before the next batch —
which is exactly where a preemption lands from the training loop's point of
view. Tests drive it to prove the subsystem's core claim: crash at an
ARBITRARY step + ``restore_latest()`` + resumed ``fit`` produces final
params bitwise-identical to the uninterrupted run.

``tear_file`` / ``flip_byte`` simulate the disk-level failure modes the
manifest layer must detect: a write torn by a crash (truncation) and silent
bit rot (flip) — both must make ``restore_latest`` fall back, never restore
garbage.
"""

from __future__ import annotations

import os


class SimulatedCrash(RuntimeError):
    """Raised by FaultInjector to simulate a preemption/crash mid-training."""


class FaultInjector:
    """Listener that raises :class:`SimulatedCrash` once ``kill_at_step``
    optimizer steps have completed. Attach with ``model.set_listeners`` (or
    alongside real listeners via ``add_listener``)::

        net.set_listeners(FaultInjector(kill_at_step=7))
        with pytest.raises(SimulatedCrash):
            net.fit(data, num_epochs=3, checkpoint_manager=cm)
    """

    def __init__(self, kill_at_step: int):
        if kill_at_step < 1:
            raise ValueError("kill_at_step must be >= 1")
        self.kill_at_step = int(kill_at_step)
        self.fired = False

    def iteration_done(self, model, iteration, epoch):
        # ``iteration`` is the model's pre-increment counter: after the k-th
        # optimizer step it reads k-1, so the crash lands exactly when
        # kill_at_step steps have fully applied their updates
        if iteration + 1 >= self.kill_at_step:
            self.fired = True
            raise SimulatedCrash(
                f"fault injection: killed training after step {iteration + 1}")

    def on_epoch_start(self, model):
        pass

    def on_epoch_end(self, model):
        pass


def tear_file(path: str, keep_fraction: float = 0.5) -> int:
    """Truncate ``path`` to ``keep_fraction`` of its bytes — a torn write.
    Returns the new size."""
    size = os.path.getsize(path)
    keep = max(0, int(size * keep_fraction))
    with open(path, "r+b") as f:
        f.truncate(keep)
    return keep


def flip_byte(path: str, offset: int = -1):
    """XOR one byte (default: the last) — silent corruption that leaves the
    file size intact, so only a checksum can catch it."""
    size = os.path.getsize(path)
    pos = offset % size
    with open(path, "r+b") as f:
        f.seek(pos)
        b = f.read(1)
        f.seek(pos)
        f.write(bytes([b[0] ^ 0xFF]))
